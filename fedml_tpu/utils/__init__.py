from fedml_tpu.utils.metrics import MetricsSink, profiler_trace

__all__ = ["MetricsSink", "profiler_trace"]
