#!/usr/bin/env python
"""Server-optimizer spine bench (ISSUE 18): the convergence contract
behind `BENCH_opt.json`.

Two workloads, each a plain-FedAvg arm vs one server-optimizer arm —
SAME seed, SAME data, SAME client recipe, fresh subprocess per arm so
no jit cache or RNG state leaks between arms:

  * ``synthetic`` (LEAF synthetic(0.5, 0.5) twin: 30 logistic users,
    8-of-30 sampled per round) — plain vs server adam
    (``--server_opt adam --server_lr 0.1``);
  * ``mnist_learnable_twin`` (class-prototype MNIST stand-in with LEAF
    power-law sizes: 64 clients, 8 sampled per round) — plain vs
    server momentum / FedAvgM (``--server_opt momentum --server_lr 1.0
    --server_momentum 0.9``).

The committed claims are re-derived from each run's own artifacts
(metrics.jsonl accuracy curve, perf.jsonl ledger), not summarized by
this script — and `perf_trend.py --opt_bench` re-derives them AGAIN
from the committed curves:

  * rounds-to-target: the optimizer arm reaches the workload's target
    accuracy in >= 1.5x fewer rounds than plain;
  * final accuracy not worse: optimizer final >= plain final - 0.02
    (one-sided — on both workloads the optimizer arm's final is in
    fact HIGHER; the tolerance guards measurement noise, not a trade);
  * zero recompiles after warmup on every arm, under ``--perf_strict``
    (the optimizer state ride-along must not poison jit caches);
  * the optimizer arms run with ``--adaptive --health`` and every
    perf-ledger round line names the optimizer AND carries the
    controller's pacing decision (``adapt`` record with reasons).

Any gate failure exits 1 and writes nothing.  CPU-container honest:
``backend`` is labeled per arm; the pinned claims are round counts and
accuracies (deterministic at fixed seed), never wall clock.

    python scripts/opt_bench.py             # full arms -> BENCH_opt.json
    python scripts/opt_bench.py --smoke     # relaxed scale, /tmp output
"""

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SPEEDUP_THRESHOLD = 1.5
FINAL_ACC_TOLERANCE = 0.02


def workloads(smoke):
    """name -> (rounds, eval_freq, target_acc, data_flags, opt_name,
    opt_flags).  The regimes were tuned so the claims hold with margin
    at seed 0 on CPU; smoke shrinks rounds/cohorts to a structural
    pipe-cleaner (the convergence gates are skipped — too few rounds
    to reach any honest target)."""
    silo8 = ["--client_num_in_total", "8", "--client_num_per_round", "8"]
    if smoke:
        return {
            "synthetic_lr": (
                4, 1, 0.45,
                ["--dataset", "synthetic", "--lr", "0.003"] + silo8,
                "adam", ["--server_opt", "adam", "--server_lr", "0.1"]),
            "mnist_twin_lr": (
                4, 1, 0.40,
                ["--dataset", "mnist_learnable_twin", "--lr", "0.1",
                 "--client_num_in_total", "16",
                 "--client_num_per_round", "4"],
                "momentum", ["--server_opt", "momentum",
                             "--server_lr", "1.0",
                             "--server_momentum", "0.9"]),
        }
    return {
        "synthetic_lr": (
            30, 1, 0.45,
            ["--dataset", "synthetic", "--lr", "0.003"] + silo8,
            "adam", ["--server_opt", "adam", "--server_lr", "0.1"]),
        "mnist_twin_lr": (
            80, 4, 0.40,
            ["--dataset", "mnist_learnable_twin", "--lr", "0.1",
             "--client_num_in_total", "64",
             "--client_num_per_round", "8"],
            "momentum", ["--server_opt", "momentum",
                         "--server_lr", "1.0",
                         "--server_momentum", "0.9"]),
    }


def _arm_cmd(rounds, eval_freq, data_flags, run_dir):
    return [sys.executable, "-m", "fedml_tpu",
            "--algo", "cross_silo", "--agg_mode", "stream",
            "--model", "lr", "--epochs", "1", "--batch_size", "10",
            "--comm_round", str(rounds),
            "--frequency_of_the_test", str(eval_freq),
            "--seed", "0", "--log_stdout", "false",
            "--perf", "true", "--perf_strict", "true",
            "--run_dir", run_dir,
            "--perf_ledger", os.path.join(run_dir, "perf.jsonl"),
            ] + data_flags


def run_arm(wl_name, arm_name, cmd, run_dir):
    import subprocess
    print(f"== {wl_name}/{arm_name}: {' '.join(cmd[2:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=1800)
    if proc.returncode != 0:
        raise SystemExit(f"{wl_name}/{arm_name} failed "
                         f"rc={proc.returncode}:\n{proc.stderr[-3000:]}")

    curve = []
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        for line in f:
            r = json.loads(line)
            if "test_acc" in r:
                curve.append([int(r["round"]), float(r["test_acc"])])
    curve.sort()
    if not curve:
        raise SystemExit(f"{wl_name}/{arm_name}: no eval rows in "
                         f"metrics.jsonl — the curve IS the claim")

    rows = [json.loads(l)
            for l in open(os.path.join(run_dir, "perf.jsonl"))
            if l.strip()]
    warm = sum(r.get("recompiles", 0) for r in rows[1:])
    adapt_rounds = sum(1 for r in rows
                       if isinstance(r.get("adapt"), dict)
                       and r["adapt"].get("reasons"))
    named_rounds = sum(1 for r in rows if "server_opt" in r)

    import jax
    print(f"   rounds={len(rows)} final_acc={curve[-1][1]:.3f} "
          f"recompiles_after_warmup={warm} adapt_rounds={adapt_rounds}")
    return {"backend": jax.default_backend(),
            "test_acc_by_round": curve,
            "final_acc": curve[-1][1],
            "recompiles_after_warmup": warm,
            "ledger_rounds": len(rows),
            "adapt_rounds": adapt_rounds,
            "server_opt_named_rounds": named_rounds,
            "cmd": cmd[2:]}


def run_workload(name, spec, workdir, smoke):
    from fedml_tpu.obs.trend import _opt_rounds_to_target
    rounds, eval_freq, target, data_flags, opt_name, opt_flags = spec
    arms, failures = {}, []
    for arm_name, extra in (
            ("plain", []),
            # the optimizer arm carries the controller too: the bench
            # pins that pacing decisions are ledgered every round, and
            # that neither ride-along costs a recompile
            (opt_name, opt_flags + ["--adaptive", "true",
                                    "--health", "true"])):
        run_dir = os.path.join(workdir, name, arm_name)
        cmd = _arm_cmd(rounds, eval_freq, data_flags, run_dir) + extra
        arms[arm_name] = run_arm(name, arm_name, cmd, run_dir)

    gates = {}
    warm = {a: arm["recompiles_after_warmup"] for a, arm in arms.items()}
    gates["zero_recompiles_after_warmup"] = {
        "ok": all(w == 0 for w in warm.values()), "per_arm": warm}
    if any(warm.values()):
        failures.append(f"{name}: recompiles after warmup under "
                        f"--perf_strict: {warm}")

    opt = arms[opt_name]
    visible = (opt["adapt_rounds"] == opt["ledger_rounds"] > 0
               and opt["server_opt_named_rounds"] == opt["ledger_rounds"])
    gates["controller_decisions_visible"] = {
        "ok": visible, "adapt_rounds": opt["adapt_rounds"],
        "named_rounds": opt["server_opt_named_rounds"],
        "ledger_rounds": opt["ledger_rounds"]}
    if not visible:
        failures.append(
            f"{name}: controller decision / optimizer name missing from "
            f"ledger round(s): adapt on {opt['adapt_rounds']}, named on "
            f"{opt['server_opt_named_rounds']} of {opt['ledger_rounds']}")

    if smoke:
        # too few rounds to reach an honest target — the convergence
        # gates are explicitly skipped, and trend.validate_opt_bench
        # refuses any smoke artifact on the committed line anyway
        gates["speedup"] = {"ok": True, "smoke_skipped": True,
                            "threshold": SPEEDUP_THRESHOLD}
        gates["final_accuracy_not_worse"] = {
            "ok": True, "smoke_skipped": True,
            "tolerance": FINAL_ACC_TOLERANCE}
        return {"target_acc": target, "arms": arms, "gates": gates}, \
            failures

    rtt = {a: _opt_rounds_to_target(arm["test_acc_by_round"], target)
           for a, arm in arms.items()}
    ratio = (rtt["plain"] / rtt[opt_name]
             if rtt["plain"] and rtt[opt_name] else 0.0)
    gates["speedup"] = {
        "ok": bool(rtt["plain"] and rtt[opt_name]
                   and ratio >= SPEEDUP_THRESHOLD),
        "rounds_to_target": rtt, "ratio": round(ratio, 2),
        "threshold": SPEEDUP_THRESHOLD}
    if not gates["speedup"]["ok"]:
        failures.append(f"{name}: rounds-to-target {rtt} — ratio "
                        f"{ratio:.2f} < {SPEEDUP_THRESHOLD}")

    finals = {a: arm["final_acc"] for a, arm in arms.items()}
    ok = finals[opt_name] >= finals["plain"] - FINAL_ACC_TOLERANCE
    gates["final_accuracy_not_worse"] = {
        "ok": ok, "final_acc": finals,
        "tolerance": FINAL_ACC_TOLERANCE}
    if not ok:
        failures.append(f"{name}: {opt_name} final {finals[opt_name]:.3f}"
                        f" worse than plain {finals['plain']:.3f} - "
                        f"{FINAL_ACC_TOLERANCE}")

    print(f"   {name}: rounds_to_target={rtt} ratio={ratio:.2f} "
          f"finals={ {a: round(v, 3) for a, v in finals.items()} }")
    return {"target_acc": target, "arms": arms, "gates": gates}, failures


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="relaxed scale; output under /tmp (never the "
                        "committed artifact)")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    out_path = args.out or (
        os.path.join(tempfile.gettempdir(), "BENCH_opt.json")
        if args.smoke else os.path.join(REPO, "BENCH_opt.json"))
    workdir = tempfile.mkdtemp(prefix="opt_bench.")

    wls, failures = {}, []
    for name, spec in workloads(args.smoke).items():
        wl, fails = run_workload(name, spec, workdir, args.smoke)
        failures += fails
        wls[name] = wl

    artifact = {
        "bench": "opt", "version": 1, "smoke": bool(args.smoke),
        "note": ("same seed, same data, fresh subprocess per arm; "
                 "claims are round counts and accuracies (deterministic "
                 "at seed 0 on CPU), never wall clock.  The final-"
                 "accuracy gate is one-sided (optimizer >= plain - tol) "
                 "— on both workloads the optimizer arm's final is "
                 "higher, so 'equal final accuracy' holds with margin"),
        "workloads": wls,
    }
    from fedml_tpu.obs import trend
    failures += [f"schema: {x}"
                 for x in trend.validate_opt_bench(artifact)]
    if failures:
        for f in failures:
            print(f"FAIL {f}")
        return 1
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"== opt bench OK -> {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
