"""Asynchronous buffered federated aggregation (FedBuff-style) — beyond
the reference.

The reference's server is a strict barrier: every sampled client must
report before aggregation (check_whether_all_receive,
FedAvgServerManager.py:51), so one straggler stalls the world and its
only escape is MPI.Abort.  Our cross-silo layer already softens that
with wait/drop/abort policies; this module removes the barrier entirely,
the Nguyen et al. 2022 (FedBuff) way:

* silos train CONTINUOUSLY: upload a delta, immediately receive the
  current global + a fresh client assignment, keep going;
* the server buffers deltas and aggregates every ``aggregation_goal``
  uploads — a "version" — applying each delta against the CURRENT global
  with a staleness discount ``(1 + s)^-alpha`` where ``s`` is how many
  versions elapsed since the silo's base model.  The discount is applied
  OUTSIDE the sample-weight normalization: mixing ratios come from raw
  ``num_samples`` (summing to 1), and each delta is then scaled by its
  own discount — so a buffer of uniformly stale deltas is damped
  absolutely (the FedBuff behavior), not just relatively.  At zero
  staleness every discount is 1 and the update is plain weighted FedAvg;
* with ``aggregation_goal = n_silos``, ``alpha`` irrelevant (zero
  staleness) and ``server_lr = 1`` the first version reduces EXACTLY to
  a synchronous FedAvg round (the parity oracle in
  tests/test_async_fl.py).

Deltas ride the existing client actor's ``encode_upload`` hook (the same
seam wire compression uses), so the client side is unchanged
FedAvgClientActor choreography — INIT/SYNC in, MODEL out.
"""

from __future__ import annotations

import collections
import logging
import math
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from fedml_tpu.comm.actors import SelfMessageTimer, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.transport import Transport
from fedml_tpu.algorithms.cross_silo import MsgType
from fedml_tpu.core.pytree import HostMirror
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

# server self-message from the re-task watchdog timer (value continues
# the MsgType numbering in algorithms/cross_silo.py)
MSG_RETASK_TICK = 7


def _payload_crc(tree) -> int:
    """Content crc32 over a delta's leaf bytes (the cheap frame identity
    the rejected-upload dedupe keys on).  Non-tree junk payloads hash to
    a sentinel — admission rejects them anyway."""
    try:
        crc = 0
        for leaf in jax.tree.leaves(tree):
            crc = zlib.crc32(
                np.ascontiguousarray(np.asarray(leaf)).tobytes(), crc)
        return crc
    except Exception:  # noqa: BLE001 — unhashable garbage payload
        return -1


def delta_encoder(new_params, global_params):
    """Client-side upload transform: send the UPDATE, not the weights —
    the async server applies it to whatever global is current."""
    return jax.tree.map(lambda a, b: np.asarray(a) - np.asarray(b),
                        new_params, global_params)


class AsyncFedServerActor(ServerManager):
    """Barrier-free aggregator: buffer ``aggregation_goal`` deltas, apply
    with staleness discounts, re-task exactly the silos whose uploads
    were consumed.

    ``num_versions`` plays comm_round's role: total aggregations before
    FINISH.  ``on_version(version, params)`` is the eval hook."""

    def __init__(self, transport: Transport, init_params,
                 client_num_in_total: int, n_silos: int,
                 num_versions: int, aggregation_goal: int,
                 staleness_exponent: float = 0.5, server_lr: float = 1.0,
                 on_version: Optional[Callable[[int, object], None]] = None,
                 seed: int = 0, checkpointer=None,
                 retask_timeout_s: Optional[float] = None,
                 admission=None,
                 defended_aggregate: Optional[Callable] = None,
                 stream_agg=None,
                 encode_once: bool = True,
                 perf=None,
                 health=None,
                 extra_state: Optional[tuple] = None,
                 journal=None,
                 faultline=None,
                 server_opt=None,
                 degrade=None,
                 ingest=None):
        """``checkpointer``: a `RoundCheckpointer`; every applied version
        is saved per its ``save_every`` gating and ``start()`` resumes
        from the latest saved version — a crashed async server restarts
        mid-federation instead of from version 0.

        ``retask_timeout_s``: liveness watchdog.  The FedBuff tasking
        rule re-tasks only the silos whose uploads were CONSUMED — if a
        silo's upload is lost on the wire, that silo falls out of
        rotation, and once fewer than ``aggregation_goal`` silos remain
        active the server wedges.  With a watchdog, any silo quiet for
        this long is re-tasked with a fresh assignment against the
        current global (a duplicate from a silo that was merely slow is
        handled by the at-most-once buffer guard).

        ``admission``: a `fedml_tpu.robust.AdmissionPipeline` built with
        ``kind="delta"`` — screen BEFORE buffering: a rejected delta
        never enters the buffer, the offending silo is struck, and a
        QUARANTINED silo is benched (not re-tasked) until its sentence
        expires at a later version, when it is re-tasked on probation.
        Honest-looking rejects (wire corruption) are re-tasked
        immediately so they stay in rotation.

        ``defended_aggregate``: a
        `fedml_tpu.robust.make_defended_aggregate` product applied to
        the static ``[goal, ...]`` stacked delta buffer with the raw
        sample weights; the staleness discount is applied AFTER the
        robust aggregate (the buffer's sample-weighted mean discount
        scales the applied step), so a Byzantine rule cannot be gamed
        through staleness claims.  When None, the exact legacy
        sample+discount weighted mean is used.

        ``stream_agg``: a `fedml_tpu.core.stream_agg.StreamingAggregator`
        built with ``kind="delta"`` (``--agg_mode stream``) — each
        admitted delta FOLDS into O(model) running state at arrival (the
        ledger's ``fold`` phase) and the buffer keeps only metadata
        tuples, so the server never holds ``goal`` model-sized deltas at
        once.  The version-close semantics mirror the defended stack
        path exactly: the rule sees raw sample weights, and the buffer's
        sample-weighted MEAN staleness discount scales the applied step
        afterwards.  Mutually exclusive with ``defended_aggregate``.

        ``encode_once``: the tasking fan-outs (initial wave, post-version
        re-task of the consumed silos) ride the transport's ``send_many``
        — the global serializes once per wave instead of once per silo.
        Single-silo re-tasks (watchdog nudges, probation releases) keep
        plain sends.

        ``perf``: a `fedml_tpu.obs.perf.PerfRecorder`; one ledger line
        per applied VERSION (the async analog of a round): tasking-wave
        serialize, admission, defended aggregate, checkpoint, publish
        (the on_version hook), wire deltas, RSS watermark, recompile
        sentry.

        ``health``: a `fedml_tpu.obs.health.HealthAccumulator` built
        with ``kind="delta"`` — every admitted delta folds its
        learning-health statistics at arrival (norm Welford moments
        reusing the admission verdict's norm, cosine alignment of the
        delta against the version's running mean direction, per-silo
        staleness), so the buffer-held metadata tuples stay the only
        per-upload state.  One ``health.jsonl`` line per applied
        version; rejected/malformed uploads tick fairness counters.

        ``extra_state``: a ``(get_fn, set_fn)`` pair folding extra
        cross-version state into every version checkpoint (the sync
        server's PR 3 hook, mirrored): ``get_fn()`` returns a
        FIXED-SHAPE host pytree saved beside params, ``set_fn(tree)``
        restores it on resume.  The runner persists the admission
        `TrustTracker` through it so a resumed server keeps strikes,
        quarantine sentences, and probation clocks.

        ``journal``: a `fedml_tpu.utils.journal.RoundJournal` — the
        async twin of the sync server's mid-round crash consistency:
        each admitted delta's fold journals a crash-safe metadata
        record (carrying its base version, so the buffer rebuilds) and
        the streaming-MEAN fold state snapshots atomically on the
        journal's cadence.  A server killed mid-version resumes the
        SAME version — the durable fold prefix and buffer metadata
        restore, and only silos outside the restored buffer re-task.
        Requires ``stream_agg``.

        ``faultline``: a `fedml_tpu.robust.faultline.Faultline` — the
        seeded process-kill injector (test/soak only); the version loop
        is threaded with the named crash points.

        ``ingest``: a `fedml_tpu.comm.ingest.IngestPipeline`
        (``--ingest_pipeline``) — the transport thread only checks the
        version window and the queued-duplicate set, then enqueues; the
        single fold worker runs screen → fold → buffer in FIFO order
        (arrival order — the async fold is order-preserving either
        way), so the pipelined version sequence is bit-identical to
        inline.  Overflow dead-letters as a network fault, never a
        strike.  Mutually exclusive with ``faultline`` (ActorKilled
        cannot escape a worker thread)."""
        super().__init__(0, transport)
        if not 1 <= aggregation_goal <= n_silos:
            raise ValueError(
                f"aggregation_goal must be in [1, n_silos={n_silos}], "
                f"got {aggregation_goal}")
        self.params = init_params
        self.client_num_in_total = client_num_in_total
        self.n_silos = n_silos
        self.num_versions = num_versions
        self.goal = aggregation_goal
        self.alpha = staleness_exponent
        self.server_lr = server_lr
        self.on_version = on_version
        self.version = 0
        # per consumed upload, BOUNDED at insert (newest 4096): one
        # entry per upload forever is O(cohort * versions) host memory
        # at mega-cohort scale — the cap-at-insert discipline every
        # per-upload history on the live path follows (admission's
        # norm/event windows, the dedupe ledger's pruning)
        self.staleness_seen: collections.deque = collections.deque(
            maxlen=4096)
        self._buffer: List[Tuple[object, float, float, int]] = []
        self._task_rng = np.random.RandomState(seed)
        self.checkpointer = checkpointer
        self.retask_timeout_s = retask_timeout_s
        self._last_heard: Dict[int, float] = {}
        self._retask_timer = SelfMessageTimer()
        # (silo, base_version) pairs already aggregated — the at-most-once
        # guard must survive buffer flushes, not just scan the live buffer
        self._consumed: set = set()
        self.admission = admission
        if defended_aggregate is not None and stream_agg is not None:
            raise ValueError("defended_aggregate (stack mode) and "
                             "stream_agg (stream mode) are mutually "
                             "exclusive; pick one --agg_mode")
        self.defended_aggregate = defended_aggregate
        self.stream_agg = stream_agg
        self.encode_once = encode_once
        self.perf = perf
        self.health = health
        self.extra_state = extra_state
        if journal is not None and stream_agg is None:
            raise ValueError(
                "journal (crash consistency) rides the streaming-fold "
                "receive path: pass --agg_mode stream; the stacked delta "
                "buffer has no incremental fold state to snapshot")
        self.journal = journal
        self.faultline = faultline
        if ingest is not None and faultline is not None:
            raise ValueError(
                "--ingest_pipeline and --faultline are mutually "
                "exclusive: ActorKilled must escape the transport event "
                "loop to reach the harness, and an ingest fold worker "
                "thread has no path there")
        self.ingest = ingest
        # (silo, round-tag) pairs whose frames sit queued, not yet
        # processed: the transport-side duplicate screen (the
        # authoritative at-most-once guard re-runs on the worker)
        self._ingest_inflight: Set[Tuple[int, object]] = set()
        self._ingest_lock = threading.RLock()
        # the server-optimizer seam (ISSUE 18), staleness-aware: the
        # buffer's discounted mean delta becomes the pseudo-gradient
        # (Δ = −davg·mean_delta), so stale buffers move the moments
        # LESS — the discount scales the gradient, never the state
        # dynamics.  None keeps the legacy host-f64 apply bit-exactly.
        self.server_opt = server_opt
        # degrade: a fedml_tpu.robust.degrade.ReliabilityTracker (ISSUE
        # 19).  In the async regime the per-silo completion history
        # (task→upload latency) adapts the WATCHDOG's quiet threshold —
        # the async analog of the sync round deadline — and every
        # watchdog nudge books a network-attributed drop (debt), never
        # a trust strike.
        self.degrade = degrade
        self._tasked_at: Dict[int, float] = {}
        if health is not None:
            # no per-version barrier set exists — the silo universe is
            # the fairness denominator from version 0.  The starvation
            # clock ticks per VERSION here, and a healthy rotation only
            # accepts ~goal of n_silos silos per version — so "N missed
            # turns" means N rotation periods, not N versions: scale
            # the accumulator's starve_after by ceil(n_silos / goal) or
            # every healthy silo would read as starved the moment
            # n_silos / goal exceeds it
            period = -(-n_silos // aggregation_goal)
            health.starve_after = health.starve_after * period
            health.register(range(1, n_silos + 1))
        # host mirror of the current global — a tasking wave re-tasks up
        # to ``goal`` silos against the SAME version, and each used to
        # pay its own device→host transfer
        self._host_mirror = HostMirror()
        # quarantined silos we declined to re-task; released on probation
        self._benched: Set[int] = set()
        # (silo, base_version) -> payload crcs already REJECTED — a
        # duplicated delivery of the SAME frame (chaos dup, transport
        # retry) must not strike twice, but a FRESH malicious upload
        # after a re-task (same silo + base version, different payload)
        # is a new offense and must strike again.  The crc is computed
        # lazily: accepted-path uploads pay one dict miss, never a
        # model-bytes hash; entries are pruned as versions advance.
        self._rejected_crcs: Dict[Tuple[int, int], set] = {}
        # defended-path templates, built on first flush and reused: the
        # shapes are static by design (the jit-once premise), so the
        # model-sized zeros trees must not be reallocated every version
        self._delta_zeros = None    # one [ ... ] zero delta (pad slots)
        self._stacked_zeros = None  # the clip reference for the jit
        self._finished = False
        # version observability: inter-aggregation gap + per-upload
        # staleness (null no-ops when telemetry is disabled)
        reg = telemetry.get_registry()
        self._h_version = reg.histogram(
            "fedml_async_version_duration_seconds")
        self._h_staleness = reg.histogram(
            "fedml_async_staleness_total", buckets=(0, 1, 2, 4, 8, 16, 32))
        self._version_t0: Optional[float] = None

    def register_handlers(self) -> None:
        self.register_handler(MsgType.C2S_MODEL, self._on_model)
        self.register_handler(MSG_RETASK_TICK, self._on_retask_tick)

    # -- tasking -----------------------------------------------------------
    def start(self) -> None:
        """Initial tasking: version-0 assignments use the same seeded
        sampler as the synchronous paths, so goal == n_silos reduces to
        the FedAvg round-0 cohort.  With a ``checkpointer`` holding a
        saved version, the server resumes from it and re-tasks every
        silo against the restored global."""
        if self.checkpointer is not None:
            step = self.checkpointer.latest_round()
            if step is not None:
                try:
                    state = self.checkpointer.restore(
                        step, like=self._checkpoint_state())
                except ValueError:
                    # schema drift on the optional "extra" leaf (a
                    # pre-trust checkpoint resumed with admission on, or
                    # the reverse): restore untemplated and take what's
                    # there — the sync server's convention
                    log.warning("checkpoint %d does not match the "
                                "current state schema; restoring "
                                "untemplated", step)
                    state = self.checkpointer.restore(step)
                self.params = state["params"]
                self.version = int(np.asarray(state["version"]))
                if self.extra_state is not None and "extra" in state:
                    self.extra_state[1](state["extra"])
                log.info("resumed from checkpoint: continuing at version "
                         "%d of %d", self.version, self.num_versions)
        resume = None
        if self.journal is not None:
            resume = self._journal_recovery()
        if self.version >= self.num_versions:
            for silo in range(1, self.n_silos + 1):
                self.send(MsgType.S2C_FINISH, silo)
            self.finish()
            return
        ids = sample_clients(0, self.client_num_in_total, self.n_silos)
        now = time.monotonic()
        self._version_t0 = now
        if self.stream_agg is not None:
            # stream mode: open the first version's fold state (later
            # versions reset at each _apply_buffer close)
            self.stream_agg.reset(self.params)
        if self.perf is not None:
            self.perf.round_start(self.version)
        buffered: Set[int] = set()
        if resume is not None:
            # continue the crashed version: the durable fold prefix and
            # the buffer's metadata tuples restore; those silos are NOT
            # re-tasked (re-tasking them would double-count their
            # version-v deltas — the at-most-once set died with the
            # process)
            with self._perf_phase("journal"):
                self.stream_agg.load_state_dict(resume.state)
                for silo, weight, extra in resume.folded:
                    base = int(extra.get("base", self.version))
                    staleness = self.version - base
                    discount = float(1.0 + staleness) ** (-self.alpha)
                    self._buffer.append((None, float(weight), discount,
                                         int(silo), base))
                    buffered.add(int(silo))
                # re-arms the journal's round state so the resumed
                # version keeps snapshotting on its cadence
                self.journal.note_resume(self.version, resume.folded,
                                         global_crc=resume.global_crc)
        else:
            self._journal_round_start()
        if self.health is not None:
            with self._perf_phase("health"):
                self.health.round_start(self.version, self._host_params())
        # one root span for the initial tasking wave, so version-0 silo
        # train/upload spans stitch into a single trace instead of N
        # disconnected fragments
        with self._root_span("tasking", f"version{self.version}",
                             version=self.version):
            assignments = {silo: int(client_idx) for silo, client_idx
                           in enumerate(ids, start=1)
                           if silo not in buffered}
            # stamp only the silos actually tasked: sample_clients caps
            # the wave at client_num_in_total, and priming the watchdog
            # clock for an untasked silo would make it re-task silos the
            # version-0 wave deliberately left idle
            for silo in assignments:
                self._last_heard[silo] = now
            with self._perf_phase("broadcast_serialize"):
                self._task_wave(assignments, MsgType.S2C_INIT)
        self._arm_retask_timer()
        if self._buffer and len(self._buffer) >= self._effective_goal():
            # the restored buffer already satisfies the goal (the crash
            # hit between goal-reached and the version close): apply now
            self._apply_buffer()

    # -- liveness watchdog --------------------------------------------------
    def _arm_retask_timer(self) -> None:
        if self.retask_timeout_s is None:
            return
        # fire only ENQUEUES a self-message; the re-task scan runs on the
        # transport's event loop like every other handler
        self._retask_timer.arm(self.retask_timeout_s,
                               lambda: self.send(MSG_RETASK_TICK, 0))

    def _cancel_retask_timer(self, join: bool = False) -> None:
        self._retask_timer.cancel(join=join)

    def _on_retask_tick(self, msg: Message) -> None:
        if self.ingest is not None:
            # frames already queued are responses, not silence: drain
            # before judging quiet silos, or the watchdog would re-task
            # a silo whose upload is simply waiting on the fold worker
            self.ingest.drain()
        if self.version >= self.num_versions:
            return
        now = time.monotonic()
        # a silo with an upload sitting in the buffer is waiting on the
        # version to close, not lost — re-tasking it would only produce a
        # duplicate the at-most-once guard rejects
        buffered = {s for _, _, _, s, _ in self._buffer}
        # adaptive quiet threshold (ISSUE 19): the observed task→upload
        # completion quantile adapts the watchdog — a warmed tracker
        # nudges a wedged silo in ~p90×slack instead of paying the full
        # static window; deadline_s clamps to [deadline_floor_s,
        # retask_timeout_s] and falls back to the static value cold
        quiet_after = self.retask_timeout_s
        if self.degrade is not None:
            adaptive = self.degrade.deadline_s(
                range(1, self.n_silos + 1), self.retask_timeout_s)
            if adaptive is not None:
                quiet_after = adaptive
        for silo in range(1, self.n_silos + 1):
            if silo in buffered or silo in self._benched:
                # benched silos are OWNED by the version-close probation
                # release — a watchdog nudge here would double-task them
                # the moment their quarantine lazily expires
                continue
            if self.admission is not None and self.admission.trust.state(
                    silo, self.version) == "quarantined":
                continue  # jailed but never benched: wait out the sentence
            quiet = now - self._last_heard.get(silo, now)
            if quiet >= quiet_after:
                log.warning("silo %d quiet for %.1fs (threshold %.1fs); "
                            "re-tasking against version %d", silo, quiet,
                            quiet_after, self.version)
                self._last_heard[silo] = now  # one nudge per timeout window
                if self.degrade is not None:
                    # a quiet silo is a NETWORK verdict (debt + fault
                    # ledger) — the trust tracker is never touched here
                    self.degrade.note_drop(silo)
                # watchdog ticks are self-messages with no inbound trace
                # context — root each nudge so its train/upload stitch
                with self._root_span("retask",
                                     f"retask-v{self.version}-s{silo}",
                                     silo=silo, version=self.version):
                    self._task(silo, self._next_client())
        self._arm_retask_timer()

    def _host_params(self):
        return self._host_mirror.get(self.params)

    def _task(self, silo: int, client_idx: int, msg_type=MsgType.S2C_SYNC):
        self._tasked_at[silo] = time.monotonic()
        self.send(msg_type, silo,
                  **{Message.ARG_MODEL_PARAMS: self._host_params(),
                     Message.ARG_CLIENT_INDEX: client_idx,
                     Message.ARG_ROUND: self.version})

    def _task_wave(self, assignments: Dict[int, int],
                   msg_type=MsgType.S2C_SYNC) -> None:
        """Task several silos against the CURRENT global: one payload
        serialization for the whole wave (send_many), falling back to
        per-silo sends when ``encode_once`` is off."""
        if not assignments:
            return
        if not self.encode_once:
            for silo in sorted(assignments):
                self._task(silo, assignments[silo], msg_type)
            return
        now = time.monotonic()
        for silo in assignments:
            self._tasked_at[silo] = now
        self.send_many(
            msg_type, sorted(assignments),
            shared_params={Message.ARG_MODEL_PARAMS: self._host_params(),
                           Message.ARG_ROUND: self.version},
            per_receiver_params={
                silo: {Message.ARG_CLIENT_INDEX: client_idx}
                for silo, client_idx in assignments.items()})

    def _next_client(self) -> int:
        return int(self._task_rng.randint(self.client_num_in_total))

    def _checkpoint_state(self) -> dict:
        """Version-state pytree (fixed shapes — doubles as the orbax
        restore template)."""
        out = {"params": self._host_params(),
               "version": np.asarray(self.version, np.int64)}
        if self.extra_state is not None:
            out["extra"] = self.extra_state[0]()
        return out

    def _journal_round_start(self) -> None:
        """Open the new version in the journal (mode/resumability from
        the fold regime; the global crc pins the tasking reference the
        fold must resume against)."""
        if self.journal is None:
            return
        from fedml_tpu.utils.journal import tree_crc
        srvopt = ""
        if self.server_opt is not None and self.server_opt.name != "plain":
            # a non-plain server optimizer tags the journal mode: a
            # resumed fold replayed into a run that would apply a
            # DIFFERENT server step silently changes the version's update
            srvopt = f"+srvopt={self.server_opt.name}"
        with self._perf_phase("journal"):
            self.journal.round_start(
                self.version,
                mode=f"stream_{self.stream_agg.method}{srvopt}",
                resumable=self.stream_agg.method == "mean",
                global_crc=tree_crc(self._host_params()))

    def _journal_recovery(self):
        """The async twin of the sync server's recovery gate: resume the
        open version only when it is exactly the checkpoint's next
        version, its fold regime is resumable, the tasking global
        matches, and a durable snapshot exists — otherwise abandon
        loudly and restart the version from the boundary."""
        from fedml_tpu.utils.journal import tree_crc
        rec = self.journal.recover()
        if rec is None:
            return None
        if rec.round_idx != self.version:
            log.warning("journal holds mid-flight version %d but the "
                        "checkpoint boundary resumes at version %d; "
                        "abandoning the journal version",
                        rec.round_idx, self.version)
            self.journal.abandon(rec.round_idx, "version mismatch")
            return None
        if not rec.resumable:
            log.error("version %d crashed mid-flight in non-resumable "
                      "mode %r (reservoir rules have no durable draw "
                      "stream); restarting the version from the boundary",
                      rec.round_idx, rec.mode)
            self.journal.abandon(rec.round_idx,
                                 f"non-resumable mode {rec.mode}")
            return None
        if rec.global_crc is not None \
                and rec.global_crc != tree_crc(self._host_params()):
            log.error("version %d journal opened against a different "
                      "global (crc mismatch); refusing to resume the "
                      "fold", rec.round_idx)
            self.journal.abandon(rec.round_idx, "global crc mismatch")
            return None
        if rec.state is None or not rec.folded:
            log.warning("version %d crashed before any durable fold "
                        "snapshot; re-tasking every silo from the "
                        "boundary", rec.round_idx)
            self.journal.abandon(rec.round_idx, "no durable snapshot")
            return None
        log.warning("version %d: resuming MID-VERSION from the journal — "
                    "%d delta(s) durably folded (silos %s) rebuild the "
                    "buffer and will not be re-tasked", rec.round_idx,
                    len(rec.folded), [s for s, _, _ in rec.folded])
        return rec

    # -- aggregation -------------------------------------------------------
    def _on_model(self, msg: Message) -> None:
        self._last_heard[msg.sender_id] = time.monotonic()
        if self.version >= self.num_versions:
            return  # late upload after FINISH
        if self.ingest is not None:
            # pipelined receive: envelope facts only here, then enqueue
            # to the single fold worker (FIFO = arrival order = the
            # inline fold order).  The at-most-once/staleness guards run
            # on the worker under the ingest lock — the version may
            # advance while the frame sits queued, and staleness must be
            # judged against the version that FOLDS it, exactly like a
            # frame that spent the same time on the wire.
            key = (msg.sender_id, msg.get(Message.ARG_ROUND))
            if key in self._ingest_inflight:
                log.info("ignoring duplicate version-%s upload from silo "
                         "%d (first copy still queued)", key[1],
                         msg.sender_id)
                return
            self._note_arrival()
            self._ingest_inflight.add(key)
            ok = self.ingest.submit(
                0, lambda: self._ingest_task(msg),
                detail=f"silo {msg.sender_id} version {key[1]}")
            if not ok:
                self._ingest_inflight.discard(key)
            return
        self._upload_body(msg, note_arrival=True)

    def _ingest_task(self, msg: Message) -> None:
        key = (msg.sender_id, msg.get(Message.ARG_ROUND))
        try:
            with self._ingest_lock:
                if self.version >= self.num_versions:
                    return  # federation closed while the frame was queued
                self._upload_body(msg, note_arrival=False)
        finally:
            with self._ingest_lock:
                self._ingest_inflight.discard(key)

    def _upload_body(self, msg: Message, note_arrival: bool) -> None:
        try:
            base_version = int(msg.get(Message.ARG_ROUND))
        except (TypeError, ValueError):
            # a frame without a round tag has no staleness — reject it
            # with a warning instead of killing the handler thread
            self._reject_malformed(
                msg, -1, f"missing/invalid round tag "
                f"{msg.get(Message.ARG_ROUND)!r}")
            return
        if base_version > self.version:
            # a FUTURE version tag is forged (the server never issued it):
            # staleness would go negative and (1+s)^-alpha would divide by
            # zero (s=-1) or go complex (s<=-2) — reject instead
            self._reject_malformed(
                msg, base_version, f"future version tag {base_version} "
                f"(current {self.version})")
            return
        if (msg.sender_id, base_version) in self._consumed or \
                any(s == msg.sender_id and b == base_version
                    for _, _, _, s, b in self._buffer):
            # at-most-once guard: a duplicated frame (lossy wire re-send,
            # chaos dup, or a watchdog re-task racing a slow upload) must
            # not count the same update twice — whether its first copy is
            # still buffered or was already aggregated into a version
            log.warning("ignoring duplicate version-%d upload from silo %d",
                        base_version, msg.sender_id)
            return
        if note_arrival:
            self._note_arrival()  # one wire arrival per (deduped) upload
        delta = msg.get(Message.ARG_MODEL_PARAMS)
        raw_samples = msg.get(Message.ARG_NUM_SAMPLES)
        delta_norm = None
        if self.admission is not None:
            pair = (msg.sender_id, base_version)
            seen = self._rejected_crcs.get(pair)
            crc = _payload_crc(delta) if seen is not None else None
            if seen is not None and crc in seen:
                # duplicate delivery of an already-rejected FRAME: one
                # offense must yield exactly one strike / counter tick
                # (the first copy's handling already re-tasked or
                # benched the silo)
                log.info("ignoring duplicate rejected version-%d upload "
                         "from silo %d", base_version, msg.sender_id)
                return
            # screen BEFORE buffering: a poisoned delta must never sit in
            # the buffer waiting to be applied
            with self._span("ingest:admission", deterministic=True), \
                    self._perf_phase("admission"):
                verdict = self.admission.admit(msg.sender_id, delta,
                                               raw_samples, None,
                                               self.version)
            if not verdict.ok:
                log.warning("rejecting version-%d upload from silo %d "
                            "(reason=%s)", base_version, msg.sender_id,
                            verdict.reason)
                if self.health is not None:
                    with self._perf_phase("health"):
                        self.health.observe_rejected(msg.sender_id,
                                                     verdict.reason)
                if self.journal is not None:
                    with self._perf_phase("journal"):
                        self.journal.note_accept(
                            self.version, msg.sender_id, 0.0,
                            folded=False, reason=verdict.reason)
                if crc is None:
                    crc = _payload_crc(delta)
                self._rejected_crcs.setdefault(pair, set()).add(crc)
                if self.degrade is not None:
                    from fedml_tpu.robust.degrade import FaultClass
                    self.degrade.note_fault(FaultClass.PAYLOAD,
                                            silo=msg.sender_id,
                                            detail=verdict.reason)
                if self.admission.trust.state(
                        msg.sender_id, self.version) == "quarantined":
                    self._bench(msg.sender_id)
                else:
                    # an honest silo behind a corrupting wire stays in
                    # rotation — only quarantine takes it out
                    self._task(msg.sender_id, self._next_client())
                return
            num_samples = verdict.num_samples
            # the screen's one O(model) norm pass is shared with health
            delta_norm = verdict.norm
        else:
            # minimal validation even undefended: float(None) used to
            # raise TypeError and kill the handler thread, and negative/
            # NaN counts corrupted every later mixing ratio
            try:
                num_samples = float(raw_samples)
            except (TypeError, ValueError):
                num_samples = float("nan")
            if not math.isfinite(num_samples) or num_samples <= 0:
                self._reject_malformed(
                    msg, base_version,
                    f"invalid num_samples {raw_samples!r} "
                    f"(version {base_version})")
                return
        if self.degrade is not None:
            # admitted: the task→upload latency feeds the watchdog's
            # adaptive threshold, and any accrued debt is repaid
            t0 = self._tasked_at.get(msg.sender_id)
            if t0 is not None:
                self.degrade.observe_completion(msg.sender_id,
                                                time.monotonic() - t0)
            self.degrade.note_accept(msg.sender_id)
        staleness = self.version - base_version
        discount = float(1.0 + staleness) ** (-self.alpha)
        self.staleness_seen.append(staleness)
        self._h_staleness.observe(staleness)
        if self.health is not None:
            # health folds BEFORE the aggregation fold consumes the
            # delta — after it, only metadata tuples survive
            with self._perf_phase("health"):
                self.health.observe_admitted(msg.sender_id, delta,
                                             num_samples, norm=delta_norm,
                                             staleness=staleness)
        if self.faultline is not None:
            self.faultline.maybe_crash("post_admission_pre_fold",
                                       round_idx=self.version,
                                       silo=msg.sender_id)
        if self.stream_agg is not None:
            # fold at arrival: the buffer keeps only the metadata tuple
            # (weights/discounts/at-most-once bookkeeping) — the delta's
            # bytes never wait for the version to close
            with self._span("ingest:fold", deterministic=True), \
                    self._perf_phase("fold"):
                self.stream_agg.fold(delta, num_samples)
            delta = None
            if self.journal is not None:
                # the base version rides the record so a resumed server
                # rebuilds the buffer tuple (staleness discount included)
                state_fn = (self.stream_agg.state_dict
                            if self.stream_agg.method == "mean" else None)
                with self._span("ingest:journal", deterministic=True), \
                        self._perf_phase("journal"):
                    self.journal.note_accept(
                        self.version, msg.sender_id, float(num_samples),
                        extra={"base": int(base_version)},
                        state_fn=state_fn)
        if self.faultline is not None:
            self.faultline.maybe_crash("post_fold_pre_ack",
                                       round_idx=self.version,
                                       silo=msg.sender_id)
        self._buffer.append(
            (delta, num_samples, discount, msg.sender_id, base_version))
        if len(self._buffer) >= self._effective_goal():
            self._apply_buffer()

    def _bench(self, silo: int) -> None:
        """Take a quarantined silo out of the rotation; flush a buffer
        the shrunk goal now satisfies; finish cleanly if NOBODY is left
        (quarantine expiry is version-based, so a frozen version counter
        could never release anyone — hanging would be forever; this is
        the defended analog of straggler_policy 'abort')."""
        self._benched.add(silo)
        if len(self._benched) >= self.n_silos:
            log.error("every silo is quarantined; no safe progress is "
                      "possible — finishing at version %d", self.version)
            for s in range(1, self.n_silos + 1):
                self.send(MsgType.S2C_FINISH, s)
            self.finish()
            return
        if self._buffer and len(self._buffer) >= self._effective_goal():
            self._apply_buffer()

    def _reject_malformed(self, msg: Message, base_version: int,
                          detail: str) -> None:
        """Shared reject path for structurally-malformed frames (bad
        round tag, bad sample count without admission): warn, strike
        (when the admission pipeline is armed — malformed spam must be
        countable and quarantinable like any other offense), then
        re-task the silo ONCE per unique offending frame — with the
        watchdog off nothing else would ever re-assign it, and the
        active pool would silently shrink below the goal; the crc
        dedupe keeps transport-duplicated copies from multiplying
        assignments."""
        pair = (msg.sender_id, base_version)
        crc = _payload_crc(msg.get(Message.ARG_MODEL_PARAMS))
        seen = self._rejected_crcs.setdefault(pair, set())
        if crc in seen:
            log.info("ignoring duplicate malformed upload from silo %d",
                     msg.sender_id)
            return
        seen.add(crc)
        log.warning("rejecting upload from silo %d: %s", msg.sender_id,
                    detail)
        if self.degrade is not None:
            from fedml_tpu.robust.degrade import FaultClass
            self.degrade.note_fault(FaultClass.PAYLOAD,
                                    silo=msg.sender_id, detail=detail)
        if self.health is not None:
            with self._perf_phase("health"):
                self.health.observe_rejected(msg.sender_id, "malformed")
        if self.admission is not None:
            # malformed metadata is structural damage: count + strike
            self.admission.reject(msg.sender_id, self.version,
                                  "fingerprint")
            if self.admission.trust.state(
                    msg.sender_id, self.version) == "quarantined":
                self._bench(msg.sender_id)
                return
        if msg.sender_id in self._benched:
            return  # owned by the probation release — never double-task
        self._task(msg.sender_id, self._next_client())

    def _effective_goal(self) -> int:
        """The aggregation goal, shrunk by quarantined silos exactly like
        the sync path's quorum: benched silos can contribute nothing, and
        a goal above the active-silo count would freeze versions forever
        (quarantine expiry is version-based, so a frozen federation could
        never release anyone)."""
        active = self.n_silos - len(self._benched)
        return max(1, min(self.goal, active))

    def _apply_buffer(self) -> None:
        if self.faultline is not None:
            self.faultline.maybe_crash("barrier_close",
                                       round_idx=self.version)
        now = time.monotonic()
        if self._version_t0 is not None:
            self._h_version.observe(now - self._version_t0)
        self._version_t0 = now
        deltas = [d for d, _, _, _, _ in self._buffer]
        samples = np.asarray([n for _, n, _, _, _ in self._buffer],
                             np.float64)
        discounts = np.asarray([c for _, _, c, _, _ in self._buffer],
                               np.float64)
        defended = (self.defended_aggregate is not None
                    or (self.stream_agg is not None
                        and self.stream_agg.defended))
        # traced as a child of whichever upload's handling tripped the
        # goal, so the async trace shows which silo closed each version
        with self._span("aggregate", version=self.version,
                        buffered=len(deltas)), \
                self._perf_phase("defended_aggregate" if defended
                                 else "aggregate"):
            def _apply_discounted(robust):
                # shared defended/stream apply step: the rule (or the
                # streamed mean) saw raw sample weights, and the
                # buffer's sample-weighted MEAN staleness discount
                # scales the applied step afterwards — one copy, so the
                # two modes' bit-identity cannot silently fork
                davg = float((discounts * samples).sum()
                             / max(samples.sum(), 1e-12))
                if self.server_opt is not None \
                        and self.server_opt.name != "plain":
                    # server-optimizer seam: Δ = −davg·d (the descent
                    # convention — w − lr·Δ recovers the legacy
                    # w + lr·davg·d), formed in host f64 like the
                    # legacy step, then one jitted optimizer step
                    pseudo = jax.tree.map(
                        lambda p, d: np.asarray(
                            -davg * np.asarray(d, np.float64)).astype(
                                np.asarray(p).dtype),
                        self.params, robust)
                    self.params = self.server_opt.apply_delta(
                        self.params, pseudo, self.version)
                    return
                self.params = jax.tree.map(
                    lambda p, d: (np.asarray(p, np.float64)
                                  + self.server_lr * davg
                                  * np.asarray(d, np.float64)).astype(
                                      np.asarray(p).dtype),
                    self.params, robust)

            if self.stream_agg is not None:
                # stream mode: the buffered deltas already folded at
                # arrival — the version close is one finalize
                _apply_discounted(self.stream_agg.finalize(self.version))
            elif self.defended_aggregate is not None:
                # staleness-aware defended variant: the Byzantine rule
                # sees the raw sample weights (staleness claims cannot
                # steer the selection), and the buffer's sample-weighted
                # MEAN discount scales the applied step afterwards —
                # zero staleness reduces to the plain defended mean.
                # The stack is padded to the FULL ``goal`` width with
                # weight-0 zero slots (every rule is padding-invariant),
                # so a quarantine-shrunk buffer keeps the static shape
                # and the jit still compiles exactly once.
                if self._delta_zeros is None:
                    self._delta_zeros = jax.tree.map(
                        lambda v: np.zeros_like(np.asarray(v)), deltas[0])
                pad = [self._delta_zeros] * (self.goal - len(deltas))
                stacked = jax.tree.map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]),
                    *(deltas + pad))
                w = np.concatenate(
                    [samples, np.zeros(len(pad))]).astype(np.float32)
                if self._stacked_zeros is None:
                    self._stacked_zeros = jax.tree.map(
                        lambda x: np.zeros(x.shape[1:], x.dtype), stacked)
                _apply_discounted(self.defended_aggregate(
                    self._stacked_zeros, stacked, w, self.version))
            else:
                # sample ratios sum to 1; the staleness discount
                # multiplies each term so stale buffers shrink the
                # applied step itself
                coeffs = discounts * samples / max(samples.sum(), 1e-12)
                mean = jax.tree.map(
                    lambda *leaves: sum(c * np.asarray(l, np.float64)
                                        for c, l in zip(coeffs, leaves)),
                    *deltas)
                self.params = jax.tree.map(
                    lambda p, d: (np.asarray(p, np.float64)
                                  + self.server_lr * d).astype(
                                      np.asarray(p).dtype),
                    self.params, mean)
        silos = [s for _, _, _, s, _ in self._buffer]
        if self.health is not None:
            # close the version's health line on the post-apply global
            # BEFORE perf.round_end, so the health phase ledgers into
            # the same version line it belongs to
            with self._perf_phase("health"):
                self.health.round_end(self.version,
                                      new_global=self._host_params(),
                                      buffered=len(silos))
        self._consumed.update((s, b) for _, _, _, s, b in self._buffer)
        self._buffer.clear()
        if self.stream_agg is not None:
            # the next version's fold state opens here, before the event
            # loop can hand us another upload
            self.stream_agg.reset(self.params)
        self.version += 1
        if self._rejected_crcs:
            # prune the dedupe ledger: a duplicate of a frame 64+
            # versions stale is indistinguishable from a fresh offense
            # at that point, and the ledger must not grow for the life
            # of a long federation
            horizon = self.version - 64
            self._rejected_crcs = {p: c for p, c in
                                   self._rejected_crcs.items()
                                   if p[1] >= horizon}
        if self.faultline is not None:
            self.faultline.maybe_crash("mid_checkpoint_write",
                                       round_idx=self.version - 1)
        if self.checkpointer is not None:
            with self._perf_phase("checkpoint"):
                self.checkpointer.maybe_save(
                    self.version - 1, self._checkpoint_state(),
                    last_round=self.version >= self.num_versions)
        if self.journal is not None:
            # after the checkpoint is durable (the sync server's
            # ordering): a crash between the two re-finalizes the
            # version from the journal snapshot on resume
            with self._perf_phase("journal"):
                self.journal.round_end(self.version - 1)
        if self.faultline is not None:
            self.faultline.maybe_crash("publish",
                                       round_idx=self.version - 1)
        if self.perf is not None:
            # close the applied version's ledger line (strict-mode
            # RecompileError raises here, on the event loop) BEFORE the
            # on_version hook — the hook runs eval/logging on a cadence
            # of its own (--frequency_of_the_test), and folding that into
            # the line would make round_s medians swing with eval cadence
            # and trip the trend gate on a non-regression (the sync
            # server closes before its eval hook for the same reason)
            vextra = ({"server_opt": self.server_opt.name}
                      if self.server_opt is not None else {})
            # the applied version's global CRC: the ingest bench's
            # bit-parity gate compares this sequence inline vs pipelined
            from fedml_tpu.utils.journal import tree_crc
            vextra["global_crc"] = tree_crc(self._host_params())
            self.perf.round_end(self.version - 1, buffered=len(silos),
                                **vextra)
        if self.on_version is not None:
            self.on_version(self.version, self.params)
        if self.version >= self.num_versions:
            for silo in range(1, self.n_silos + 1):
                self.send(MsgType.S2C_FINISH, silo)
            self.finish()
            return
        if self.perf is not None:
            # the next version's line opens AFTER the eval hook (its cost
            # belongs to no line) and before the tasking wave, so the
            # wave's serialize is its first phase
            self.perf.round_start(self.version)
        # the journal opens the next version BEFORE the tasking wave: a
        # delta can arrive the moment the wave lands, and its accept
        # record must fall inside an open round
        self._journal_round_start()
        if self.health is not None:
            with self._perf_phase("health"):
                self.health.round_start(self.version, self._host_params())
        # only the consumed silos need new work; assignments draw in
        # buffer order (the legacy per-silo RNG schedule), the wave then
        # serializes the new global once for all of them
        with self._perf_phase("broadcast_serialize"):
            self._task_wave({silo: self._next_client() for silo in silos})
        if self.admission is not None:
            # sweep trust states once per version: transitions expired
            # quarantines to probation and refreshes the
            # fedml_robust_quarantined_total gauge (the sync path's
            # per-broadcast sweep, mirrored here)
            self.admission.trust.quarantined(
                self.version, range(1, self.n_silos + 1))
            # probation release: silos whose quarantine expired at this
            # version re-enter the rotation against the current global
            for silo in sorted(self._benched):
                if self.admission.trust.state(
                        silo, self.version) != "quarantined":
                    self._benched.discard(silo)
                    log.info("silo %d released from quarantine at version "
                             "%d; re-tasking on probation", silo,
                             self.version)
                    self._task(silo, self._next_client())

    def finish(self) -> None:
        self._finished = True
        self._cancel_retask_timer(join=True)
        if self.ingest is not None:
            # no drain: finish may run ON the fold worker (the closing
            # version applied there); stop() never joins its caller
            self.ingest.stop()
        super().finish()
