"""Adversary injection: seeded malicious silos over the real message path.

Symmetric to `comm/chaos.py` — chaos perturbs the WIRE, this perturbs
the PAYLOAD at its source.  A malicious silo is an unmodified
`FedAvgClientActor` whose ``train_fn`` is wrapped by
`make_malicious_train_fn`: the silo really trains, really uploads over
the real transport, and the server sees exactly what a compromised
trust domain would send.  Attacks are selected per silo with the CLI
``--adversary`` spec::

    --adversary "2:scale:20,3:sign_flip"       # silo 2 scales x20, 3 flips
    --adversary "4:nan_bomb"                   # silo 4 NaNs a leaf
    --adversary "1:inflate:1e9,2:backdoor"     # weight inflation + backdoor

Kinds (classic Byzantine attack zoo):

* ``sign_flip``  — upload ``global - param * (update)`` (param: flip
  magnitude, default 1 = pure sign flip; Bernstein et al. 2018);
* ``scale``      — upload ``global + param * update`` (param: scale
  factor, default 10; the model-replacement/boosting attack);
* ``gauss``      — add N(0, param) noise to the update (default std 1);
* ``nan_bomb``   — one parameter leaf becomes all-NaN (the crash/poison
  probe the finite guard must catch);
* ``inflate``    — honest update, but ``num_samples`` claimed as
  ``param`` (default 1e9 — the weight-capture attack the admission cap
  must catch);
* ``backdoor``   — trains on trigger-stamped, target-relabeled data
  (`data/edge_case.apply_pixel_trigger` via the shard transform below,
  reusing the `algorithms/backdoor.py` poison semantics); param is the
  target label (omitted: the run's ``--target_label``).

All randomness is seeded per ``(seed, silo, round)``, so attacked runs
replay bit-identically.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

ATTACK_KINDS = ("sign_flip", "scale", "gauss", "nan_bomb", "inflate",
                "backdoor")

# backdoor's -1 sentinel means "use the run's --target_label"
_DEFAULT_PARAM = {"sign_flip": 1.0, "scale": 10.0, "gauss": 1.0,
                  "nan_bomb": 0.0, "inflate": 1e9, "backdoor": -1.0}


@dataclasses.dataclass(frozen=True)
class Attack:
    kind: str
    param: float

    def __post_init__(self):
        if self.kind not in ATTACK_KINDS:
            raise ValueError(f"unknown attack kind {self.kind!r}; "
                             f"available: {ATTACK_KINDS}")


def parse_adversary_spec(spec: str) -> Dict[int, Attack]:
    """``"silo:kind[:param],..."`` → {silo_id: Attack}.  Silo ids are the
    1-based actor ids of the cross-silo/async deployments."""
    out: Dict[int, Attack] = {}
    if not spec:
        return out
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"bad --adversary entry {entry!r}; expected "
                f"silo:kind[:param] (e.g. '2:scale:20')")
        try:
            silo = int(parts[0])
        except ValueError:
            raise ValueError(f"bad --adversary silo id {parts[0]!r} "
                             f"in {entry!r}") from None
        if silo < 1:
            raise ValueError(f"--adversary silo ids are 1-based actor ids; "
                             f"got {silo}")
        kind = parts[1].strip()
        param = float(parts[2]) if len(parts) == 3 else _DEFAULT_PARAM.get(
            kind, 0.0)
        if silo in out:
            raise ValueError(f"--adversary lists silo {silo} twice")
        out[silo] = Attack(kind, param)
    return out


def _tree_map2(fn, a, b):
    """Structure-preserving two-tree map over the plain dict/list nests
    the wire codec produces (numpy host math — no device bounce)."""
    if hasattr(a, "items"):
        return {k: _tree_map2(fn, a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        t = [_tree_map2(fn, x, y) for x, y in zip(a, b)]
        return tuple(t) if isinstance(a, tuple) else t
    return fn(np.asarray(a), np.asarray(b))


def _tree_map1(fn, t):
    """One-tree map (numpy host leaves)."""
    if hasattr(t, "items"):
        return {k: _tree_map1(fn, v) for k, v in t.items()}
    if isinstance(t, (list, tuple)):
        out = [_tree_map1(fn, v) for v in t]
        return tuple(out) if isinstance(t, tuple) else out
    return fn(np.asarray(t))


def _tree_host(t):
    """One-tree host materialization (np.asarray every leaf)."""
    return _tree_map1(lambda a: a, t)


def _first_float_leaf_to_nan(tree):
    """Copy the tree with its first float leaf replaced by all-NaN."""
    done = [False]

    def _walk(t):
        if hasattr(t, "items"):
            return {k: _walk(v) for k, v in t.items()}
        if isinstance(t, (list, tuple)):
            out = [_walk(v) for v in t]
            return tuple(out) if isinstance(t, tuple) else out
        arr = np.asarray(t)
        if not done[0] and np.issubdtype(arr.dtype, np.floating):
            done[0] = True
            return np.full_like(arr, np.nan)
        return arr

    return _walk(tree)


def make_malicious_train_fn(attack: Attack, train_fn: Callable,
                            silo: int, seed: int = 0) -> Callable:
    """Wrap a silo's honest ``train_fn(params, client_idx, round_idx)``
    with the attack.  The wrapped function keeps the SiloTrainFn
    contract, so the standard client actor (and therefore the real
    transport, codec, compression, and tracing) carries the attack —
    no test-only message forging."""

    def malicious(params, client_idx, round_idx):
        new_params, num_samples = train_fn(params, client_idx, round_idx)
        if attack.kind == "backdoor":
            # the poisoning happened in the shard transform (the silo
            # genuinely trained on triggered data); the upload is honest
            return new_params, num_samples
        if attack.kind == "inflate":
            return new_params, float(attack.param)
        host_new = _tree_host(new_params)
        host_old = _tree_host(params)
        if attack.kind == "sign_flip":
            out = _tree_map2(lambda g, n: (g - attack.param * (n - g))
                             .astype(n.dtype), host_old, host_new)
        elif attack.kind == "scale":
            out = _tree_map2(lambda g, n: (g + attack.param * (n - g))
                             .astype(n.dtype), host_old, host_new)
        elif attack.kind == "gauss":
            rng = np.random.RandomState(
                (seed * 1_000_003 + silo * 7919 + int(round_idx) * 101)
                % (2 ** 32))
            out = _tree_map1(
                lambda n: (n + rng.normal(0.0, attack.param, n.shape))
                .astype(n.dtype) if np.issubdtype(n.dtype, np.floating)
                else n, host_new)
        elif attack.kind == "nan_bomb":
            out = _first_float_leaf_to_nan(host_new)
        else:  # pragma: no cover — Attack.__post_init__ already validated
            raise ValueError(f"unhandled attack kind {attack.kind!r}")
        return out, num_samples

    return malicious


def make_backdoor_shard_transform(target_label: int, trigger_size: int = 3,
                                  poison_frac: float = 1.0,
                                  seed: int = 0) -> Callable:
    """A ``shard_transform(shard, client_idx, round_idx)`` hook for the
    silo training setup: stamps the pixel trigger + target relabel onto
    ``poison_frac`` of the shard's real (masked) samples, exactly the
    `algorithms/backdoor.poison_stacked_clients` semantics but applied
    silo-side per round — the attacker poisons whatever client shard it
    is assigned, as a real compromised silo would."""
    from fedml_tpu.data.edge_case import apply_pixel_trigger

    def transform(shard, client_idx, round_idx):
        x = np.array(shard["x"], copy=True)
        y = np.array(shard["y"], copy=True)
        mask = np.asarray(shard["mask"])
        sample_shape = x.shape[2:]  # shard is [S, B, ...]
        flat_x = x.reshape((-1,) + tuple(sample_shape))
        flat_y = y.reshape(-1)
        real = np.where(mask.reshape(-1) > 0)[0]
        k = int(round(poison_frac * len(real)))
        if k:
            rng = np.random.RandomState(
                (seed * 1_000_003 + int(client_idx) * 7919
                 + int(round_idx) * 101) % (2 ** 32))
            sel = rng.choice(real, k, replace=False)
            px, py = apply_pixel_trigger(flat_x[sel], target_label,
                                         trigger_size=trigger_size)
            flat_x[sel] = px
            flat_y[sel] = py
        return {**shard, "x": flat_x.reshape(x.shape),
                "y": flat_y.reshape(y.shape)}

    return transform


def attacked_silos(adversaries: Dict[int, Attack],
                   kinds: Optional[List[str]] = None) -> List[int]:
    """Silo ids running one of ``kinds`` (all kinds when None)."""
    return sorted(s for s, a in adversaries.items()
                  if kinds is None or a.kind in kinds)


# ---------------------------------------------------------------------------
# wave-level poisoning (--cross_device; ISSUE 16)
# ---------------------------------------------------------------------------

# the cross-device engine has no per-silo message seam (clients train
# INSIDE one compiled wave program), so per-silo kinds like inflate/
# backdoor don't apply; these perturb the WAVE SUMMARY — the weighted
# partial mean the admission screen and the streaming fold both see
WAVE_ATTACK_KINDS = ("sign_flip", "scale", "gauss", "nan_bomb")


@dataclasses.dataclass(frozen=True)
class WaveAttack:
    """One poisoned wave: at ``(round_idx, wave)`` (both 0-based), the
    wave's summary is replaced per ``kind`` before admission — the
    mega-cohort path's first-class attacker."""
    round_idx: int
    wave: int
    kind: str
    param: float

    def __post_init__(self):
        if self.kind not in WAVE_ATTACK_KINDS:
            raise ValueError(f"unknown wave attack kind {self.kind!r}; "
                             f"available: {WAVE_ATTACK_KINDS}")
        if self.round_idx < 0 or self.wave < 0:
            raise ValueError(f"--wave_adversary round/wave indices are "
                             f"0-based and non-negative; got round="
                             f"{self.round_idx} wave={self.wave}")


def parse_wave_adversary_spec(spec: str) -> Dict[tuple, WaveAttack]:
    """``"round:wave:kind[:param],..."`` → {(round, wave): WaveAttack}.

        --wave_adversary "3:0:scale:50"        # round 3, wave 0, x50
        --wave_adversary "1:0:sign_flip,2:1:gauss:5"
    """
    out: Dict[tuple, WaveAttack] = {}
    if not spec:
        return out
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"bad --wave_adversary entry {entry!r}; expected "
                f"round:wave:kind[:param] (e.g. '3:0:scale:50')")
        try:
            round_idx, wave = int(parts[0]), int(parts[1])
        except ValueError:
            raise ValueError(f"bad --wave_adversary round/wave in "
                             f"{entry!r}") from None
        kind = parts[2].strip()
        param = float(parts[3]) if len(parts) == 4 \
            else _DEFAULT_PARAM.get(kind, 0.0)
        key = (round_idx, wave)
        if key in out:
            raise ValueError(f"--wave_adversary lists round {round_idx} "
                             f"wave {wave} twice")
        out[key] = WaveAttack(round_idx, wave, kind, param)
    return out


def poison_wave_summary(attack: WaveAttack, mean_host, global_host,
                        seed: int = 0):
    """Apply ``attack`` to a wave's summary (the weighted partial MEAN,
    params-like) relative to the round's global — the same update
    semantics as the per-silo kinds, at wave granularity.  Host numpy
    math, seeded per ``(seed, round, wave)`` so attacked runs replay
    bit-identically."""
    if attack.kind == "sign_flip":
        return _tree_map2(
            lambda g, m: (g - attack.param * (m - g)).astype(m.dtype),
            global_host, mean_host)
    if attack.kind == "scale":
        return _tree_map2(
            lambda g, m: (g + attack.param * (m - g)).astype(m.dtype),
            global_host, mean_host)
    if attack.kind == "gauss":
        rng = np.random.RandomState(
            (seed * 1_000_003 + attack.round_idx * 7919
             + attack.wave * 101) % (2 ** 32))
        return _tree_map1(
            lambda m: (m + rng.normal(0.0, attack.param, m.shape))
            .astype(m.dtype) if np.issubdtype(m.dtype, np.floating)
            else m, mean_host)
    if attack.kind == "nan_bomb":
        return _first_float_leaf_to_nan(mean_host)
    raise ValueError(  # pragma: no cover — __post_init__ validated
        f"unhandled wave attack kind {attack.kind!r}")
