"""Server-optimizer spine tests (ISSUE 18).

The seam contract, pinned:

* ``--server_opt plain`` is BIT-IDENTICAL to today's mean finalize —
  ``apply`` returns the finalized tree itself, on the replicated AND
  the sharded wire (no silent behavior change for every existing run).
* The seam's momentum/adam match the standalone optax trajectories on a
  fixed pseudo-gradient sequence (tolerance stated per test); fedac
  matches a NumPy transcription of Yuan & Ma '20 Alg. 1's server form
  and collapses to plain SGD at (alpha=1, beta=1, gamma=lr).
* Optimizer state round-trips ``state_dict``/``load_state_dict``
  bit-exactly — replicated and laid out along a PR 14 shard plan — and
  every foreign snapshot (different optimizer, different
  hyperparameters, different shard plan, sharded<->replicated) is
  refused with the named ``ServerOptMismatchError``.
* Kill -> resume with live momentum/adam/fedac state is bit-identical
  to the uncrashed run (the PR 12 recovery contract extends to the
  optimizer slots).
* The adaptive controller is a deterministic pure function of the
  health-line trace, and its state resumes mid-trajectory.
* Every incompatible flag combination fails loudly at config time.
"""

import jax
import numpy as np
import optax
import pytest

from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                             FedAvgServerActor)
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.core.stream_agg import StreamingAggregator
from fedml_tpu.robust.faultline import ActorKilled, CrashSpec, Faultline
from fedml_tpu.server_opt import (SERVER_OPT_NAMES, AdaptiveController,
                                  ServerOptConfigError,
                                  ServerOptMismatchError, ServerOptimizer)
from fedml_tpu.shard_spine import build_shard_spine
from fedml_tpu.utils.checkpoint import RoundCheckpointer
from fedml_tpu.utils.journal import RoundJournal


def _params(seed=3, shape=(4, 3)):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(*shape).astype(np.float32),
                      "bias": rng.randn(shape[-1]).astype(np.float32)}}


def _deltas(template, steps, seed=7):
    """A fixed pseudo-gradient sequence, deterministic in seed."""
    rng = np.random.RandomState(seed)
    return [jax.tree.map(
        lambda v: rng.randn(*np.shape(v)).astype(np.float32) * 0.1,
        template) for _ in range(steps)]


def _leaves_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _train_fn(silo):
    """Deterministic in (silo, round): replayed rounds reproduce the
    exact bytes (the recovery contract's silo half)."""
    def fn(params, client_idx, round_idx):
        rng = np.random.RandomState(1000 * silo + int(round_idx or 0))
        return jax.tree.map(
            lambda v: v + rng.randn(*np.shape(v)).astype(np.float32) * 0.1,
            params), 10 + silo
    return fn


def _run_stream(init, rounds, n=3, server_opt=None, ck=None, jr=None,
                fl=None, spine=None, extra_state=None):
    """One pump-mode stream federation (test_crash_recovery harness),
    with the server-optimizer seam on the wire."""
    hub = LocalHub(codec_roundtrip=True)
    agg = spine.agg if spine is not None else StreamingAggregator(
        init, method="mean", kind="params", norm_clip=1.0, seed=0,
        reservoir_k=8)
    server = FedAvgServerActor(
        hub.transport(0), init, n, n, rounds, checkpointer=ck,
        stream_agg=agg, shard_wire=spine, journal=jr, faultline=fl,
        server_opt=server_opt, extra_state=extra_state)
    silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i))
             for i in range(1, n + 1)]
    server.register_handlers()
    for s in silos:
        s.register_handlers()
    server.start()
    hub.pump()
    return server


# ---------------------------------------------------------------------------
# the seam, unit-level: each optimizer against its reference math
# ---------------------------------------------------------------------------

class TestSeamUnit:
    def test_plain_apply_returns_finalized_itself(self):
        init = _params()
        opt = ServerOptimizer("plain", init)
        finalized = _params(5)
        assert opt.apply(init, finalized, 0) is finalized

    def test_plain_apply_delta_is_exact_sgd(self):
        init = _params()
        opt = ServerOptimizer("plain", init, lr=0.5)
        delta = _deltas(init, 1)[0]
        got = opt.apply_delta(init, delta, 0)
        want = jax.tree.map(lambda w, d: w - np.float32(0.5) * d,
                            init, delta)
        assert _leaves_equal(got, want)

    def test_momentum_matches_optax(self):
        init = _params()
        opt = ServerOptimizer("momentum", init, lr=0.3, momentum=0.9)
        ref_opt = optax.sgd(0.3, momentum=0.9)
        ref_state, ref_w = ref_opt.init(init), init
        w = init
        for d in _deltas(init, 5):
            w = opt.apply_delta(w, d, 0)
            upd, ref_state = ref_opt.update(d, ref_state, ref_w)
            ref_w = optax.apply_updates(ref_w, upd)
            for a, b in zip(jax.tree.leaves(w), jax.tree.leaves(ref_w)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-6, atol=1e-7)

    def test_adam_matches_optax(self):
        init = _params()
        opt = ServerOptimizer("adam", init, lr=0.05, beta1=0.9,
                              beta2=0.999, eps=1e-8)
        ref_opt = optax.adam(0.05, b1=0.9, b2=0.999, eps=1e-8)
        ref_state, ref_w = ref_opt.init(init), init
        w = init
        for d in _deltas(init, 5):
            w = opt.apply_delta(w, d, 0)
            upd, ref_state = ref_opt.update(d, ref_state, ref_w)
            ref_w = optax.apply_updates(ref_w, upd)
            for a, b in zip(jax.tree.leaves(w), jax.tree.leaves(ref_w)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-5, atol=1e-6)

    def test_fedac_default_knobs_collapse_to_plain_sgd(self):
        """(alpha=1, beta=1, gamma=lr): x_md == x == w inductively, so
        apply() lands exactly on the finalized tree — the fedac.py
        collapse, at the seam."""
        init = _params()
        opt = ServerOptimizer("fedac", init, lr=1.0)
        w = init
        for seed in (5, 6):
            finalized = _params(seed)
            w = opt.apply(w, finalized, 0)
            for a, b in zip(jax.tree.leaves(w),
                            jax.tree.leaves(finalized)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-6, atol=1e-7)

    def test_fedac_matches_numpy_reference(self):
        init = _params()
        lr, gamma, alpha, beta = 0.4, 0.6, 2.0, 3.0
        opt = ServerOptimizer("fedac", init, lr=lr, fedac_gamma=gamma,
                              fedac_alpha=alpha, fedac_beta=beta)
        w = init
        for d in _deltas(init, 4):
            w = opt.apply_delta(w, d, 0)
        # NumPy transcription, run independently (x^0 = x^ag,0)
        w_ag = jax.tree.map(np.asarray, init)
        x = jax.tree.map(np.asarray, init)
        for d in _deltas(init, 4):
            x_md = jax.tree.map(
                lambda xi, ai: (xi / beta
                                + (1 - 1 / beta) * ai).astype(np.float32),
                x, w_ag)
            w_ag = jax.tree.map(
                lambda m, di: (m - lr * di).astype(np.float32), x_md, d)
            x = jax.tree.map(
                lambda xi, m, di: ((1 - 1 / alpha) * xi + m / alpha
                                   - gamma * di).astype(np.float32),
                x, x_md, d)
        for a, b in zip(jax.tree.leaves(w), jax.tree.leaves(w_ag)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_fedac_mu_derives_the_paper_coupling(self):
        from fedml_tpu.algorithms.fedac import fedac_coupling
        init = _params()
        opt = ServerOptimizer("fedac", init, lr=0.1, fedac_mu=0.5,
                              local_steps=4)
        gamma, alpha, beta = fedac_coupling(0.1, 0.5, 4)
        assert opt.coupling == {"gamma": gamma, "alpha": alpha,
                                "beta": beta}

    def test_fedac_refuses_invalid_coupling(self):
        with pytest.raises(ServerOptConfigError, match="alpha >= 1"):
            ServerOptimizer("fedac", _params(), lr=0.1,
                            fedac_alpha=0.5, fedac_gamma=0.1)

    def test_unknown_name_refused(self):
        with pytest.raises(ServerOptConfigError, match="unknown"):
            ServerOptimizer("sgdx", _params())


# ---------------------------------------------------------------------------
# state round-trip: bit-exact, refusal-guarded, replicated AND sharded
# ---------------------------------------------------------------------------

class TestStateRoundtrip:
    @pytest.mark.parametrize("name", ["momentum", "adam", "fedac"])
    def test_roundtrip_bit_exact_and_same_next_step(self, name):
        init = _params()
        kw = dict(lr=0.3, fedac_gamma=0.2, fedac_alpha=2.0,
                  fedac_beta=3.0)
        opt = ServerOptimizer(name, init, **kw)
        w = init
        for d in _deltas(init, 2):
            w = opt.apply_delta(w, d, 0)
        snap = opt.state_dict()
        opt2 = ServerOptimizer(name, init, **kw)
        opt2.load_state_dict(snap)
        assert _leaves_equal(opt2.state_dict(), snap)
        nxt = _deltas(init, 1, seed=11)[0]
        assert _leaves_equal(opt.apply_delta(w, nxt, 0),
                             opt2.apply_delta(w, nxt, 0))
        assert _leaves_equal(opt.state_dict(), opt2.state_dict())

    def test_cross_optimizer_snapshot_refused(self):
        init = _params()
        snap = ServerOptimizer("momentum", init).state_dict()
        with pytest.raises(ServerOptMismatchError,
                           match="--server_opt 'momentum'"):
            ServerOptimizer("adam", init).load_state_dict(snap)

    def test_hyperparameter_fingerprint_refused(self):
        init = _params()
        snap = ServerOptimizer("adam", init, lr=0.1).state_dict()
        with pytest.raises(ServerOptMismatchError, match="fingerprint"):
            ServerOptimizer("adam", init, lr=0.2).load_state_dict(snap)

    def test_sharded_roundtrip_and_layout_refusals(self):
        init = {"w": np.random.RandomState(0).randn(16, 16)
                .astype(np.float32)}
        spine = build_shard_spine(init, num_shards=2, min_split_elems=64,
                                  mesh=None)
        opt = ServerOptimizer("adam", init, lr=0.1, plan=spine.plan)
        w = init
        for d in _deltas(init, 2):
            w = opt.apply_delta(w, d, 0)
        snap = opt.state_dict()
        assert "shard_fp" in snap
        opt2 = ServerOptimizer("adam", init, lr=0.1, plan=spine.plan)
        opt2.load_state_dict(snap)
        nxt = _deltas(init, 1, seed=11)[0]
        assert _leaves_equal(opt.apply_delta(w, nxt, 0),
                             opt2.apply_delta(w, nxt, 0))
        assert _leaves_equal(opt.state_dict(), opt2.state_dict())
        # sharded snapshot into a replicated run: refused
        with pytest.raises(ServerOptMismatchError, match="replicated"):
            ServerOptimizer("adam", init, lr=0.1).load_state_dict(snap)
        # replicated snapshot into the sharded spine: refused
        rsnap = ServerOptimizer("adam", init, lr=0.1).state_dict()
        with pytest.raises(ServerOptMismatchError,
                           match="no shard-plan"):
            ServerOptimizer("adam", init, lr=0.1,
                            plan=spine.plan).load_state_dict(rsnap)


# ---------------------------------------------------------------------------
# plain parity, end-to-end: the seam's presence must not move one bit
# ---------------------------------------------------------------------------

class TestPlainParityE2E:
    def test_plain_bit_identical_on_replicated_wire(self):
        init = _params()
        ref = _run_stream(init, 3)
        got = _run_stream(init, 3,
                          server_opt=ServerOptimizer("plain", init))
        assert ref.round_idx == got.round_idx == 3
        assert _leaves_equal(ref.params, got.params)

    def test_plain_bit_identical_on_sharded_wire(self):
        init = {"w": np.random.RandomState(0).randn(16, 16)
                .astype(np.float32)}
        ref = _run_stream(
            init, 3, spine=build_shard_spine(init, num_shards=2,
                                             min_split_elems=64,
                                             mesh=None))
        got = _run_stream(
            init, 3, spine=build_shard_spine(init, num_shards=2,
                                             min_split_elems=64,
                                             mesh=None),
            server_opt=ServerOptimizer("plain", init))
        assert _leaves_equal(ref.params, got.params)

    def test_non_plain_actually_moves_the_global(self):
        init = _params()
        ref = _run_stream(init, 3)
        got = _run_stream(init, 3,
                          server_opt=ServerOptimizer("adam", init,
                                                     lr=0.1))
        assert not _leaves_equal(ref.params, got.params)


# ---------------------------------------------------------------------------
# crash recovery: optimizer slots ride the PR 12 kill -> resume contract
# ---------------------------------------------------------------------------

class TestCrashResume:
    @pytest.mark.parametrize("name", ["momentum", "adam", "fedac"])
    def test_kill_at_checkpoint_write_resumes_bit_identical(
            self, tmp_path, name):
        """Kill mid-checkpoint-write in round 1 of 3 with live optimizer
        state: the resumed run must land bit-identical to the uncrashed
        run — params AND every optimizer slot."""
        init = _params()
        kw = dict(lr=0.3, fedac_gamma=0.2, fedac_alpha=2.0,
                  fedac_beta=3.0)
        opt_ref = ServerOptimizer(name, init, **kw)
        ref = _run_stream(init, 3, server_opt=opt_ref)
        assert ref.round_idx == 3

        opt1 = ServerOptimizer(name, init, **kw)
        fl = Faultline(crashes=[CrashSpec(point="mid_checkpoint_write",
                                          hit=1, round_idx=1)])
        with pytest.raises(ActorKilled):
            _run_stream(
                init, 3, server_opt=opt1,
                ck=RoundCheckpointer(str(tmp_path / "ck"), save_every=1),
                jr=RoundJournal(str(tmp_path / "j"), snapshot_every=1),
                fl=fl,
                extra_state=(lambda: {"srv_opt": opt1.state_dict()},
                             lambda t: opt1.load_state_dict(
                                 t["srv_opt"])))

        opt2 = ServerOptimizer(name, init, **kw)
        resumed = _run_stream(
            init, 3, server_opt=opt2,
            ck=RoundCheckpointer(str(tmp_path / "ck"), save_every=1),
            jr=RoundJournal(str(tmp_path / "j"), snapshot_every=1),
            extra_state=(lambda: {"srv_opt": opt2.state_dict()},
                         lambda t: opt2.load_state_dict(t["srv_opt"])))
        assert resumed.round_idx == 3
        assert _leaves_equal(resumed.params, ref.params)
        assert _leaves_equal(opt2.state_dict(), opt_ref.state_dict())


# ---------------------------------------------------------------------------
# the adaptive controller: deterministic policy, resumable state
# ---------------------------------------------------------------------------

def _line(misaligned=False, blowup=False, starved=False, sev=1.5):
    def alarm(fired):
        return {"ok": not fired, "value": sev if fired else 0.1,
                "threshold": 1.0}
    return {"alarms": {"alignment_collapse": alarm(misaligned),
                       "norm_variance_blowup": alarm(blowup),
                       "participation_starvation": alarm(starved)}}


_TRACE = [_line(), _line(misaligned=True), _line(blowup=True), _line(),
          _line(), _line(), _line(starved=True), _line(), _line(),
          _line(misaligned=True, sev=2.5), _line(), _line()]


class TestController:
    def _mk(self):
        return AdaptiveController(cohort=8, epochs=3, wave_size=4,
                                  min_cohort=2, max_cohort=16,
                                  patience=2)

    def test_same_trace_same_decisions(self):
        a, b = self._mk(), self._mk()
        da = [a.decide(i, l).as_ledger() for i, l in enumerate(_TRACE)]
        db = [b.decide(i, l).as_ledger() for i, l in enumerate(_TRACE)]
        assert da == db
        # the trace actually exercises the policy: growth, cut, decay
        assert any("cohort+" in r for d in da for r in d["reasons"])
        assert any("epochs->" in r for d in da for r in d["reasons"])
        assert any(r.startswith("calm:") for d in da for r in d["reasons"])

    def test_resume_continues_the_same_trajectory(self):
        full, half = self._mk(), self._mk()
        want = [full.decide(i, l).as_ledger()
                for i, l in enumerate(_TRACE)]
        got = [half.decide(i, l).as_ledger()
               for i, l in enumerate(_TRACE[:6])]
        snap = half.state_dict()
        resumed = self._mk()
        resumed.load_state_dict(snap)
        got += [resumed.decide(i + 6, l).as_ledger()
                for i, l in enumerate(_TRACE[6:])]
        assert got == want

    def test_cohort_never_drops_below_baseline(self):
        c = self._mk()
        for i, l in enumerate(_TRACE * 3):
            d = c.decide(i, l)
            assert d.cohort >= 8

    def test_epoch_cuts_are_named_pinned_on_compiled_engines(self):
        c = self._mk()
        c.decide(0, _line(blowup=True))
        d = c.decide(1, _line(blowup=True))
        assert any("epochs" in r and "[pinned:static-shape]" in r
                   for r in d.reasons), d.reasons

    def test_cohort_growth_clamps_at_max_and_names_the_clamp(self):
        c = AdaptiveController(cohort=8, epochs=1, max_cohort=8)
        d = c.decide(0, _line(misaligned=True))
        assert d.cohort == 8
        assert any("clamped" in r for r in d.reasons), d.reasons

    def test_missing_health_line_holds(self):
        c = self._mk()
        d = c.decide(0, None)
        assert d.as_ledger()["reasons"] == ["hold"]
        assert d.cohort == 8 and d.epochs == 3


# ---------------------------------------------------------------------------
# config gates: every bad combination refuses at config time, by name
# ---------------------------------------------------------------------------

class TestConfigGates:
    def _cfg(self, **kw):
        from fedml_tpu.experiments.config import ExperimentConfig
        return ExperimentConfig(**kw)

    @pytest.mark.parametrize("kw,match", [
        (dict(server_opt="sgdx"), "unknown --server_opt"),
        (dict(server_opt="adam", algo="fedopt"),
         "applies to --algo cross_silo"),
        (dict(server_opt="adam", algo="cross_silo", robust_agg="median"),
         "order-statistic finalize"),
        (dict(server_opt="adam", algo="cross_silo", agg_mode="stream",
              secagg="pairwise"), "masked-sum protocol"),
        (dict(server_opt="adam", algo="cross_device",
              local_alg="fednova"), "fednova"),
        (dict(adaptive=True, algo="cross_silo"), "requires --health"),
        (dict(adaptive=True, health=True, algo="async_fl"),
         "no round cohort to pace"),
        (dict(adapt_min_cohort=0), "--adapt_min_cohort must be"),
        (dict(adapt_patience=0), "--adapt_patience must be"),
    ])
    def test_bad_combo_fails_loudly(self, kw, match):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ServerOptConfigError, match=match):
            main(self._cfg(**kw))

    def test_actor_gate_secagg(self):
        from fedml_tpu.secure.protocol import (SecAggServer,
                                               masked_template)
        from fedml_tpu.robust import AdmissionPipeline
        init = _params()
        hub = LocalHub()
        with pytest.raises(ValueError, match="masked-sum"):
            FedAvgServerActor(
                hub.transport(0), init, 2, 2, 1,
                admission=AdmissionPipeline(masked_template(init),
                                            kind="masked"),
                secagg=SecAggServer(threshold=0, clip=64.0,
                                    weight_cap=10.0),
                server_opt=ServerOptimizer("adam", init))

    def test_actor_gate_controller_requires_health(self):
        hub = LocalHub()
        with pytest.raises(ValueError, match="--health"):
            FedAvgServerActor(
                hub.transport(0), _params(), 2, 2, 1,
                stream_agg=StreamingAggregator(_params(), method="mean",
                                               kind="params"),
                controller=AdaptiveController(cohort=2))

    def test_journal_mode_names_the_optimizer(self, tmp_path):
        """A journal written under a non-plain seam must refuse replay
        into a plain run: the optimizer is part of the round mode."""
        init = _params()
        jr = RoundJournal(str(tmp_path / "j"), snapshot_every=1)
        opt = ServerOptimizer("adam", init, lr=0.1)
        server = _run_stream(init, 2, server_opt=opt, jr=jr)
        assert server.round_idx == 2
        assert "srvopt=adam" in server._journal_mode()
