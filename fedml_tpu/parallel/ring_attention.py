"""Ring attention: sequence/context parallelism over a device mesh.

The reference has no long-context machinery at all (SURVEY.md §5.7 — its
largest NLP model is a 2-layer LSTM, fedml_api/model/nlp/rnn.py:18-22), so
sequences are capped by one device's memory.  This module removes that cap
the TPU way: the sequence axis is sharded across a ``sequence`` mesh axis,
each device holds a block of queries, and key/value blocks rotate around the
ring via `lax.ppermute` (one ICI hop per step) while a flash-attention-style
online softmax accumulates exact results — attention over a sequence of
length T costs each device O(T/D) memory instead of O(T), with compute and
communication overlapped by XLA across ring steps.

Exactness: the online-softmax recurrence (running max m, normalizer l,
unnormalized accumulator o) reproduces full softmax attention bitwise up to
float reassociation; `tests/test_ring_attention.py` checks parity against
the dense path on an 8-device mesh.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _online_softmax_block(q, k, v, q_pos, kv_pos, m, l, o, causal):
    """Accumulate one key/value block into the (m, l, o) running state.

    q [B, Tq, H, d]; k/v [B, Tk, H, d]; positions are GLOBAL token indices,
    so causal masking stays correct no matter which ring step delivered the
    block.  Scores and accumulators are f32 (softmax is range-sensitive);
    q/k/v may be bf16.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
        scores = jnp.where(mask, scores, -1e30)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    if causal:
        # a fully-masked block has scores == m_new == -1e30, where the exp
        # above degenerates to 1 — zero those entries explicitly
        p = p * mask
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m_new, l, o


def ring_attention(q, k, v, q_pos, kv_pos, axis_name: str,
                   causal: bool = True) -> jax.Array:
    """Exact attention with the sequence axis sharded over ``axis_name``.

    Must run inside `shard_map`.  Each device holds its local query block
    ``q [B, Tq_local, H, d]`` and initial key/value block; over D ring steps
    the k/v blocks (and their global position vector) rotate one neighbor
    forward via `ppermute`, and every device folds each visiting block into
    its online-softmax state.  Returns [B, Tq_local, H, d].

    The causal variant still visits every block (a fully-future block
    contributes zeros) — with D devices that wastes ~half the FLOPs vs a
    skew-scheduled ring, but keeps one program for causal and full attention;
    at FL model sizes attention is not the dominant cost.
    """
    n = jax.lax.psum(1, axis_name)
    B, Tq, H, d = q.shape
    m = jnp.full((B, H, Tq), -1e30, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)
    o = jnp.zeros((B, H, Tq, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    for s in range(n):
        m, l, o = _online_softmax_block(q, k, v, q_pos, kv_pos, m, l, o,
                                        causal)
        if s != n - 1:
            k, v, kv_pos = jax.lax.ppermute((k, v, kv_pos), axis_name, perm)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3)


def full_attention(q, k, v, q_pos, kv_pos, causal: bool = True) -> jax.Array:
    """Single-device dense path: the same online-softmax math with one block
    covering the whole sequence, so the sharded and dense paths can never
    drift numerically."""
    B, Tq, H, d = q.shape
    m = jnp.full((B, H, Tq), -1e30, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)
    o = jnp.zeros((B, H, Tq, d), jnp.float32)
    m, l, o = _online_softmax_block(q, k, v, q_pos, kv_pos, m, l, o, causal)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3)


def blockwise_attention(q, k, v, q_pos, kv_pos, block_size: int,
                        causal: bool = True) -> jax.Array:
    """Single-device flash-style attention: `lax.scan` over key/value blocks
    with the same online-softmax state as the ring — O(T·block) peak memory
    for the scores instead of the dense path's O(T²), so one chip can run
    sequences far past the [B, H, T, T] materialization limit.  Exact (same
    accumulation as `full_attention`); the backward pass rematerializes each
    block's scores through the scan's VJP.

    ``block_size`` must divide the key length.
    """
    B, Tk, H, d = k.shape
    if Tk % block_size:
        raise ValueError(f"block_size {block_size} must divide key length "
                         f"{Tk}")
    Tq = q.shape[1]
    n_blocks = Tk // block_size
    k_b = k.reshape(B, n_blocks, block_size, H, d).transpose(1, 0, 2, 3, 4)
    v_b = v.reshape(B, n_blocks, block_size, H, d).transpose(1, 0, 2, 3, 4)
    pos_b = kv_pos.reshape(n_blocks, block_size)

    m0 = jnp.full((B, H, Tq), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    o0 = jnp.zeros((B, H, Tq, d), jnp.float32)

    def scan_body(carry, blk):
        m, l, o = carry
        kb, vb, pb = blk
        m, l, o = _online_softmax_block(q, kb, vb, q_pos, pb, m, l, o,
                                        causal)
        return (m, l, o), None

    (m, l, o), _ = jax.lax.scan(scan_body, (m0, l0, o0), (k_b, v_b, pos_b))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3)


def make_sequence_parallel_apply(model, mesh: Mesh,
                                 axis_name: str = "sequence"):
    """Jit ``model.apply`` with activations sharded on the sequence axis.

    ``model`` is a TransformerLM (or any module taking ``positions`` and
    ``ring_axis``).  Params replicate; the [B, T] token array shards its T
    axis over ``axis_name``; each device computes its block's global
    positions from its mesh coordinate, and attention runs as a ring.
    Output logits come back sharded the same way ([B, T, V] on T).
    """

    def _apply(params, x):
        t_local = x.shape[1]
        idx = jax.lax.axis_index(axis_name)
        positions = idx * t_local + jnp.arange(t_local)
        return model.apply({"params": params}, x, positions=positions,
                           ring_axis=axis_name)

    from fedml_tpu.parallel.cohort import compat_shard_map
    fn = compat_shard_map(
        _apply, mesh=mesh,
        in_specs=(P(), P(None, axis_name)),
        out_specs=P(None, axis_name))
    return jax.jit(fn)


def make_sequence_mesh(n_devices: Optional[int] = None,
                       axis_name: str = "sequence") -> Mesh:
    import numpy as np
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis_name,))
