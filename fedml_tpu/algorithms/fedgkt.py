"""FedGKT — group knowledge transfer (client CNN ⇄ server ResNet).

Reference choreography (``fedml_api/distributed/fedgkt/``):

1. each client trains its small CNN for ``epochs_client`` epochs with
   CE + α·KL(client ∥ server-logits) when server logits exist
   (GKTClientTrainer.py:67-78);
2. the client then runs feature extraction over its WHOLE dataset and ships
   (feature maps, client logits, labels) to the server
   (GKTClientTrainer.py:83-120);
3. the server trains its large net on the received features for
   ``epochs_server`` epochs with CE + α·KL(server ∥ client-logits)
   (GKTServerTrainer.train_and_eval via :101-130), then returns per-client
   server logits for the next round's distillation.

KL term parity (fedgkt/utils.py KL_Loss:75-89):
``T² · KL(softmax(teacher/T) ∥ log_softmax(student/T))`` with the teacher
softmax floored at 1e-7.

TPU-native design: client training is ONE vmap'd jit over the stacked client
cohort (every client's small CNN trains in parallel on the MXU, instead of
N sequential processes); feature extraction is a second vmap'd jit; the
server phase is a standard scanned SGD over the pooled feature dataset.
No per-batch wire: features move host<->device once per round.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

Pytree = Any


@dataclasses.dataclass
class FedGKTConfig:
    rounds: int = 10
    epochs_client: int = 1
    epochs_server: int = 1
    lr_client: float = 0.01
    lr_server: float = 0.01
    temperature: float = 3.0     # --temperature default (main_fedgkt)
    alpha: float = 1.0           # KD weight (GKTClientTrainer.py:78)
    seed: int = 0

    def __post_init__(self):
        if self.epochs_client < 1 or self.epochs_server < 1:
            raise ValueError("FedGKT requires epochs_client >= 1 and "
                             "epochs_server >= 1 (both phases must run)")


def kd_kl_loss(student_logits: jnp.ndarray, teacher_logits: jnp.ndarray,
               temperature: float) -> jnp.ndarray:
    """T²-scaled distillation KL, teacher floored at 1e-7 per batch-mean
    (fedgkt/utils.py:75-89)."""
    T = temperature
    log_p = jax.nn.log_softmax(student_logits / T, axis=-1)
    q = jax.nn.softmax(teacher_logits / T, axis=-1) + 1e-7
    return T * T * jnp.sum(q * (jnp.log(q) - log_p), axis=-1)


class FedGKT:
    """client_model: flax module -> (logits, feature maps);
    server_model: flax module feature maps -> logits."""

    def __init__(self, client_model, server_model, cfg: FedGKTConfig):
        self.client_model = client_model
        self.server_model = server_model
        self.cfg = cfg
        self.client_opt = optax.sgd(cfg.lr_client, momentum=0.9)
        self.server_opt = optax.sgd(cfg.lr_server, momentum=0.9)
        self._build()

    def _build(self):
        cfg = self.cfg

        def client_loss(cp, batch, server_logits, use_kd):
            logits, _ = self.client_model.apply({"params": cp}, batch["x"])
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"])
            kd = kd_kl_loss(logits, server_logits, cfg.temperature)
            per_row = ce + cfg.alpha * use_kd * kd
            m = batch["mask"]
            return jnp.sum(per_row * m) / jnp.maximum(jnp.sum(m), 1.0)

        def client_epoch(cp, opt_state, data, server_logits, use_kd):
            """scan over one client's batches; server_logits [S, B, C]."""
            def step(carry, xs):
                cp, opt_state = carry
                batch, s_logits = xs
                loss, g = jax.value_and_grad(client_loss)(
                    cp, batch, s_logits, use_kd)
                updates, opt_state = self.client_opt.update(g, opt_state, cp)
                return (optax.apply_updates(cp, updates), opt_state), loss

            (cp, opt_state), losses = jax.lax.scan(
                step, (cp, opt_state), (data, server_logits))
            return cp, opt_state, jnp.mean(losses)

        def client_round(cp, opt_state, data, server_logits, use_kd):
            for _ in range(cfg.epochs_client):
                cp, opt_state, loss = client_epoch(
                    cp, opt_state, data, server_logits, use_kd)
            # phase 2: extract features + logits over the whole local set
            logits, feats = self.client_model.apply(
                {"params": cp},
                data["x"].reshape((-1,) + data["x"].shape[2:]))
            return cp, opt_state, loss, feats, logits

        # vmap across the stacked client axis: every client trains at once
        self._clients_round = jax.jit(jax.vmap(
            client_round, in_axes=(0, 0, 0, 0, None)))

        def server_loss(sp, feats, labels, client_logits, mask):
            logits = self.server_model.apply({"params": sp}, feats)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            kd = kd_kl_loss(logits, client_logits, cfg.temperature)
            per_row = ce + cfg.alpha * kd
            return jnp.sum(per_row * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        def server_epoch(sp, opt_state, feats, labels, client_logits, mask):
            def step(carry, xs):
                sp, opt_state = carry
                f, y, cl, m = xs
                loss, g = jax.value_and_grad(server_loss)(sp, f, y, cl, m)
                updates, opt_state = self.server_opt.update(g, opt_state, sp)
                return (optax.apply_updates(sp, updates), opt_state), loss

            (sp, opt_state), losses = jax.lax.scan(
                step, (sp, opt_state), (feats, labels, client_logits, mask))
            return sp, opt_state, jnp.mean(losses)

        self._server_epoch = jax.jit(server_epoch)

        def server_infer(sp, feats):
            return self.server_model.apply({"params": sp}, feats)

        self._server_infer = jax.jit(server_infer)

    def init(self, rng: jax.Array, cohort: Dict[str, jnp.ndarray]
             ) -> Tuple[Pytree, Pytree, Pytree, Pytree]:
        """cohort: stacked {"x": [C, S, B, ...], "y", "mask"}.  Per-client
        client params (each client keeps its own small net, GKT never
        averages them) + one server net."""
        C = cohort["x"].shape[0]
        rngs = jax.random.split(rng, C + 1)
        sample_x = cohort["x"][0, 0]
        cp0 = self.client_model.init(rngs[0], sample_x)["params"]
        client_params = jax.vmap(
            lambda r: self.client_model.init(r, sample_x)["params"]
        )(rngs[:C])
        _, feats = self.client_model.apply({"params": cp0}, sample_x)
        server_params = self.server_model.init(rngs[C], feats)["params"]
        return (client_params,
                jax.vmap(self.client_opt.init)(client_params),
                server_params, self.server_opt.init(server_params))

    def run(self, cohort: Dict[str, jnp.ndarray],
            rng: Optional[jax.Array] = None) -> Dict[str, Any]:
        cfg = self.cfg
        rng = rng if rng is not None else jax.random.key(cfg.seed)
        client_params, client_opt, server_params, server_opt = self.init(
            rng, cohort)
        C, S, B = cohort["x"].shape[:3]
        num_classes = self.client_model.num_classes
        server_logits = jnp.zeros((C, S, B, num_classes))
        history: List[Dict[str, float]] = []

        for rnd in range(cfg.rounds):
            use_kd = jnp.asarray(0.0 if rnd == 0 else 1.0)
            client_params, client_opt, c_loss, feats, c_logits = \
                self._clients_round(client_params, client_opt,
                                    {k: cohort[k] for k in ("x", "y", "mask")},
                                    server_logits, use_kd)
            # pool all clients' extracted features into one server dataset
            fs = feats.reshape((C * S, B) + feats.shape[2:])
            ys = cohort["y"].reshape(C * S, B)
            cls = c_logits.reshape(C * S, B, num_classes)
            ms = cohort["mask"].reshape(C * S, B)
            for _ in range(cfg.epochs_server):
                server_params, server_opt, s_loss = self._server_epoch(
                    server_params, server_opt, fs, ys, cls, ms)
            # distill back: per-client server logits for next round
            s_logits = self._server_infer(
                server_params, fs.reshape((-1,) + fs.shape[2:]))
            server_logits = s_logits.reshape(C, S, B, num_classes)
            history.append({"round": rnd,
                            "client_loss": float(jnp.mean(c_loss)),
                            "server_loss": float(s_loss)})
        return {"client_params": client_params,
                "server_params": server_params, "history": history}

    def evaluate(self, client_params, server_params,
                 cohort: Dict[str, jnp.ndarray]) -> Dict[str, float]:
        """End-to-end accuracy: client features -> server logits (the
        deployed GKT pipeline; GKTServerTrainer eval path)."""
        @jax.jit
        def fwd(cp, sp, x):
            _, feats = self.client_model.apply({"params": cp}, x)
            return self.server_model.apply({"params": sp}, feats)

        correct, total = 0.0, 0.0
        C, S = cohort["x"].shape[:2]
        for c in range(C):
            cp = jax.tree.map(lambda v: v[c], client_params)
            for s in range(S):
                logits = fwd(cp, server_params, cohort["x"][c, s])
                pred = jnp.argmax(logits, -1)
                m = cohort["mask"][c, s]
                correct += float(jnp.sum((pred == cohort["y"][c, s]) * m))
                total += float(jnp.sum(m))
        return {"acc": correct / max(total, 1.0)}
