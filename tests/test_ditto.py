"""Ditto personalized FL (algorithms/ditto.py).

Pins the paper's structure (Li et al. 2021, arXiv:2012.04221): the global
stream is EXACTLY FedAvg; personalized models decouple at λ=0, pin to the
globals as λ grows, and win under concept shift — the regime
personalization exists for (same input ↦ different labels across clients,
which no single global model can fit)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms import (Ditto, DittoConfig, FedAvg, FedAvgConfig)
from fedml_tpu.algorithms.ditto import make_ditto_local
from fedml_tpu.data.stacking import FederatedData, stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _concept_shift_clients(n_clients=4, dim=8, per=32, seed=0):
    """Same marginal x, per-client label flips: client c labels by
    sign(w·x) XOR (c odd) — global accuracy is capped near 50%, while each
    personalized model can fit its own concept."""
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    xs, ys = [], []
    for c in range(n_clients):
        x = rng.randn(per, dim).astype(np.float32)
        y = (x @ w > 0).astype(np.int32)
        if c % 2:
            y = 1 - y
        xs.append(x)
        ys.append(y)
    return xs, ys


def _fed(xs, ys, batch=8, classes=2):
    train = stack_client_data(xs, ys, batch)
    return FederatedData(client_num=len(xs), class_num=classes,
                         train=train, test=train)


def _wl(dim=8, classes=2):
    return ClassificationWorkload(LogisticRegression(dim, classes),
                                  num_classes=classes, grad_clip_norm=None)


def _cfg_kwargs(rounds=3, clients=4):
    return dict(comm_round=rounds, client_num_per_round=clients, epochs=1,
                batch_size=8, lr=0.1, frequency_of_the_test=100, seed=0)


def test_global_stream_is_bit_identical_to_fedavg():
    xs, ys = _concept_shift_clients()
    w_fed = FedAvg(_wl(), _fed(xs, ys),
                   FedAvgConfig(**_cfg_kwargs())).run()
    w_ditto = Ditto(_wl(), _fed(xs, ys),
                    DittoConfig(ditto_lambda=0.3, **_cfg_kwargs())).run()
    for a, b in zip(jax.tree.leaves(w_fed), jax.tree.leaves(w_ditto)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lambda_zero_decouples_into_pure_local_training():
    """λ=0: v_i is plain local SGD on client i's shard, starting from the
    round-0 globals, untouched by aggregation — replay it directly through
    the module's own local solver."""
    xs, ys = _concept_shift_clients(n_clients=3)
    data = _fed(xs, ys)
    wl = _wl()
    cfg = DittoConfig(ditto_lambda=0.0, **_cfg_kwargs(rounds=2, clients=3))
    algo = Ditto(wl, data, cfg)
    rng = jax.random.key(cfg.seed)
    rng, init_rng = jax.random.split(rng)
    w0 = wl.init(init_rng, jax.tree.map(
        lambda v: v[0, 0], {k: data.train[k] for k in ("x", "y", "mask")}))
    algo.run(params=w0, rng=rng)

    local = make_ditto_local(wl, cfg.lr, cfg.epochs, 0.0)
    batches = {k: data.train[k] for k in ("x", "y", "mask")}
    for c in range(3):
        v = w0
        run_rng = rng
        for r in range(2):
            run_rng, round_rng = jax.random.split(run_rng)
            p_rng = jax.random.fold_in(round_rng, 0x44495454)
            v = local(v, v,  # w_ref unused at λ=0
                      jax.tree.map(lambda x: jnp.asarray(x[c]), batches),
                      jax.random.fold_in(p_rng, c))
        got = jax.tree.map(lambda t: np.asarray(t[c]), algo.v_locals)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(v)):
            np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6)


def test_large_lambda_pins_personal_to_global():
    xs, ys = _concept_shift_clients()
    dists = {}
    for lam in (0.0, 10.0):
        algo = Ditto(_wl(), _fed(xs, ys),
                     DittoConfig(ditto_lambda=lam, **_cfg_kwargs(rounds=4)))
        w = algo.run()
        d = 0.0
        for vw, gw in zip(jax.tree.leaves(algo.v_locals),
                          jax.tree.leaves(w)):
            d += float(jnp.sum((vw - gw[None]) ** 2))
        dists[lam] = d
    assert dists[10.0] < 0.05 * dists[0.0]


def test_personalization_beats_global_under_concept_shift():
    xs, ys = _concept_shift_clients(n_clients=4, per=48)
    algo = Ditto(_wl(), _fed(xs, ys),
                 DittoConfig(ditto_lambda=0.01, personal_epochs=4,
                             **_cfg_kwargs(rounds=12)))
    params = algo.run()
    out = algo.evaluate_global(params)
    assert out["personal_test_acc"] > 0.9
    assert out["test_acc"] < 0.75  # the global model cannot fit both concepts
    assert out["personal_test_acc"] > out["test_acc"] + 0.2


def test_unsampled_clients_keep_their_personal_state():
    xs, ys = _concept_shift_clients(n_clients=6)
    algo = Ditto(_wl(), _fed(xs, ys),
                 DittoConfig(ditto_lambda=0.1,
                             **_cfg_kwargs(rounds=1, clients=2)))
    algo.run()
    from fedml_tpu.core.sampling import sample_clients
    sampled = set(sample_clients(0, 6, 2).tolist())
    # v was lazily initialized to the round-start globals; unsampled
    # clients must still hold exactly that broadcast value
    init_like = {c for c in range(6) if c not in sampled}
    leaves = jax.tree.leaves(algo.v_locals)
    for c in init_like:
        for c2 in init_like:
            for leaf in leaves:
                np.testing.assert_array_equal(np.asarray(leaf[c]),
                                              np.asarray(leaf[c2]))
    # sampled clients moved away from the shared init
    ref = init_like.pop()
    moved = any(
        not np.array_equal(np.asarray(leaf[c]), np.asarray(leaf[ref]))
        for c in sampled for leaf in leaves)
    assert moved


def test_kill_and_resume_bit_identical(tmp_path):
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    xs, ys = _concept_shift_clients()
    kw = _cfg_kwargs(rounds=4)

    straight = Ditto(_wl(), _fed(xs, ys), DittoConfig(ditto_lambda=0.2, **kw))
    w_straight = straight.run()

    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    first = Ditto(_wl(), _fed(xs, ys), DittoConfig(
        ditto_lambda=0.2, **{**kw, "comm_round": 2}))
    first.run(checkpointer=ck)
    resumed = Ditto(_wl(), _fed(xs, ys), DittoConfig(ditto_lambda=0.2, **kw))
    w_resumed = resumed.run(
        checkpointer=RoundCheckpointer(str(tmp_path / "ck"), save_every=1))

    for a, b in zip(jax.tree.leaves(w_straight), jax.tree.leaves(w_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(straight.v_locals),
                    jax.tree.leaves(resumed.v_locals)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_rejects_stateful():
    xs, ys = _concept_shift_clients()

    class _Stateful:
        stateful = True
    with pytest.raises(ValueError, match="stateful"):
        Ditto(_Stateful(), _fed(xs, ys), DittoConfig(**_cfg_kwargs()))


def test_mesh_sharded_ditto_equals_single_chip():
    """Mesh runs (global stream on FedAvg's sharded cohort step, personal
    pass as a pure shard_map with GLOBAL-slot rng folding) must match
    single-chip to float tolerance — global params AND personalized
    state — including a padded cohort (second case: 4 live clients in 8
    slots over 4 devices)."""
    from fedml_tpu.parallel.mesh import make_mesh
    for n_clients, m, axis in ((4, 4, 4), (4, 8, 4)):
        xs, ys = _concept_shift_clients(n_clients=n_clients)
        cfg = dict(ditto_lambda=0.2, comm_round=2, client_num_per_round=m,
                   epochs=2, batch_size=8, lr=0.1,
                   frequency_of_the_test=100)
        single = Ditto(_wl(), _fed(xs, ys), DittoConfig(**cfg))
        meshed = Ditto(_wl(), _fed(xs, ys), DittoConfig(**cfg),
                       mesh=make_mesh(client_axis=axis,
                                      devices=jax.devices()[:axis]))
        out_s = single.run(rng=jax.random.key(0))
        out_m = meshed.run(rng=jax.random.key(0))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6), out_s, out_m)
        for a, b in zip(jax.tree.leaves(single.v_locals),
                        jax.tree.leaves(meshed.v_locals)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


def test_personalized_eval_chunking_is_exact():
    """eval_chunk_clients chunking must not change personalized metrics
    (zero-padded rows carry zero masks — the shared convention)."""
    xs, ys = _concept_shift_clients(n_clients=5)
    runs = {}
    for chunk in (0, 2):
        algo = Ditto(_wl(), _fed(xs, ys),
                     DittoConfig(ditto_lambda=0.1, eval_chunk_clients=chunk,
                                 **_cfg_kwargs(rounds=2, clients=5)))
        algo.run()
        runs[chunk] = algo.evaluate_personalized()
    assert runs[0].keys() == runs[2].keys()
    for k in runs[0]:
        np.testing.assert_allclose(runs[0][k], runs[2][k], rtol=1e-6)


def test_personalized_eval_never_pads_above_corpus():
    """The DEFAULT chunk (1024) on a small run must not stack 1024
    zero-padded copies of the params per eval — chunk is capped at the
    split's client count (evaluate_global's `n_clients > chunk` rule)."""
    xs, ys = _concept_shift_clients(n_clients=3)
    algo = Ditto(_wl(), _fed(xs, ys),
                 DittoConfig(ditto_lambda=0.1,
                             **_cfg_kwargs(rounds=1, clients=3)))
    algo.run()
    seen = []
    orig = algo._personal_eval

    def spy(vs, data):
        seen.append(jax.tree.leaves(vs)[0].shape[0])
        return orig(vs, data)

    algo._personal_eval = spy
    metrics = algo.evaluate_personalized()
    assert metrics and seen and max(seen) == 3


def test_stacked_state_is_host_resident_at_scale():
    """The full [N, ...] personalized state must be HOST numpy, never a
    device array — at stackoverflow scale (342k clients) HBM cannot hold
    N model copies; only the cohort's rows ride to the device per round
    (the stacked-state convention, fedavg.py)."""
    n = 20_000
    rng = np.random.RandomState(0)
    xs = [rng.randn(2, 8).astype(np.float32) for _ in range(n)]
    ys = [rng.randint(0, 2, 2).astype(np.int32) for _ in range(n)]
    algo = Ditto(_wl(), _fed(xs, ys, batch=2),
                 DittoConfig(ditto_lambda=0.1, comm_round=2,
                             client_num_per_round=8, epochs=1, batch_size=2,
                             lr=0.1, frequency_of_the_test=100,
                             eval_chunk_clients=512))
    algo.run()
    for leaf in jax.tree.leaves(algo.v_locals):
        assert isinstance(leaf, np.ndarray), type(leaf)
    assert jax.tree.leaves(algo.v_locals)[0].shape[0] == n


def test_async_save_is_immune_to_post_save_mutation(tmp_path):
    """THE async-save contract the stacked-state algorithms rely on:
    mutating a host numpy buffer IN PLACE right after save() returns (what
    scatter_client_rows does every round) must never change what the
    checkpoint restores.  Today orbax copies at enqueue AND
    RoundCheckpointer snapshots numpy leaves (defense-in-depth,
    checkpoint.py:save); this pins the observable contract against either
    layer changing."""
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1,
                           async_save=True)
    state = {"buf": np.ones((64, 8), np.float32), "round": 0}
    ck.save(0, state)
    state["buf"][:] = 999.0  # next round's in-place scatter, simulated
    ck.flush()
    restored = ck.restore(0, like={"buf": np.zeros((64, 8), np.float32),
                                   "round": 0})
    np.testing.assert_array_equal(np.asarray(restored["buf"]),
                                  np.ones((64, 8), np.float32))
