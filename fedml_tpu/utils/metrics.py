"""Observability: per-round metrics sink + profiler hook.

The reference logs per-round Train/Test acc+loss to wandb on rank 0
(``fedml_api/distributed/fedavg/FedAVGAggregator.py:136-162``) and its CI
asserts on the exported ``wandb-summary.json``
(``CI-script-fedavg.sh:43-48``).  The TPU-native equivalent is dependency-
free and machine-readable:

* ``metrics.jsonl`` — one JSON object per ``log()`` call (the wandb event
  stream);
* ``summary.json`` — last value per key (the wandb summary file the CI
  reads), rewritten on ``close()``;
* optional stdout mirroring through stdlib logging.

``profiler_trace(dir)`` wraps ``jax.profiler.trace`` so any run can capture
an XLA trace with one flag (SURVEY.md §5.1 — the reference has no profiling
at all; coarse wall-clock prints only, FedAVGAggregator.py:59,85-86).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Any, Dict, Optional

# module-level with a guarded fallback: _jsonable runs on EVERY logged
# event, and a per-call ``import numpy`` pays the sys.modules lookup on
# each scalar coerced
try:
    import numpy as _np
except ImportError:  # pragma: no cover — numpy is a hard dep in practice
    _np = None

logger = logging.getLogger(__name__)


def _jsonable(v: Any) -> Any:
    """Best-effort scalar coercion (jax/numpy scalars -> python floats)."""
    try:
        if _np is not None and isinstance(v, _np.generic):
            return v.item()
        if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
            return v.item()
    except Exception:
        pass
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


class MetricsSink:
    """wandb-style run logger: ``log(dict, step=...)`` appends an event,
    ``summary`` holds the last value per key, ``close()`` persists
    ``summary.json``.

    ``run_dir=None`` keeps everything in memory (hermetic tests); the event
    stream is then available as ``sink.events``.

    ``summary.json`` is written ATOMICALLY (tmp + ``os.replace``) and
    flushed every ``flush_summary_every`` ``log()`` calls, not only on
    ``close()`` — a run that crashes mid-federation (the crash-recovery
    path resumes it) leaves a readable recent summary beside the jsonl
    stream instead of nothing, and a crash mid-write can never leave a
    torn file.
    """

    def __init__(self, run_dir: Optional[str] = None, stdout: bool = False,
                 name: str = "run", flush_summary_every: int = 25):
        self.run_dir = run_dir
        self.stdout = stdout
        self.name = name
        self.flush_summary_every = max(int(flush_summary_every), 1)
        self.summary: Dict[str, Any] = {}
        self.events = []
        self._t0 = time.time()
        self._fh = None
        self._since_flush = 0
        if run_dir is not None:
            os.makedirs(run_dir, exist_ok=True)
            self._fh = open(os.path.join(run_dir, "metrics.jsonl"), "a",
                            buffering=1)

    def log(self, metrics: Dict[str, Any], step: Optional[int] = None) -> None:
        event = {k: _jsonable(v) for k, v in metrics.items()}
        if step is not None:
            event["step"] = int(step)
        event["_runtime_s"] = round(time.time() - self._t0, 3)
        self.summary.update(
            {k: v for k, v in event.items() if not k.startswith("_")})
        self.events.append(event)
        if self._fh is not None:
            self._fh.write(json.dumps(event) + "\n")
            self._since_flush += 1
            if self._since_flush >= self.flush_summary_every:
                self._write_summary()
        if self.stdout:
            logger.info("[%s] %s", self.name, event)

    def _write_summary(self) -> None:
        if self.run_dir is None:
            return
        path = os.path.join(self.run_dir, "summary.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.summary, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        self._since_flush = 0

    def close(self) -> None:
        self._write_summary()
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def stats_from_metrics(m, prefix: str = "") -> Dict[str, float]:
    """Summable metric dict {correct, loss_sum, total, correct_top5?} ->
    reported stats {acc, loss, acc_top5?} — THE one derivation, shared by
    every eval path so new metric keys cannot drift between them."""
    total = max(float(m["total"]), 1.0)
    out = {f"{prefix}acc": float(m["correct"]) / total,
           f"{prefix}loss": float(m["loss_sum"]) / total}
    if "correct_top5" in m:
        out[f"{prefix}acc_top5"] = float(m["correct_top5"]) / total
    return out


@contextlib.contextmanager
def profiler_trace(trace_dir: Optional[str]):
    """Capture a jax/XLA profiler trace into ``trace_dir`` (viewable with
    tensorboard/perfetto).  ``None`` disables tracing with zero overhead."""
    if not trace_dir:
        yield
        return
    import jax
    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield
