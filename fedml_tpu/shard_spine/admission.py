"""Per-shard upload admission for the sharded spine.

The replicated `robust.admission.AdmissionPipeline` screens one
full-model upload per silo.  On the sharded wire a silo's update arrives
as S shard slices, and the screens split across two moments:

* **per slice, at arrival** — quarantine state, the structural
  fingerprint against that SHARD's template (the shard id is part of
  the screened structure, so a wrong-shard slice is a fingerprint
  reject even when shapes collide), the finite guard, ``num_samples``
  validation (first slice) and cross-slice consistency;
* **per silo, at completion** — the norm-outlier screen over the
  COMBINED update norm ``sqrt(sum_s sumsq_s)``: the same f64 quantity
  the replicated screen computes (`robust.admission.update_sumsq` per
  slice), against the same rolling median+MAD threshold
  (`norm_outlier_threshold` — one formula, shared, never forked).

Rejection granularity is the SILO: one bad slice rejects the whole
upload before anything folds (matching the replicated semantics where
one bad leaf rejects the upload), the silo satisfies the barrier at
weight 0, and the strike feeds the shared `TrustTracker` — quarantine /
probation / strike-decay work unchanged, and the rejection lands in the
same ``fedml_robust_rejected_total{reason}`` series every dashboard
already watches (plus ``fedml_shard_rejected_total`` for the
shard-path-specific view).

Held state: a silo's slices are buffered only until its last slice
lands or the round closes — O(in-flight silos * model) worst case on
the host, but per DEVICE the fold state stays O(model/S); the hold is
the price of whole-silo rejection granularity and the global clip norm.
"""

from __future__ import annotations

import collections
import logging
import math
from typing import Deque, Dict, Optional, Tuple

import numpy as np

from fedml_tpu.obs import telemetry
from fedml_tpu.robust.admission import (REASONS, TrustTracker, all_finite,
                                        flatten_leaves,
                                        norm_outlier_threshold,
                                        params_fingerprint, update_sumsq)
from fedml_tpu.shard_spine.plan import ShardPlan

log = logging.getLogger(__name__)

# offer() outcomes
WAIT = "wait"          # banked; more slices outstanding
ACCEPT = "accept"      # all slices arrived and passed every screen
REJECT = "reject"      # the SILO is rejected (reason attached)


class ShardAdmission:
    """The sharded bouncer.  ``template_slices``: the plan's split of
    the federation-start template (fingerprint + acc-shape contract).

    Round protocol::

        adm.round_start(host_params)          # caches per-shard f64 refs
        status, payload = adm.offer(silo, shard, nshards, slice, n, r)
        ...
        adm.round_end()                       # drops unfinished holds
    """

    def __init__(self, plan: ShardPlan, template, *,
                 max_num_samples: float = 1e6, norm_k: float = 6.0,
                 norm_window: int = 64, norm_min_history: int = 8,
                 trust: Optional[TrustTracker] = None):
        if max_num_samples < 0:
            raise ValueError(f"max_num_samples must be >= 0 (0 disables "
                             f"the cap), got {max_num_samples}")
        if norm_window < 1 or norm_min_history < 1:
            raise ValueError("norm_window and norm_min_history must be "
                             ">= 1")
        self.plan = plan
        import jax
        leaves = [np.asarray(x) for x in jax.tree.leaves(template)]
        self.template_slices = plan.split_leaves(leaves)
        self.fingerprints = [params_fingerprint(sl)
                             for sl in self.template_slices]
        self.max_num_samples = max_num_samples
        self.norm_k = norm_k
        self.norm_min_history = norm_min_history
        self._norms: Deque[float] = collections.deque(maxlen=norm_window)
        self.trust = trust if trust is not None else TrustTracker()
        reg = telemetry.get_registry()
        self._c_admitted = reg.counter("fedml_robust_admitted_total")
        self._c_rejected = {r: reg.counter("fedml_robust_rejected_total",
                                           reason=r) for r in REASONS}
        self._c_shard_rej = {r: reg.counter("fedml_shard_rejected_total",
                                            reason=r) for r in REASONS}
        # the SAME histogram the replicated screen observes per upload
        # (robust/admission.py) — a sharded federation must not leave
        # the norm dashboards silently empty
        self._h_norm = reg.histogram(
            "fedml_robust_update_norm_total",
            buckets=(0.01, 0.1, 0.5, 1, 2, 5, 10, 50, 100, 1000, 1e5))
        self.rejected: Dict[str, int] = {r: 0 for r in REASONS}
        self.admitted = 0
        # per-round state
        self._ref_slices: Optional[list] = None   # per-shard f64 leaves
        self._pending: Dict[int, Dict[int, dict]] = {}
        self._sumsq: Dict[int, Dict[int, float]] = {}
        self._num_samples: Dict[int, float] = {}

    # -- round lifecycle -----------------------------------------------------
    def round_start(self, host_params) -> None:
        """Cache the round's reference slices as f64 host leaves (one
        device→host materialization per round, the `AdmissionPipeline`
        ``_ref_cache`` discipline — never one per slice)."""
        import jax
        leaves = [np.asarray(x) for x in jax.tree.leaves(host_params)]
        slices = self.plan.split_leaves(leaves)
        self._ref_slices = [
            [np.asarray(leaf, np.float64)
             for leaf in flatten_leaves(sl)] for sl in slices]
        self.round_end()

    def round_end(self) -> None:
        """Drop unfinished holds (stragglers whose remaining slices
        never arrived — the round closed over them at weight 0)."""
        self._pending.clear()
        self._sumsq.clear()
        self._num_samples.clear()

    def norm_threshold(self) -> Optional[float]:
        return norm_outlier_threshold(self._norms, self.norm_k,
                                      self.norm_min_history)

    # -- the screens ---------------------------------------------------------
    def _reject(self, silo: int, round_idx: int, reason: str,
                norm: Optional[float] = None) -> Tuple[str, dict]:
        self._drop(silo)
        self.rejected[reason] += 1
        self._c_rejected[reason].inc()
        self._c_shard_rej[reason].inc()
        if reason != "quarantined":
            self.trust.strike(silo, round_idx, reason)
        return REJECT, {"reason": reason, "norm": norm}

    def _drop(self, silo: int) -> None:
        self._pending.pop(silo, None)
        self._sumsq.pop(silo, None)
        self._num_samples.pop(silo, None)

    def offer(self, silo: int, shard, num_shards, slice_payload,
              num_samples, round_idx: int, pre=None) -> Tuple[str, dict]:
        """Screen + bank one shard slice.  Returns ``(WAIT, {})``,
        ``(REJECT, {reason, norm})``, or ``(ACCEPT, {slices,
        num_samples, norm})`` with the silo's S slices in shard order —
        the exact payload `ShardedStreamingAggregator.fold_slices`
        consumes.

        ``pre`` (a `comm.ingest.ArenaScreen` from the shard's ingest
        arena) stands in for the host screens it already ran on the raw
        frame: structural header check → fingerprint, fused device
        reduction → finite + sumsq.  Screen ORDER is unchanged, and the
        caller passes ``pre.tree`` (the staged device slices) as
        ``slice_payload`` so the banked slices are device-resident."""
        if self._ref_slices is None:
            raise RuntimeError("offer() before round_start(): the "
                               "round's reference slices are not cached")
        if self.trust.state(silo, round_idx) == TrustTracker.QUARANTINED:
            return self._reject(silo, round_idx, "quarantined")
        # the slice's own shard/count claims must match the plan — a
        # mislabeled frame is structural damage, same bucket as a
        # fingerprint mismatch
        try:
            shard = int(shard)
            num_shards = int(num_shards)
        except (TypeError, ValueError):
            return self._reject(silo, round_idx, "fingerprint")
        if num_shards != self.plan.num_shards \
                or not 0 <= shard < self.plan.num_shards:
            return self._reject(silo, round_idx, "fingerprint")
        if pre is not None:
            fp_ok = pre.structural_ok
        else:
            try:
                fp_ok = (params_fingerprint(slice_payload)
                         == self.fingerprints[shard])
            except Exception:  # noqa: BLE001 — unhashable garbage payload
                fp_ok = False
        if not fp_ok:
            return self._reject(silo, round_idx, "fingerprint")
        n = self._validate_num_samples(silo, num_samples)
        if n is None:
            return self._reject(silo, round_idx, "bad_num_samples")
        if not (pre.finite if pre is not None else
                all_finite(slice_payload)):
            return self._reject(silo, round_idx, "nonfinite")
        held = self._pending.setdefault(silo, {})
        if shard in held:
            # duplicate slice delivery (chaos dup / transport retry):
            # the first copy was already screened and banked
            log.info("ignoring duplicate shard-%d slice from silo %d",
                     shard, silo)
            return WAIT, {}
        held[shard] = slice_payload
        self._sumsq.setdefault(silo, {})[shard] = (
            pre.sumsq if pre is not None else update_sumsq(
                slice_payload, self._ref_slices[shard]))
        if len(held) < self.plan.num_shards:
            return WAIT, {}
        # completion: the combined norm screen over the whole update
        norm = math.sqrt(sum(self._sumsq[silo].values()))
        self._h_norm.observe(norm)
        thresh = self.norm_threshold()
        if thresh is not None and norm > thresh:
            return self._reject(silo, round_idx, "norm_outlier", norm)
        slices = [held[s] for s in range(self.plan.num_shards)]
        self._drop(silo)
        self._norms.append(norm)
        self.admitted += 1
        self._c_admitted.inc()
        self.trust.record_clean(silo, round_idx)
        return ACCEPT, {"slices": slices, "num_samples": float(n),
                        "norm": norm}

    def _validate_num_samples(self, silo: int,
                              num_samples) -> Optional[float]:
        try:
            n = float(num_samples)
        except (TypeError, ValueError):
            return None
        if not math.isfinite(n) or n <= 0 \
                or (self.max_num_samples > 0 and n > self.max_num_samples):
            return None
        prev = self._num_samples.get(silo)
        if prev is not None and prev != n:
            # a silo claiming different weights on different slices is
            # weight confusion, not an honest upload
            return None
        self._num_samples[silo] = n
        return n

    def pending_silos(self) -> set:
        """Silos with at least one banked slice still waiting for the
        rest (diagnostics; the straggler timer reads the barrier, not
        this)."""
        return set(self._pending)

    def reject(self, silo: int, round_idx: int, reason: str):
        """Administrative rejection for damage detected upstream (the
        `AdmissionPipeline.reject` twin): counted and struck so every
        rejected upload appears in the rejected series."""
        if reason not in REASONS:
            raise ValueError(f"unknown rejection reason {reason!r}; "
                             f"available: {REASONS}")
        return self._reject(silo, round_idx, reason)
