"""Typed message envelope with a binary pytree codec.

Reference equivalent: ``fedml_core/distributed/communication/message.py:5-74``
— a dict of params with ``msg_type/sender/receiver`` plus arbitrary keys, and
model weights carried under ``"model_params"``.  The reference serializes to
JSON with weights converted tensor→nested-python-list
(fedml_api/distributed/fedavg/utils.py:7-16), which both bloats the wire size
~4x and costs a slow float-by-float decode.

Here a message serializes to one frame::

    [4-byte header length][JSON header][raw buffer 0][raw buffer 1]...

Array-valued params (numpy arrays, JAX arrays, and arbitrary pytrees of them)
are flattened; the header records the treedef, dtypes, and shapes; buffers are
the arrays' raw bytes.  Scalars/strings/lists of plain python stay in the
JSON header.

Copy discipline (the wire hot path — see README "Wire format & round hot
path" for the per-round inventory):

* **encode** — each contiguous leaf is copied exactly ONCE, straight into
  the output frame (``b"".join`` over memoryviews of the source arrays; the
  old path paid ``arr.tobytes()`` + join = two copies per leaf).  A
  non-contiguous leaf pays one extra ``ascontiguousarray`` copy.
* **decode** — ``from_bytes`` takes read-only ``memoryview`` slices of the
  inbound frame and ``np.frombuffer``s each leaf in place: zero copies, and
  every decoded array is READ-ONLY (frames are immutable — the robust
  admission pipeline screens them as delivered, so nothing downstream may
  mutate a decoded leaf in place).  Decoded leaves keep the whole frame
  buffer alive; model-sized payloads dominate their frame, so retention is
  ~1x.
* **fan-out** — `SharedPayload` serializes a payload ONCE for a whole
  broadcast; each receiver's frame varies only the small JSON header.  Wire
  transports that must hand the kernel one contiguous buffer (gRPC) pay a
  single memcpy of the shared block per receiver; the in-process hub decodes
  straight from the parts (`Message.from_frame_parts`) and pays none.

A torn or truncated frame raises ``ValueError`` from every decode entry
point — transports catch it, count ``fedml_wire_torn_frames_total``, and
drop the frame instead of letting a corrupt wire kill a receive thread.

``CODEC_COUNTS`` is the test/bench spy: it counts payload serializations
(the expensive array-section encodes) and per-leaf byte copies, so
`scripts/wire_bench.py` reports measured copy inventories and
tests/test_wire.py pins "send_many serializes the shared payload exactly
once" without reaching into private state.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Any, Dict, List, Optional

import numpy as np

from fedml_tpu.obs import telemetry

_HDR = struct.Struct("<I")

# codec spy counters (module-global, monotonically increasing):
#   payload_encodes — array-section serializations (one per to_bytes with
#                     array params; ONE per SharedPayload regardless of
#                     fan-out width)
#   payload_decodes — array-section decodes
#   leaf_copies     — per-leaf byte copies paid while encoding (1 per
#                     contiguous leaf, 2 for a non-contiguous one)
CODEC_COUNTS = {"payload_encodes": 0, "payload_decodes": 0, "leaf_copies": 0}


def _encode_params(params: Dict[str, Any], idx_offset: int = 0):
    """Serialize the array half of ``params``.

    Returns ``(header, buffers, n_buffers)`` where ``header`` is the
    JSON-able ``{"plain": ..., "arrays": ...}`` dict (buffer indices start
    at ``idx_offset``), and ``buffers`` is the flat ``[len-prefix,
    memoryview, ...]`` part list whose concatenation is the frame's buffer
    section — each part a view into the SOURCE array, so the single copy
    per leaf happens where the caller materializes the frame.
    """
    header: Dict[str, Any] = {"plain": {}, "arrays": {}}
    parts: List[Any] = []
    n_buffers = 0
    for key, value in params.items():
        leaves, spec = _flatten_arrays(value)
        if leaves is None:
            header["plain"][key] = value
        else:
            descr = []
            for leaf in leaves:
                src = np.asarray(leaf)
                arr = np.ascontiguousarray(src)
                if arr is not src:
                    CODEC_COUNTS["leaf_copies"] += 1
                CODEC_COUNTS["leaf_copies"] += 1  # the copy into the frame
                # ascontiguousarray promotes 0-d to shape (1,) — record
                # the ORIGINAL shape so 0-d leaves round-trip exactly
                descr.append({"dtype": arr.dtype.str, "shape": src.shape,
                              "idx": idx_offset + n_buffers})
                parts.append(_HDR.pack(arr.nbytes))
                # empty leaves cannot be cast to a flat byte view
                parts.append(memoryview(arr).cast("B") if arr.nbytes
                             else b"")
                n_buffers += 1
            header["arrays"][key] = {"spec": spec, "leaves": descr}
    if n_buffers:
        CODEC_COUNTS["payload_encodes"] += 1
    return header, parts, n_buffers


def _freeze_parts(parts: List[Any]) -> bytearray:
    """Materialize an ``_encode_params`` part list into one preallocated
    buffer (the single copy per leaf)."""
    total = sum(len(p) if isinstance(p, bytes) else p.nbytes for p in parts)
    block = bytearray(total)
    mv = memoryview(block)
    off = 0
    for p in parts:
        n = len(p) if isinstance(p, bytes) else p.nbytes
        mv[off:off + n] = p
        off += n
    return block


def _parse_buffer_stream(mv: memoryview, buffers: List[memoryview]) -> None:
    """Walk one ``[4-byte len][raw bytes]...`` stream, appending read-only
    views.  Raises ``ValueError`` on a torn/truncated stream."""
    offset, end = 0, len(mv)
    while offset < end:
        if offset + _HDR.size > end:
            raise ValueError(
                f"torn frame: {end - offset} trailing bytes where a "
                f"{_HDR.size}-byte buffer length was expected")
        (n,) = _HDR.unpack_from(mv, offset)
        offset += _HDR.size
        if offset + n > end:
            raise ValueError(
                f"truncated frame: buffer {len(buffers)} declares {n} "
                f"bytes but only {end - offset} remain")
        buffers.append(mv[offset:offset + n])
        offset += n


def _readonly(data) -> memoryview:
    mv = data if isinstance(data, memoryview) else memoryview(data)
    return mv if mv.readonly else mv.toreadonly()


class Message:
    """Key-value message envelope (type, sender, receiver, params)."""

    # canonical param keys, mirroring the reference's Message constants
    # (message.py:9-24) so algorithm choreography reads the same
    ARG_TYPE = "msg_type"
    ARG_SENDER = "sender"
    ARG_RECEIVER = "receiver"
    ARG_MODEL_PARAMS = "model_params"
    ARG_NUM_SAMPLES = "num_samples"
    ARG_CLIENT_INDEX = "client_idx"
    ARG_ROUND = "round_idx"
    ARG_ACCEPTED = "accepted_silos"  # silo ids aggregated last round (EF ack)
    ARG_EDGE_COUNT = "edge_count"    # uploads folded into a pre-reduced
    #                                  edge update (multi-level topology).
    #                                  DIAGNOSTIC-ONLY: the root's
    #                                  aggregation weights ride
    #                                  ARG_NUM_SAMPLES; this field exists
    #                                  for wire-level observability and
    #                                  tests, nothing load-bearing reads it
    ARG_HEALTH = "health_summary"    # compact per-round learning-health
    #                                  rollup an edge aggregator ships
    #                                  inside its existing edge frame
    #                                  (obs/health.compact_summary) — the
    #                                  tree stays one-frame-per-round;
    #                                  DIAGNOSTIC-ONLY like ARG_EDGE_COUNT
    ARG_SHARD = "shard_idx"          # sharded global-model spine
    #                                  (fedml_tpu/shard_spine): which
    #                                  shard's slice this frame carries —
    #                                  broadcasts ship S per-shard
    #                                  frames (one encode-once
    #                                  SharedPayload per SHARD, never
    #                                  per receiver) and uploads arrive
    #                                  as S slice frames screened per
    #                                  shard before any fold
    ARG_SHARD_COUNT = "shard_count"  # S, on every shard frame (a lone
    #                                  slice is meaningless without it)
    ARG_SHARD_SPEC = "shard_spec"    # the plan descriptor (plain JSON,
    #                                  rides shard 0's sync frame) — a
    #                                  silo rebuilds split/join from it
    #                                  with zero configuration, like the
    #                                  secagg masking parameters
    ARG_SECAGG = "secagg"            # secure-aggregation protocol frames
    #                                  (secure/protocol.py): the sync
    #                                  broadcast's masking parameters
    #                                  (group/threshold/clip/weight_cap),
    #                                  a silo's advert (pk + Shamir share
    #                                  envelopes), the roster relay, and
    #                                  the unmask request/reveal payloads
    #                                  — all plain-JSON dicts of ints, so
    #                                  they ride the header beside the
    #                                  masked uint32 model payload
    # span context (obs/trace.py CTX_KEY): a {"t","s"} dict riding the
    # plain JSON header, so one federated round stitches into a single
    # cross-process trace
    ARG_TRACE = "_trace"

    def __init__(self, msg_type: int | str = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.params: Dict[str, Any] = {
            self.ARG_TYPE: msg_type,
            self.ARG_SENDER: sender_id,
            self.ARG_RECEIVER: receiver_id,
        }
        # encode-once fan-out: build_fanout() points every sibling of a
        # broadcast at ONE SharedPayload, and to_bytes() reuses its
        # already-serialized block instead of re-encoding the model bytes
        self._shared: Optional["SharedPayload"] = None
        # raw-frame stash (decode path only): the parsed array headers +
        # buffer views, so the ingest arena can stage straight from the
        # frame without a tree walk (`raw_payload`)
        self._arrays: Optional[dict] = None
        self._buffers: Optional[List[memoryview]] = None

    # -- accessors (reference message.py:26-60) ------------------------------
    @property
    def type(self):
        return self.params[self.ARG_TYPE]

    @property
    def sender_id(self) -> int:
        return self.params[self.ARG_SENDER]

    @property
    def receiver_id(self) -> int:
        return self.params[self.ARG_RECEIVER]

    def add(self, key: str, value: Any) -> "Message":
        self.params[key] = value
        return self

    def get(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)

    def __repr__(self):
        keys = [k for k in self.params
                if k not in (self.ARG_TYPE, self.ARG_SENDER, self.ARG_RECEIVER)]
        return (f"Message(type={self.type}, {self.sender_id}->"
                f"{self.receiver_id}, params={keys})")

    # -- binary codec --------------------------------------------------------
    def to_bytes(self) -> bytes:
        """One frame: header + buffer stream (byte-identical to the
        historical format — old/new nodes interoperate, and chaos-replay
        seeds keyed on frame sizes stay valid).  Each contiguous array
        leaf is copied exactly once, by the final join."""
        shared = self._shared
        if shared is not None:
            return shared.frame_bytes(self)
        t0 = time.perf_counter()
        header, parts, n_buffers = _encode_params(self.params)
        hdr = json.dumps(header).encode()
        frame = b"".join([_HDR.pack(len(hdr)), hdr] + parts)
        if n_buffers:
            _observe_encode(time.perf_counter() - t0)
        return frame

    def frame_parts(self) -> List[Any]:
        """The frame as a list of buffer segments (zero-copy where a
        shared payload is attached) — for transports that can scatter
        instead of joining.  ``b"".join(map(bytes, parts))`` is always
        byte-identical to ``to_bytes()``."""
        shared = self._shared
        if shared is not None:
            return shared.frame_parts(self)
        return [self.to_bytes()]

    @classmethod
    def from_bytes(cls, data) -> "Message":
        """Zero-copy decode: array leaves are read-only views into
        ``data``.  Raises ``ValueError`` for any torn, truncated, or
        structurally damaged frame — callers on receive threads catch it
        and drop the frame (counting ``fedml_wire_torn_frames_total``)."""
        mv = _readonly(data)
        if len(mv) < _HDR.size:
            raise ValueError(
                f"truncated frame: {len(mv)} bytes is shorter than the "
                f"{_HDR.size}-byte header length")
        (hlen,) = _HDR.unpack_from(mv, 0)
        if _HDR.size + hlen > len(mv):
            raise ValueError(
                f"truncated frame: header declares {hlen} bytes but only "
                f"{len(mv) - _HDR.size} follow")
        header = cls._parse_header(mv[_HDR.size:_HDR.size + hlen])
        buffers: List[memoryview] = []
        _parse_buffer_stream(mv[_HDR.size + hlen:], buffers)
        return cls._from_header(header, buffers)

    @classmethod
    def from_frame_parts(cls, parts) -> "Message":
        """Decode a `frame_parts` segment list without materializing one
        contiguous frame: segment 0 is ``[hdr len][hdr][buffers...]``,
        later segments are pure buffer streams."""
        mv0 = _readonly(parts[0])
        if len(mv0) < _HDR.size:
            raise ValueError("truncated frame: empty header segment")
        (hlen,) = _HDR.unpack_from(mv0, 0)
        if _HDR.size + hlen > len(mv0):
            raise ValueError("truncated frame: header crosses segments")
        header = cls._parse_header(mv0[_HDR.size:_HDR.size + hlen])
        buffers: List[memoryview] = []
        _parse_buffer_stream(mv0[_HDR.size + hlen:], buffers)
        for part in parts[1:]:
            _parse_buffer_stream(_readonly(part), buffers)
        return cls._from_header(header, buffers)

    @staticmethod
    def _parse_header(mv: memoryview) -> dict:
        try:
            header = json.loads(bytes(mv))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise ValueError(f"undecodable frame header: {exc}") from exc
        if (not isinstance(header, dict)
                or not isinstance(header.get("plain"), dict)
                or not isinstance(header.get("arrays"), dict)):
            raise ValueError("malformed frame header: expected "
                             "{'plain': {...}, 'arrays': {...}}")
        return header

    def raw_payload(self, key: str):
        """The raw-frame view of one array param, for the ingest arena:
        ``(leaf_descriptors, spec, buffers)`` — header facts plus the
        frame's zero-copy buffer views, no tree walk.  ``None`` when the
        message never crossed the wire (an in-process object message) or
        carries no such array param."""
        if self._arrays is None or self._buffers is None:
            return None
        info = self._arrays.get(key)
        if not isinstance(info, dict):
            return None
        try:
            return info["leaves"], info["spec"], self._buffers
        except (TypeError, KeyError):
            return None

    @classmethod
    def _from_header(cls, header: dict, buffers: List[memoryview]):
        msg = cls.__new__(cls)
        msg._shared = None
        msg._arrays = header["arrays"]
        msg._buffers = buffers
        msg.params = dict(header["plain"])
        decoded_payload = False
        for key, info in header["arrays"].items():
            leaves = []
            try:
                descr = info["leaves"]
            except (TypeError, KeyError) as exc:
                raise ValueError(f"malformed array header for {key!r}") \
                    from exc
            for d in descr:
                try:
                    idx, dtype, shape = d["idx"], d["dtype"], d["shape"]
                except (TypeError, KeyError) as exc:
                    raise ValueError(
                        f"malformed leaf descriptor for {key!r}") from exc
                if not isinstance(idx, int) or not 0 <= idx < len(buffers):
                    raise ValueError(
                        f"frame header references buffer {idx!r} but only "
                        f"{len(buffers)} arrived")
                try:
                    arr = np.frombuffer(buffers[idx], dtype=np.dtype(dtype))
                    leaves.append(arr.reshape(shape))
                except (TypeError, ValueError) as exc:
                    raise ValueError(
                        f"buffer {idx} does not match its declared "
                        f"dtype/shape ({dtype}, {shape}): {exc}") from exc
            decoded_payload = decoded_payload or bool(descr)
            try:
                msg.params[key] = _unflatten_arrays(info["spec"], leaves)
            except (TypeError, KeyError, IndexError) as exc:
                raise ValueError(
                    f"malformed pytree spec for {key!r}") from exc
        if decoded_payload:
            CODEC_COUNTS["payload_decodes"] += 1
        return msg


class SharedPayload:
    """Encode-once payload for a transport fan-out (``send_many``).

    The expensive serialization — flattening the pytree and copying every
    array leaf — runs ONCE, here, into one immutable block.  Each
    receiver's frame is then ``[hdr][shared block][own block]``: only the
    small JSON header (and any receiver-private params, e.g. the trace
    context or ``client_idx``) varies per receiver.  The shared block is
    never mutated after construction, so a wrapper that damages one
    receiver's payload (chaos ``corrupt``) must — and does — drop its
    message's reference to this object and re-encode its own copy.
    """

    def __init__(self, params: Dict[str, Any]):
        self.keys = frozenset(params)
        self.params = dict(params)
        t0 = time.perf_counter()
        self._header, parts, self._n_buffers = _encode_params(params)
        self._block = _freeze_parts(parts)
        # the arrays section (one descriptor per leaf — the bulk of a big
        # model's header) is identical for every receiver: serialize its
        # JSON once so each receiver's header costs only its few plain
        # keys, keeping fan-out cost flat in BOTH payload and leaf count
        self._arrays_json = json.dumps(self._header["arrays"]).encode()
        if self._n_buffers:
            _observe_encode(time.perf_counter() - t0)

    @property
    def nbytes(self) -> int:
        return len(self._block)

    def _header_and_own(self, msg: Message):
        own = {k: v for k, v in msg.params.items() if k not in self.keys}
        hdr_own, own_parts, _ = _encode_params(own,
                                               idx_offset=self._n_buffers)
        plain = {**self._header["plain"], **hdr_own["plain"]}
        if not hdr_own["arrays"]:
            # splice the cached arrays JSON around this receiver's plain
            # keys — same document shape json.dumps would produce
            hdr = (b'{"plain": ' + json.dumps(plain).encode()
                   + b', "arrays": ' + self._arrays_json + b'}')
            return hdr, own_parts
        header = {"plain": plain,
                  "arrays": {**self._header["arrays"], **hdr_own["arrays"]}}
        return json.dumps(header).encode(), own_parts

    def frame_bytes(self, msg: Message) -> bytes:
        """A standalone contiguous frame for single-buffer wires (gRPC,
        MQTT): one memcpy of the already-encoded shared block, no
        re-serialization."""
        hdr, own_parts = self._header_and_own(msg)
        return b"".join([_HDR.pack(len(hdr)), hdr, self._block] + own_parts)

    def frame_parts(self, msg: Message) -> List[Any]:
        """The zero-copy form: ``[prefix, shared-block view, own...]`` —
        the shared block is not copied at all (the in-process hub decodes
        straight from the view)."""
        hdr, own_parts = self._header_and_own(msg)
        parts: List[Any] = [_HDR.pack(len(hdr)) + hdr,
                            memoryview(self._block).toreadonly()]
        if own_parts:
            parts.append(bytes(_freeze_parts(own_parts)))
        return parts


def build_fanout(msg_type, sender_id: int, receivers,
                 shared_params: Optional[Dict[str, Any]] = None,
                 per_receiver_params: Optional[Dict[int, Dict[str, Any]]]
                 = None) -> List[Message]:
    """Build one `Message` per receiver, all sharing ONE encoded payload.

    ``shared_params`` (the model bytes, round tag, EF ack) serialize once;
    ``per_receiver_params[r]`` (e.g. ``client_idx``) ride each receiver's
    JSON header.  Every message also carries the shared params in
    ``msg.params`` BY REFERENCE, so in-process delivery and wrappers that
    inspect payloads (chaos corrupt, observers) see a normal message.

    The two key sets must be disjoint: a per-receiver override of a
    shared key would be honored by in-process delivery but dropped from
    the wire frame (the shared block is immutable), a silent
    backend-dependent divergence — so it is rejected here instead.
    """
    shared = SharedPayload(shared_params or {})
    per_receiver_params = per_receiver_params or {}
    for receiver, own in per_receiver_params.items():
        clash = shared.keys & set(own)
        if clash:
            raise ValueError(
                f"per-receiver params for {receiver} override shared "
                f"keys {sorted(clash)}; shared-payload values cannot "
                f"vary per receiver — send those keys per-receiver only")
    out = []
    for receiver in receivers:
        msg = Message(msg_type, sender_id, receiver)
        msg.params.update(shared.params)
        msg.params.update(per_receiver_params.get(receiver, {}))
        msg._shared = shared
        out.append(msg)
    return out


def _observe_encode(seconds: float) -> None:
    reg = telemetry.get_registry()
    if reg.enabled:
        reg.histogram("fedml_wire_encode_seconds").observe(seconds)


def _is_array(x) -> bool:
    if isinstance(x, (np.ndarray, np.generic)):  # includes 0-d numpy scalars
        return True
    return hasattr(x, "__array__") and hasattr(x, "dtype") and hasattr(x, "shape")


def _flatten_arrays(value):
    """Flatten a pytree-of-arrays into (leaves, json-able spec).

    Returns (None, None) when the value contains no arrays — it then travels
    in the JSON header verbatim.  Supports dict/list/tuple nests of arrays,
    the shapes model params (nested dicts) and stacked batches take.
    """
    if _is_array(value):
        return [value], {"k": "leaf"}
    if isinstance(value, dict):
        if not any(_contains_array(v) for v in value.values()):
            return None, None
        keys = sorted(value.keys())
        leaves, specs = [], []
        for k in keys:
            sub_leaves, sub_spec = _flatten_arrays(value[k])
            if sub_leaves is None:  # plain sub-value inside an array dict
                sub_leaves, sub_spec = [], {"k": "plain", "v": value[k]}
            leaves.extend(sub_leaves)
            specs.append(sub_spec)
        return leaves, {"k": "dict", "keys": keys, "children": specs}
    if isinstance(value, (list, tuple)):
        if not any(_contains_array(v) for v in value):
            return None, None
        leaves, specs = [], []
        for v in value:
            sub_leaves, sub_spec = _flatten_arrays(v)
            if sub_leaves is None:
                sub_leaves, sub_spec = [], {"k": "plain", "v": v}
            leaves.extend(sub_leaves)
            specs.append(sub_spec)
        kind = "tuple" if isinstance(value, tuple) else "list"
        return leaves, {"k": kind, "children": specs}
    return None, None


def _contains_array(value) -> bool:
    if _is_array(value):
        return True
    if isinstance(value, dict):
        return any(_contains_array(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(_contains_array(v) for v in value)
    return False


def _unflatten_arrays(spec, leaves, _pos=None):
    if _pos is None:
        _pos = [0]
    kind = spec["k"]
    if kind == "leaf":
        out = leaves[_pos[0]]
        _pos[0] += 1
        return out
    if kind == "plain":
        return spec["v"]
    if kind == "dict":
        return {k: _unflatten_arrays(c, leaves, _pos)
                for k, c in zip(spec["keys"], spec["children"])}
    children = [_unflatten_arrays(c, leaves, _pos) for c in spec["children"]]
    return tuple(children) if kind == "tuple" else children
