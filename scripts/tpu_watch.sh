#!/usr/bin/env bash
# Recurring tunnel probe (VERDICT r3 item 1: "check for the tunnel early
# and repeatedly — a cron-style retry during the session").  The moment
# the backend answers, fire the full capture; on a mid-capture wedge go
# back to probing and retry (stage 1 reruns are cache-warm and cheap).
# A sentinel file marks capture-in-progress so interactive work can
# avoid contaminating the timings on this small host.
cd "$(dirname "$0")/.."
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch.log}
SENTINEL=/tmp/tpu_capture_running
trap 'rm -f "$SENTINEL"' EXIT
while true; do
  if timeout 75 python -c "import jax, jax.numpy as jnp; \
jax.block_until_ready(jax.jit(lambda a: a + 1)(jnp.ones(8)))" \
      >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) alive — launching capture" >> "$LOG"
    touch "$SENTINEL"
    if bash scripts/tpu_capture.sh >> "$LOG" 2>&1; then
      rm -f "$SENTINEL"
      echo "$(date -u +%FT%TZ) capture COMPLETE" >> "$LOG"
      exit 0
    fi
    rm -f "$SENTINEL"
    # promote the freshest partial so a later wedged bench run (or the
    # driver's end-of-round commit of uncommitted work) still carries the
    # newest REAL on-chip measurements (_emit_skipped freshness contract)
    python - <<'EOF'
import json, os, shutil
src, dst = "BENCH_DETAILS.json.partial", "BENCH_PARTIAL_LATEST.json"
if os.path.exists(src):
    try:
        new = json.load(open(src))
        old_ts = (json.load(open(dst)).get("captured_at", 0.0)
                  if os.path.exists(dst) else 0.0)
        fresh = new.get("captured_at", 0.0) > old_ts
        has_data = new.get("platform") == "tpu" and any(
            c.get("rounds_per_s") for c in new.get("configs", {}).values())
        if fresh and has_data:
            shutil.copy(src, dst)
            print("promoted", src, "->", dst)
    except Exception as e:
        print("partial promotion skipped:", e)
EOF
    echo "$(date -u +%FT%TZ) capture incomplete — back to probing" >> "$LOG"
  else
    echo "$(date -u +%FT%TZ) wedged" >> "$LOG"
  fi
  sleep 140
done
