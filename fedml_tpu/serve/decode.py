"""Continuous-batching decode scheduler for autoregressive serving.

The `MicroBatcher` pads requests into a bucket, runs ONE forward, and
drains the whole batch — correct for one-shot models, but an
autoregressive sequence is hundreds of steps long and sequences finish
at different times: pad-to-bucket decode drains to occupancy ~1 while
one long sequence finishes, wasting most of the accelerator.  This
module schedules the way production LLM servers do (continuous
batching): ONE persistent compiled decode step over a fixed
``[slots]`` batch, where a finished sequence vacates its slot at the
end of a step and a queued request joins the free slot at the start of
the next — admission happens per STEP, not per batch, so occupancy
stays near capacity under backlog.

The compiled step is `TransformerLM`'s incremental decode: per-layer KV
caches as explicit carried state (`models.transformer.init_decode_cache`),
donated in place every step.  Shapes are fully static — ``[slots]``
tokens, ``[slots]`` positions, ``[slots, cache_len, ...]`` caches — so
the whole serving lifetime is ONE jit cache entry per (slots,
cache-bucket) pair; the scheduler exposes ``_cache_size`` and registers
with the PR 9 `RecompileSentry`/compile ledger so a retrace on the
decode hot path is named, never silent.  Prompts are consumed through
the same step (one prompt token per step, logits ignored until the last
one) — slower than a dedicated prefill program for long prompts, but it
keeps the one-entry compile contract and prompt tokens interleave with
other slots' decode steps instead of stalling them.

Model-version consistency (the registry's torn-read contract, extended
in time): a KV cache computed under version v is NOT valid state for
version v+1, so a hot swap must never land mid-sequence.  The scheduler
pins one `ServedModel` snapshot while any slot is live; when the
registry moves on, it stops ADMITTING (a swap barrier) and lets live
sequences finish on the pinned version — bounded by ``max_new`` steps —
then swaps and resumes.  Every result carries the version that decoded
ALL of its tokens.

``continuous=False`` is the drain-per-batch baseline the bench compares
against: admission only when every slot is free, exactly the
pad-to-bucket discipline, kept as a first-class mode so the occupancy
claim is measured against the real alternative, not a strawman.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from fedml_tpu.obs import telemetry, trace
from fedml_tpu.serve.batcher import (SHED_REASONS, TIERS, ShedError,
                                     TierAdmission, _settle,
                                     best_effort_cap)

log = logging.getLogger(__name__)


class DecodeResult:
    """One finished sequence: the generated token ids, the model version
    that produced EVERY one of them (the swap barrier guarantees a
    single version per sequence), and whether generation was cut by the
    cache bucket rather than max_new/EOS."""
    __slots__ = ("tokens", "version", "truncated")

    def __init__(self, tokens: List[int], version: int, truncated: bool):
        self.tokens = tokens
        self.version = version
        self.truncated = truncated


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "deadline", "enq_t", "future",
                 "tier", "capped", "ctx")

    def __init__(self, prompt, max_new, deadline, enq_t, future, tier,
                 capped=False, ctx=None):
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline
        self.enq_t = enq_t
        self.future = future
        self.tier = tier
        self.capped = capped   # max_new was cut at admission to fit the
        #                        cache bucket: the result is `truncated`
        self.ctx = ctx         # submitter's span context, if any


class _Slot:
    """Host-side state of one in-flight sequence."""
    __slots__ = ("req", "pos", "generated")

    def __init__(self, req: _DecodeRequest):
        self.req = req
        self.pos = 0          # next sequence index to feed
        self.generated: List[int] = []

    def next_token(self) -> int:
        if self.pos < len(self.req.prompt):
            return int(self.req.prompt[self.pos])
        return self.generated[-1]


class DecodeScheduler:
    """Continuous-batching greedy decode over a fixed-slot compiled step.

    ``registry``: a `ModelRegistry` whose published params belong to
    ``model`` (a `TransformerLM`); the registry's ``apply_fn`` is not
    used here — the scheduler compiles its own decode step.
    ``slots``: the fixed batch width; ``cache_len``: the KV cache bucket
    (prompt + generated tokens must fit; a sequence hitting the wall
    finishes ``truncated``).  ``eos_id``: optional stop token.
    ``continuous``: per-step slot admission (False = drain-per-batch
    baseline).  ``worker``/``slo``/``best_effort_headroom``: the same
    tiered-admission surface as `MicroBatcher`.
    """

    def __init__(self, registry, model, *, slots: int = 8,
                 cache_len: int = 128, queue_depth: int = 256,
                 max_new: int = 32, eos_id: Optional[int] = None,
                 continuous: bool = True,
                 default_deadline_s: Optional[float] = None,
                 worker: Optional[str] = None, slo=None,
                 best_effort_headroom: float = 0.5,
                 cache_dtype=None):
        import jax
        import jax.numpy as jnp

        from fedml_tpu.models.transformer import init_decode_cache
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        self.registry = registry
        self.model = model
        self.slots = slots
        self.cache_len = cache_len
        self.max_new = max_new
        self.eos_id = eos_id
        self.continuous = continuous
        self.default_deadline_s = default_deadline_s
        self.worker = worker
        # captured once (the actor idiom): disabled tracing pays one
        # `is None` branch per step/finish, no lookups on the hot loop
        self._tracer = trace.get_tracer()
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._slots: List[Optional[_Slot]] = [None] * slots
        self._snapshot = None           # pinned ServedModel
        self._params_dev = None         # device-put params of _snapshot
        self._swap_pending = False
        self._stopped = False
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self._admit_lock = threading.Lock()
        self._wake = threading.Event()
        # bench-readable occupancy accounting (telemetry-independent)
        self.steps = 0
        self.live_steps = 0             # sum of live slots over steps

        cache_dtype = cache_dtype if cache_dtype is not None \
            else jnp.float32
        self._fresh_cache = lambda: init_decode_cache(
            model, slots, cache_len, dtype=cache_dtype)
        self._cache = None

        def _step(params, cache, tokens, positions):
            logits, cache = model.apply(params, tokens,
                                        positions=positions, cache=cache)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        # ONE jit entry for the scheduler's lifetime: static [slots]
        # shapes, donated cache.  _cache_size is the sentry probe.
        # Donation is auto-off on CPU (the backend ignores it with a
        # warning — the make_defended_aggregate convention).
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._step_jit = jax.jit(_step, donate_argnums=donate)
        self._step_fn = self._step_jit   # obs instrumentation wraps this

        reg = telemetry.get_registry()
        lbl = {} if worker is None else {"worker": str(worker)}
        self._c_requests = reg.counter("fedml_serve_decode_requests_total",
                                       **lbl)
        self._c_steps = reg.counter("fedml_serve_decode_steps_total",
                                    **lbl)
        self._c_tokens = reg.counter("fedml_serve_decode_tokens_total",
                                     **lbl)
        self._c_swaps = reg.counter("fedml_serve_decode_swaps_total",
                                    **lbl)
        self._adm = TierAdmission(
            {(r, t): reg.counter("fedml_serve_decode_shed_total",
                                 reason=r, tier=t, **lbl)
             for r in SHED_REASONS for t in TIERS},
            slo, best_effort_cap(queue_depth, best_effort_headroom))
        self.tier_gate = self._adm.gate
        self._h_occupancy = reg.histogram(
            "fedml_serve_decode_occupancy_total",
            buckets=tuple(float(i) for i in range(1, slots + 1)), **lbl)
        self._h_request = reg.histogram("fedml_serve_request_seconds",
                                        path="decode", **lbl)
        self._g_util = reg.gauge("fedml_serve_queue_utilization_ratio",
                                 path="decode", **lbl)

    # -- observability -------------------------------------------------------
    def _cache_size(self) -> int:
        """Jit cache entries of the decode step (the sentry probe): must
        stay 1 for the scheduler's lifetime — slot churn, mid-flight
        joins, and swap barriers never change a shape."""
        return int(self._step_jit._cache_size())

    def register_obs(self, recorder=None, sentry=None,
                     name: Optional[str] = None) -> str:
        """Register the decode step with the PR 9 observatory: the
        compile ledger names it ``decode_step[s<slots>,c<cache_len>]``
        and the recompile sentry watches its jit cache.  Returns the
        ledger name."""
        name = name or f"decode_step[s{self.slots},c{self.cache_len}]"
        if sentry is not None:
            sentry.register(name, self)
        if recorder is not None:
            self._step_fn = recorder.instrument(
                name, self._step_jit, sentry=sentry, sentry_name=name)
        return name

    def occupancy(self) -> Optional[float]:
        """Mean live slots per step so far (None before any step)."""
        return self.live_steps / self.steps if self.steps else None

    def depth(self) -> int:
        return self._q.qsize()

    # -- client side ---------------------------------------------------------
    def _shed(self, reason: str, tier: str = "interactive") -> ShedError:
        return self._adm.shed(reason, tier)

    def submit(self, prompt, max_new: Optional[int] = None,
               deadline_s: Optional[float] = None,
               tier: str = "interactive") -> Future:
        """Enqueue one sequence: ``prompt`` is a non-empty list of token
        ids; the Future resolves to a `DecodeResult`.  ``deadline_s``
        bounds QUEUE wait (admission), not generation — once a sequence
        holds a slot it runs to completion.  Sheds exactly like
        `MicroBatcher.submit` (queue_full / deadline-at-admission /
        shutdown / no_model / slo_degraded for best-effort)."""
        self._adm.screen(tier, self._q.qsize())
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt: decode needs >= 1 token")
        max_new = self.max_new if max_new is None else int(max_new)
        capped = False
        if len(prompt) + max_new > self.cache_len:
            # admission-time honesty: the cache bucket cannot hold it —
            # cap max_new here and flag the request, so the result says
            # `truncated` (the generation WAS cut by the bucket, the cut
            # just happened at admission instead of mid-flight; a prompt
            # alone overflowing the bucket is a client error)
            if len(prompt) >= self.cache_len:
                raise ValueError(
                    f"prompt of {len(prompt)} tokens does not fit the "
                    f"cache bucket ({self.cache_len})")
            max_new = self.cache_len - len(prompt)
            capped = True
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        now = time.monotonic()
        ctx = (self._tracer.current_context()
               if self._tracer is not None else None)
        req = _DecodeRequest(
            prompt, max_new,
            None if deadline_s is None else now + deadline_s,
            now, Future(), tier, capped, ctx)
        with self._admit_lock:
            if self._stopped:
                raise self._shed("shutdown", tier)
            try:
                self._q.put_nowait(req)
            except queue.Full:
                raise self._shed("queue_full", tier) from None
        self._c_requests.inc()
        self._note_util()
        self._wake.set()
        return req.future

    def _note_util(self) -> None:
        """Refresh the queue-fill gauge.  Called on submit AND from the
        worker loop after admission — a gauge only written on submit
        would latch a burst's high-water mark forever once traffic
        stops, self-sustaining an SLO breach (and best-effort shedding)
        on an idle instance."""
        if self._q.maxsize > 0:   # maxsize 0 = unbounded: no fill ratio
            self._g_util.set(self._q.qsize() / self._q.maxsize)

    def generate(self, prompt, max_new: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 timeout: Optional[float] = 60.0,
                 tier: str = "interactive") -> DecodeResult:
        """Blocking submit-and-wait convenience."""
        return self.submit(prompt, max_new, deadline_s,
                           tier=tier).result(timeout)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DecodeScheduler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="serve-decode")
            self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop admitting; with ``drain`` finish every in-flight AND
        queued sequence first (bounded by max_new steps each), without
        it shed the queue and fail live slots.  Idempotent.  The worker
        never blocks on the queue (it polls with a bounded wait), so a
        flag + wake is enough — no sentinel needed."""
        with self._admit_lock:
            if self._stopped and self._thread is None:
                return
            self._stopped = True
            self._drain = drain
        self._wake.set()
        if self._thread is None:
            # never started: honor the drain contract inline (the
            # MicroBatcher convention — queued work still gets answers)
            if drain and self._refresh_snapshot():
                self._drain_all()
            self._flush_queue(shed=True)
            return
        self._thread.join(timeout=120)
        if self._thread.is_alive():
            # a drain deeper than the timeout: the worker is STILL
            # stepping — marking it stopped would let a second stop()
            # take the inline-drain path and mutate slots/cache
            # concurrently with the live worker
            log.warning("decode scheduler: worker still draining after "
                        "120s; call stop() again to keep waiting")
            return
        self._thread = None

    def warmup(self) -> bool:
        """Pay the decode-step compile before serving (one all-dead step
        against the live model).  No-op without a published model."""
        if not self._refresh_snapshot(force=True):
            return False
        self._ensure_cache()
        tokens = np.zeros(self.slots, np.int32)
        positions = np.zeros(self.slots, np.int32)
        out, self._cache = self._step_fn(self._params_dev, self._cache,
                                         tokens, positions)
        np.asarray(out)   # block: the compile must land here, not later
        return True

    # -- worker --------------------------------------------------------------
    def _refresh_snapshot(self, force: bool = False) -> bool:
        """Pin the registry's current snapshot (device-putting params
        once).  With live slots a NEWER version only marks the swap
        barrier — the pinned snapshot keeps serving until they drain."""
        import jax
        cur = self.registry.current()
        if cur is None:
            return self._snapshot is not None
        if self._snapshot is None or force \
                or (cur.version != self._snapshot.version
                    and not any(self._slots)):
            swapped = (self._snapshot is not None
                       and cur.version != self._snapshot.version)
            self._snapshot = cur
            self._params_dev = jax.device_put(cur.params)
            self._swap_pending = False
            if swapped:
                self._c_swaps.inc()
        elif cur.version != self._snapshot.version:
            self._swap_pending = True
        return True

    def _ensure_cache(self) -> None:
        if self._cache is None:
            self._cache = self._fresh_cache()

    def _admit(self) -> None:
        """Fill free slots from the queue.  Continuous mode admits into
        any free slot every step; drain mode only refills once EVERY
        slot is free (the pad-to-bucket baseline).  The swap barrier
        blocks all admission until live sequences finish."""
        if self._swap_pending:
            return
        if not self.continuous and any(self._slots):
            return
        now = time.monotonic()
        for i in range(self.slots):
            if self._slots[i] is not None:
                continue
            while True:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    return
                if req.deadline is not None and now > req.deadline:
                    _settle(req.future,
                            exc=self._shed("deadline", req.tier))
                    continue
                self._slots[i] = _Slot(req)
                break

    def _finish(self, i: int, truncated: bool) -> None:
        slot = self._slots[i]
        self._slots[i] = None
        done = time.monotonic()
        self._h_request.observe(done - slot.req.enq_t)
        if self._tracer is not None:
            # one retroactive span per finished sequence, hung under
            # the submitter's request span when it carried one
            self._tracer.record_span(
                "serve_decode", done - slot.req.enq_t,
                parent=slot.req.ctx, tokens=len(slot.generated),
                version=self._snapshot.version, truncated=truncated)
        _settle(slot.req.future,
                DecodeResult(slot.generated, self._snapshot.version,
                             truncated))

    def _step_once(self) -> None:
        live_idx = [i for i, s in enumerate(self._slots) if s is not None]
        if not live_idx:
            return
        tokens = np.zeros(self.slots, np.int32)
        positions = np.zeros(self.slots, np.int32)
        for i in live_idx:
            s = self._slots[i]
            tokens[i] = s.next_token()
            positions[i] = s.pos
        self._ensure_cache()
        t0 = time.perf_counter()
        out, self._cache = self._step_fn(self._params_dev, self._cache,
                                         tokens, positions)
        out = np.asarray(out)
        if self._tracer is not None:
            self._tracer.record_span("decode_step",
                                     time.perf_counter() - t0,
                                     live=len(live_idx))
        self.steps += 1
        self.live_steps += len(live_idx)
        self._c_steps.inc()
        self._c_tokens.inc(len(live_idx))
        self._h_occupancy.observe(len(live_idx))
        for i in live_idx:
            s = self._slots[i]
            feeding_prompt = s.pos < len(s.req.prompt) - 1
            s.pos += 1
            if feeding_prompt:
                # mid-prompt logits predict a token the prompt already
                # pins — ignored (teacher forcing)
                continue
            tok = int(out[i])
            s.generated.append(tok)
            if self.eos_id is not None and tok == self.eos_id:
                self._finish(i, truncated=False)   # a natural stop is
                #          never a truncation, even on a capped request
            elif len(s.generated) >= s.req.max_new:
                self._finish(i, truncated=s.req.capped)
            elif s.pos >= self.cache_len:   # unreachable given the
                # admission cap; kept as belt-and-braces against a
                # future admission change silently overrunning the cache
                self._finish(i, truncated=True)

    def _flush_queue(self, shed: bool, reason: str = "shutdown") -> None:
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if shed:
                _settle(req.future, exc=self._shed(reason, req.tier))

    def _run(self) -> None:
        while True:
            with self._admit_lock:
                stopped = self._stopped
            if stopped:
                break
            if not self._refresh_snapshot():
                # no model yet: requests would wait forever on an empty
                # registry — fail them the way MicroBatcher does
                self._flush_queue(shed=True, reason="no_model")
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            self._admit()
            self._note_util()
            if not any(self._slots):
                if self._swap_pending:
                    # all sequences drained: complete the barrier swap
                    self._refresh_snapshot()
                    continue
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            self._step_once()
        # shutdown: drain answers every admitted AND queued sequence
        # (the swap barrier still clears between batches), abort fails
        # them all.  _refresh_snapshot, not a _snapshot check: a stop()
        # racing the worker's FIRST loop iteration must still pin the
        # published model and honor the drain contract
        if self._drain and self._refresh_snapshot():
            self._drain_all()
        for i, s in enumerate(self._slots):
            if s is not None:
                self._slots[i] = None
                _settle(s.req.future,
                        exc=self._shed("shutdown", s.req.tier))
        self._flush_queue(shed=True)

    def _drain_all(self) -> None:
        """Run the step loop until every admitted and queued sequence
        has answered (bounded: each costs <= cache_len steps)."""
        while True:
            self._refresh_snapshot()
            self._admit()
            if not any(self._slots):
                break
            self._step_once()
