"""Live secure-aggregation round protocol over the real transport.

`secure/secagg.py` proves the ring algebra in simulation (one jit, one
process); this module is the DISTRIBUTED protocol — the practical-SecAgg
construction (Bonawitz et al. 2017) spoken over `Message` frames between
real actors, composed with the repo's admission, streaming-fold, and
observability seams (ROADMAP item 3):

* **mask agreement** — each silo of a round's masking group advertises a
  DH public key (``pk_i = g^sk_i mod p``, the binding commitment to its
  pairwise secret) plus t-of-N Shamir shares of BOTH its pairwise secret
  ``sk_i`` and its self-mask seed ``b_i`` (`field.bgw_encode`), addressed
  per peer.  The server relays: one ROSTER frame per silo carries the
  cohort's public keys and the shares addressed to it.  Pairwise seeds
  derive without any pair ever talking directly:
  ``s_ij = pk_j^sk_i = g^(sk_i*sk_j) = pk_i^sk_j`` — symmetric.
* **masked upload** — the silo quantizes its weighted update into the
  uint32 ring (clip → fixed-point; the scale auto-derives from the group
  size so the cohort sum cannot wrap — `secagg.ring_budget_scale`),
  then adds the pairwise masks (``+PRG(s_ij)`` for ``j > i``, ``−`` for
  ``j < i``) and its self-mask ``PRG(b_i)``.  The payload carries the
  masked update tree AND a masked quantized weight scalar, so the server
  recovers the exact weighted mean as ``Σ q(x_i·u_i) / Σ q(u_i)`` —
  the weight normalizer cancels in the ratio.
* **ring fold** — the server folds each admitted masked upload into
  O(model) standing uint32 state at arrival (ring addition IS the fold),
  preserving the PR 7 O(1)-memory spine; nothing cohort-sized is held.
* **unmask** — at barrier close the server asks the survivors for the
  shares it needs: self-mask-seed shares of every UPLOADER (their
  ``PRG(b_i)`` must leave the sum) and pairwise-secret shares of every
  DEAD roster member (their stray ``±PRG(s_ij)`` terms must leave the
  sum — the dropout-recovery path, fed by the straggler policy and the
  PR 1 `FailureDetector`).  Shamir reconstruction (`field.bgw_decode`)
  needs any t of the N shares, so the round survives up to
  ``len(roster) − t`` dropouts and fails LOUDLY beyond that.  A silo
  never reveals both share kinds for the same peer (revealing ``sk_j``
  AND ``b_j`` would unmask a live upload) — enforced client-side.

Threat model (the README table is the full statement): the server learns
only the cohort SUM; individual updates never cross the wire in
plaintext and a silo's masked frame is information-free without t
colluding share holders.  Share envelopes ride the server relay
UNENCRYPTED in this implementation — an actively malicious server (or an
observer of every link) could reassemble seeds; the known fix is
peer-to-peer envelope encryption under the same DH keys (a second
agreement round-trip), documented as future hardening.  The server here
is honest-but-curious: it relays envelopes without combining them.

Everything is host-side numpy at message rate (the admission-pipeline
discipline — no jit, nothing for the recompile sentry to watch); the
PRG is jax's threefry bit stream so both ends of a pair derive identical
masks on any backend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import math
import secrets as _secrets
import threading
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from fedml_tpu.obs import telemetry
from fedml_tpu.secure.field import P_DEFAULT, bgw_decode, bgw_encode
from fedml_tpu.secure.secagg import ring_budget_scale

log = logging.getLogger(__name__)

SECAGG_MODES = ("off", "pairwise", "grouped")

# message types: continue the shared numbering (cross_silo.MsgType 1-6,
# async MSG_RETASK_TICK 7, hierarchical MSG_EDGE_TIMEOUT 8)
MSG_SECAGG_ADVERT = 9   # silo -> server: pk + per-peer Shamir shares
MSG_SECAGG_ROSTER = 10  # server -> silo: cohort pks + shares addressed to it
MSG_SECAGG_UNMASK = 11  # server -> silo: survivors/dead share request
MSG_SECAGG_SHARES = 12  # silo -> server: the revealed shares

# DH generator in Z_p (p = 2^31 - 1, Mersenne).  31-bit DH is a
# protocol-shape demonstrator, not production-strength key agreement —
# the README threat model says so explicitly.
GENERATOR = 7
_P = int(P_DEFAULT)


class SecAggError(RuntimeError):
    """Loud protocol failure: too few shares to unmask, a commitment
    mismatch, or a wrapped/degenerate sum — the round is LOST, never
    silently mis-aggregated."""


# ---------------------------------------------------------------------------
# ring arithmetic helpers (host numpy; exact two's-complement fixed point)
# ---------------------------------------------------------------------------

def quantize_np(x: np.ndarray, scale: float, clip: float) -> np.ndarray:
    """Clip to ±clip, fixed-point encode into the uint32 ring (two's
    complement for negatives) — the host-numpy twin of `secagg.quantize`."""
    q = np.round(np.clip(np.asarray(x, np.float64), -clip, clip)
                 * scale).astype(np.int64).astype(np.int32)
    return q.view(np.uint32)


def dequantize_np(q: np.ndarray, scale: float) -> np.ndarray:
    return q.astype(np.uint32).view(np.int32).astype(np.float64) / scale


def _flat_leaves(tree) -> List[np.ndarray]:
    """Canonical leaf order shared with the admission pipeline (sorted
    Mapping keys), so the masked template fingerprint and the mask PRG
    walk the same sequence everywhere."""
    from fedml_tpu.robust.admission import _leaves
    return _leaves(tree)


def _tree_map_np(fn, tree):
    """Structure-preserving map over dict/list/tuple/leaf nests (the wire
    payload shapes `Message` carries) without requiring jax pytree
    registration of decoded read-only views."""
    if hasattr(tree, "items"):
        return {k: _tree_map_np(fn, v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        out = [_tree_map_np(fn, v) for v in tree]
        return tuple(out) if isinstance(tree, tuple) else out
    return fn(np.asarray(tree))


def prg_mask(seed: int, round_idx: int, shapes: List[tuple]) -> List[np.ndarray]:
    """Deterministic uint32 mask stream for one (seed, round): leaf i of
    the payload gets ``bits(fold_in(fold_in(key(seed), round), i))``.
    Both ends of a pair call this with the same seed and MUST get the
    same words — jax's threefry is deterministic across processes and
    backends, which is why this is not np.random."""
    key = jax.random.fold_in(jax.random.key(int(seed) & 0x7FFFFFFFFFFFFFFF),
                             int(round_idx) & 0xFFFFFFFF)
    out = []
    for i, shape in enumerate(shapes):
        k = jax.random.fold_in(key, i)
        out.append(np.asarray(jax.random.bits(k, shape, jax.numpy.uint32)))
    return out


def payload_scale(group_size: int, clip: float) -> float:
    """The round's fixed-point scale, derived IDENTICALLY by every
    client and server from (group size, clip).  The masked payload has
    two channels sharing one scale: the value tree (entries bounded by
    ±clip) and the weight scalar (bounded by 1.0) — the budget must hold
    for BOTH, so the bound is max(clip, 1): with a sub-1 clip the value
    channel alone would allow a scale large enough for N full weights to
    wrap the ring."""
    return ring_budget_scale(group_size, max(float(clip), 1.0))


def masked_template(params) -> Dict[str, object]:
    """The structural contract of a masked upload: the params tree with
    every leaf quantized to uint32, plus the masked weight scalar.  The
    admission pipeline fingerprints THIS (kind="masked"), so structure
    screens run pre-mask-removal exactly as the plaintext path screens
    plaintext uploads."""
    q = _tree_map_np(lambda l: np.zeros(np.shape(l), np.uint32), params)
    return {"q": q, "w": np.zeros((1,), np.uint32)}


def _apply_mask_inplace(leaves: List[np.ndarray],
                        masks: List[np.ndarray], sign: int) -> None:
    """In-place ± masks, leafwise in canonical order.  Every mask site
    owns its target exclusively — the client's payload is freshly
    quantized (nothing else references it) and the server's accumulator
    is consumed by the round's finalize — so the N-masks-per-upload and
    S+D·S-removals-per-unmask passes never pay a full-model copy each."""
    assert len(leaves) == len(masks)
    for a, m in zip(leaves, masks):
        if sign > 0:
            a += m
        else:
            a -= m


def _rebuild_like(tree, new_leaves: List[np.ndarray]):
    """Re-nest flat leaves into tree's structure (canonical key order —
    the inverse of `_flat_leaves`)."""
    pos = [0]

    def walk(t):
        if hasattr(t, "items"):
            return {k: walk(v) for k, v in
                    sorted(t.items(), key=_canon_sort_key)}
        if isinstance(t, (list, tuple)):
            out = [walk(v) for v in t]
            return tuple(out) if isinstance(t, tuple) else out
        leaf = new_leaves[pos[0]]
        pos[0] += 1
        return leaf

    return walk(tree)


def _canon_sort_key(kv):
    from fedml_tpu.robust.admission import _canon_key
    return _canon_key(kv[0])


def _commit(value: int, round_idx: int, owner: int, kind: str) -> str:
    """Binding commitment to a secret seed: published in the advert so
    a reconstruction from (possibly corrupted) shares is VERIFIED before
    its PRG is subtracted from the sum."""
    return hashlib.sha256(
        f"secagg:{kind}:{owner}:{round_idx}:{value}".encode()).hexdigest()


def _as_int_shares(shares: np.ndarray) -> List[int]:
    return [int(s) for s in np.asarray(shares).reshape(-1)]


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _ClientRound:
    round_idx: int
    group: List[int]            # sorted transport ids of the masking group
    threshold: int
    clip: float
    scale: float
    weight_cap: float
    sk: int
    b: int
    pks: Optional[Dict[int, int]] = None      # roster pks (after ROSTER)
    roster: Optional[List[int]] = None
    inbound: Optional[Dict[int, Tuple[int, int]]] = None  # peer -> (sk, b) share
    # which share KIND this client already revealed per peer this round:
    # the cross-REQUEST half of the never-both invariant (one request is
    # checked by the survivors∩dead guard; two sequential well-formed
    # requests naming the same peer differently must also be refused)
    revealed: Dict[int, str] = dataclasses.field(default_factory=dict)


class SecAggClient:
    """Silo-side protocol endpoint.

    Stateless across rounds except the current `_ClientRound`; every
    secret (``sk_i``, ``b_i``, Shamir coefficients) draws from
    ``secrets``-grade entropy unless a test injects ``rng``.  The sum is
    EXACT regardless of these draws — masks cancel bit-for-bit — so a
    federation with entropy-seeded clients still reproduces the
    plaintext mean up to quantization."""

    def __init__(self, node_id: int,
                 rng: Optional[np.random.RandomState] = None):
        self.node_id = int(node_id)
        self._rng = rng
        self._round: Optional[_ClientRound] = None
        self._advert: Optional[Dict] = None

    def _rand_field(self) -> int:
        if self._rng is not None:
            return int(self._rng.randint(1, _P))
        return _secrets.randbelow(_P - 1) + 1

    def begin_round(self, round_idx: int, info: Dict) -> Dict:
        """Open a round from the sync frame's ``ARG_SECAGG`` info and
        return the ADVERT payload: the DH public key (commitment to
        ``sk``), the self-mask-seed commitment, and per-peer Shamir
        shares of both secrets.

        Idempotent per round: a duplicated sync frame (chaos dup,
        transport retry) returns the SAME advert instead of re-keying —
        fresh keys behind an already-banked advert would desynchronize
        the masks from what the server relays, and the sum would never
        cancel."""
        r = self._round
        if r is not None and r.round_idx == int(round_idx) \
                and self._advert is not None:
            return self._advert
        group = sorted(int(s) for s in info["group"])
        if self.node_id not in group:
            raise SecAggError(f"silo {self.node_id} tasked with a masking "
                              f"group it is not a member of: {group}")
        threshold = int(info["threshold"])
        clip = float(info["clip"])
        scale = payload_scale(len(group), clip)
        sk = self._rand_field()
        b = self._rand_field()
        n = len(group)
        share_rng = (self._rng if self._rng is not None
                     else np.random.RandomState(np.random.MT19937(
                         np.random.SeedSequence(_secrets.randbits(128)))))
        sk_shares = _as_int_shares(bgw_encode(
            np.asarray([[sk]], np.int64), n, threshold - 1, rng=share_rng))
        b_shares = _as_int_shares(bgw_encode(
            np.asarray([[b]], np.int64), n, threshold - 1, rng=share_rng))
        self._round = _ClientRound(
            round_idx=int(round_idx), group=group, threshold=threshold,
            clip=clip, scale=scale, weight_cap=float(info["weight_cap"]),
            sk=sk, b=b)
        self._advert = {
            # pk doubles as the binding commitment to sk: pair-key
            # reconstructions verify g^sk_rec == pk, so no separate
            # sk commitment rides the wire
            "pk": pow(GENERATOR, sk, _P),
            "b_commit": _commit(b, round_idx, self.node_id, "b"),
            # share index = the peer's position in the sorted group
            "shares": {str(peer): [sk_shares[i], b_shares[i]]
                       for i, peer in enumerate(group)},
        }
        return self._advert

    def has_roster(self, round_idx: int) -> bool:
        r = self._round
        return (r is not None and r.round_idx == int(round_idx)
                and r.roster is not None)

    def on_roster(self, round_idx: int, payload: Dict) -> bool:
        """Bank the cohort's public keys and the shares addressed to this
        silo.  Returns False (and ignores the frame) on a stale round."""
        r = self._round
        if r is None or r.round_idx != int(round_idx):
            return False
        r.roster = sorted(int(s) for s in payload["roster"])
        r.pks = {int(k): int(v) for k, v in payload["pks"].items()}
        r.inbound = {int(k): (int(v[0]), int(v[1]))
                     for k, v in payload.get("shares", {}).items()}
        return True

    def mask(self, round_idx: int, update, num_samples: float) -> Dict:
        """Quantize the weighted update and add every mask.  The weight
        rides the ring too (``u = min(n/weight_cap, 1)`` quantized), so
        the server's recovered ratio is the exact weighted mean and the
        normalizer cancels."""
        r = self._round
        if r is None or r.round_idx != int(round_idx) or r.roster is None:
            raise SecAggError(f"mask() before a round-{round_idx} roster")
        u = min(float(num_samples) / r.weight_cap, 1.0)
        if u <= 0:
            raise SecAggError(f"non-positive masked weight {u}")
        payload = {
            "q": _tree_map_np(
                lambda l: quantize_np(l.astype(np.float64) * u,
                                      r.scale, r.clip), update),
            "w": quantize_np(np.asarray([u]), r.scale, 1.0),
        }
        leaves = _flat_leaves(payload)
        shapes = [l.shape for l in leaves]
        for peer in r.roster:
            if peer == self.node_id:
                continue
            seed = pow(r.pks[peer], r.sk, _P)
            sign = 1 if peer > self.node_id else -1
            _apply_mask_inplace(leaves, prg_mask(seed, r.round_idx, shapes),
                                sign)
        _apply_mask_inplace(leaves, prg_mask(r.b, r.round_idx, shapes), 1)
        return payload

    def reveal(self, round_idx: int, survivors, dead) -> Dict:
        """Answer an UNMASK request: the self-mask-seed shares this silo
        holds for SURVIVORS and the pairwise-secret shares for DEAD
        roster members.  Refuses — loudly — to reveal both kinds for the
        same silo: that pair of shares unmasks a live upload.  The
        refusal is STATEFUL per round: a second, individually well-formed
        request that flips a peer between the survivor and dead sets
        (a compromised/replayed UNMASK frame — legitimate re-requests
        repeat the SAME snapshot) is refused before anything leaves."""
        r = self._round
        if r is None or r.round_idx != int(round_idx) or r.inbound is None:
            raise SecAggError(f"reveal() without round-{round_idx} shares")
        survivors = {int(s) for s in survivors}
        dead = {int(s) for s in dead}
        both = survivors & dead
        if both:
            raise SecAggError(
                f"refusing unmask request naming silos {sorted(both)} as "
                f"BOTH survivor and dead: revealing sk and b together "
                f"would expose a live upload")
        want = {**{p: "b" for p in survivors}, **{p: "sk" for p in dead}}
        flipped = sorted(p for p, kind in want.items()
                         if r.revealed.get(p, kind) != kind)
        if flipped:
            raise SecAggError(
                f"refusing unmask request that flips silos {flipped} "
                f"between survivor and dead across requests: the share "
                f"pair would expose a live upload")
        out = {"b": {}, "sk": {}}
        for peer, (sk_share, b_share) in r.inbound.items():
            kind = want.get(peer)
            if kind is None:
                continue
            r.revealed[peer] = kind
            if kind == "b":
                out["b"][str(peer)] = b_share
            else:
                out["sk"][str(peer)] = sk_share
        return out


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _ServerRound:
    round_idx: int
    group: List[int]
    threshold: int
    scale: float
    adverts: Dict[int, Dict] = dataclasses.field(default_factory=dict)
    roster: Optional[List[int]] = None
    acc: Optional[Dict] = None            # running ring sum (uint32 leaves)
    folded: Dict[int, float] = dataclasses.field(default_factory=dict)
    reveals: Dict[int, Dict] = dataclasses.field(default_factory=dict)
    unmask_sent: bool = False


class SecAggServer:
    """Server/edge-side protocol endpoint: relay + ring fold + unmask.

    One instance serves one aggregation point (the flat root, or one
    edge block under ``--secagg grouped``); per-round state lives in a
    `_ServerRound` and is O(model + group) — the fold is ring addition
    into one uint32 tree at arrival, so server memory stays flat in
    cohort size (the PR 7 spine, preserved under masking).

    ``norm_screen_*``: the POST-unmask sum screen — per-silo norms are
    unavailable by construction, so the defense that remains is a
    rolling median+MAD screen over the recovered SUM's update norm (and
    the sum-level clip + weak-DP noise of ``finalize``).  The pre-mask
    screens (structure fingerprint, ``num_samples``) run in the
    admission pipeline against `masked_template`, before the fold.
    """

    def __init__(self, *, threshold: int = 0, clip: float = 2.0**14,
                 weight_cap: float = 1.0, norm_clip: float = 0.0,
                 noise_std: float = 0.0, seed: int = 0,
                 norm_screen_k: float = 6.0, norm_screen_window: int = 64,
                 norm_screen_min_history: int = 8, node: str = "server"):
        if clip <= 0:
            raise ValueError(f"clip must be > 0, got {clip}")
        if weight_cap <= 0:
            raise ValueError(f"weight_cap must be > 0, got {weight_cap}")
        if threshold < 0:
            raise ValueError(f"threshold must be >= 0 (0 = majority), "
                             f"got {threshold}")
        self.threshold_cfg = int(threshold)
        self.clip = float(clip)
        self.weight_cap = float(weight_cap)
        self.norm_clip = float(norm_clip)
        self.noise_std = float(noise_std)
        self.seed = int(seed)
        self.node = node
        self.norm_screen_k = norm_screen_k
        self.norm_screen_min_history = norm_screen_min_history
        import collections
        self._sum_norms = collections.deque(maxlen=norm_screen_window)
        self._round: Optional[_ServerRound] = None
        self._lock = threading.Lock()
        reg = telemetry.get_registry()
        self._c_masked = reg.counter("fedml_secagg_masked_uploads_total")
        self._c_share_frames = reg.counter("fedml_secagg_share_frames_total")
        # envelopes = per-pair Shamir shares relayed (inside adverts) or
        # revealed (inside unmask answers): the O(N^2) [flat] vs O(N^2/E)
        # [grouped] agreement-traffic quantity BENCH_secagg.json pins —
        # frame counts alone are O(N) either way and cannot show it
        self._c_share_env = reg.counter("fedml_secagg_share_envelopes_total")
        self._c_reconstruct = {
            kind: reg.counter("fedml_secagg_unmask_reconstructions_total",
                              kind=kind)
            for kind in ("self_mask", "pair_key")}
        self._c_rounds = reg.counter("fedml_secagg_rounds_total")
        self._c_sum_rejected = reg.counter("fedml_secagg_sum_rejected_total")
        self._h_agreement = reg.histogram("fedml_secagg_agreement_seconds")
        self._h_unmask = reg.histogram("fedml_secagg_unmask_seconds")
        self._agreement_t0: Optional[float] = None

    # -- round lifecycle -----------------------------------------------------
    def _threshold_for(self, n: int) -> int:
        t = self.threshold_cfg or (n // 2 + 1)
        return max(2, min(t, n))

    def round_start(self, round_idx: int, group) -> None:
        import time
        group = sorted(int(s) for s in group)
        if len(group) < 2:
            raise SecAggError(
                f"secure aggregation needs a masking group of >= 2 silos "
                f"(got {group}): a single member's 'sum' IS its update")
        with self._lock:
            self._round = _ServerRound(
                round_idx=int(round_idx), group=group,
                threshold=self._threshold_for(len(group)),
                scale=payload_scale(len(group), self.clip))
        self._agreement_t0 = time.perf_counter()

    def sync_info(self) -> Dict:
        """The ``ARG_SECAGG`` dict the sync broadcast ships: everything a
        client needs to agree on the round's masking parameters without
        any silo-side configuration."""
        r = self._require_round()
        return {"group": list(r.group), "threshold": r.threshold,
                "clip": self.clip, "weight_cap": self.weight_cap}

    def _require_round(self) -> _ServerRound:
        if self._round is None:
            raise SecAggError("no secagg round open")
        return self._round

    # -- mask agreement ------------------------------------------------------
    def note_advert(self, silo: int, payload: Dict) -> bool:
        """Bank one silo's advert; True when the whole group advertised
        (time to flush the roster)."""
        r = self._require_round()
        silo = int(silo)
        with self._lock:
            if silo not in r.group or r.roster is not None:
                return False
            if silo in r.adverts:
                return False  # duplicate delivery (chaos dup)
            self._c_share_frames.inc()
            self._c_share_env.inc(len(payload.get("shares", {})))
            r.adverts[silo] = {
                "pk": int(payload["pk"]),
                "b_commit": payload.get("b_commit"),
                "shares": {int(k): (int(v[0]), int(v[1]))
                           for k, v in payload.get("shares", {}).items()},
            }
            return set(r.adverts) >= set(r.group)

    def advertised(self) -> set:
        r = self._require_round()
        with self._lock:
            return set(r.adverts)

    def roster_ready(self) -> bool:
        r = self._require_round()
        return r.roster is not None

    def roster_members(self) -> List[int]:
        r = self._require_round()
        with self._lock:
            return list(r.roster or [])

    def folded_silos(self) -> List[int]:
        r = self._require_round()
        with self._lock:
            return sorted(r.folded)

    def flush_roster(self, subset=None) -> Dict[int, Dict]:
        """Fix the round's roster (everyone who advertised, or a subset)
        and build each member's ROSTER frame: the cohort pks + the
        shares every peer addressed to it.  Needs >= threshold members —
        below that the unmask phase could never reconstruct."""
        import time
        r = self._require_round()
        with self._lock:
            members = sorted(set(subset) if subset is not None
                             else set(r.adverts))
            members = [m for m in members if m in r.adverts]
            if len(members) < r.threshold:
                raise SecAggError(
                    f"cannot fix a roster of {len(members)} members below "
                    f"the share threshold t={r.threshold}: the round could "
                    f"never be unmasked")
            r.roster = members
            out = {}
            for m in members:
                out[m] = {
                    "roster": list(members),
                    "pks": {str(i): r.adverts[i]["pk"] for i in members},
                    "shares": {str(i): list(r.adverts[i]["shares"][m])
                               for i in members if m in r.adverts[i]["shares"]},
                }
        if self._agreement_t0 is not None:
            self._h_agreement.observe(time.perf_counter()
                                      - self._agreement_t0)
        return out

    # -- ring fold -----------------------------------------------------------
    def fold(self, silo: int, payload, num_samples: float) -> None:
        """Fold one ADMITTED masked upload at arrival: leafwise uint32
        ring addition into O(model) standing state (the streaming-fold
        seam of `core/stream_agg.py`, in the ring)."""
        r = self._require_round()
        silo = int(silo)
        with self._lock:
            if r.roster is None or silo not in r.roster:
                raise SecAggError(
                    f"masked upload from silo {silo} outside the round's "
                    f"roster {r.roster}")
            if silo in r.folded:
                return  # duplicate delivery already folded
            leaves = [np.asarray(l) for l in _flat_leaves(payload)]
            if r.acc is None:
                r.acc = _rebuild_like(
                    payload, [l.astype(np.uint32, copy=True) for l in leaves])
            else:
                acc_leaves = _flat_leaves(r.acc)
                for a, l in zip(acc_leaves, leaves):
                    a += l.astype(np.uint32)  # in-place ring add
            r.folded[silo] = float(num_samples)
            self._c_masked.inc()

    @property
    def count(self) -> int:
        r = self._round
        return len(r.folded) if r is not None else 0

    @property
    def weight_total(self) -> float:
        """Plaintext sum of the admitted sample counts (ledger / edge
        frame bookkeeping; the AGGREGATION divisor is the masked weight
        sum recovered at finalize)."""
        r = self._round
        return float(sum(r.folded.values())) if r is not None else 0.0

    # -- unmask --------------------------------------------------------------
    def unmask_request(self) -> Tuple[List[int], List[int]]:
        """(survivors, dead): uploaders whose self-masks must be removed,
        and roster members that never uploaded whose stray pairwise
        masks must be reconstructed away."""
        r = self._require_round()
        with self._lock:
            r.unmask_sent = True
            survivors = sorted(r.folded)
            dead = sorted(set(r.roster or []) - set(r.folded))
            return survivors, dead

    def note_reveal(self, silo: int, payload: Dict) -> bool:
        """Bank one survivor's revealed shares; True when every survivor
        has answered (finalize may also proceed earlier once
        `can_finalize`)."""
        r = self._require_round()
        silo = int(silo)
        with self._lock:
            if silo not in r.folded or silo in r.reveals:
                return False
            self._c_share_frames.inc()
            self._c_share_env.inc(len(payload.get("b", {}))
                                  + len(payload.get("sk", {})))
            r.reveals[silo] = {
                "b": {int(k): int(v)
                      for k, v in payload.get("b", {}).items()},
                "sk": {int(k): int(v)
                       for k, v in payload.get("sk", {}).items()},
            }
            return set(r.reveals) >= set(r.folded)

    def can_finalize(self) -> bool:
        r = self._require_round()
        with self._lock:
            return len(r.reveals) >= r.threshold

    def _reconstruct(self, owner: int, kind: str, r: _ServerRound) -> int:
        """Shamir-reconstruct one silo's secret from the revealed shares
        and VERIFY it against the advert's commitment."""
        key = "b" if kind == "self_mask" else "sk"
        pairs = []  # (position in group, share)
        for responder, reveal in r.reveals.items():
            share = reveal[key].get(owner)
            if share is not None:
                pairs.append((r.group.index(responder), share))
        if len(pairs) < r.threshold:
            raise SecAggError(
                f"cannot reconstruct {kind} of silo {owner}: "
                f"{len(pairs)} shares revealed, threshold t={r.threshold} "
                f"— too many dropouts for the configured tolerance")
        pairs = pairs[:r.threshold]
        idx = [p for p, _ in pairs]
        shares = np.asarray([[[s]] for _, s in pairs], np.int64)
        value = int(bgw_decode(shares, idx)[0, 0])
        advert = r.adverts[owner]
        if kind == "self_mask":
            want = advert.get("b_commit")
            got = _commit(value, r.round_idx, owner, "b")
            if want is not None and got != want:
                raise SecAggError(
                    f"self-mask seed of silo {owner} reconstructed to a "
                    f"value that does not match its advert commitment — "
                    f"corrupted or forged shares; refusing to unmask")
        else:
            if pow(GENERATOR, value, _P) != advert["pk"]:
                raise SecAggError(
                    f"pairwise secret of silo {owner} reconstructed to a "
                    f"value whose public key does not match its advert — "
                    f"corrupted or forged shares; refusing to unmask")
        self._c_reconstruct[kind].inc()
        return value

    def finalize(self, reference=None) -> Tuple[object, float]:
        """Remove every residual mask from the ring sum, dequantize, and
        return ``(weighted_mean_tree, recovered_weight_sum)``.

        ``reference``: the round's global params (host tree).  When set,
        the post-unmask defenses run ON THE SUM: the rolling median+MAD
        norm screen over ``||mean − reference||`` (a breached round
        returns ``(None, 0.0)`` and counts
        ``fedml_secagg_sum_rejected_total`` — the global stays put), then
        sum-level norm clipping and weak-DP noise when configured."""
        import time
        t0 = time.perf_counter()
        r = self._require_round()
        with self._lock:
            if not r.folded:
                raise SecAggError("finalize() with no folded uploads")
            survivors = sorted(r.folded)
            dead = sorted(set(r.roster) - set(r.folded))
            acc = r.acc
            acc_leaves = _flat_leaves(acc)
            shapes = [l.shape for l in acc_leaves]
            # survivors' self-masks leave the sum (in place: the acc is
            # server-owned and consumed by this round's finalize)
            for silo in survivors:
                b = self._reconstruct(silo, "self_mask", r)
                _apply_mask_inplace(acc_leaves,
                                    prg_mask(b, r.round_idx, shapes), -1)
            # dead roster members' stray pairwise masks leave the sum:
            # uploader i carried sign_i(j)*PRG(s_ij) for dead j
            for j in dead:
                sk_j = self._reconstruct(j, "pair_key", r)
                for i in survivors:
                    s_ij = pow(r.adverts[i]["pk"], sk_j, _P)
                    sign = 1 if j > i else -1
                    _apply_mask_inplace(
                        acc_leaves, prg_mask(s_ij, r.round_idx, shapes),
                        -sign)
            num = _tree_map_np(lambda l: dequantize_np(l, r.scale),
                               acc["q"])
            den = float(dequantize_np(np.asarray(acc["w"]), r.scale)[0])
            self._c_rounds.inc()
        if den <= 0 or not math.isfinite(den):
            raise SecAggError(
                f"unmasked weight sum {den} is not positive — the ring "
                f"sum wrapped or the unmask removed the wrong masks; "
                f"refusing to publish a corrupted aggregate")
        mean = _tree_map_np(lambda l: (l / den).astype(np.float32), num)
        if reference is not None:
            mean = self._post_unmask_defenses(mean, reference, r.round_idx)
        self._h_unmask.observe(time.perf_counter() - t0)
        return mean, den

    # -- post-unmask sum defenses -------------------------------------------
    def _post_unmask_defenses(self, mean, reference, round_idx: int):
        """The norm screen and defended finalize, on the SUM only (the
        per-upload versions are unavailable by construction under
        masking)."""
        ref_leaves = [np.asarray(l, np.float64)
                      for l in _flat_leaves(reference)]
        mean_leaves = [np.asarray(l, np.float64)
                       for l in _flat_leaves(mean)]
        delta = [m - g for m, g in zip(mean_leaves, ref_leaves)]
        norm = math.sqrt(sum(float(np.sum(d * d)) for d in delta))
        thresh = self._sum_norm_threshold()
        if thresh is not None and norm > thresh:
            self._c_sum_rejected.inc()
            log.warning("secagg round %d: recovered sum norm %.4g beyond "
                        "the rolling screen threshold %.4g — round "
                        "DISCARDED, global unchanged", round_idx, norm,
                        thresh)
            return None
        self._sum_norms.append(norm)
        if self.norm_clip > 0 and norm > self.norm_clip:
            factor = self.norm_clip / norm
            delta = [d * factor for d in delta]
        if self.noise_std > 0:
            key = jax.random.fold_in(jax.random.key(self.seed),
                                     int(round_idx) & 0xFFFFFFFF)
            noisy = []
            for i, d in enumerate(delta):
                k = jax.random.fold_in(key, i)
                noisy.append(d + self.noise_std * np.asarray(
                    jax.random.normal(k, d.shape), np.float64))
            delta = noisy
        if self.norm_clip > 0 or self.noise_std > 0:
            out = [(g + d).astype(np.float32)
                   for g, d in zip(ref_leaves, delta)]
            return _rebuild_like(mean, out)
        return mean

    def _sum_norm_threshold(self) -> Optional[float]:
        if len(self._sum_norms) < self.norm_screen_min_history:
            return None
        arr = np.asarray(self._sum_norms, np.float64)
        med = float(np.median(arr))
        mad = float(np.median(np.abs(arr - med)))
        return med + self.norm_screen_k * max(mad, 0.05 * med, 1e-12)
