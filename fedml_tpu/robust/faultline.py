"""Process-level fault injection: named crash points + seeded kills +
disk faults.

`comm/chaos.py` perturbs the WIRE (drop/delay/dup/reorder/partition/
corrupt); nothing in the tree could exercise process death, disk
faults, or crash-at-a-point — exactly the failure modes a server
resuming mid-round (utils/journal.py) must be proven against.  This
module is the process-level twin, with the same determinism contract as
`ChaosTransport` so schedules replay:

* **Crash points** — a closed registry of named sites threaded through
  the live round loop (`cross_silo.py`, `async_fl.py`,
  `hierarchical.py`).  `Faultline.maybe_crash(point, ...)` counts every
  arrival deterministically (the event loop is single-threaded) and
  raises `ActorKilled` when a `CrashSpec` matches — the exception
  derives from **BaseException** so no ``except Exception`` guard on
  the receive path can accidentally "survive" a kill -9: it propagates
  out of the event loop with no FINISH, no cleanup, no checkpoint
  flush, exactly like a real SIGKILL.
* **Seeded random kills** — ``kill_rate`` draws one uniform per
  crash-point arrival from a seeded RNG: same seed + same message
  schedule = same kill schedule (the soak campaign's replay contract).
* **Disk faults** — `DiskFaultSpec`/`DiskFaultInjector` inject
  ENOSPC/EIO (or a TORN write: a partial prefix lands, then the error)
  into named writer channels (``perf_ledger`` / ``health_ledger`` /
  ``journal`` / ``journal_snapshot``) via the
  `utils.journal.install_disk_faults` seam every ledger writer routes
  through.

In-process respawn: the soak harness (scripts/soak.py) and
tests/test_crash_recovery.py catch `ActorKilled` out of the transport
drive, call `Faultline.respawn()` (fired specs stay fired — one spec,
one kill), cancel the dead actor's timers (a real process's timer
threads die with it), and rebuild the actor from its checkpoint +
journal on a fresh transport endpoint.
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
from typing import Dict, Optional, Sequence

import numpy as np

from fedml_tpu.obs import telemetry
from fedml_tpu.utils import journal as _journal

log = logging.getLogger(__name__)

# the closed registry of named crash sites on the live round loop; a
# spec naming anything else is a config error, caught at construction
CRASH_POINTS = (
    "post_admission_pre_fold",   # upload admitted, fold not yet applied
    "post_fold_pre_ack",         # fold applied, report not yet recorded
    "mid_checkpoint_write",      # barrier closed, checkpoint not durable
    "mid_unmask",                # secagg: share reveals collected, sum
    #                              not yet recovered (abort-only proof)
    "barrier_close",             # the round barrier just satisfied
    "publish",                   # checkpoint durable, publish pending
    "canary_promote",            # release gate: verdict passed — fired
    #                              BEFORE and AFTER the atomic registry
    #                              promote (hit 1 = pre, hit 2 = post),
    #                              so a respawn sees exactly one of the
    #                              two consistent states, never between
    "canary_rollback",           # release gate: verdict failed — fired
    #                              around the canary discard the same way
)

# writer channels the disk-fault seam can hit (utils/journal callers)
DISK_CHANNELS = ("perf_ledger", "health_ledger", "journal",
                 "journal_snapshot", "checkpoint_manifest",
                 "release_journal")


class ActorKilled(BaseException):
    """Stands in for ``kill -9``: raised out of the actor's event loop
    with NO cleanup.  Derives from BaseException so broad ``except
    Exception`` guards on the receive path (decode fallbacks, heartbeat
    loops) cannot swallow a kill."""

    def __init__(self, point: str, round_idx=None, hit: int = 0):
        super().__init__(f"injected kill at crash point {point!r} "
                         f"(round={round_idx}, hit={hit})")
        self.point = point
        self.round_idx = round_idx
        self.hit = hit


@dataclasses.dataclass
class CrashSpec:
    """Kill the actor at the ``hit``-th arrival at ``point`` (1-based;
    arrivals filtered to ``round_idx`` when set).  Each spec fires at
    most ONCE per `Faultline` — a respawned actor survives the site it
    died at, so the campaign makes progress."""
    point: str
    hit: int = 1
    round_idx: Optional[int] = None

    def __post_init__(self):
        if self.point not in CRASH_POINTS:
            raise ValueError(f"unknown crash point {self.point!r}; "
                             f"registered: {CRASH_POINTS}")
        if self.hit < 1:
            raise ValueError(f"hit is 1-based, got {self.hit}")


class Faultline:
    """Deterministic, seeded crash scheduler threaded through the live
    actors (``faultline=`` parameter).  ``maybe_crash`` is a cheap no-op
    when no specs and no ``kill_rate`` are armed, so production runs
    pay one attribute check per site."""

    def __init__(self, crashes: Sequence[CrashSpec] = (),
                 kill_rate: float = 0.0, seed: int = 0,
                 node: str = "server"):
        if not 0.0 <= kill_rate < 1.0:
            raise ValueError(f"kill_rate must be in [0, 1), got "
                             f"{kill_rate}")
        self.specs = [s if isinstance(s, CrashSpec) else CrashSpec(**s)
                      for s in crashes]
        self.kill_rate = float(kill_rate)
        self.node = node
        self._rng = np.random.RandomState(
            (int(seed) * 1_000_003 + 17) % (2 ** 32))
        self._fired = [False] * len(self.specs)
        self._hits: Dict[tuple, int] = {}   # (point, round_key) -> count
        self.kills = 0
        self.respawns = 0
        reg = telemetry.get_registry()
        self._m_kills = {p: reg.counter("fedml_fault_kills_total", point=p)
                         for p in CRASH_POINTS}
        self._c_respawns = reg.counter("fedml_fault_respawns_total")

    @property
    def armed(self) -> bool:
        return bool(self.specs) or self.kill_rate > 0

    def maybe_crash(self, point: str, round_idx=None, **ctx) -> None:
        """Count one arrival at ``point``; raise `ActorKilled` when a
        spec (or the seeded random schedule) says so."""
        if not self.armed:
            return
        if point not in CRASH_POINTS:
            raise ValueError(f"unregistered crash point {point!r}")
        # per-(point, round) AND per-point arrival counters: specs with a
        # round filter count hits within their round, unfiltered specs
        # count global arrivals at the point
        key_any = (point, None)
        self._hits[key_any] = self._hits.get(key_any, 0) + 1
        if round_idx is not None:
            key_r = (point, int(round_idx))
            self._hits[key_r] = self._hits.get(key_r, 0) + 1
        for i, spec in enumerate(self.specs):
            if self._fired[i] or spec.point != point:
                continue
            if spec.round_idx is not None:
                if round_idx is None or int(round_idx) != spec.round_idx:
                    continue
                hits = self._hits[(point, spec.round_idx)]
            else:
                hits = self._hits[key_any]
            if hits == spec.hit:
                self._fired[i] = True
                self._kill(point, round_idx, hits)
        if self.kill_rate > 0:
            # one fixed-size draw per arrival, in arrival order — the
            # ChaosTransport determinism contract: same seed + same
            # message schedule = same kill schedule
            if float(self._rng.uniform()) < self.kill_rate:
                self._kill(point, round_idx, self._hits[key_any])

    def _kill(self, point: str, round_idx, hit: int) -> None:
        self.kills += 1
        self._m_kills[point].inc()
        log.warning("faultline[%s]: KILLING actor at %s (round=%s, "
                    "hit=%d)", self.node, point, round_idx, hit)
        raise ActorKilled(point, round_idx=round_idx, hit=hit)

    def respawn(self) -> None:
        """Mark one in-process respawn (the harness calls this when it
        rebuilds a killed actor).  Fired specs stay fired."""
        self.respawns += 1
        self._c_respawns.inc()


# ---------------------------------------------------------------------------
# disk faults
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DiskFaultSpec:
    """Inject one OSError into the ``hit``-th write on ``channel``.
    ``torn=True`` writes a partial prefix of the payload before raising
    (append channels only) — the torn-tail case every ledger reader must
    tolerate.  Fires at most once."""
    channel: str
    hit: int = 1
    err: int = errno.ENOSPC
    torn: bool = False

    def __post_init__(self):
        if self.channel not in DISK_CHANNELS:
            raise ValueError(f"unknown disk channel {self.channel!r}; "
                             f"registered: {DISK_CHANNELS}")
        if self.hit < 1:
            raise ValueError(f"hit is 1-based, got {self.hit}")


class DiskFaultInjector:
    """The hook `utils.journal.install_disk_faults` installs: counts
    writes per channel and raises the scheduled OSErrors.  ``install()``
    wires it process-wide; ``remove()`` (or
    `utils.journal.clear_disk_faults`) restores clean disks — tests use
    try/finally."""

    def __init__(self, specs: Sequence[DiskFaultSpec] = ()):
        self.specs = [s if isinstance(s, DiskFaultSpec)
                      else DiskFaultSpec(**s) for s in specs]
        self._fired = [False] * len(self.specs)
        self._hits: Dict[str, int] = {}
        self.injected = 0
        reg = telemetry.get_registry()
        self._m_disk = {c: reg.counter("fedml_fault_disk_faults_total",
                                       channel=c) for c in DISK_CHANNELS}

    def __call__(self, channel: str, path: str, data) -> None:
        self._hits[channel] = self._hits.get(channel, 0) + 1
        for i, spec in enumerate(self.specs):
            if self._fired[i] or spec.channel != channel:
                continue
            if self._hits[channel] != spec.hit:
                continue
            self._fired[i] = True
            self.injected += 1
            self._m_disk[channel].inc()
            if spec.torn and isinstance(data, str) and data:
                # land a torn prefix, then fail — the reader-side
                # torn-tail contract's sparring partner
                try:
                    with open(path, "a") as f:
                        f.write(data[:max(1, len(data) // 2)])
                except OSError:
                    pass
            log.warning("disk fault: injecting %s into channel %r "
                        "(write #%d%s)", errno.errorcode.get(spec.err,
                                                             spec.err),
                        channel, spec.hit,
                        ", torn" if spec.torn else "")
            raise OSError(spec.err, f"injected disk fault on {channel}",
                          path)

    def install(self) -> "DiskFaultInjector":
        _journal.install_disk_faults(self)
        return self

    def remove(self) -> None:
        _journal.clear_disk_faults()


def kill_actor(actor) -> None:
    """Emulate the machine-level aftermath of a kill -9 on an IN-PROCESS
    actor: a real process's timer/heartbeat threads die with it, but an
    in-process 'corpse' would keep firing timers into the transport of
    its successor.  Cancels every known timer WITHOUT running finish()
    (no FINISH frames, no checkpoint flush — the dead say nothing)."""
    for attr in ("_timer", "_retask_timer"):
        t = getattr(actor, attr, None)
        if t is not None:
            try:
                t.cancel(join=True)
            except Exception:  # noqa: BLE001 — best-effort corpse cleanup
                pass
    stop = getattr(actor, "_hb_stop", None)
    if stop is not None:
        stop.set()
