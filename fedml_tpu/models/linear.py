"""Logistic regression (parity: fedml_api/model/linear/lr.py:4-14).

The reference applies a sigmoid to the linear output *and then* feeds it to
``nn.CrossEntropyLoss`` (MyModelTrainer.train) — i.e. the sigmoid outputs are
used as logits.  We reproduce that exactly so MNIST-LR accuracy curves match
(the same mild logit squashing happens in both)."""

import flax.linen as nn
import jax.numpy as jnp


class LogisticRegression(nn.Module):
    input_dim: int
    output_dim: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.sigmoid(nn.Dense(self.output_dim)(x))
