"""Federation health observatory (obs/health.py) — ISSUE 9.

The load-bearing pins:

* Welford moments agree with numpy on random streams, and Chan's merge
  (the per-edge rollup combine) agrees with one pass over the union;
* stream and stack agg modes emit IDENTICAL health lines on the
  defended-mean path (same stats from the scan and the fold);
* per-silo fairness counters track quarantine and straggler drops;
* the edge topology's per-frame rollups merge to the flat run's norm
  moments, and the tree stays one-frame-per-round;
* the ledger keeps the torn-tail-tolerant O_APPEND contract and the
  trend gate rejects a malformed ledger;
* alarm threshold edges (breach strictly-above, ok at the threshold);
* the health path is host-side numpy — no jitted stat exists to
  recompile (pinned against the recompile sentry's registry).
"""

import json
import math

import numpy as np
import pytest

import jax

from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                             FedAvgServerActor, MsgType)
from fedml_tpu.algorithms.hierarchical import EdgeAggregatorActor
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import Message
from fedml_tpu.core.stream_agg import StreamingAggregator
from fedml_tpu.obs.health import (HEALTH_SLOS, HealthAccumulator, Welford,
                                  _sketch_f32, merge_moments)
from fedml_tpu.robust import (AdmissionPipeline, Attack, TrustTracker,
                              make_defended_aggregate,
                              make_malicious_train_fn)


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)}}


# ---------------------------------------------------------------------------
# the moments themselves
# ---------------------------------------------------------------------------

class TestWelford:
    @pytest.mark.parametrize("seed,n", [(0, 1), (1, 2), (2, 50), (3, 997)])
    def test_matches_numpy_on_random_streams(self, seed, n):
        vals = np.random.RandomState(seed).lognormal(0, 2, n)
        w = Welford()
        for v in vals:
            w.push(float(v))
        assert w.count == n
        assert w.mean == pytest.approx(vals.mean(), rel=1e-12)
        assert w.var == pytest.approx(vals.var(), rel=1e-9, abs=1e-12)
        assert w.std == pytest.approx(vals.std(), rel=1e-9, abs=1e-12)
        assert w.min == vals.min() and w.max == vals.max()

    def test_empty_summary_is_nulls(self):
        s = Welford().summary()
        assert s == {"count": 0, "mean": None, "std": None,
                     "min": None, "max": None}

    def test_merge_moments_equals_one_pass_over_the_union(self):
        rng = np.random.RandomState(7)
        chunks = [rng.rand(n) * 10 for n in (5, 1, 17, 40)]
        summaries = []
        for c in chunks:
            w = Welford()
            for v in c:
                w.push(float(v))
            summaries.append(w.summary())
        merged = merge_moments(summaries)
        union = np.concatenate(chunks)
        assert merged["count"] == union.size
        assert merged["mean"] == pytest.approx(union.mean(), rel=1e-12)
        assert merged["std"] == pytest.approx(union.std(), rel=1e-9)
        assert merged["min"] == union.min()
        assert merged["max"] == union.max()
        # empty / null summaries merge as absence, not as zeros
        assert merge_moments(summaries + [Welford().summary(), {}]) == merged


def test_sketch_is_deterministic_and_rescales_norms():
    rng = np.random.RandomState(3)
    tree = {"a": rng.randn(1000).astype(np.float32),
            "b": rng.randn(3000).astype(np.float32)}
    full, s_full = _sketch_f32(tree, 0)
    assert s_full == 1.0 and full.size == 4000
    sk1, scale = _sketch_f32(tree, 400)
    sk2, scale2 = _sketch_f32(tree, 400)
    np.testing.assert_array_equal(sk1, sk2)
    assert scale == scale2 > 1.0
    # proportional prefixes: each leaf contributes ~size*cap/total
    assert sk1.size == 1000 * 400 // 4000 + 3000 * 400 // 4000
    # rescaled sketch norm estimates the full norm (generic vector)
    est = float(np.linalg.norm(sk1)) * scale
    true = float(np.linalg.norm(full))
    assert est == pytest.approx(true, rel=0.15)


# ---------------------------------------------------------------------------
# the accumulator unit protocol
# ---------------------------------------------------------------------------

def _obs(h, silo, tree, w, **kw):
    h.observe_admitted(silo, tree, w, **kw)


class TestAccumulator:
    def test_norm_moments_and_alignment(self, tmp_path):
        h = HealthAccumulator(ledger_path=str(tmp_path / "health.jsonl"))
        ref = {"a": np.zeros(8, np.float32)}
        h.round_start(0, ref, expected=[1, 2, 3])
        d1 = {"a": np.ones(8, np.float32)}
        d2 = {"a": np.full(8, 2.0, np.float32)}       # same direction
        d3 = {"a": -np.ones(8, np.float32)}           # anti-aligned
        _obs(h, 1, d1, 10.0)
        _obs(h, 2, d2, 10.0)
        _obs(h, 3, d3, 10.0)
        line = h.round_end(0, new_global=d1)
        norms = [math.sqrt(8), 2 * math.sqrt(8), math.sqrt(8)]
        assert line["norm"]["count"] == 3
        assert line["norm"]["mean"] == pytest.approx(np.mean(norms))
        assert line["norm"]["std"] == pytest.approx(np.std(norms))
        # alignment observed from the 2nd upload on: cos(d2, d1)=1,
        # cos(d3, d1*10+d2*10)=-1
        assert line["alignment"]["count"] == 2
        assert line["alignment"]["mean"] == pytest.approx(0.0, abs=1e-6)
        assert line["alignment"]["min"] == pytest.approx(-1.0)
        assert line["global_delta_norm"] == pytest.approx(math.sqrt(8))
        assert line["weight"] == pytest.approx(30.0)
        # the admission-verdict norm is banked verbatim, not recomputed
        h.round_start(1, ref, expected=[1])
        _obs(h, 1, d1, 1.0, norm=123.5)
        line = h.round_end(1, new_global=ref)
        assert line["norm"]["mean"] == pytest.approx(123.5)

    def test_delta_kind_reads_uploads_raw(self):
        h = HealthAccumulator(kind="delta", alarms=False)
        h.round_start(0, {"a": np.full(4, 7.0, np.float32)})
        _obs(h, 1, {"a": np.ones(4, np.float32)}, 5.0, staleness=2)
        line = h.round_end(0, new_global={"a": np.full(4, 7.5, np.float32)})
        assert line["norm"]["mean"] == pytest.approx(2.0)  # ||ones(4)||
        assert line["staleness"]["mean"] == 2.0
        # the reference still anchors the round-over-round delta norm
        assert line["global_delta_norm"] == pytest.approx(1.0)

    def test_fairness_counters_under_drop_reject_exclusion(self):
        h = HealthAccumulator(alarms=False)
        ref = {"a": np.zeros(2, np.float32)}
        up = {"a": np.ones(2, np.float32)}
        for r in range(3):
            h.round_start(r, ref, expected=[1, 2, 3], excluded=[4])
            _obs(h, 1, up, 1.0)
            h.observe_rejected(2, "nonfinite")
            # silo 3 never reports (straggler drop)
            h.round_end(r, new_global=ref)
        silos = h.per_silo()
        assert silos[1]["accepted"] == 3 and silos[1]["rounds_since_accept"] == 0
        assert silos[2]["rejected"] == 3 and silos[2]["accepted"] == 0
        assert silos[2]["rounds_since_accept"] == 3
        assert silos[3]["dropped"] == 3 and silos[3]["tasked"] == 3
        assert silos[4]["excluded"] == 3 and silos[4]["tasked"] == 0
        # starvation: 3 of 4 known silos (2 rejected, 3 dropped,
        # 4 excluded) have gone starve_after=3 rounds without an accept
        line = h.last_line
        assert line["alarms"]["participation_starvation"]["value"] \
            == pytest.approx(0.75)

    def test_alarm_threshold_edges(self):
        # at the threshold = ok; strictly above = breach (and only
        # breaches tick the counter)
        from fedml_tpu.obs.telemetry import TelemetryRegistry
        reg = TelemetryRegistry()
        h = HealthAccumulator(thresholds={"health_starvation_ratio": 0.5},
                              starve_after=1, registry=reg)
        ref = {"a": np.zeros(2, np.float32)}
        up = {"a": np.ones(2, np.float32)}
        h.round_start(0, ref, expected=[1, 2])
        _obs(h, 1, up, 1.0)
        _obs(h, 2, up, 1.0)
        h.round_end(0, new_global=ref)       # starvation 0/2 -> ok
        h.round_start(1, ref, expected=[1, 2])
        _obs(h, 1, up, 1.0)
        line = h.round_end(1, new_global=ref)  # 1/2 == threshold -> ok
        assert line["alarms"]["participation_starvation"]["value"] == 0.5
        assert line["alarms"]["participation_starvation"]["ok"]
        h.round_start(2, ref, expected=[1, 2])
        line = h.round_end(2, new_global=ref)  # 2/2 > threshold -> breach
        assert not line["alarms"]["participation_starvation"]["ok"]
        snap = reg.snapshot()
        breaches = {k: v for k, v in snap["counters"].items()
                    if k.startswith("fedml_health_breaches_total")}
        assert breaches[
            'fedml_health_breaches_total{alarm="participation_starvation"}'
        ] == 1

    def test_unknown_threshold_name_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown health"):
            HealthAccumulator(thresholds={"typo_ratio": 1.0})
        assert set(HEALTH_SLOS) == {
            "health_misalignment_ratio", "health_norm_cv_ratio",
            "health_starvation_ratio"}

    def test_nonfinite_values_ledger_as_null_not_nan(self, tmp_path):
        path = tmp_path / "health.jsonl"
        h = HealthAccumulator(ledger_path=str(path), alarms=False)
        h.round_start(0, {"a": np.zeros(2, np.float32)}, expected=[1])
        _obs(h, 1, {"a": np.ones(2, np.float32)}, 1.0, norm=float("inf"))
        h.round_end(0)
        line = json.loads(path.read_text())
        assert line["norm"]["count"] == 0  # the inf norm never banked
        json.dumps(line, allow_nan=False)  # strictly valid JSON

    def test_ledger_rotates_prev_run_aside(self, tmp_path):
        path = tmp_path / "health.jsonl"
        path.write_text('{"round": 99}\n')
        h = HealthAccumulator(ledger_path=str(path))
        h.round_start(0, {"a": np.zeros(2, np.float32)})
        h.round_end(0)
        assert (tmp_path / "health.jsonl.prev").read_text() \
            == '{"round": 99}\n'
        assert json.loads(path.read_text())["round"] == 0

    def test_no_jitted_stat_exists_to_recompile(self):
        """The health path is host-side numpy by design: it exposes no
        _cache_size probe, so the recompile sentry has nothing to watch
        — and a full round protocol triggers zero jax compilation."""
        h = HealthAccumulator(alarms=False)
        assert not hasattr(h, "_cache_size")
        ref = {"a": np.zeros(64, np.float32)}
        with jax.checking_leaks():
            for r in range(3):
                h.round_start(r, ref, expected=[1])
                _obs(h, 1, {"a": np.ones(64, np.float32)}, 1.0)
                h.round_end(r, new_global=ref)
        from fedml_tpu.obs.perf import RecompileSentry
        assert RecompileSentry().register("health", h) is False


# ---------------------------------------------------------------------------
# torn tail + schema gate
# ---------------------------------------------------------------------------

class TestLedgerContracts:
    def _lines(self, tmp_path, rounds=3):
        path = tmp_path / "health.jsonl"
        h = HealthAccumulator(ledger_path=str(path), alarms=False)
        ref = {"a": np.zeros(4, np.float32)}
        for r in range(rounds):
            h.round_start(r, ref, expected=[1, 2])
            _obs(h, 1, {"a": np.ones(4, np.float32)}, 1.0)
            _obs(h, 2, {"a": np.full(4, 1.5, np.float32)}, 2.0)
            h.round_end(r, new_global=ref)
        return path

    def test_torn_tail_is_tolerated_by_every_reader(self, tmp_path):
        from fedml_tpu.obs.report import load_jsonl
        from fedml_tpu.obs.trend import load_ledger, validate_health_ledger
        path = self._lines(tmp_path)
        with open(path, "a") as f:
            f.write('{"round": 3, "uploads": 2, "torn...')
        assert len(load_jsonl(str(path))) == 3
        rows = load_ledger(str(path))
        assert len(rows) == 3
        assert validate_health_ledger(rows) == []

    def test_malformed_mid_ledger_fails_loudly(self, tmp_path):
        from fedml_tpu.obs.trend import load_ledger
        path = self._lines(tmp_path)
        lines = path.read_text().splitlines()
        lines.insert(1, "{broken")
        path.write_text("\n".join(lines) + "\n")
        with pytest.raises(ValueError, match="malformed"):
            load_ledger(str(path))

    def test_schema_gate_names_missing_fields(self, tmp_path):
        from fedml_tpu.obs.trend import load_ledger, validate_health_ledger
        path = self._lines(tmp_path)
        rows = load_ledger(str(path))
        del rows[1]["norm"]
        rows[2]["alarms"] = {"x": "not-a-verdict"}
        problems = validate_health_ledger(rows)
        assert any("missing 'norm'" in p for p in problems)
        assert any("without ok/threshold" in p for p in problems)
        assert validate_health_ledger([]) == ["health ledger is empty"]

    def test_trend_cli_gates_health_ledger(self, tmp_path, capsys):
        from fedml_tpu.obs import trend
        path = self._lines(tmp_path)
        assert trend.main(["--health_ledger", str(path)]) == 0
        rows = [json.loads(l) for l in path.read_text().splitlines()]
        del rows[0]["alarms"]
        bad = tmp_path / "bad.jsonl"
        bad.write_text("".join(json.dumps(r) + "\n" for r in rows))
        assert trend.main(["--health_ledger", str(bad)]) == 1
        assert trend.main(["--health_ledger",
                           str(tmp_path / "nope.jsonl")]) == 2


# ---------------------------------------------------------------------------
# live federation: stream == stack health lines, quarantine fairness
# ---------------------------------------------------------------------------

def _drift_train_fn(scale=0.01):
    def fn(params, client_idx, round_idx):
        return (jax.tree.map(
            lambda v: np.asarray(v)
            + np.float32(scale * (client_idx + 1)), params),
            10 * (client_idx + 1))
    return fn


def _run_sync(mode, tmp_path, name, n_silos=4, n_rounds=3, admission=None,
              attack=None, attacker=2, deaf=(), norm_clip=5.0):
    hub = LocalHub(codec_roundtrip=True)
    init = _params()
    health = HealthAccumulator(
        ledger_path=str(tmp_path / f"{name}.jsonl"))
    kw = {}
    if mode == "stream":
        kw["stream_agg"] = StreamingAggregator(init, method="mean",
                                               norm_clip=norm_clip)
    else:
        kw["aggregate_fn"] = make_defended_aggregate("mean",
                                                     norm_clip=norm_clip)
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=n_silos,
        client_num_per_round=n_silos, num_rounds=n_rounds,
        admission=admission, health=health,
        straggler_policy="drop" if deaf else "wait",
        round_timeout_s=3600 if deaf else None, min_silo_frac=0.5, **kw)
    server.register_handlers()
    silos = []
    for i in range(1, n_silos + 1):
        fn = _drift_train_fn()
        if attack is not None and i == attacker:
            fn = make_malicious_train_fn(attack, fn, silo=i, seed=0)
        if i in deaf:
            class Deaf(FedAvgClientActor):
                def register_handlers(self):
                    self.register_handler(MsgType.S2C_FINISH,
                                          lambda m: self.finish())
            silos.append(Deaf(i, hub.transport(i), fn))
        else:
            silos.append(FedAvgClientActor(i, hub.transport(i), fn))
    for s in silos:
        s.register_handlers()
    server.start()
    hub.pump()
    while deaf and server.round_idx < n_rounds:
        server.send(MsgType.ROUND_TIMEOUT, 0,
                    **{Message.ARG_ROUND: server.round_idx})
        hub.pump()
    return server, health


def _lines(tmp_path, name):
    rows = [json.loads(l)
            for l in (tmp_path / f"{name}.jsonl").read_text().splitlines()]
    for r in rows:
        r.pop("ts")  # the only field allowed to differ between modes
    return rows


class TestLiveHealthEquivalence:
    def test_stream_and_stack_emit_identical_lines(self, tmp_path):
        _run_sync("stack", tmp_path, "stack")
        _run_sync("stream", tmp_path, "stream")
        stack, stream = _lines(tmp_path, "stack"), _lines(tmp_path, "stream")
        assert len(stack) == len(stream) == 3
        assert stack == stream

    def test_identical_lines_with_dropped_straggler(self, tmp_path):
        _run_sync("stack", tmp_path, "stack", deaf=(4,))
        _run_sync("stream", tmp_path, "stream", deaf=(4,))
        stack, stream = _lines(tmp_path, "stack"), _lines(tmp_path, "stream")
        assert stack == stream
        assert stack[-1]["dropped"] == 1
        assert stack[-1]["silos"]["4"]["dropped"] == 3

    def test_quarantined_attacker_fairness_accounting(self, tmp_path):
        admission = AdmissionPipeline(
            _params(), norm_min_history=3,
            trust=TrustTracker(strikes_to_quarantine=2,
                               quarantine_rounds=10))
        server, health = _run_sync(
            "stream", tmp_path, "quar", n_rounds=6, admission=admission,
            attack=Attack("scale", 100.0))
        rows = _lines(tmp_path, "quar")
        silos = health.per_silo()
        # the attacker struck out, then was excluded from later quorums
        # (at most its round-0 upload landed, while the norm screen was
        # still warming up — screens arm on history, not on faith)
        assert silos[2]["rejected"] >= 2
        assert silos[2]["excluded"] >= 1
        assert silos[2]["accepted"] <= 1
        # once quarantined it is EXCLUDED (ticked at broadcast), and the
        # round line accounts it there, not as a drop
        assert rows[-1]["excluded"] == 1
        assert rows[-1]["accepted"] == 3
        # honest silos never starve
        for s in (1, 3, 4):
            assert silos[s]["rounds_since_accept"] == 0
        # ... and the starvation alarm names the frozen-out minority
        assert rows[-1]["alarms"]["participation_starvation"]["value"] \
            == pytest.approx(0.25)
        # the attacker's norm never polluted the banked moments: round 0
        # (pre-quarantine, norm screen warming) sees its 100x upload
        # REJECTED only after history arms; by the last round only
        # honest norms remain
        assert rows[-1]["norm"]["count"] == 3

    def test_async_rotation_never_reads_as_starvation(self, tmp_path):
        """The starvation clock ticks per VERSION on the async path, but
        a healthy rotation only accepts ~goal of n_silos silos per
        version — the server scales starve_after by the rotation period
        so a healthy deployment with n_silos/goal > starve_after never
        alarms (the review-caught false-positive)."""
        from fedml_tpu.algorithms.async_fl import (AsyncFedServerActor,
                                                   delta_encoder)
        hub = LocalHub(codec_roundtrip=True)
        init = _params()
        health = HealthAccumulator(
            kind="delta", ledger_path=str(tmp_path / "async.jsonl"))
        assert health.starve_after == 3
        server = AsyncFedServerActor(
            hub.transport(0), init, client_num_in_total=8, n_silos=8,
            num_versions=6, aggregation_goal=2, health=health)
        assert health.starve_after == 3 * 4  # ceil(8/2) rotation periods
        server.register_handlers()
        silos = [FedAvgClientActor(i, hub.transport(i), _drift_train_fn(),
                                   encode_upload=delta_encoder)
                 for i in range(1, 9)]
        for s in silos:
            s.register_handlers()
        server.start()
        hub.pump()
        rows = [json.loads(l) for l in
                (tmp_path / "async.jsonl").read_text().splitlines()]
        assert len(rows) == 6
        for r in rows:
            assert r["alarms"]["participation_starvation"]["ok"], r
            assert r["kind"] == "delta"

    def test_health_rides_the_perf_ledger_as_its_own_phase(self, tmp_path):
        from fedml_tpu.obs.perf import PerfRecorder
        hub = LocalHub(codec_roundtrip=True)
        init = _params()
        rec = PerfRecorder(str(tmp_path / "perf.jsonl"))
        health = HealthAccumulator(alarms=False)
        server = FedAvgServerActor(
            hub.transport(0), init, client_num_in_total=2,
            client_num_per_round=2, num_rounds=2, perf=rec, health=health,
            stream_agg=StreamingAggregator(init, method="mean"))
        server.register_handlers()
        silos = [FedAvgClientActor(i, hub.transport(i), _drift_train_fn())
                 for i in (1, 2)]
        for s in silos:
            s.register_handlers()
        server.start()
        hub.pump()
        rec.close()
        rows = [json.loads(l) for l in
                (tmp_path / "perf.jsonl").read_text().splitlines()]
        assert len(rows) == 2
        for r in rows:
            assert r["phases"]["health"] > 0


# ---------------------------------------------------------------------------
# the multi-level topology: per-edge rollups, one frame per round
# ---------------------------------------------------------------------------

def _edge_federation(tmp_path, n_edges=2, n_silos=4, n_rounds=3):
    hub = LocalHub(codec_roundtrip=True)
    init = _params()
    health = HealthAccumulator(
        ledger_path=str(tmp_path / "root.jsonl"))
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=n_silos,
        client_num_per_round=n_edges, num_rounds=n_rounds,
        stream_agg=StreamingAggregator(init, method="mean"),
        health=health)
    server.register_handlers()
    blocks = np.array_split(np.arange(1, n_silos + 1), n_edges)
    edges = []
    for e, block in enumerate(blocks, start=1):
        edges.append(EdgeAggregatorActor(
            e, hub.transport(e),
            {n_edges + int(g): int(g) for g in block},
            cohort_total=n_silos, client_num_in_total=n_silos,
            stream_agg=StreamingAggregator(init, method="mean"),
            health=HealthAccumulator(kind="params", node=f"edge{e}",
                                     alarms=False)))
    edge_of = {int(g): e for e, block in enumerate(blocks, start=1)
               for g in block}
    silos = [FedAvgClientActor(n_edges + g, hub.transport(n_edges + g),
                               _drift_train_fn(), server_id=edge_of[g])
             for g in range(1, n_silos + 1)]
    for a in edges + silos:
        a.register_handlers()
    return hub, server, edges, silos, health


class TestEdgeHealthRollup:
    def test_rollup_matches_flat_norm_moments(self, tmp_path):
        hub, server, edges, silos, health = _edge_federation(tmp_path)
        server.start()
        hub.pump()
        root = _lines(tmp_path, "root")
        assert len(root) == 3
        _run_sync("stream", tmp_path, "flat", norm_clip=0.0)
        flat = _lines(tmp_path, "flat")
        for edge_row, flat_row in zip(root, flat):
            # the root's own tier sees 2 edge means; each frame carried
            # its block's rollup, and the merged moments equal the flat
            # topology's one-pass moments over the same 4 uploads
            assert set(edge_row["edges"]) == {"1", "2"}
            rollup = edge_row["edge_rollup"]
            assert rollup["count"] == flat_row["norm"]["count"] == 4
            assert rollup["mean"] == pytest.approx(
                flat_row["norm"]["mean"], rel=1e-6)
            assert rollup["std"] == pytest.approx(
                flat_row["norm"]["std"], rel=1e-5, abs=1e-9)
            assert rollup["min"] == pytest.approx(
                flat_row["norm"]["min"], rel=1e-6)
            assert rollup["max"] == pytest.approx(
                flat_row["norm"]["max"], rel=1e-6)
            # per-edge accounting: every silo accepted at its edge
            for s in edge_row["edges"].values():
                assert s["accepted"] == 2 and s["rejected"] == 0

    def test_tree_stays_one_frame_per_round(self, tmp_path):
        hub, server, edges, silos, health = _edge_federation(
            tmp_path, n_rounds=1)
        got = []
        orig = server._on_model

        def spy(msg):
            got.append((msg.sender_id, msg.get(Message.ARG_HEALTH)))
            orig(msg)
        server.register_handler(MsgType.C2S_MODEL, spy)
        server.start()
        hub.pump()
        # exactly E frames reached the root, each carrying its compact
        # rollup INSIDE the existing frame — no extra health messages
        assert sorted(s for s, _ in got) == [1, 2]
        for _, summary in got:
            assert summary["uploads"] == 2
            assert summary["norm"]["count"] == 2
            assert "silos" not in summary  # compact: no per-silo dump


# ---------------------------------------------------------------------------
# SLO / deep healthz / report integration
# ---------------------------------------------------------------------------

class TestIntegration:
    def test_slo_evaluator_gates_on_health_gauges(self):
        from fedml_tpu.obs.perf import DEFAULT_SLOS, SloEvaluator
        from fedml_tpu.obs.telemetry import TelemetryRegistry
        assert set(HEALTH_SLOS) <= set(DEFAULT_SLOS)
        reg = TelemetryRegistry()
        ev = SloEvaluator(registry=reg)
        # absent gauges: vacuously healthy (health off)
        verdict = ev.evaluate(count_breaches=False)
        assert verdict["health_norm_cv_ratio"]["value"] is None
        assert verdict["health_norm_cv_ratio"]["ok"]
        # a health round that blows the variance budget breaches (three
        # norms: a 2-value cv is bounded by 1.0 and could never breach)
        h = HealthAccumulator(registry=reg)
        ref = {"a": np.zeros(4, np.float32)}
        h.round_start(0, ref, expected=[1, 2, 3])
        _obs(h, 1, {"a": np.ones(4, np.float32)}, 1.0, norm=1.0)
        _obs(h, 2, {"a": np.ones(4, np.float32)}, 1.0, norm=1.0)
        _obs(h, 3, {"a": np.ones(4, np.float32)}, 1.0, norm=500.0)
        h.round_end(0, new_global=ref)
        verdict = ev.evaluate()
        assert not verdict["health_norm_cv_ratio"]["ok"]
        snap = reg.snapshot()
        assert snap["gauges"]["fedml_slo_health_norm_cv_ratio"] > 1.0
        assert any(k.startswith("fedml_slo_breaches_total")
                   and "health_norm_cv_ratio" in k and v >= 1
                   for k, v in snap["counters"].items())

    def test_parse_slo_spec_accepts_health_thresholds(self):
        from fedml_tpu.obs.perf import parse_slo_spec
        spec = parse_slo_spec("health_norm_cv_ratio=0.8,"
                              "health_misalignment_ratio=1.9")
        assert spec == {"health_norm_cv_ratio": 0.8,
                        "health_misalignment_ratio": 1.9}

    def test_deep_healthz_carries_the_health_verdict(self):
        import http.client
        from fedml_tpu.obs.perf import SloEvaluator
        from fedml_tpu.obs.telemetry import TelemetryRegistry
        from fedml_tpu.serve import (MicroBatcher, ModelRegistry,
                                     ServeFrontend)
        reg = TelemetryRegistry()
        slo = SloEvaluator(registry=reg)
        h = HealthAccumulator(registry=reg)
        registry = ModelRegistry(lambda p, x: x, history=8)
        batcher = MicroBatcher(registry, buckets=(1,))
        frontend = ServeFrontend(registry, batcher, port=0, slo=slo,
                                 health=h).start()
        try:
            registry.publish({"w": np.ones(2, np.float32)}, 0)
            ref = {"a": np.zeros(4, np.float32)}
            h.round_start(0, ref, expected=[1, 2, 3])
            _obs(h, 1, {"a": np.ones(4, np.float32)}, 1.0, norm=1.0)
            _obs(h, 2, {"a": np.ones(4, np.float32)}, 1.0, norm=1.0)
            _obs(h, 3, {"a": np.ones(4, np.float32)}, 1.0, norm=500.0)
            h.round_end(0, new_global=ref)
            conn = http.client.HTTPConnection("127.0.0.1", frontend.port,
                                              timeout=10)
            conn.request("GET", "/healthz?deep=1")
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            assert resp.status == 503
            assert body["status"] == "slo_breach"
            assert not body["slo"]["health_norm_cv_ratio"]["ok"]
            assert not body["health"]["alarms"]["norm_variance_blowup"]["ok"]
            assert body["health"]["round"] == 0
        finally:
            frontend.stop(drain=False)

    def test_report_renders_health_section(self, tmp_path):
        from fedml_tpu.obs.report import render_report
        h = HealthAccumulator(
            ledger_path=str(tmp_path / "health.jsonl"),
            thresholds={"health_norm_cv_ratio": 0.1})
        ref = {"a": np.zeros(4, np.float32)}
        h.round_start(0, ref, expected=[1, 2])
        _obs(h, 1, {"a": np.ones(4, np.float32)}, 1.0, norm=1.0)
        _obs(h, 2, {"a": np.ones(4, np.float32)}, 1.0, norm=9.0)
        h.round_end(0, new_global=ref)
        out = render_report(str(tmp_path))
        assert "learning health" in out
        assert "norm_variance_blowup" in out
        assert "DRIFT ALARMS fired 1 time(s)" in out

    def test_perf_only_run_dir_renders_cleanly(self, tmp_path):
        """ISSUE 9 bugfix pin: a run dir holding perf.jsonl (or
        health.jsonl) but no metrics.jsonl must render its ledger
        sections AND say why the rounds table is absent — never an
        empty/misleading report."""
        from fedml_tpu.obs.report import render_report
        (tmp_path / "perf.jsonl").write_text(json.dumps(
            {"round": 0, "ts": 1, "node": "node0", "round_s": 0.5,
             "phases": {"aggregate": 0.1}, "wire": {"bytes_out": 1,
                                                    "bytes_in": 1},
             "rss": None, "recompiles": 0, "jit_cache_sizes": {}}) + "\n")
        out = render_report(str(tmp_path))
        assert "perf ledger" in out
        assert "perf/health-only run" in out
        assert "no artifacts found" not in out
        # health-only: same contract
        (tmp_path / "perf.jsonl").unlink()
        h = HealthAccumulator(
            ledger_path=str(tmp_path / "health.jsonl"), alarms=False)
        h.round_start(0, {"a": np.zeros(2, np.float32)}, expected=[1])
        _obs(h, 1, {"a": np.ones(2, np.float32)}, 1.0)
        h.round_end(0)
        out = render_report(str(tmp_path))
        assert "learning health" in out
        assert "no artifacts found" not in out
