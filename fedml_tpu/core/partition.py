"""Non-IID data partitioners (host-side, numpy).

Re-implements the reference's two partitioning stacks:

* the core LDA partitioner
  ``fedml_core/non_iid_partition/noniid_partition.py:6-91`` (classification
  and multi-label segmentation variants, per-class Dirichlet split with a
  min-size-10 retry loop), and
* the cifar-style ``partition_data`` switch
  (``fedml_api/data_preprocessing/cifar10/data_loader.py:113-161``):
  ``homo`` uniform split, ``hetero`` Dirichlet split with the
  capacity-capping trick ``p * (len(idx_j) < N / client_num)``.

Partitioning is inherently host-side and sequential — it runs once at setup —
so numpy is the right tool; the TPU work starts downstream where the
resulting per-client index lists are stacked into padded device arrays
(`fedml_tpu.data.stacking`).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Sequence

import numpy as np


def _dirichlet_split_class(N: int, alpha: float, client_num: int,
                           idx_batch: List[List[int]], idx_k: np.ndarray,
                           rng: np.random.RandomState):
    """One class's Dirichlet allocation (noniid_partition.py:76-91).

    Clients already holding >= N/client_num samples get probability 0 for this
    class, which bounds the imbalance.
    """
    rng.shuffle(idx_k)
    proportions = rng.dirichlet(np.repeat(alpha, client_num))
    proportions = np.array(
        [p * (len(idx_j) < N / client_num) for p, idx_j in zip(proportions, idx_batch)])
    proportions = proportions / proportions.sum()
    cuts = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
    idx_batch = [idx_j + idx.tolist() for idx_j, idx in zip(idx_batch, np.split(idx_k, cuts))]
    min_size = min(len(idx_j) for idx_j in idx_batch)
    return idx_batch, min_size


def partition_dirichlet(label_list, client_num: int, classes, alpha: float,
                        task: str = "classification",
                        seed: int | None = None,
                        min_size_floor: int = 10) -> Dict[int, np.ndarray]:
    """LDA partition (noniid_partition.py:6-73).

    ``classes`` is an int (number of classes) for classification or a list of
    category ids for segmentation (where one instance can hold multiple
    categories and is assigned by its first matching category).
    Retries until every client holds at least ``min_size_floor`` samples.
    """
    rng = np.random.RandomState(seed) if seed is not None else np.random
    if task == "segmentation":
        N = len(label_list)
    else:
        label_list = np.asarray(label_list)
        N = label_list.shape[0]

    min_size = 0
    while min_size < min_size_floor:
        idx_batch: List[List[int]] = [[] for _ in range(client_num)]
        if task == "segmentation":
            for c, cat in enumerate(classes):
                if c > 0:
                    hit = np.asarray([
                        np.any(label_list[i] == cat)
                        and not np.any(np.isin(label_list[i], classes[:c]))
                        for i in range(len(label_list))])
                else:
                    hit = np.asarray([np.any(label_list[i] == cat)
                                      for i in range(len(label_list))])
                idx_k = np.where(hit)[0]
                idx_batch, min_size = _dirichlet_split_class(
                    N, alpha, client_num, idx_batch, idx_k, rng)
        else:
            for k in range(int(classes)):
                idx_k = np.where(label_list == k)[0]
                idx_batch, min_size = _dirichlet_split_class(
                    N, alpha, client_num, idx_batch, idx_k, rng)

    out = {}
    for i in range(client_num):
        rng.shuffle(idx_batch[i])
        out[i] = np.asarray(idx_batch[i], dtype=np.int64)
    return out


def partition_dirichlet_hetero(labels, client_num: int, class_num: int,
                               alpha: float, seed: int | None = None
                               ) -> Dict[int, np.ndarray]:
    """The cifar-style ``hetero`` partition (cifar10/data_loader.py:124-148):
    same per-class Dirichlet + capacity cap as the LDA partitioner, with the
    min-size-10 retry loop."""
    return partition_dirichlet(labels, client_num, class_num, alpha,
                               task="classification", seed=seed)


def partition_homo(n_samples: int, client_num: int,
                   seed: int | None = None) -> Dict[int, np.ndarray]:
    """IID split (cifar10/data_loader.py:119-123): shuffle then array_split."""
    rng = np.random.RandomState(seed) if seed is not None else np.random
    idxs = rng.permutation(n_samples)
    # keep the permuted within-client order (the reference does not re-sort)
    return {i: part.astype(np.int64)
            for i, part in enumerate(np.array_split(idxs, client_num))}


def partition_from_distribution(labels: Sequence[int],
                                distribution: Dict[int, Dict[int, int]]
                                ) -> Dict[int, np.ndarray]:
    """`hetero-fix` mode: assign counts per (client, class) from a fixed table
    (cifar10/data_loader.py:150-156 reads these from distribution files)."""
    labels = np.asarray(labels)
    per_class = {k: list(np.where(labels == k)[0]) for k in np.unique(labels)}
    out: Dict[int, List[int]] = {}
    for cid, cls_counts in distribution.items():
        take: List[int] = []
        for k, cnt in cls_counts.items():
            pool = per_class[k]
            take.extend(pool[:cnt])
            del pool[:cnt]
        out[int(cid)] = np.asarray(take, dtype=np.int64)
    return out


def record_data_stats(y_train, net_dataidx_map: Dict[int, np.ndarray],
                      task: str = "classification") -> Dict[int, Dict[int, int]]:
    """Per-client class histograms (noniid_partition.py:96-105)."""
    y_train = np.asarray(y_train, dtype=object) if task == "segmentation" else np.asarray(y_train)
    net_cls_counts = {}
    for net_i, dataidx in net_dataidx_map.items():
        if task == "segmentation":
            vals = np.concatenate([np.asarray(y_train[i]).ravel() for i in dataidx])
        else:
            vals = y_train[dataidx]
        unq, unq_cnt = np.unique(vals, return_counts=True)
        net_cls_counts[net_i] = {int(u): int(c) for u, c in zip(unq, unq_cnt)}
    logging.debug("Data statistics: %s", net_cls_counts)
    return net_cls_counts
