"""gRPC transport for true cross-silo (WAN / DCN) federation.

Reference equivalent: ``GRPCCommManager``
(fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:53-97): one
gRPC server per node at port ``base_port + node_id``, peers resolved from a
CSV rank→IP table, messages pushed via a unary ``sendMessage`` RPC.

TPU-native redesign:

- **no codegen**: grpc's generic handler API with identity (bytes) serializers
  replaces the protoc-generated string-payload stubs
  (gRPC/proto/grpc_comm_manager.proto:3-16) — the wire format is the binary
  array framing of `fedml_tpu.comm.message`, not JSON-in-a-proto-string.
- inbound dispatch is a plain blocking queue consumed by ``run()`` — no
  lock-guarded polling subroutine (grpc_comm_manager.py:86-97).
- the reference's 100 MB message cap is kept (grpc_comm_manager.py:20-24)
  but configurable.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Dict

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.transport import Transport
from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

_SERVICE = "fedml_tpu.Comm"
_METHOD = "Send"
_STOP = object()


def _ident(x: bytes) -> bytes:
    return x


class GrpcTransport(Transport):
    """One endpoint of a full gRPC mesh (every node runs a server)."""

    def __init__(self, node_id: int, ip_table: Dict[int, str],
                 base_port: int = 50000, max_message_mb: int = 1000,
                 send_timeout_s: float = 120.0,
                 idle_timeout_s: float = 0.0,
                 workers: int = 4):
        """``send_timeout_s`` bounds each unary send; sends also set
        ``wait_for_ready`` so a broadcast to a peer that is still booting
        blocks until its server binds instead of failing UNAVAILABLE (the
        reference has the same race and papers over it with sleep-ordered
        launches).  ``idle_timeout_s`` > 0 makes ``run()`` return after that
        long with no traffic — without it a silo whose server died leaks
        forever in the receive loop.  ``workers`` sizes the inbound RPC
        thread pool (the server node of a wide federation should raise it
        with the cohort — ``--grpc_workers``); ``max_message_mb`` is the
        reference's 100 MB cap made configurable (``--grpc_max_message_mb``),
        and sends log a loud warning at 80% of it instead of surfacing a
        bare RESOURCE_EXHAUSTED from deep inside the channel."""
        super().__init__()
        import grpc  # deferred: optional at import time of the package
        self._grpc = grpc
        self.node_id = node_id
        self.ip_table = dict(ip_table)
        self.base_port = base_port
        self._inbox: "queue.Queue" = queue.Queue()
        self._channels: Dict[int, object] = {}
        self._max_message_bytes = max_message_mb * 1024 * 1024
        self._warned_large = False
        reg = telemetry.get_registry()
        self._m_torn = reg.counter("fedml_wire_torn_frames_total")

        opts = [("grpc.max_send_message_length", self._max_message_bytes),
                ("grpc.max_receive_message_length", self._max_message_bytes)]
        inbox = self._inbox
        torn = self._m_torn

        def _handle_send(request: bytes, context) -> bytes:
            try:
                msg = Message.from_bytes(request)
            except ValueError as exc:
                # a torn/corrupt frame is dropped like a lost packet — it
                # must never kill the receive path (the sender's retry or
                # the round's straggler policy owns recovery)
                torn.inc()
                log.warning("node %d: dropping undecodable %d-byte frame: "
                            "%s", node_id, len(request), exc)
                return b""
            inbox.put(msg)
            return b""

        rpc = grpc.unary_unary_rpc_method_handler(
            _handle_send, request_deserializer=_ident,
            response_serializer=_ident)
        handler = grpc.method_handlers_generic_handler(_SERVICE, {_METHOD: rpc})
        import concurrent.futures
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=workers),
            handlers=(handler,), options=opts)
        self._port = self._server.add_insecure_port(
            f"[::]:{base_port + node_id}")
        if self._port == 0:
            raise RuntimeError(
                f"grpc transport node {node_id}: failed to bind port "
                f"{base_port + node_id} (already in use?)")
        self._opts = opts
        self._send_timeout_s = send_timeout_s
        self._idle_timeout_s = idle_timeout_s
        self._lock = threading.Lock()
        self._stopped = False
        self._server.start()
        log.info("grpc transport node %d listening on :%d", node_id, self._port)

    def _stub(self, receiver_id: int):
        with self._lock:
            if self._stopped:
                # a send racing stop() must not repopulate the channel
                # cache stop() just closed — that channel would leak
                raise RuntimeError(
                    f"grpc transport node {self.node_id} is stopped")
            if receiver_id not in self._channels:
                addr = (f"{self.ip_table[receiver_id]}:"
                        f"{self.base_port + receiver_id}")
                channel = self._grpc.insecure_channel(addr, options=self._opts)
                call = channel.unary_unary(
                    f"/{_SERVICE}/{_METHOD}", request_serializer=_ident,
                    response_deserializer=_ident)
                self._channels[receiver_id] = (channel, call)
            return self._channels[receiver_id][1]

    def send_message(self, msg: Message) -> None:
        # to_bytes reuses the fan-out's shared block when one is attached
        # (send_many): per receiver this is one small header encode + one
        # memcpy, never a re-serialization of the model bytes
        data = msg.to_bytes()
        if len(data) > 0.8 * self._max_message_bytes \
                and not self._warned_large:
            self._warned_large = True  # once per transport, not per silo
            log.warning(
                "node %d: encoded frame is %.1f MB — over 80%% of the "
                "%.0f MB gRPC message limit; raise --grpc_max_message_mb "
                "before this surfaces as RESOURCE_EXHAUSTED",
                self.node_id, len(data) / 1e6,
                self._max_message_bytes / 1e6)
        self._obs_send(msg, len(data))
        self._stub(msg.receiver_id)(
            data, wait_for_ready=True,
            timeout=self._send_timeout_s or None)

    def reconnect(self) -> None:
        """Drop every cached client channel so the next send dials fresh.

        The reconnection hook `ResilientTransport` calls between retry
        attempts: a peer that restarted (new process, same address) gets a
        clean channel instead of a channel wedged in TRANSIENT_FAILURE."""
        with self._lock:
            channels, self._channels = dict(self._channels), {}
        for channel, _ in channels.values():
            channel.close()

    def run(self) -> None:
        while True:
            try:
                item = self._inbox.get(
                    timeout=self._idle_timeout_s or None)
            except queue.Empty:
                log.warning("grpc transport node %d: no traffic for %.0fs; "
                            "shutting down receive loop", self.node_id,
                            self._idle_timeout_s)
                # release the port and client channels now rather than at
                # interpreter shutdown (stop() also enqueues _STOP, which
                # is harmless — this loop is already returning)
                self.stop()
                return
            if item is _STOP:
                return
            self._notify(item)

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return  # idempotent: run()'s idle path and callers both stop
            self._stopped = True
            channels, self._channels = dict(self._channels), {}
        self._inbox.put(_STOP)
        for channel, _ in channels.values():
            channel.close()
        self._server.stop(grace=None)


def load_ip_table(csv_path: str) -> Dict[int, str]:
    """Parse the reference's rank→IP CSV (``grpc_ipconfig.csv``; parser at
    fedml_api/distributed/utils/ip_config_utils.py:4-14)."""
    table: Dict[int, str] = {}
    with open(csv_path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line or (i == 0 and not line.split(",")[0].isdigit()):
                continue  # header row
            rank, ip = line.split(",")[:2]
            table[int(rank)] = ip.strip()
    return table
