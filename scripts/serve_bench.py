#!/usr/bin/env python
"""Open-loop load generator for the serving layer → BENCH_serve.json.

Open-loop (arrivals paced by a clock, not by completions — the honest
way to measure a queueing system: a closed loop self-throttles and hides
collapse) against the linear/MNIST model (784→10).  Reports p50/p95/p99
latency, sustained throughput, shed rate, and the batch-occupancy
histogram, while a swapper thread hot-swaps the model version mid-load
``--swaps`` times; every response is probed for torn reads.

Torn-read probe: version v serves kernel ``W[0, :] = v`` and bias
``onehot(v % 10)``, and every request sends ``x = e_0``, so a response
must satisfy BOTH ``round(min(y)) == version`` (kernel half) and
``argmax(y) == version % 10`` (bias half) for the version the batcher
says served it.  A swap landing mid-batch that mixed leaves from two
versions fails one of the two.

Default drive is in-process (request → batcher future), isolating the
serving stack from HTTP client throughput; ``--http`` routes the same
schedule through the ThreadingHTTPServer frontend with keep-alive
connections.  ``--ckpt_dir`` serves a real checkpoint directory through
the `CheckpointWatcher` instead of the synthetic fingerprint models
(torn-read probing is then skipped — real params have no fingerprint).

    JAX_PLATFORMS=cpu python scripts/serve_bench.py \
        --rate 2000 --duration_s 5 --swaps 10 --out BENCH_serve.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DIM, CLASSES = 784, 10  # MNIST linear


def fingerprint_params(version: int):
    w = np.zeros((DIM, CLASSES), np.float32)
    w[0, :] = float(version)
    b = np.zeros(CLASSES, np.float32)
    b[version % CLASSES] = 1.0
    return {"w": w, "b": b}


def is_torn(y: np.ndarray, version: int) -> bool:
    return (int(round(float(y.min()))) != version
            or int(np.argmax(y)) != version % CLASSES)


def build_stack(args):
    import jax

    from fedml_tpu.obs import telemetry
    from fedml_tpu.serve import MicroBatcher, ModelRegistry

    telemetry.enable()
    apply_fn = jax.jit(lambda p, x: x @ p["w"] + p["b"])
    registry = ModelRegistry(apply_fn, history=max(4, args.swaps + 2))
    watcher = None
    if args.ckpt_dir:
        from fedml_tpu.experiments.models import create_workload
        from fedml_tpu.serve.registry import CheckpointWatcher
        wl = create_workload(args.model, args.dataset, CLASSES, (28, 28, 1))
        predict = jax.jit(lambda p, x: wl.apply(p, x))
        registry = ModelRegistry(predict, history=16)
        watcher = CheckpointWatcher(registry, args.ckpt_dir, poll_s=0.25)
        watcher.poll_once()  # publish what's already on disk
        watcher.start()
        if registry.current() is None:
            raise SystemExit(f"no loadable checkpoint under {args.ckpt_dir}")
    else:
        registry.publish(fingerprint_params(0), 0)
    batcher = MicroBatcher(
        registry,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        max_delay_s=args.batch_delay_ms / 1e3,
        queue_depth=args.queue_depth,
        default_deadline_s=args.deadline_ms / 1e3).start()
    return registry, batcher, watcher


def run_bench(args):
    registry, batcher, watcher = build_stack(args)
    sample = np.zeros(DIM, np.float32)
    sample[0] = 1.0
    if args.ckpt_dir:
        sample = np.zeros((28, 28, 1), np.float32)
    batcher.warmup(sample)

    results = []          # (latency_s, version, torn) — appended per future
    shed = [0]
    issued = [0]
    lock = threading.Lock()
    stop_swapper = threading.Event()

    def swapper():
        """--swaps mid-load hot swaps, evenly spaced over the run."""
        for i in range(1, args.swaps + 1):
            if stop_swapper.wait(args.duration_s / (args.swaps + 1)):
                return
            registry.publish(fingerprint_params(i), i)
        stop_swapper.wait()

    swap_thread = None
    if args.swaps and not args.ckpt_dir:
        swap_thread = threading.Thread(target=swapper, daemon=True)
        swap_thread.start()

    def on_done(t_submit, fut):
        try:
            r = fut.result()
        except Exception:  # ShedError (deadline) rides the future
            with lock:
                shed[0] += 1
            return
        lat = time.perf_counter() - t_submit
        torn = (not args.ckpt_dir) and is_torn(np.asarray(r.y), r.version)
        with lock:
            results.append((lat, r.version, torn))

    def drive_inproc():
        from fedml_tpu.serve.batcher import ShedError
        interval = 1.0 / args.rate
        t_next = time.perf_counter()
        t_end = t_next + args.duration_s
        while (now := time.perf_counter()) < t_end:
            if now < t_next:
                time.sleep(t_next - now)
            t_next += interval
            issued[0] += 1
            t0 = time.perf_counter()
            try:
                fut = batcher.submit(sample)
            except ShedError:
                with lock:
                    shed[0] += 1
                continue
            fut.add_done_callback(lambda f, t0=t0: on_done(t0, f))

    def drive_http():
        import http.client

        from fedml_tpu.serve import ServeFrontend
        frontend = ServeFrontend(registry, batcher, port=args.port).start()
        payload = json.dumps({"x": sample.tolist()})
        hdrs = {"Content-Type": "application/json"}
        n_threads = args.http_clients
        per_rate = args.rate / n_threads

        def fresh_conn():
            import socket
            conn = http.client.HTTPConnection("127.0.0.1", frontend.port)
            conn.connect()
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return conn

        def client(tid):
            conn = fresh_conn()
            interval = 1.0 / per_rate
            t_next = time.perf_counter()
            t_end = t_next + args.duration_s
            while (now := time.perf_counter()) < t_end:
                if now < t_next:
                    time.sleep(t_next - now)
                t_next += interval
                with lock:  # shared across client threads
                    issued[0] += 1
                t0 = time.perf_counter()
                try:
                    conn.request("POST", "/predict", payload, hdrs)
                    resp = conn.getresponse()
                    body = json.loads(resp.read())
                except Exception:
                    conn.close()
                    conn = fresh_conn()
                    with lock:
                        shed[0] += 1
                    continue
                lat = time.perf_counter() - t0
                if resp.status != 200:
                    with lock:
                        shed[0] += 1
                    continue
                y = np.asarray(body["y"])
                torn = (not args.ckpt_dir) and is_torn(y, body["version"])
                with lock:
                    results.append((lat, body["version"], torn))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        frontend.stop()
        return time.perf_counter() - t0

    t0 = time.perf_counter()
    if args.http:
        wall = drive_http()
    else:
        drive_inproc()
        batcher.stop(drain=True)  # drain: every queued request answers
        wall = time.perf_counter() - t0
    stop_swapper.set()
    if watcher is not None:
        watcher.stop()

    lats = sorted(r[0] for r in results)
    torn_count = sum(1 for r in results if r[2])
    versions = sorted({r[1] for r in results})
    from fedml_tpu.obs import telemetry
    snap = telemetry.get_registry().snapshot()
    occupancy = snap.get("histograms", {}).get(
        "fedml_serve_batch_occupancy_total", {})
    pct = (lambda p: lats[min(len(lats) - 1, int(p * len(lats)))]
           if lats else None)
    out = {
        "bench": "serve",
        "mode": "http" if args.http else "inproc",
        "model": "linear_mnist_784x10",
        "rate_target_rps": args.rate,
        "duration_s": round(wall, 3),
        "issued": issued[0],
        "completed": len(results),
        "throughput_rps": round(len(results) / wall, 1) if wall else 0.0,
        "shed": shed[0],
        "shed_rate": round(shed[0] / max(issued[0], 1), 4),
        "deadline_ms": args.deadline_ms,
        "latency_ms": {p: round(v * 1e3, 3) if v is not None else None
                       for p, v in (("p50", pct(0.50)), ("p95", pct(0.95)),
                                    ("p99", pct(0.99)),
                                    ("max", lats[-1] if lats else None))},
        "hot_swaps": args.swaps if not args.ckpt_dir else None,
        "versions_served": versions,
        "torn_responses": torn_count,
        "batch_occupancy": occupancy,
    }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=2000.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--duration_s", type=float, default=5.0)
    ap.add_argument("--swaps", type=int, default=10,
                    help="mid-load hot swaps (synthetic mode)")
    ap.add_argument("--buckets", default="1,2,4,8,16,32,64")
    ap.add_argument("--deadline_ms", type=float, default=50.0)
    ap.add_argument("--batch_delay_ms", type=float, default=2.0)
    ap.add_argument("--queue_depth", type=int, default=512)
    ap.add_argument("--http", action="store_true",
                    help="drive through the HTTP frontend (keep-alive)")
    ap.add_argument("--http_clients", type=int, default=8)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ckpt_dir", default="",
                    help="serve a RoundCheckpointer dir via the watcher "
                         "instead of synthetic fingerprint models")
    ap.add_argument("--model", default="lr")
    ap.add_argument("--dataset", default="mnist")
    ap.add_argument("--out", default="BENCH_serve.json")
    args = ap.parse_args(argv)

    out = run_bench(args)
    print(json.dumps(out, indent=2))
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
    print(f"wrote {args.out}")

    p99 = out["latency_ms"]["p99"]
    ok = (out["throughput_rps"] >= 1000 if args.rate >= 1000 else True) \
        and out["torn_responses"] == 0 \
        and (p99 is None or p99 <= args.deadline_ms)
    if not ok:
        print("BENCH FAILED acceptance: need >=1k req/s, p99 under "
              f"deadline, zero torn; got {out['throughput_rps']} rps, "
              f"p99={p99}ms, torn={out['torn_responses']}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
