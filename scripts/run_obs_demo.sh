#!/usr/bin/env bash
# End-to-end observability demo (ISSUE 2 acceptance): a chaos-enabled
# 2-silo federated run with distributed tracing + telemetry on, then the
# merged run report — asserting every artifact actually materializes:
#
#   * a stitched multi-process Perfetto trace covering
#     broadcast -> train -> upload -> aggregate,
#   * a Prometheus text snapshot with link/chaos counters and
#     failure-detector gauges,
#   * an obs_report per-round timeline.
#
# Usage: scripts/run_obs_demo.sh [workdir]  (default: a fresh mktemp dir)
set -euo pipefail
cd "$(dirname "$0")/.."

DIR="${1:-$(mktemp -d /tmp/fedml_obs_demo.XXXXXX)}"
RUN="$DIR/run" TRACE="$DIR/trace"
echo "== obs demo: artifacts under $DIR"

env JAX_PLATFORMS=cpu python -m fedml_tpu \
    --algo cross_silo --model lr --dataset mnist \
    --client_num_in_total 4 --client_num_per_round 2 --comm_round 3 \
    --frequency_of_the_test 1 --batch_size 4 --log_stdout false \
    --straggler_policy drop --round_timeout_s 2 --min_silo_frac 0.5 \
    --chaos_drop 0.05 --chaos_delay 0.3 --chaos_dup 0.1 \
    --chaos_reorder 0.1 --chaos_seed 7 \
    --heartbeat_s 0.2 --dead_after_s 5 \
    --run_dir "$RUN" --trace_dir "$TRACE" --telemetry true

REPORT="$DIR/report.txt"
env JAX_PLATFORMS=cpu python scripts/obs_report.py \
    --run_dir "$RUN" --trace_dir "$TRACE" \
    --merge_trace "$DIR/trace_merged.json" | tee "$REPORT"

echo "== asserting artifacts"
# the report renders a per-round timeline with every phase stitched in
grep -q "round timelines" "$REPORT"
for phase in broadcast train upload aggregate; do
    grep -q "$phase" "$REPORT"
done
# the Prometheus snapshot carries link counters, chaos fault counters,
# and failure-detector gauges
for series in fedml_comm_send_total fedml_chaos_faults_total \
              fedml_failure_detector_alive_total \
              fedml_round_duration_seconds_count; do
    grep -q "$series" "$RUN/telemetry.prom"
done
# the merged Perfetto trace is non-trivial valid trace_event JSON
python - "$DIR/trace_merged.json" <<'EOF'
import json, sys
events = json.load(open(sys.argv[1]))["traceEvents"]
names = {e["name"] for e in events}
assert {"round", "broadcast", "train", "upload", "aggregate"} <= names, names
print(f"merged trace OK: {len(events)} spans, phases {sorted(names)}")
EOF
echo "== obs demo OK ($DIR)"
