"""Edge-case / backdoor example sets for robust-FL evaluation.

The reference ships loaders for externally-downloaded poison sets — Southwest
airliner images relabeled "truck" for CIFAR10, ARDIS digits relabeled "7" for
(E)MNIST, plus pixel-pattern triggers — and mixes a poisoned client into the
cohort while tracking "targetted task" accuracy
(``edge_case_examples/data_loader.py:223-330``,
``fedavg_robust/FedAvgRobustAggregator.py:117-136, 270``).

Poison construction is data math, not IO, so the core here is generic:
``apply_pixel_trigger`` stamps a corner pattern and relabels (the classic
badnets trigger), ``make_poisoned_dataset`` blends a poison set into one
client's shard, and ``load_external_poison`` reads the reference's pickled
edge-case sets when present.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np


def apply_pixel_trigger(x: np.ndarray, target_label: int,
                        trigger_size: int = 3, value: float = 1.0
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Stamp a trigger_size² bright square in the bottom-right corner of each
    [N, H, W, C] image and relabel everything to ``target_label``."""
    x = x.copy()
    x[..., -trigger_size:, -trigger_size:, :] = value
    y = np.full(len(x), target_label, dtype=np.int32)
    return x, y


def make_poisoned_dataset(x_clean: np.ndarray, y_clean: np.ndarray,
                          x_poison: np.ndarray, y_poison: np.ndarray,
                          poison_frac: float = 0.5, seed: int = 0
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Blend poison into a clean shard (attacker's local dataset): keep all
    clean samples, append round(poison_frac * n_clean) poison samples,
    shuffle (the reference's attacker datasets are similar fixed blends)."""
    rng = np.random.RandomState(seed)
    n_poison = min(len(y_poison), int(round(poison_frac * len(y_clean))))
    sel = rng.choice(len(y_poison), n_poison, replace=False)
    x = np.concatenate([x_clean, x_poison[sel]])
    y = np.concatenate([y_clean, y_poison[sel]])
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


def load_external_poison(path: str, target_label: int
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Read a pickled image array (e.g. southwest_images_new_train.pkl) and
    relabel to the attack target — target 9 ("truck") for southwest, 7 for
    ARDIS (edge_case_examples/data_loader.py:283-330)."""
    with open(path, "rb") as f:
        imgs = pickle.load(f)
    x = np.asarray(imgs, dtype=np.float32)
    if x.max() > 1.5:
        x = x / 255.0
    y = np.full(len(x), target_label, dtype=np.int32)
    return x, y


def targeted_task_eval_set(dataset: str, data_dir: Optional[str] = None,
                           image_shape: Tuple[int, ...] = (32, 32, 3),
                           target_label: int = 9, n: int = 64,
                           seed: int = 0) -> Dict[str, np.ndarray]:
    """The "targetted task" test set: external poison images when the
    reference's pickles are on disk, otherwise trigger-stamped noise images
    (hermetic).  Accuracy on this set measures backdoor persistence."""
    if data_dir:
        for fname in ("southwest_images_new_test.pkl",
                      "ardis_test_dataset.pt"):
            p = os.path.join(data_dir, fname)
            if not os.path.exists(p):
                continue
            if fname.endswith(".pkl"):
                x, y = load_external_poison(p, target_label)
            else:  # torch-pickled ARDIS TensorDataset (data_loader.py:320)
                import torch
                obj = torch.load(p, map_location="cpu", weights_only=False)
                tensors = getattr(obj, "tensors", obj)
                x = np.asarray(tensors[0], dtype=np.float32)
                if x.max() > 1.5:
                    x = x / 255.0
                # torch ships NCHW (or [N, H, W]); everything here is NHWC
                if x.ndim == 3:
                    x = x[..., None]
                elif x.ndim == 4 and x.shape[1] in (1, 3) \
                        and x.shape[-1] not in (1, 3):
                    x = x.transpose(0, 2, 3, 1)
                y = np.full(len(x), target_label, dtype=np.int32)
            return {"x": x, "y": y}
    rng = np.random.RandomState(seed)
    x = rng.rand(n, *image_shape).astype(np.float32)
    x, y = apply_pixel_trigger(x, target_label)
    return {"x": x, "y": y}
