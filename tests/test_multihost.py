"""Multi-host execution: `jax.distributed.initialize` actually running.

The reference launches N+1 OS processes via mpirun + hostfile
(run_fedavg_distributed_pytorch.sh:17-21).  The TPU replacement is
`init_distributed` (parallel/mesh.py) — every host runs the same program,
`jax.devices()` spans all hosts, collectives ride ICI/DCN.  These tests
execute that path for real: TWO separate OS processes on localhost, a
shared coordinator, one global [clients] mesh with one device per process,
and a full cohort training round whose psum-aggregated result must be
bit-identical on both processes.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
from fedml_tpu.parallel.mesh import init_distributed, make_mesh, stage_global
assert init_distributed(f"127.0.0.1:{{port}}", nproc, pid)
assert jax.process_count() == nproc
assert jax.device_count() == nproc        # one CPU device per process

import hashlib
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from fedml_tpu.data.stacking import stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.parallel.cohort import make_cohort_step
from fedml_tpu.trainer.local_sgd import make_local_trainer
from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                        make_client_optimizer)

n_dev = jax.device_count()
mesh = make_mesh(client_axis=n_dev)
rng = np.random.RandomState(0)   # same seed everywhere: every process
xs = [rng.randn(8, 12).astype(np.float32) for _ in range(n_dev)]
ys = [rng.randint(0, 3, 8).astype(np.int32) for _ in range(n_dev)]
stacked = stack_client_data(xs, ys, batch_size=4)
wl = ClassificationWorkload(LogisticRegression(12, 3), num_classes=3)
local = make_local_trainer(wl, make_client_optimizer("sgd", 0.1), epochs=1)
step = make_cohort_step(local, mesh=mesh)
params = wl.init(jax.random.key(0), jax.tree.map(
    lambda v: jnp.asarray(v[0, 0]),
    {{k: stacked[k] for k in ("x", "y", "mask")}}))
new_params, _ = step(stage_global(params, mesh),
                     stage_global(stacked, mesh, P("clients")),
                     stage_global(jax.random.key(1), mesh))
jax.block_until_ready(new_params)
host = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), new_params)
moved = max(float(abs(np.asarray(a - b)).max())
            for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(params)))
assert moved > 0, "training round did not update parameters"
digest = hashlib.sha256(b"".join(
    np.ascontiguousarray(l).tobytes()
    for l in jax.tree.leaves(host))).hexdigest()
print(f"DIGEST {{pid}} {{digest}}", flush=True)
"""


_WORKER_2LEVEL = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
from fedml_tpu.parallel.mesh import (init_distributed, make_two_level_mesh,
                                     stage_global)
assert init_distributed(f"127.0.0.1:{{port}}", nproc, pid)
assert jax.process_count() == nproc
assert jax.device_count() == nproc * 4    # four local devices per process

import hashlib
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from fedml_tpu.algorithms.hierarchical import (make_grouped_round,
                                               make_two_level_round)
from fedml_tpu.data.stacking import stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.local_sgd import make_local_trainer
from fedml_tpu.trainer.workload import (ClassificationWorkload,
                                        make_client_optimizer)

# two-level [groups=nproc, clients=4] global mesh: jax.devices() orders
# process 0's four local devices first, so the groups axis IS the process
# (DCN) boundary and the clients axis stays process-local (the ICI tier)
mesh = make_two_level_mesh(group_axis=nproc, client_axis=4)
assert [d.process_index for d in mesh.devices[pid]] == [pid] * 4

G, M = nproc, 4
rng = np.random.RandomState(0)   # same seed everywhere: every process
xs = [rng.randn(8, 12).astype(np.float32) for _ in range(G * M)]
ys = [rng.randint(0, 3, 8).astype(np.int32) for _ in range(G * M)]
flat = stack_client_data(xs, ys, batch_size=4)
cohorts = jax.tree.map(
    lambda v: v.reshape((G, M) + v.shape[1:]), flat)  # [G, M, S, B, ...]
wl = ClassificationWorkload(LogisticRegression(12, 3), num_classes=3)
local = make_local_trainer(wl, make_client_optimizer("sgd", 0.1), epochs=1)
params = wl.init(jax.random.key(0), jax.tree.map(
    lambda v: jnp.asarray(v[0, 0]),
    {{k: flat[k] for k in ("x", "y", "mask")}}))

two = make_two_level_round(local, group_comm_round=2, mesh=mesh)
out = two(stage_global(params, mesh),
          stage_global(cohorts, mesh, P("groups", "clients")),
          stage_global(jax.random.key(1), mesh))
jax.block_until_ready(out)
host = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), out)

# single-process oracle: the vmapped simulation twin on local data only —
# no collectives, so it needs nothing from the other process
sim = jax.tree.map(np.asarray, make_grouped_round(local, 2)(
    params, jax.tree.map(jnp.asarray, cohorts), jax.random.key(1)))
err = max(float(abs(a - b).max())
          for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(sim)))
assert err < 1e-5, f"two-level pod round != single-process sim ({{err}})"

digest = hashlib.sha256(b"".join(
    np.ascontiguousarray(l).tobytes()
    for l in jax.tree.leaves(host))).hexdigest()
print(f"DIGEST {{pid}} {{digest}}", flush=True)
"""


_WORKER_SCAFFOLD = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
from fedml_tpu.parallel.mesh import init_distributed, make_mesh
assert init_distributed(f"127.0.0.1:{{port}}", nproc, pid)
assert jax.process_count() == nproc
assert jax.device_count() == nproc * 4    # four local devices per process

import hashlib
import numpy as np
import jax.numpy as jnp
from fedml_tpu.algorithms.scaffold import Scaffold, ScaffoldConfig
from fedml_tpu.data.stacking import FederatedData, stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload

n_dev = jax.device_count()
mesh = make_mesh(client_axis=n_dev)
rng = np.random.RandomState(0)   # same seed everywhere: every process
xs = [rng.randn(8, 12).astype(np.float32) for _ in range(n_dev)]
ys = [rng.randint(0, 3, 8).astype(np.int32) for _ in range(n_dev)]
train = stack_client_data(xs, ys, batch_size=4)
data = FederatedData(client_num=n_dev, class_num=3, train=train, test=train)
wl = ClassificationWorkload(LogisticRegression(12, 3), num_classes=3)
cfg = dict(comm_round=3, client_num_per_round=n_dev, epochs=1,
           batch_size=4, lr=0.1, frequency_of_the_test=100)

# the mesh run crosses the process boundary (psum over clients; the
# updated control variates come back replicated via the wrap's
# all_gather, so BOTH processes scatter identical rows into their
# host-resident state mirrors)
algo = Scaffold(wl, data, ScaffoldConfig(**cfg), mesh=mesh)
p_mesh = algo.run(rng=jax.random.key(7))
jax.block_until_ready(p_mesh)
host = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), p_mesh)
c_locals_host = jax.tree.map(np.asarray, algo.c_locals)

# single-chip oracle runs locally in the same worker (no collectives):
# multi-process mesh must match it leaf-for-leaf, per-client state too
solo = Scaffold(wl, data, ScaffoldConfig(**cfg))
p_solo = jax.tree.map(np.asarray, solo.run(rng=jax.random.key(7)))
err = max(float(abs(a - b).max())
          for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(p_solo)))
assert err < 1e-5, f"scaffold 2-proc mesh != single-chip params ({{err}})"
err_c = max(float(abs(np.asarray(a) - np.asarray(b)).max())
            for a, b in zip(jax.tree.leaves(c_locals_host),
                            jax.tree.leaves(solo.c_locals)))
assert err_c < 1e-5, f"scaffold 2-proc control variates diverged ({{err_c}})"

# Ditto on the same cluster: the one caller that passes a single
# (non-tuple) out_specs P("clients") to make_sharded_stateful_round, so
# this exercises the wrap's single-spec gather/eff_out branch for real
from fedml_tpu.algorithms.ditto import Ditto, DittoConfig
d_cfg = dict(cfg)
d_algo = Ditto(wl, data, DittoConfig(**d_cfg, ditto_lambda=0.1), mesh=mesh)
d_mesh = d_algo.run(rng=jax.random.key(11))
jax.block_until_ready(d_mesh)
d_host = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), d_mesh)
v_host = jax.tree.map(np.asarray, d_algo.v_locals)

d_solo = Ditto(wl, data, DittoConfig(**d_cfg, ditto_lambda=0.1))
d_ref = jax.tree.map(np.asarray, d_solo.run(rng=jax.random.key(11)))
err_d = max(float(abs(a - b).max())
            for a, b in zip(jax.tree.leaves(d_host),
                            jax.tree.leaves(d_ref)))
assert err_d < 1e-5, f"ditto 2-proc mesh != single-chip params ({{err_d}})"
err_v = max(float(abs(np.asarray(a) - np.asarray(b)).max())
            for a, b in zip(jax.tree.leaves(v_host),
                            jax.tree.leaves(d_solo.v_locals)))
assert err_v < 1e-5, f"ditto 2-proc personal models diverged ({{err_v}})"

digest = hashlib.sha256(b"".join(
    np.ascontiguousarray(l).tobytes()
    for l in jax.tree.leaves(host) + jax.tree.leaves(c_locals_host)
    + jax.tree.leaves(d_host) + jax.tree.leaves(v_host))).hexdigest()
print(f"DIGEST {{pid}} {{digest}}", flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_round(tmp_path):
    """2 OS processes x 1 CPU device: init_distributed wires a global mesh,
    the federated round's psum aggregation crosses the process boundary,
    and both processes finish with the SAME global model."""
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=REPO))
    port = _free_port()
    env = dict(os.environ)
    # one local device per process — scrub the parent suite's virtual-mesh
    # flag so the device count measured is the distributed one
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:  # a worker stuck at the coordinator barrier must
            p.kill()     # not outlive the test holding the port

    digests = sorted(line.split()[2] for out in outs
                     for line in out.splitlines()
                     if line.startswith("DIGEST"))
    assert len(digests) == 2 and digests[0] == digests[1], outs


@pytest.mark.slow
def test_two_process_four_device_hierarchical_round(tmp_path):
    """2 OS processes x 4 virtual CPU devices each: the two-level
    [groups=2, clients=4] mesh puts the groups axis exactly on the
    process (DCN) boundary and the clients axis process-local (ICI).  A
    full hierarchical round — 2 group-local FedAvg rounds + global
    weighted psum across processes — must match the single-process
    vmapped simulation leaf-for-leaf and agree bit-identically between
    the processes (VERDICT r3 item 8)."""
    script = tmp_path / "worker2.py"
    script.write_text(_WORKER_2LEVEL.format(repo=REPO))
    port = _free_port()
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=4")
    env["XLA_FLAGS"] = " ".join(flags)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:
            p.kill()

    digests = sorted(line.split()[2] for out in outs
                     for line in out.splitlines()
                     if line.startswith("DIGEST"))
    assert len(digests) == 2 and digests[0] == digests[1], outs


@pytest.mark.slow
def test_two_process_four_device_scaffold_round(tmp_path):
    """2 OS processes x 4 virtual CPU devices: STATEFUL algorithms on a
    multi-process [clients=8] mesh (round-4 verdict item 4).  SCAFFOLD
    (tuple out_specs) and Ditto (the single non-tuple out_specs caller,
    covering the wrap's other gather branch), three rounds each with
    host-resident per-client state: inputs staged global, state outputs
    all_gather-replicated, every process scatters the same rows into its
    own mirror.  Both must match the single-chip run leaf-for-leaf
    (params AND per-client state) and agree bit-identically between the
    processes."""
    script = tmp_path / "worker_scaffold.py"
    script.write_text(_WORKER_SCAFFOLD.format(repo=REPO))
    port = _free_port()
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=4")
    env["XLA_FLAGS"] = " ".join(flags)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:
            p.kill()

    digests = sorted(line.split()[2] for out in outs
                     for line in out.splitlines()
                     if line.startswith("DIGEST"))
    assert len(digests) == 2 and digests[0] == digests[1], outs
