"""Test harness: run everything on a virtual 8-device CPU mesh.

This replaces the reference's "multi-node without a cluster" strategy of
launching N+1 MPI processes on localhost
(run_fedavg_distributed_pytorch.sh:19) — here the N "processes" are N virtual
XLA devices inside one pytest process.

The environment may eagerly initialize JAX on a TPU platform before pytest
even starts (a PJRT plugin imports jax at interpreter startup), so setting
env vars alone is not enough: we clear any live backend and re-initialize on
CPU with 8 forced host devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# jax is typically already imported (but not yet initialized) at this point;
# re-point the platform config at CPU before any backend is created.  Only if
# something already created a backend do we clear and re-initialize (private
# API, so guard it — on a jax upgrade the env-var path above still works).
jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge
    if xla_bridge._backends:
        if any(p != "cpu" for p in xla_bridge._backends):
            # clearing a live TPU/axon backend hangs (see
            # .claude/skills/verify/SKILL.md) — fail fast instead
            raise RuntimeError(
                "a non-CPU JAX backend was initialized before conftest ran; "
                "run pytest in a fresh process without touching jax.devices()")
        xla_bridge._clear_backends()
        xla_bridge.get_backend.cache_clear()
except (ImportError, AttributeError):
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def identity_lm_data(vocab=12, clients=4, samples=16, seq=8, batch=8,
                     seed=13):
    """Deterministic next-token (y_t = x_t) federated LM dataset — the
    shared learning-proof task for the NLP families (RNN + transformer):
    any sequence model must drive token accuracy to ~1.  Tokens start at 2
    so labels never collide with NWPWorkload's pad_id=0 mask."""
    from fedml_tpu.data.stacking import FederatedData, stack_client_data
    rs = np.random.RandomState(seed)
    xs = [rs.randint(2, vocab, (samples, seq)).astype(np.int32)
          for _ in range(clients)]
    ys = [x.copy() for x in xs]
    train = stack_client_data(xs, ys, batch_size=batch)
    return FederatedData(client_num=clients, class_num=vocab, train=train,
                         test=train)
