"""SCAFFOLD control-variate FL (algorithms/scaffold.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms import (FedAvg, FedAvgConfig, Scaffold,
                                  ScaffoldConfig)
from fedml_tpu.data.stacking import FederatedData, stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _skewed_clients(n_clients=4, dim=10, per=24, seed=0):
    """Pathological heterogeneity: each client holds ONE class only — the
    client-drift regime SCAFFOLD exists for."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(n_clients, dim) * 2.0
    xs = [(centers[c] + 0.5 * rng.randn(per, dim)).astype(np.float32)
          for c in range(n_clients)]
    ys = [np.full(per, c, np.int32) for c in range(n_clients)]
    return xs, ys


def _fed(xs, ys, batch, classes):
    train = stack_client_data(xs, ys, batch)
    return FederatedData(client_num=len(xs), class_num=classes, train=train,
                         test=train)


@pytest.fixture(scope="module")
def workload():
    return ClassificationWorkload(LogisticRegression(10, 4), num_classes=4,
                                  grad_clip_norm=None)


def test_first_round_with_zero_variates_equals_fedavg(workload):
    """Round 1 corrections are zero (c = c_i = 0), so SCAFFOLD's first
    round must land exactly on FedAvg's (same rng chain, plain SGD)."""
    xs, ys = _skewed_clients()
    data = _fed(xs, ys, batch=8, classes=4)
    cfg = dict(comm_round=1, client_num_per_round=4, epochs=2, batch_size=8,
               lr=0.1, frequency_of_the_test=100)
    fa = FedAvg(workload, data, FedAvgConfig(**cfg))
    sc = Scaffold(workload, data, ScaffoldConfig(**cfg))
    p0 = fa.init_params(jax.random.key(3))
    out_fa = fa.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    out_sc = sc.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 out_fa, out_sc)


def test_scaffold_beats_fedavg_under_client_drift(workload):
    """The paper's claim on its home turf: one-class-per-client skew with
    many local epochs — SCAFFOLD's corrections must reach a lower global
    train loss than FedAvg at the same budget."""
    xs, ys = _skewed_clients()
    data = _fed(xs, ys, batch=8, classes=4)
    cfg = dict(comm_round=20, client_num_per_round=2, epochs=5,
               batch_size=8, lr=0.1, frequency_of_the_test=19)
    fa = FedAvg(workload, data, FedAvgConfig(**cfg))
    sc = Scaffold(workload, data, ScaffoldConfig(**cfg))
    fa.run(rng=jax.random.key(0))
    sc.run(rng=jax.random.key(0))
    loss_fa = fa.history[-1]["train_loss"]
    loss_sc = sc.history[-1]["train_loss"]
    assert loss_sc < loss_fa, (loss_sc, loss_fa)


def test_control_variates_update_and_checkpoint_roundtrip(workload,
                                                          tmp_path):
    xs, ys = _skewed_clients()
    data = _fed(xs, ys, batch=8, classes=4)
    cfg = dict(comm_round=3, client_num_per_round=2, epochs=2, batch_size=8,
               lr=0.1, frequency_of_the_test=100)
    sc = Scaffold(workload, data, ScaffoldConfig(**cfg))
    sc.run(rng=jax.random.key(1))
    assert sc.c_global is not None
    assert max(float(jnp.abs(x).max())
               for x in jax.tree.leaves(sc.c_global)) > 0
    # state template matches live state structure (checkpoint contract)
    tmpl = sc._extra_state_template(sc.init_params(jax.random.key(0)))
    live = sc._extra_state()
    assert jax.tree.structure(tmpl) == jax.tree.structure(live)


def test_rerun_on_same_instance_resets_sampling_mirror(workload):
    """run() twice on one instance must not desynchronize the internal
    round counter from run()'s own sampling chain."""
    xs, ys = _skewed_clients()
    data = _fed(xs, ys, batch=8, classes=4)
    cfg = dict(comm_round=2, client_num_per_round=2, epochs=1, batch_size=8,
               lr=0.1, frequency_of_the_test=100)
    sc = Scaffold(workload, data, ScaffoldConfig(**cfg))
    sc.run(rng=jax.random.key(0))
    assert sc._round_counter == 2
    sc.run(rng=jax.random.key(0))
    assert sc._round_counter == 2  # reset, then advanced by exactly 2


def test_scaffold_rejects_unsupported_configs(workload):
    xs, ys = _skewed_clients()
    data = _fed(xs, ys, batch=8, classes=4)
    base = dict(comm_round=1, client_num_per_round=2, epochs=1,
                batch_size=8, lr=0.1)
    with pytest.raises(ValueError, match="plain SGD"):
        Scaffold(workload, data,
                 ScaffoldConfig(client_optimizer="adam", **base))
    stateful_wl = ClassificationWorkload(
        LogisticRegression(10, 4), num_classes=4, stateful=True)
    with pytest.raises(ValueError, match="stateful"):
        Scaffold(stateful_wl, data, ScaffoldConfig(**base))


def test_first_round_parity_holds_with_grad_clip():
    """The clip-after-correction ordering keeps round-1 parity exact for
    the CLI's default clipped classification workload too."""
    wl = ClassificationWorkload(LogisticRegression(10, 4), num_classes=4,
                                grad_clip_norm=1.0)
    xs, ys = _skewed_clients()
    data = _fed(xs, ys, batch=8, classes=4)
    cfg = dict(comm_round=1, client_num_per_round=4, epochs=2, batch_size=8,
               lr=0.5, frequency_of_the_test=100)
    fa = FedAvg(wl, data, FedAvgConfig(**cfg))
    sc = Scaffold(wl, data, ScaffoldConfig(**cfg))
    p0 = fa.init_params(jax.random.key(3))
    out_fa = fa.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    out_sc = sc.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 out_fa, out_sc)


def test_cli_scaffold_end_to_end():
    from fedml_tpu.experiments.main import main
    summary = main(["--algo", "scaffold", "--model", "lr", "--dataset",
                    "mnist", "--client_num_in_total", "8",
                    "--client_num_per_round", "4", "--comm_round", "2",
                    "--frequency_of_the_test", "1", "--batch_size", "4",
                    "--log_stdout", "false"])
    assert np.isfinite(summary["train_loss"])


def test_mesh_sharded_scaffold_equals_single_chip(workload):
    """The 8-device mesh path (shard_map + psum, per-client rng folded by
    GLOBAL cohort slot) must match the single-chip run to float tolerance
    (the psum reassociates the reduction order) — params AND control
    variates."""
    from fedml_tpu.parallel.mesh import make_mesh
    xs, ys = _skewed_clients(n_clients=8)
    data = _fed(xs, ys, batch=8, classes=4)
    cfg = dict(comm_round=3, client_num_per_round=8, epochs=2, batch_size=8,
               lr=0.1, frequency_of_the_test=100)
    single = Scaffold(workload, data, ScaffoldConfig(**cfg))
    meshed = Scaffold(workload, data, ScaffoldConfig(**cfg),
                      mesh=make_mesh(client_axis=8))
    p0 = single.init_params(jax.random.key(3))
    out_s = single.run(params=jax.tree.map(jnp.copy, p0),
                       rng=jax.random.key(4))
    out_m = meshed.run(params=jax.tree.map(jnp.copy, p0),
                       rng=jax.random.key(4))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6), out_s, out_m)
    for a, b in zip(jax.tree.leaves(single.c_locals),
                    jax.tree.leaves(meshed.c_locals)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_mesh_sharded_scaffold_with_genuinely_padded_cohort(workload):
    """6 live clients in an 8-slot cohort over 4 devices: two slots are
    REAL padding (live==0), exercising the live-mask freeze, k_safe
    guard, and aliased client-0 slot under psum — and the padded slots
    must leave the stacked variates of every client untouched relative
    to the single-chip run."""
    from fedml_tpu.parallel.mesh import make_mesh
    xs, ys = _skewed_clients(n_clients=6)
    data = _fed(xs, ys, batch=8, classes=4)
    cfg = dict(comm_round=2, client_num_per_round=8, epochs=2, batch_size=8,
               lr=0.1, frequency_of_the_test=100)
    single = Scaffold(workload, data, ScaffoldConfig(**cfg))
    meshed = Scaffold(workload, data, ScaffoldConfig(**cfg),
                      mesh=make_mesh(client_axis=4,
                                     devices=jax.devices()[:4]))
    out_s = single.run(rng=jax.random.key(0))
    out_m = meshed.run(rng=jax.random.key(0))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6), out_s, out_m)
    for a, b in zip(jax.tree.leaves(single.c_locals),
                    jax.tree.leaves(meshed.c_locals)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
