"""Federation health observatory: streaming learning-health statistics
on the receive path (ISSUE 9).

PR 6 made the *machine* observable (phase wall-times, RSS, recompiles);
this module makes the *learning process* observable.  Once the stream
fold (`core/stream_agg.py`) consumes an upload at arrival, nothing
downstream can ever ask "were the cohort's updates coherent, who is
drifting, which silo never participates?" — the evidence is destroyed on
the receive path.  So the statistics are computed there too, FedJAX-style
per-client metric aggregation (arXiv 2108.02117) fused with the
Smart-NIC argument (arXiv 2307.06561) that per-upload processing belongs
in the receive path: every stat folds at arrival in **O(model) +
O(silos)** standing state, never a post-hoc scan of retained uploads —
the contract the mega-cohort north star (1k–100k sampled clients per
round) requires.

Per-round statistics (one ``health.jsonl`` line per round/version, the
same torn-tail-tolerant single-``write()`` O_APPEND contract as
``perf.jsonl``):

* **update-norm running moments** — mean/var/min/max via Welford over
  the admitted update norms.  The norm itself is REUSED from the
  `AdmissionVerdict` the admission pipeline already computed (one
  O(model) pass shared by defense, health, and telemetry — computed here
  only when no screen ran);
* **cosine alignment** — each admitted upload's update direction against
  the round's running weighted-mean direction so far (one dot product
  against O(model) state — the same fold-at-arrival state shape
  `StreamingAggregator` holds; health keeps its own f32 host work
  vector so stream and stack mode emit IDENTICAL lines, pinned by
  test).  Past ``sketch_coords`` model coordinates the statistics ride
  a deterministic proportional-prefix coordinate sketch, bounding
  per-upload health work at O(cap) for arbitrarily large models —
  sketched norms rescale by sqrt(total/m), cosines are
  subspace-exact, and the admission screen (a *defense*) still walks
  the full payload either way;
* **per-silo fairness counters** — tasked/accepted/rejected/dropped/
  excluded counts, staleness, and rounds-since-last-accept per silo
  (O(silos) state, bounded by the deployment);
* **global round-over-round delta norm** — how far the aggregate
  actually moved the model;
* **per-edge rollups** — under the multi-level topology each
  `EdgeAggregatorActor` ships its compact summary inside the existing
  per-round edge frame (`Message.ARG_HEALTH`; the tree stays
  one-frame-per-round) and the root merges the edge moments exactly
  (Chan's parallel-Welford combine) beside its own edge-tier stats.

Drift/anomaly detection: three alarms evaluated at round close, each a
``larger-is-worse`` ratio so the PR 6 `SloEvaluator` (and
``/healthz?deep=1``) can gate on the exported gauges with its existing
``value <= threshold`` contract — thresholds configurable through the
same ``--slo`` spec:

* ``health_misalignment_ratio`` = 1 - mean cosine alignment (alignment
  collapse: the cohort's updates stopped agreeing on a direction);
* ``health_norm_cv_ratio`` = std/mean of admitted update norms (norm
  variance blowup: somebody's updates are wildly out of scale);
* ``health_starvation_ratio`` = fraction of known silos with no
  accepted upload for ``starve_after`` consecutive rounds
  (participation starvation: fairness accounting — quarantine,
  dead-drop, or scheduler bias is freezing silos out).

Everything here is host-side numpy at message rate — no jit, no device
transfers beyond the per-round reference the server already
materialized (`HostMirror`), so the recompile sentry has nothing to
watch and the health path cannot retrace.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

# default alarm thresholds — merged into `obs/perf.DEFAULT_SLOS`, so the
# --slo spec ("health_misalignment_ratio=0.8,...") overrides them and a
# typo'd name fails loudly at config time like every other objective.
#
# Calibration note (misalignment = 1 - mean cosine): an honest but
# HETEROGENEOUS cohort trains near-orthogonal update directions — mean
# cosine ~0, misalignment ~1.0 — so the safe-by-default threshold sits
# at 1.5 (mean cosine below -0.5: a coordinated anti-aligned mass, the
# sign-flip-fleet signature).  An iid/homogeneous deployment whose
# healthy cosine sits near 1 should tighten it via --slo
# ("health_misalignment_ratio=0.5").  Scale/inflate attacks show up in
# norm_cv instead: honest cohorts' update norms are tight (cv ~0.1),
# one 30x-scaled attacker in a small cohort pushes cv past 1.
HEALTH_SLOS = {
    "health_misalignment_ratio": 1.5,   # 1 - mean cosine alignment
    "health_norm_cv_ratio": 1.0,        # std/mean of update norms
    "health_starvation_ratio": 0.5,     # starved / known silos
}

# alarm name (ledger + breach-counter label) per SLO objective
ALARMS = {
    "health_misalignment_ratio": "alignment_collapse",
    "health_norm_cv_ratio": "norm_variance_blowup",
    "health_starvation_ratio": "participation_starvation",
}


def _sketch_f32(tree, cap: int):
    """The health work vector: an f32 flatten in canonical leaf order,
    coordinate-SKETCHED past ``cap`` total coordinates — each leaf
    contributes a proportional contiguous prefix, so the sketch is the
    same fixed linear subspace for every upload of the round (and
    across agg modes / topologies: it depends only on the tree's leaf
    shapes).  Returns ``(vec, scale)`` where ``scale = sqrt(total/m)``
    un-biases a sketched norm back to the full-vector estimate (cosines
    need no correction — the factor cancels).  Keeps per-upload health
    work O(min(model, cap)) instead of O(model): alignment/variance are
    drift *statistics*, not defenses — the admission screen still walks
    the full payload, and its exact f64 norm is what health banks
    whenever a screen ran."""
    from fedml_tpu.robust.admission import _leaves
    leaves = [np.asarray(l).reshape(-1) for l in _leaves(tree)]
    total = sum(l.size for l in leaves)
    if total == 0:
        return np.zeros(0, np.float32), 1.0
    if cap <= 0 or total <= cap:
        if len(leaves) == 1:
            return leaves[0].astype(np.float32, copy=False), 1.0
        return np.concatenate([l.astype(np.float32, copy=False)
                               for l in leaves]), 1.0
    parts, took = [], 0
    for l in leaves:
        k = max(1, (l.size * cap) // total)
        parts.append(l[:k].astype(np.float32, copy=False))
        took += parts[-1].size
    return np.concatenate(parts), math.sqrt(total / took)


def _finite(v) -> Optional[float]:
    """JSON-safe float: non-finite values ledger as null, never as the
    bare NaN token that breaks every downstream json.loads."""
    if v is None:
        return None
    v = float(v)
    return v if math.isfinite(v) else None


class Welford:
    """Streaming mean/variance/min/max — one O(1) update per value, so
    the moments of a 100k-upload round cost the same state as an
    8-upload one."""

    __slots__ = ("count", "mean", "m2", "min", "max")

    def __init__(self):
        self.count = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def push(self, x: float) -> None:
        x = float(x)
        self.count += 1
        delta = x - self.mean
        self.mean += delta / self.count
        self.m2 += delta * (x - self.mean)
        if self.min is None or x < self.min:
            self.min = x
        if self.max is None or x > self.max:
            self.max = x

    @property
    def var(self) -> float:
        """Population variance (ddof=0) — the alarm-facing moment; a
        1-value round has zero variance, not an undefined one."""
        return self.m2 / self.count if self.count else 0.0

    @property
    def std(self) -> float:
        return math.sqrt(max(self.var, 0.0))

    def summary(self) -> dict:
        return {"count": self.count,
                "mean": _finite(self.mean) if self.count else None,
                "std": _finite(self.std) if self.count else None,
                "min": _finite(self.min), "max": _finite(self.max)}


def merge_moments(summaries: List[dict]) -> dict:
    """Chan's parallel combine over `Welford.summary()` dicts — the root
    merges per-edge norm moments into cohort-level moments EXACTLY (same
    count/mean/var as one pass over all uploads, up to fp association),
    without any upload ever crossing the edge tier."""
    count, mean, m2 = 0, 0.0, 0.0
    mn = mx = None
    for s in summaries:
        if not s or not s.get("count"):
            continue
        n_b = int(s["count"])
        mean_b = float(s["mean"])
        var_b = float(s["std"] or 0.0) ** 2
        delta = mean_b - mean
        tot = count + n_b
        m2 += var_b * n_b + delta * delta * count * n_b / tot
        mean += delta * n_b / tot
        count = tot
        if s.get("min") is not None:
            mn = s["min"] if mn is None else min(mn, s["min"])
        if s.get("max") is not None:
            mx = s["max"] if mx is None else max(mx, s["max"])
    out = Welford()
    out.count, out.mean, out.m2, out.min, out.max = count, mean, m2, mn, mx
    return out.summary()


class _SiloHealth:
    """Cross-round fairness ledger for one silo (O(1) each, O(silos)
    total — the only state that outlives a round besides thresholds)."""

    __slots__ = ("tasked", "accepted", "rejected", "dropped", "excluded",
                 "staleness_sum", "staleness_n", "rounds_since_accept",
                 "last_accept_round")

    def __init__(self):
        self.tasked = 0
        self.accepted = 0
        self.rejected = 0
        self.dropped = 0
        self.excluded = 0
        self.staleness_sum = 0.0
        self.staleness_n = 0
        self.rounds_since_accept = 0
        self.last_accept_round: Optional[int] = None

    def summary(self) -> dict:
        out = {"tasked": self.tasked, "accepted": self.accepted,
               "rejected": self.rejected, "dropped": self.dropped,
               "excluded": self.excluded,
               "rounds_since_accept": self.rounds_since_accept,
               "last_accept_round": self.last_accept_round}
        if self.staleness_n:
            out["mean_staleness"] = _finite(
                self.staleness_sum / self.staleness_n)
        return out


def compact_summary(line: dict) -> dict:
    """The subset of a health line an edge ships inside its per-round
    frame: small, pure-Python, codec-safe — the tree stays
    one-frame-per-round (the model mean dwarfs this by orders of
    magnitude)."""
    return {k: line[k] for k in
            ("uploads", "accepted", "rejected", "dropped", "weight",
             "norm", "alignment", "global_delta_norm") if k in line}


class HealthAccumulator:
    """Per-round learning-health statistics on the admission-accept →
    fold seam of both live servers and the edge actors.

    Round protocol (mirrors `PerfRecorder`)::

        h.round_start(round_idx, reference, expected=[...])
        h.observe_admitted(silo, upload, weight, norm=..., staleness=...)
        h.observe_rejected(silo, reason)        # per inadmissible upload
        h.note_edge(edge_id, summary)           # root, per edge frame
        line = h.round_end(round_idx, new_global=...)

    ``kind="params"`` (sync uploads are parameter trees; the update is
    ``upload - reference``) or ``"delta"`` (async uploads ARE updates).
    ``reference`` at round_start is the round's global either way — the
    delta-norm baseline; for params kind it is also the per-upload
    update reference.

    ``ledger_path``: one ``health.jsonl`` line per round, formatted fully
    and written with ONE O_APPEND ``write()`` (crash tears at most the
    tail; `trend.load_ledger` / `report.load_jsonl` both tolerate it).
    An existing file rotates to ``.prev`` like ``perf.jsonl`` — one
    ledger, one run.

    ``alarms=False`` (edge actors): statistics only — no gauges, no
    breach counters, no ledger; the root owns the verdicts.

    Thread-safety: observation may run on receive threads while the
    round closes on the event loop — one lock guards the per-round
    state, the same discipline as `PerfRecorder`'s phase dict.
    """

    def __init__(self, *, kind: str = "params", node: str = "server",
                 ledger_path: Optional[str] = None,
                 thresholds: Optional[dict] = None,
                 starve_after: int = 3, alarms: bool = True,
                 sketch_coords: int = 1_000_000,
                 suppress_payload: Optional[str] = None,
                 registry=None):
        """``sketch_coords``: past this many model coordinates the
        per-upload statistics ride a deterministic proportional-prefix
        coordinate sketch (`_sketch_f32`) instead of the full vector —
        bounding health work per upload at O(cap) for arbitrarily large
        models (0 = always exact).  Sketched norms are rescaled by
        sqrt(total/m); cosines need no correction.

        ``suppress_payload``: a REASON string (e.g.
        ``"secagg_pairwise_masking"``) that disables every payload-
        derived statistic — update-norm moments and cosine alignment —
        because the uploads are ciphertext and per-silo learning stats
        are unavailable BY CONSTRUCTION (the privacy↔observability
        trade of secure aggregation).  Fairness counters, participation,
        and the round-over-round global delta norm (computed on the
        published PLAINTEXT global) keep working, and every ledger line
        carries a ``suppressed`` section NAMING the missing fields and
        the reason — the observatory degrades honestly, never to a
        silent zero that reads as 'perfectly aligned cohort'."""
        if kind not in ("params", "delta"):
            raise ValueError(f"kind must be 'params' or 'delta', got {kind!r}")
        if starve_after < 1:
            raise ValueError(f"starve_after must be >= 1, got {starve_after}")
        unknown = set(thresholds or {}) - set(HEALTH_SLOS)
        if unknown:
            raise ValueError(f"unknown health thresholds {sorted(unknown)}; "
                             f"available: {sorted(HEALTH_SLOS)}")
        self.kind = kind
        self.node = node
        self.path = ledger_path
        self._ledger_disabled = False
        self.thresholds = {**HEALTH_SLOS, **(thresholds or {})}
        self.starve_after = starve_after
        self.alarms_enabled = alarms
        self.sketch_coords = int(sketch_coords)
        self.suppress_payload = suppress_payload
        if ledger_path:
            d = os.path.dirname(ledger_path)
            if d:
                os.makedirs(d, exist_ok=True)
            if os.path.exists(ledger_path):
                # one ledger == one run (the perf.jsonl rotation contract):
                # splicing a previous run's rounds would poison every
                # reader's round-over-round view
                os.replace(ledger_path, ledger_path + ".prev")
        reg = registry if registry is not None else telemetry.get_registry()
        self._g = {
            "norm_mean": reg.gauge("fedml_health_update_norm_mean_value"),
            "norm_max": reg.gauge("fedml_health_update_norm_max_value"),
            "norm_cv": reg.gauge("fedml_health_norm_cv_ratio"),
            "align_mean": reg.gauge("fedml_health_alignment_mean_ratio"),
            "misalign": reg.gauge("fedml_health_misalignment_ratio"),
            "starvation": reg.gauge("fedml_health_starvation_ratio"),
            "starved": reg.gauge("fedml_health_starved_silos_total"),
            "participation": reg.gauge("fedml_health_participation_ratio"),
            "delta_norm": reg.gauge("fedml_health_global_delta_norm_value"),
        }
        self._c_rounds = reg.counter("fedml_health_rounds_total")
        self._c_breaches = {slo: reg.counter("fedml_health_breaches_total",
                                             alarm=alarm)
                            for slo, alarm in ALARMS.items()}
        self._lock = threading.Lock()
        self._silos: Dict[int, _SiloHealth] = {}
        self.last_line: Optional[dict] = None
        self._round: Optional[int] = None
        self._reset_round_state()

    def _reset_round_state(self) -> None:
        self._norms = Welford()
        self._aligns = Welford()
        self._stale = Welford()
        self._ref_vec: Optional[np.ndarray] = None  # f32 (sketched) global
        self._ref_scale = 1.0   # sqrt(total/m) norm un-bias factor
        self._dir_sum: Optional[np.ndarray] = None  # running weighted update
        self._dir_sq = 0.0   # ||dir_sum||^2, maintained incrementally:
        #                      ||s + w*d||^2 = ||s||^2 + 2w(s.d) + w^2(d.d)
        #                      reuses the dots the cosine already paid, so
        #                      no per-upload re-walk of the O(model) state
        self._dir_weight = 0.0
        self._expected: List[int] = []
        self._excluded: List[int] = []
        self._seen: Dict[int, str] = {}  # silo -> "accepted" | "rejected"
        self._weight_total = 0.0
        self._edges: Dict[int, dict] = {}

    def _silo(self, silo: int) -> _SiloHealth:
        rec = self._silos.get(silo)
        if rec is None:
            rec = self._silos[silo] = _SiloHealth()
        return rec

    def register(self, silos) -> None:
        """Pre-register the silo universe (the barrier-free async path,
        where no per-version 'expected' set exists): registered silos
        count toward the starvation denominator from version 0 even if
        they never manage an accepted upload."""
        with self._lock:
            for s in silos:
                self._silo(int(s))

    # -- round lifecycle -----------------------------------------------------
    def round_start(self, round_idx, reference=None, *,
                    expected=None, excluded=None) -> None:
        """Open a round.  ``reference``: the round's global (a HOST tree
        — the server's `HostMirror` copy, so opening a round costs no new
        device transfer); flattened ONCE here to f64.  ``expected``: the
        silos the barrier waits on (None for the barrier-free async
        path); ``excluded``: silos dropped at broadcast (dead /
        quarantined) — their fairness counters tick without ever seeing
        an upload."""
        with self._lock:
            self._reset_round_state()
            self._round = round_idx
            if reference is not None:
                self._ref_vec, self._ref_scale = _sketch_f32(
                    reference, self.sketch_coords)
            self._expected = sorted(int(s) for s in (expected or []))
            self._excluded = sorted(int(s) for s in (excluded or []))
            for s in self._expected:
                self._silo(s).tasked += 1
            for s in self._excluded:
                self._silo(s).excluded += 1

    def observe_admitted(self, silo: int, upload, weight, *,
                         norm: Optional[float] = None,
                         staleness: Optional[float] = None) -> None:
        """Fold one ADMITTED upload's statistics at arrival.  O(model)
        work (the update flatten + one dot against the running
        direction), O(model) standing state.  ``norm``: the update norm
        the admission pipeline already computed (`AdmissionVerdict.norm`)
        — passed through so the screen's one O(model) norm pass is the
        only one; computed here only when no screen ran."""
        delta = None
        if self.suppress_payload is None:
            delta, scale = _sketch_f32(upload, self.sketch_coords)
            if self.kind == "params":
                if self._ref_vec is None:
                    raise RuntimeError(
                        "observe_admitted() before round_start(): the "
                        "round's update reference is not set")
                delta = delta - self._ref_vec
        # else: ciphertext upload — the payload-derived stats below are
        # suppressed BY NAME in the ledger line; only the shared
        # fairness/participation tail runs
        with self._lock:
            try:
                w = float(weight)
            except (TypeError, ValueError):
                w = 0.0
            if not math.isfinite(w) or w < 0:
                w = 0.0
            if delta is not None:
                dd = float(np.dot(delta, delta))
                if norm is None:
                    # no screen ran: the norm is the sketch's rescaled
                    # estimate (exact below the sketch cap, scale == 1)
                    norm = math.sqrt(dd) * scale
                norm = float(norm)
                if math.isfinite(norm):
                    self._norms.push(norm)
                if self._dir_sum is None:
                    eff_w = w if w > 0 else 1.0
                    self._dir_sum = eff_w * delta
                    self._dir_sq = eff_w * eff_w * dd
                else:
                    # one dot product against the O(model) running
                    # weighted-mean direction (cos is scale-invariant, so
                    # the un-normalized running SUM is the same direction);
                    # the same dot then advances the incremental ||sum||^2
                    sd = float(np.dot(delta, self._dir_sum))
                    denom = math.sqrt(max(dd, 0.0)) \
                        * math.sqrt(max(self._dir_sq, 0.0))
                    if denom > 0 and math.isfinite(denom):
                        cos = sd / denom
                        if math.isfinite(cos):
                            self._aligns.push(cos)
                    eff_w = w if w > 0 else 1.0
                    self._dir_sum += eff_w * delta
                    self._dir_sq += 2.0 * eff_w * sd + eff_w * eff_w * dd
                self._dir_weight += w if w > 0 else 1.0
            self._weight_total += w
            self._seen[int(silo)] = "accepted"
            rec = self._silo(int(silo))
            rec.accepted += 1
            rec.rounds_since_accept = 0
            rec.last_accept_round = self._round
            if staleness is not None:
                s = float(staleness)
                self._stale.push(s)
                rec.staleness_sum += s
                rec.staleness_n += 1

    def observe_rejected(self, silo: int, reason: str) -> None:
        """One inadmissible upload: the silo reported, its payload did
        not count — fairness accounting ticks, statistics do not."""
        with self._lock:
            self._seen.setdefault(int(silo), "rejected")
            self._silo(int(silo)).rejected += 1

    def note_edge(self, edge: int, summary) -> None:
        """Root side of the multi-level topology: bank the compact health
        summary an edge shipped inside its per-round frame."""
        if not isinstance(summary, dict):
            return
        with self._lock:
            self._edges[int(edge)] = summary

    # -- alarms ---------------------------------------------------------------
    def _alarm_values(self) -> Dict[str, float]:
        misalign = (1.0 - self._aligns.mean) if self._aligns.count else 0.0
        cv = (self._norms.std / self._norms.mean
              if self._norms.count >= 2 and self._norms.mean > 0 else 0.0)
        known = list(self._silos)
        starved = [s for s in known
                   if self._silos[s].rounds_since_accept >= self.starve_after]
        starvation = len(starved) / len(known) if known else 0.0
        return {"health_misalignment_ratio": misalign,
                "health_norm_cv_ratio": cv,
                "health_starvation_ratio": starvation,
                "_starved_silos": float(len(starved))}

    def round_end(self, round_idx, new_global=None, **extra) -> dict:
        """Close the round: per-silo bookkeeping for who never showed,
        the global delta norm against the round's reference, alarm
        verdicts, gauges, and one ledger line.  Returns the line dict
        (``extra`` lands verbatim — quorum sizes, version tags)."""
        with self._lock:
            missing = [s for s in self._expected if s not in self._seen]
            for s in missing:
                self._silos[s].dropped += 1
            # starvation clock: every known silo that did not land an
            # accepted upload this round ages one round
            for s, rec in self._silos.items():
                if self._seen.get(s) != "accepted":
                    rec.rounds_since_accept += 1
            delta_norm = None
            if new_global is not None and self._ref_vec is not None:
                d = _sketch_f32(new_global, self.sketch_coords)[0] \
                    - self._ref_vec
                delta_norm = _finite(math.sqrt(float(np.dot(d, d)))
                                     * self._ref_scale)
            values = self._alarm_values()
            starved = int(values.pop("_starved_silos"))
            alarms = {}
            for slo, alarm in ALARMS.items():
                thr = float(self.thresholds[slo])
                v = values[slo]
                ok = v <= thr
                alarms[alarm] = {"value": _finite(v), "threshold": thr,
                                 "ok": ok}
                if not ok and self.alarms_enabled:
                    self._c_breaches[slo].inc()
            accepted = sum(1 for v in self._seen.values() if v == "accepted")
            line = {
                "round": round_idx,
                "ts": time.time(),
                "node": self.node,
                "kind": self.kind,
                "uploads": len(self._seen),
                "accepted": accepted,
                "rejected": len(self._seen) - accepted,
                "dropped": len(missing),
                "excluded": len(self._excluded),
                "expected": len(self._expected),
                "weight": _finite(self._weight_total),
                "norm": self._norms.summary(),
                "alignment": {"count": self._aligns.count,
                              "mean": (_finite(self._aligns.mean)
                                       if self._aligns.count else None),
                              "min": _finite(self._aligns.min)},
                "global_delta_norm": delta_norm,
                "alarms": alarms,
                "silos": {str(s): self._silos[s].summary()
                          for s in sorted(set(self._seen)
                                          | set(self._expected)
                                          | set(self._excluded))},
            }
            if self.suppress_payload is not None:
                # the named privacy↔observability trade: these fields ARE
                # absent (count-0 summaries), and the line says why
                line["suppressed"] = {"fields": ["norm", "alignment"],
                                      "reason": self.suppress_payload}
            if self._stale.count:
                line["staleness"] = self._stale.summary()
            if self._edges:
                line["edges"] = {str(e): self._edges[e]
                                 for e in sorted(self._edges)}
                line["edge_rollup"] = merge_moments(
                    [s.get("norm") for s in self._edges.values()])
            line.update(extra)
            self.last_line = line
            self._round = None
        if self.alarms_enabled:
            self._export(line, values, starved)
        if self.path:
            self._write(line)
        return line

    def _export(self, line: dict, values: Dict[str, float],
                starved: int) -> None:
        self._c_rounds.inc()
        norm = line["norm"]
        if norm["mean"] is not None:
            self._g["norm_mean"].set(norm["mean"])
        if norm["max"] is not None:
            self._g["norm_max"].set(norm["max"])
        self._g["norm_cv"].set(values["health_norm_cv_ratio"])
        if line["alignment"]["mean"] is not None:
            self._g["align_mean"].set(line["alignment"]["mean"])
        self._g["misalign"].set(values["health_misalignment_ratio"])
        self._g["starvation"].set(values["health_starvation_ratio"])
        self._g["starved"].set(starved)
        if line["expected"]:
            self._g["participation"].set(
                line["accepted"] / line["expected"])
        if line["global_delta_norm"] is not None:
            self._g["delta_norm"].set(line["global_delta_norm"])

    def _write(self, line: dict) -> None:
        if self._ledger_disabled:
            return
        from fedml_tpu.utils.journal import durable_append
        data = json.dumps(line, sort_keys=True) + "\n"
        # one write() on an O_APPEND fd (the perf.jsonl contract): a
        # crash tears at most the tail, which every reader tolerates.
        # A disk fault (ENOSPC/EIO) warns ONCE and disables the ledger —
        # it must never kill the receive thread or the round loop; the
        # in-memory stats, gauges, and alarms keep working.
        try:
            durable_append(self.path, data, channel="health_ledger")
        except OSError as e:
            self._ledger_disabled = True
            log.warning("health ledger append failed (%s); disabling the "
                        "ledger — stats and alarms continue in memory", e)

    # -- queries --------------------------------------------------------------
    def round_summary(self) -> Optional[dict]:
        """The compact frame-ready summary of the LAST closed round
        (what an edge ships to the root)."""
        if self.last_line is None:
            return None
        return compact_summary(self.last_line)

    def healthz(self) -> Optional[dict]:
        """The deep-health payload: last round's verdicts, small enough
        for every LB probe."""
        if self.last_line is None:
            return None
        return {"round": self.last_line.get("round"),
                "alarms": self.last_line.get("alarms"),
                "uploads": self.last_line.get("uploads"),
                "accepted": self.last_line.get("accepted")}

    def per_silo(self) -> Dict[int, dict]:
        """Cross-round fairness ledger snapshot (tests / demos)."""
        with self._lock:
            return {s: rec.summary() for s, rec in sorted(self._silos.items())}
