"""Ditto (Li et al. 2021, arXiv:2012.04221) — personalized FL via a
bi-level objective: a normal FedAvg global stream plus, per client, a
persistent personalized model trained against its own data with a
proximal pull toward the current global weights.

Beyond the reference's algorithm list — nothing in ``fedml_api`` covers
personalization (its closest knob is FedProx's μ, which regularizes the
*global* stream; SURVEY.md §2.2).  Included because the cohort engine
makes it nearly free: like SCAFFOLD's control variates
(algorithms/scaffold.py), the personalized models live as ONE stacked
pytree ``[client_num_in_total, ...]`` host-side between rounds, with a
cohort gather/scatter per round and a vmap'd local scan inside one jit.

Round structure (Algorithm 1 of the paper, full-batch SGD solver):

    global:    w-stream is EXACTLY FedAvg — the base cohort step consumes
               the same rng it would under plain FedAvg, so the global
               trajectory is bit-identical (parity-tested);
    personal:  v_i ← v_i − η_p · (∇F_i(v_i) + λ (v_i − w^t))
               for ``personal_epochs`` local epochs.  Every v_i is
               initialized to w^0 (the paper's Algorithm 1 init; the
               stacked state is broadcast once, lazily, at the first
               round).  λ=0 decouples v_i into pure local training;
               λ→∞ pins v_i to the global stream.

Eval: ``evaluate_personalized`` scores each client's OWN model on its
own shard (the metric the paper reports); ``evaluate_global`` appends
those columns to the standard global metrics so ``run()``'s history
carries both.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import (FedAvg, FedAvgConfig,
                                         gather_client_rows,
                                         scatter_client_rows,
                                         zeros_client_state)
from fedml_tpu.trainer.workload import Workload

Pytree = Any

# distinct fold_in stream for the personal updates, so adding Ditto's
# second training pass cannot perturb the global FedAvg rng chain
_PERSONAL_STREAM = 0x44495454  # ASCII "DITT"


@dataclasses.dataclass
class DittoConfig(FedAvgConfig):
    ditto_lambda: float = 0.1
    # 0 -> inherit the corresponding global hyperparameter
    personal_lr: float = 0.0
    personal_epochs: int = 0


def make_ditto_local(workload: Workload, lr: float, epochs: int,
                     lam: float):
    """``train(v, w_ref, data, rng) -> v'`` — the personalized solver.

    Plain SGD on ∇F_i(v) + λ(v − w_ref), the paper's Algorithm 1 inner
    loop.  The workload's ``grad_clip_norm`` is honored AFTER the
    proximal coupling — the same corrected-then-clipped ordering the
    FedProx/SCAFFOLD trainers use (local_sgd.py).  Fully-padded batches
    freeze the carry, so ragged clients take exactly their own steps.
    """
    import optax
    clip = (optax.clip_by_global_norm(workload.grad_clip_norm)
            if workload.grad_clip_norm is not None else None)
    grad_fn = jax.grad(lambda p, b, r: workload.loss_fn(p, b, r, True)[0])

    def train(v: Pytree, w_ref: Pytree, data: Dict[str, jax.Array],
              rng: jax.Array):
        num_steps = jax.tree.leaves(data)[0].shape[0]
        clip_state = clip.init(v) if clip is not None else None

        def step(carry, step_idx):
            v, rng = carry
            rng, drng = jax.random.split(rng)
            batch = jax.tree.map(lambda x: x[step_idx % num_steps], data)
            grads = grad_fn(v, batch, drng)
            grads = jax.tree.map(lambda g, vi, wi: g + lam * (vi - wi),
                                 grads, v, w_ref)
            if clip is not None:
                grads, _ = clip.update(grads, clip_state)
            gd = (jnp.sum(batch["mask"]) > 0).astype(jnp.float32)
            v = jax.tree.map(lambda p, g: p - lr * gd * g, v, grads)
            return (v, rng), None

        (v, _), _ = jax.lax.scan(step, (v, rng),
                                 jnp.arange(epochs * num_steps))
        return v

    return train


class Ditto(FedAvg):
    """FedAvg.run drives this via the replaced ``cohort_step`` (host-gather
    path — the stacked v_i state is scattered back per round, which the
    HBM fast paths don't model).  The step re-derives the round's client
    ids from the same seeded sampling chain run() used to gather the
    cohort (the SCAFFOLD pattern).

    ``mesh=`` shards the clients axis: the global stream rides FedAvg's
    sharded cohort step and the personal pass is a pure shard_map (no
    cross-client reductions; matches single-chip to float tolerance —
    parity-tested).  v_i stays host-resident; multi-process meshes ride
    the shared wrap (make_sharded_stateful_round: global input staging +
    replicated state outputs, every process mirrors the full state)."""

    def __init__(self, workload, data, config: DittoConfig, mesh=None,
                 sink=None):
        if getattr(workload, "stateful", False):
            raise ValueError(
                "ditto does not support stateful (BatchNorm) workloads: "
                "the proximal pull over running statistics is undefined — "
                "use a GroupNorm model (e.g. resnet18_gn)")
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        cfg = config
        self._round_counter = 0
        self.v_locals = None  # stacked [client_num_in_total, ...]
        p_lr = cfg.personal_lr or cfg.lr
        p_epochs = cfg.personal_epochs or cfg.epochs
        personal = make_ditto_local(workload, p_lr, p_epochs,
                                    cfg.ditto_lambda)

        def personal_core(w_ref, cohort, rng, v_cohort,
                          psum_axis=None, index_offset=0):
            """The personal pass over (a shard of) the cohort.  Purely
            per-client — no cross-client reductions, so ``psum_axis`` is
            accepted for the shared mesh-wrap convention but unused; rng
            folds by GLOBAL cohort slot (parallel/cohort.py)."""
            del psum_axis
            n = cohort["num_samples"].shape[0]
            rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                jnp.arange(n) + index_offset)
            batches = {k: v for k, v in cohort.items()
                       if k != "num_samples"}
            new_v = jax.vmap(personal, in_axes=(0, None, 0, 0))(
                v_cohort, w_ref, batches, rngs)
            # padded slots (weight 0) keep their previous state
            live = (cohort["num_samples"] > 0).astype(jnp.float32)
            return jax.tree.map(
                lambda nv, v: jnp.where(
                    live.reshape((-1,) + (1,) * (v.ndim - 1)) > 0, nv, v),
                new_v, v_cohort)

        if mesh is None:
            jitted = jax.jit(personal_core)
        else:
            from jax.sharding import PartitionSpec as P
            from fedml_tpu.parallel.cohort import make_sharded_stateful_round
            jitted = make_sharded_stateful_round(
                personal_core, mesh,
                in_specs=(P(), P("clients"), P(), P("clients")),
                out_specs=P("clients"))
        self._personal_round = jitted
        # vmapped per-client evaluator: client i's OWN params on its OWN
        # shard; metric dicts are sums, so cross-client aggregation is a
        # tree-sum (same convention as cohort_eval)
        self._personal_eval = jax.jit(
            lambda vs, data: jax.tree.map(
                lambda m: jnp.sum(m, axis=0),
                jax.vmap(self.evaluate, in_axes=(0, 0))(vs, data)))
        self.cohort_step = self._ditto_step

    def run(self, params=None, rng=None, checkpointer=None):
        # fresh runs restart the sampling-chain mirror AND the personalized
        # state (v_i = w^0 on first sight); a checkpoint resume restores
        # both via _load_extra_state afterwards
        self._round_counter = 0
        self.v_locals = None
        return super().run(params=params, rng=rng, checkpointer=checkpointer)

    def _ditto_step(self, params, cohort, rng):
        if self.v_locals is None:
            # paper init: v_i = w^0, as HOST buffers (the stacked-state
            # convention, fedavg.py — full [N, ...] state never sits in HBM)
            self.v_locals = jax.tree.map(
                lambda x: np.broadcast_to(
                    np.asarray(x)[None],
                    (self.data.client_num,) + x.shape).copy(), params)
        # global stream: EXACTLY FedAvg, consuming the round rng unchanged
        new_params, aux = self._base_cohort_step(params, cohort, rng)
        # THE loop's own sampling hook (not sample_clients directly), so a
        # subclass overriding _sample_round cannot desync the state mirror
        ids = self._sample_round(self._round_counter)
        self._round_counter += 1
        v_cohort = gather_client_rows(self.v_locals, ids,
                                      cohort["num_samples"].shape[0])
        p_rng = jax.random.fold_in(rng, _PERSONAL_STREAM)
        new_v = self._personal_round(params, cohort, p_rng, v_cohort)
        self.v_locals = scatter_client_rows(self.v_locals, ids, new_v)
        return new_params, aux

    # -- personalized evaluation ------------------------------------------
    def evaluate_personalized(self) -> Dict[str, float]:
        """Sample-weighted metrics of each client's PERSONAL model on its
        own train/test shard (the paper's reported metric), swept in
        ``eval_chunk_clients`` chunks like evaluate_global."""
        from fedml_tpu.utils.metrics import stats_from_metrics
        if self.v_locals is None:
            return {}
        out: Dict[str, float] = {}
        for split, stacked in (("train", self.data.train),
                               ("test", self.data.test)):
            if stacked is None:
                continue
            # never pad ABOVE the corpus size: a 3-client run with the
            # default chunk=1024 would otherwise stack 1024 zero-padded
            # copies of the model params per eval (evaluate_global's gate
            # is `n_clients > chunk`; this is the same rule)
            n_clients = stacked["num_samples"].shape[0]
            chunk = min(self.cfg.eval_chunk_clients or n_clients, n_clients)
            from fedml_tpu.algorithms.fedavg import sweep_eval_chunks
            from fedml_tpu.parallel.cohort import pad_clients

            def run_chunk(part, lo):
                # per-client params ride the same zero-pad convention as
                # the data rows: padded rows carry mask 0, so the
                # zero-padded params rows contribute nothing
                v_chunk = jax.tree.map(
                    lambda v: pad_clients(
                        {"v": v[lo:lo + chunk]}, chunk)["v"],
                    self.v_locals)
                return self._personal_eval(
                    v_chunk, {k: part[k] for k in ("x", "y", "mask")})

            total = sweep_eval_chunks(stacked, chunk, run_chunk)
            out.update(stats_from_metrics(total,
                                          prefix=f"personal_{split}_"))
        return out

    def evaluate_global(self, params) -> Dict[str, float]:
        out = super().evaluate_global(params)
        out.update(self.evaluate_personalized())
        return out

    # personalized state rides the round checkpoint (async saves snapshot
    # the mutable numpy buffers — RoundCheckpointer.save)
    def _extra_state(self):
        return {"v_locals": self.v_locals,
                "round_counter": self._round_counter}

    def _extra_state_template(self, params):
        return {"v_locals": zeros_client_state(params,
                                               self.data.client_num),
                "round_counter": 0}

    def _load_extra_state(self, extra) -> None:
        # stacked state is host-resident by convention (fedavg.py)
        self.v_locals = jax.tree.map(np.asarray, extra["v_locals"])
        self._round_counter = int(extra["round_counter"])
