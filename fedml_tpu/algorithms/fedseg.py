"""FedSeg — federated semantic segmentation (FedAvg + seg losses/metrics).

Reference (``fedml_api/distributed/fedseg/``): FedAvg aggregation over
DeeplabV3+/U-Net, with segmentation-specific machinery:

* ``SegmentationLosses`` (fedseg/utils.py:71-113): pixel CE with
  ``ignore_index=255`` and a focal variant (γ=2, α=0.5);
* ``Evaluator`` confusion-matrix metrics: pixel accuracy, per-class
  accuracy, mIoU, FWIoU — tracked per round in ``EvaluationMetricsKeeper``
  (fedseg/utils.py:62-69, FedSegAggregator.py:12-160).

TPU-native: the loss and the confusion matrix are jit'd (the confusion
matrix is a one-hot matmul — MXU-friendly); the federated loop reuses the
shared cohort engine via a `SegmentationWorkload`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.trainer.workload import Workload

IGNORE_INDEX = 255


def segmentation_ce(logits: jnp.ndarray, target: jnp.ndarray,
                    ignore_index: int = IGNORE_INDEX) -> jnp.ndarray:
    """Mean pixel CE over non-ignored pixels (SegmentationLosses
    .CrossEntropyLoss, fedseg/utils.py:86-95)."""
    valid = (target != ignore_index)
    safe_t = jnp.where(valid, target, 0)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, safe_t)
    m = valid.astype(logits.dtype)
    return jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)


def segmentation_focal(logits: jnp.ndarray, target: jnp.ndarray,
                       gamma: float = 2.0, alpha: float = 0.5,
                       ignore_index: int = IGNORE_INDEX) -> jnp.ndarray:
    """Focal loss -α(1-p)^γ log p (fedseg/utils.py:97-112)."""
    valid = (target != ignore_index)
    safe_t = jnp.where(valid, target, 0)
    logpt = -optax.softmax_cross_entropy_with_integer_labels(logits, safe_t)
    pt = jnp.exp(logpt)
    loss = -alpha * ((1.0 - pt) ** gamma) * logpt
    m = valid.astype(logits.dtype)
    return jnp.sum(loss * m) / jnp.maximum(jnp.sum(m), 1.0)


def confusion_matrix(pred: jnp.ndarray, target: jnp.ndarray,
                     num_classes: int,
                     ignore_index: int = IGNORE_INDEX) -> jnp.ndarray:
    """[num_classes, num_classes] counts, rows = truth, cols = prediction
    (the reference Evaluator's generate_matrix).  One-hot matmul keeps it on
    the MXU instead of a scatter."""
    valid = (target != ignore_index) & (target >= 0) & (target < num_classes)
    t1 = jax.nn.one_hot(jnp.where(valid, target, 0), num_classes,
                        dtype=jnp.float32)
    p1 = jax.nn.one_hot(pred, num_classes, dtype=jnp.float32)
    t1 = t1 * valid[..., None]
    return jnp.einsum("...i,...j->ij", t1, p1)


def metrics_from_confusion(cm: np.ndarray) -> Dict[str, float]:
    """Pixel acc / class acc / mIoU / FWIoU (reference Evaluator formulas)."""
    cm = np.asarray(cm, np.float64)
    eps = 1e-12
    total = cm.sum()
    acc = np.diag(cm).sum() / max(total, eps)
    per_class = np.diag(cm) / np.maximum(cm.sum(axis=1), eps)
    acc_class = np.nanmean(np.where(cm.sum(axis=1) > 0, per_class, np.nan))
    union = cm.sum(axis=1) + cm.sum(axis=0) - np.diag(cm)
    iou = np.diag(cm) / np.maximum(union, eps)
    miou = np.nanmean(np.where(union > 0, iou, np.nan))
    freq = cm.sum(axis=1) / max(total, eps)
    fwiou = (freq[freq > 0] * iou[freq > 0]).sum()
    return {"acc": float(acc), "acc_class": float(acc_class),
            "mIoU": float(miou), "FWIoU": float(fwiou)}


@dataclasses.dataclass
class EvaluationMetricsKeeper:
    """fedseg/utils.py:62-69."""
    accuracy: float
    accuracy_class: float
    mIoU: float
    FWIoU: float
    loss: float


def SegmentationWorkload(model, num_classes: int, loss_mode: str = "ce",
                         grad_clip_norm: Optional[float] = None) -> Workload:
    """Per-pixel workload pluggable into the shared cohort/FedAvg engine.
    Batches: {"x": [B, H, W, C], "y": [B, H, W] int, "mask": [B]}."""
    loss_core = segmentation_ce if loss_mode == "ce" else segmentation_focal

    def loss_fn(params, batch, rng, train):
        logits = model.apply({"params": params}, batch["x"], train=train)
        # fold the row mask in by marking padded rows as ignore
        y = jnp.where(batch["mask"][:, None, None] > 0, batch["y"],
                      IGNORE_INDEX)
        loss = loss_core(logits, y)
        return loss, {"loss": loss}

    def metric_fn(params, batch):
        logits = model.apply({"params": params}, batch["x"], train=False)
        y = jnp.where(batch["mask"][:, None, None] > 0, batch["y"],
                      IGNORE_INDEX)
        pred = jnp.argmax(logits, axis=-1)
        cm = confusion_matrix(pred, y, num_classes)
        valid = (y != IGNORE_INDEX)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.where(valid, y, 0))
        return {"confusion": cm,
                "correct": jnp.sum((pred == y) * valid),
                "loss_sum": jnp.sum(ce * valid),
                "total": jnp.sum(valid)}

    return Workload(model=model, loss_fn=loss_fn, metric_fn=metric_fn,
                    grad_clip_norm=grad_clip_norm)


def evaluate_segmentation(workload: Workload, params,
                          data: Dict[str, jnp.ndarray]
                          ) -> EvaluationMetricsKeeper:
    """Run metric_fn over [S, B, ...] batches and fold into the keeper
    (FedSegAggregator.test_on_server_for_all_clients analog).

    Deliberately NOT the scan-based ``make_evaluator``: pixel counts are
    accumulated host-side in float64 because an on-device f32 scan sum stops
    registering +1 increments once any confusion cell passes 2^24 (~16.7M
    pixels — a few hundred 512² images), silently corrupting acc/mIoU."""
    fn = jax.jit(workload.metric_fn)
    agg = None
    for s in range(data["x"].shape[0]):
        m = fn(params, {k: data[k][s] for k in ("x", "y", "mask")})
        m64 = {k: np.asarray(v, np.float64) for k, v in m.items()}
        agg = m64 if agg is None else {k: agg[k] + m64[k] for k in agg}
    stats = metrics_from_confusion(agg["confusion"])
    total = float(agg["total"])
    return EvaluationMetricsKeeper(
        accuracy=stats["acc"], accuracy_class=stats["acc_class"],
        mIoU=stats["mIoU"], FWIoU=stats["FWIoU"],
        loss=float(agg["loss_sum"]) / max(total, 1.0))
