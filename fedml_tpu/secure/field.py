"""Finite-field MPC toolbox (host-side, vectorized numpy).

Capability parity with the reference's TurboAggregate kernel
(``fedml_api/distributed/turboaggregate/mpc_function.py``): modular inverse
(:4), modular division (:21), products mod p (:29), Lagrange coefficients
(:38), BGW/Shamir encoding & decoding (:61,:91), LCC encoding/decoding with
both centered-range and explicit evaluation points (:110,:195,:228,:249),
additive secret shares (:215), and the DH-style key helpers (:264,:271).

Re-designed, not translated: the reference builds everything from scalar
Python loops over ``np.mod`` scalars; here polynomial evaluation and share
reconstruction are vectorized matmul-like contractions with a reduction-mod
after every rank-1 term (terms are < p² < 2⁶², so int64 accumulate-then-mod
per term is exact).  Inverses use Fermat's little theorem (p is prime) with
square-and-multiply, vectorized over arrays.

Default prime: 2³¹ − 1 (Mersenne), the largest prime whose products fit
int64.  All shapes follow the reference: secrets are [m, d] matrices shared
into [N, m, d] share tensors.
"""

from __future__ import annotations

import numpy as np

P_DEFAULT = np.int64(2**31 - 1)


def _as_field(x, p) -> np.ndarray:
    return np.mod(np.asarray(x, dtype=np.int64), p)


def pow_mod(base, exp: int, p) -> np.ndarray:
    """Vectorized base**exp mod p by square-and-multiply (exp a python int)."""
    base = _as_field(base, p)
    result = np.ones_like(base)
    e = int(exp)
    while e > 0:
        if e & 1:
            result = np.mod(result * base, p)
        base = np.mod(base * base, p)
        e >>= 1
    return result


def mod_inv(a, p=P_DEFAULT) -> np.ndarray:
    """a^{-1} mod p for prime p (Fermat), vectorized.

    Parity: ``modular_inv`` (mpc_function.py:4-18), which is the scalar
    extended-Euclid; same output for all units of Z_p."""
    a = _as_field(a, p)
    if np.any(a == 0):
        raise ZeroDivisionError("0 has no inverse mod p")
    return pow_mod(a, int(p) - 2, p)


def mod_div(num, den, p=P_DEFAULT) -> np.ndarray:
    """num / den mod p (parity: ``divmod``, mpc_function.py:21-27)."""
    return np.mod(_as_field(num, p) * mod_inv(den, p), p)


def prod_mod(vals, p=P_DEFAULT) -> np.ndarray:
    """Product of values mod p (parity: ``PI``, mpc_function.py:29-35)."""
    acc = np.int64(1)
    for v in np.asarray(vals, dtype=np.int64).ravel():
        acc = np.mod(acc * np.mod(v, p), p)
    return acc


def lagrange_coeffs(alpha_s, beta_s, p=P_DEFAULT) -> np.ndarray:
    """U[i, j] = prod_{k≠j} (alpha_i - beta_k) / (beta_j - beta_k) mod p.

    Evaluating at ``alpha_s`` the interpolation polynomial through points
    ``beta_s``.  Parity: ``gen_Lagrange_coeffs`` (mpc_function.py:38-57);
    vectorized over i with one inverse batch instead of O(n²) scalar
    inversions."""
    alpha_s = _as_field(alpha_s, p).ravel()
    beta_s = _as_field(beta_s, p).ravel()
    n_a, n_b = len(alpha_s), len(beta_s)
    # dens[j] = prod_{k != j} (beta_j - beta_k)
    diff_b = np.mod(beta_s[:, None] - beta_s[None, :], p)  # [n_b, n_b]
    np.fill_diagonal(diff_b, 1)
    dens = np.ones(n_b, dtype=np.int64)
    for k in range(n_b):
        dens = np.mod(dens * diff_b[:, k], p)
    inv_dens = mod_inv(dens, p)
    # nums[i, j] = prod_{k != j} (alpha_i - beta_k)
    diff_ab = np.mod(alpha_s[:, None] - beta_s[None, :], p)  # [n_a, n_b]
    U = np.empty((n_a, n_b), dtype=np.int64)
    for j in range(n_b):
        num = np.ones(n_a, dtype=np.int64)
        for k in range(n_b):
            if k != j:
                num = np.mod(num * diff_ab[:, k], p)
        U[:, j] = np.mod(num * inv_dens[j], p)
    return U


def _coded_combine(U: np.ndarray, X_sub: np.ndarray, p) -> np.ndarray:
    """out[i] = sum_j U[i,j] * X_sub[j] mod p, with mod after every rank-1
    term so int64 never overflows (each term < p²)."""
    out = np.zeros((U.shape[0],) + X_sub.shape[1:], dtype=np.int64)
    for j in range(U.shape[1]):
        out = np.mod(out + np.mod(U[:, j].reshape((-1,) + (1,) * (X_sub.ndim - 1))
                                  * X_sub[j], p), p)
    return out


# -- BGW / Shamir ------------------------------------------------------------

def bgw_encode(X, N: int, T: int, p=P_DEFAULT,
               rng: np.random.RandomState | None = None) -> np.ndarray:
    """Shamir-share secret [m, d] into N shares with threshold T.

    Share i is the degree-T polynomial f(alpha_i) with f(0)=X and random
    higher coefficients.  Parity: ``BGW_encoding`` (mpc_function.py:61-75),
    vectorized: evaluation is a Vandermonde contraction."""
    X = _as_field(X, p)
    rng = rng or np.random.RandomState()
    coeffs = np.concatenate([
        X[None], rng.randint(0, int(p), size=(T,) + X.shape).astype(np.int64)])
    alpha_s = _as_field(np.arange(1, N + 1), p)
    # vandermonde[i, t] = alpha_i^t
    vander = np.stack([pow_mod(alpha_s, t, p) for t in range(T + 1)], axis=1)
    return _coded_combine(vander, coeffs, p)


def bgw_decode(shares: np.ndarray, worker_idx, p=P_DEFAULT) -> np.ndarray:
    """Reconstruct the secret from ≥ T+1 shares by Lagrange interpolation at
    0.  ``worker_idx`` are 0-based share owners (alpha_i = idx+1).  Parity:
    ``BGW_decoding`` + ``gen_BGW_lambda_s`` (mpc_function.py:78-107)."""
    worker_idx = np.asarray(worker_idx)
    alpha_eval = _as_field(worker_idx + 1, p)
    lam = lagrange_coeffs(np.zeros(1), alpha_eval, p)  # evaluate at 0
    return _coded_combine(lam, _as_field(shares, p), p)[0]


# -- Lagrange-coded computing ------------------------------------------------

def _centered_points(N: int, K: int, T: int, p):
    """Interpolation grid (beta, K+T points, centered) and evaluation grid
    (alpha, N points).

    The reference centers BOTH grids at 0 (mpc_function.py:119-124), which
    makes them overlap: a worker whose alpha equals a secret chunk's beta
    receives that chunk in PLAINTEXT (Lagrange evaluation at a node is the
    identity), voiding T-privacy.  Here the alpha grid starts right after
    the beta grid so the two are disjoint and every share is a proper
    polynomial mixture."""
    n_beta = K + T
    stt_b = -int(np.floor(n_beta / 2))
    beta_s = _as_field(np.arange(stt_b, stt_b + n_beta), p)
    stt_a = stt_b + n_beta  # first point past the beta grid
    alpha_s = _as_field(np.arange(stt_a, stt_a + N), p)
    return alpha_s, beta_s


def lcc_encode(X, N: int, K: int, T: int, p=P_DEFAULT,
               rng: np.random.RandomState | None = None,
               R: np.ndarray | None = None,
               worker_idx=None) -> np.ndarray:
    """LCC-encode secret [m, d] (m divisible by K) into N coded shares.

    The secret splits into K chunks + T random chunks, interpolated through
    the beta grid and evaluated on the alpha grid.  Covers the reference's
    three variants in one function: ``LCC_encoding`` (mpc_function.py:110-133,
    R drawn internally), ``LCC_encoding_w_Random`` (:136-163, caller-supplied
    R), and ``_partial`` (:166-192, only ``worker_idx`` rows)."""
    X = _as_field(X, p)
    m = X.shape[0]
    assert m % K == 0, f"number of secret rows ({m}) must be a multiple of K ({K})"
    chunk = m // K
    X_sub = X.reshape(K, chunk, *X.shape[1:])
    if T > 0:
        if R is None:
            rng = rng or np.random.RandomState()
            R = rng.randint(0, int(p), size=(T, chunk) + X.shape[1:])
        X_sub = np.concatenate([X_sub, _as_field(R, p)])
    alpha_s, beta_s = _centered_points(N, K, T, p)
    if worker_idx is not None:
        alpha_s = alpha_s[np.asarray(worker_idx)]
    U = lagrange_coeffs(alpha_s, beta_s, p)
    return _coded_combine(U, X_sub, p)


def lcc_decode(f_eval, N: int, K: int, T: int, worker_idx,
               p=P_DEFAULT) -> np.ndarray:
    """Decode LCC evaluations back to the K secret chunks (stacked).

    Parity target: ``LCC_decoding`` (mpc_function.py:195-212) — interpolate
    through the surviving workers' alpha points, evaluate at the secret
    chunks' beta points.  NOTE a correctness divergence: the reference
    rebuilds its beta grid over only K points (``n_beta = K``, :198), which
    matches the K+T-point *encoding* grid (:119-124) only when T == 0 — with
    privacy chunks (T > 0) its decode evaluates at shifted points and returns
    garbage for part of the secret.  Here decode evaluates at the first K
    betas of the actual encoding grid, so encode→decode round-trips for all
    T."""
    worker_idx = np.asarray(worker_idx)
    if len(worker_idx) < K + T:
        raise ValueError(
            f"LCC decode needs at least K+T = {K + T} surviving shares to "
            f"interpolate a degree-{K + T - 1} polynomial; got "
            f"{len(worker_idx)}")
    alpha_s, beta_enc = _centered_points(N, K, T, p)
    beta_s = beta_enc[:K]
    alpha_eval = alpha_s[worker_idx]
    U_dec = lagrange_coeffs(beta_s, alpha_eval, p)
    out = _coded_combine(U_dec, _as_field(f_eval, p), p)
    return out.reshape((-1,) + out.shape[2:]) if out.ndim > 2 else out


def lcc_encode_with_points(X, alpha_s, beta_s, p=P_DEFAULT) -> np.ndarray:
    """Evaluate the polynomial through (alpha_s, X) at points beta_s.

    Parity: ``LCC_encoding_with_points`` (mpc_function.py:228-246).  Note the
    reference's argument naming swaps alpha/beta relative to lcc_encode."""
    U = lagrange_coeffs(beta_s, alpha_s, p)
    return _coded_combine(U, _as_field(X, p), p)


def lcc_decode_with_points(f_eval, eval_points, target_points,
                           p=P_DEFAULT) -> np.ndarray:
    """Parity: ``LCC_decoding_with_points`` (mpc_function.py:249-261)."""
    U_dec = lagrange_coeffs(target_points, eval_points, p)
    return _coded_combine(U_dec, _as_field(f_eval, p), p)


# -- additive shares & key agreement ----------------------------------------

def additive_shares(x, n_out: int, p=P_DEFAULT,
                    rng: np.random.RandomState | None = None) -> np.ndarray:
    """Split vector [d] into n_out additive shares summing to x mod p.

    Parity: ``Gen_Additive_SS`` (mpc_function.py:215-225) — but shares the
    *input* rather than returning zero-sum noise only."""
    x = _as_field(x, p)
    rng = rng or np.random.RandomState()
    shares = rng.randint(0, int(p), size=(n_out - 1,) + x.shape).astype(np.int64)
    last = np.mod(x - np.mod(shares.sum(axis=0), p), p)
    return np.concatenate([shares, last[None]])


def pk_gen(sk, p=P_DEFAULT, g: int = 0):
    """Public key g^sk mod p (g=0 ⇒ identity map, the reference's test mode).
    Parity: ``my_pk_gen`` (mpc_function.py:264-268)."""
    return sk if g == 0 else pow_mod(np.int64(g), int(sk), p)


def key_agreement(my_sk, peer_pk, p=P_DEFAULT, g: int = 0):
    """Shared secret peer_pk^sk mod p (g=0 ⇒ product map).
    Parity: ``my_key_agreement`` (mpc_function.py:271-275)."""
    if g == 0:
        return np.mod(np.int64(my_sk) * np.int64(peer_pk), p)
    return pow_mod(np.int64(peer_pk), int(my_sk), p)
