"""Versioned model registry with atomic hot-swap — the serving side of
the checkpoint contract.

The federation produces a new global model every round; requests must
never see half of one.  The whole live state is one immutable
`ServedModel` snapshot (params, apply_fn, version) swapped by a single
attribute assignment, so a reader that grabbed the snapshot keeps a
consistent triple no matter how many swaps land mid-request — zero
request downtime, zero torn reads (tests/test_serve.py hammers this
under concurrent load).

Feeds:

* ``publish(params, version)`` — direct, used by the cross-silo server's
  serve-while-train hook (`FedAvgServerActor(publish=registry.publish)`):
  the federation serves its own global model *while training*.
* `CheckpointWatcher` — a background thread polling a `RoundCheckpointer`
  directory (utils/checkpoint.py) for new round steps and publishing
  them; tolerant of a step directory GC'd (``keep_last_n``) between list
  and load.

Operational controls: ``pin(version)`` freezes serving on a known-good
version while publishes keep accumulating history; ``rollback()`` steps
the live model back one version (and pins there, so the next publish
doesn't immediately re-roll); ``unpin()`` resumes following the newest.

Release states (ISSUE 16): every history entry is either **promoted**
(vetted — has served, or was published on the direct ungated path) or a
**canary** (entered via ``publish(..., canary=True)`` by the
`serve.release.ReleaseController`; in history for shadow evaluation but
NEVER the live slot until ``promote()``).  ``rollback()`` steps back to
the previous *promoted* version — a failed canary can never roll
serving onto another unvetted model — and fails loudly when no older
promoted version exists (the promoted horizon).  Canaries are
eviction-protected while pending (the gate always resolves them to
``promote`` or ``discard``), so a verdict can never race retention.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

Pytree = Any


class ServedModel:
    """One immutable serving snapshot.  Readers hold the OBJECT, never the
    registry's mutable slot — consistency by construction."""
    __slots__ = ("params", "apply_fn", "version")

    def __init__(self, params: Pytree, apply_fn: Callable, version: int):
        self.params = params
        self.apply_fn = apply_fn
        self.version = int(version)

    def __repr__(self):
        return f"ServedModel(version={self.version})"


class ModelRegistry:
    """Monotonic version store + the single live-model slot.

    Writers (publish/pin/rollback) serialize on a lock; readers call
    ``current()`` lock-free — the live slot is swapped by one reference
    assignment (atomic under the GIL), and every snapshot is immutable.
    """

    def __init__(self, apply_fn: Callable, history: int = 4):
        if history < 2:
            raise ValueError(f"history must keep >= 2 versions for "
                             f"rollback; got {history}")
        self._apply_fn = apply_fn
        self._max_history = history
        self._lock = threading.Lock()
        self._history: "OrderedDict[int, ServedModel]" = OrderedDict()
        self._state: dict = {}  # version -> "promoted" | "canary"
        self._pinned: Optional[int] = None
        self._live: Optional[ServedModel] = None
        reg = telemetry.get_registry()
        self._g_version = reg.gauge("fedml_serve_model_version_total")
        self._c_swap = reg.counter("fedml_serve_hot_swap_total")
        self._c_rollback = reg.counter("fedml_serve_rollback_total")

    # -- read path (request hot path) ---------------------------------------
    def current(self) -> Optional[ServedModel]:
        """The live snapshot, or None before the first publish."""
        return self._live

    @property
    def version(self) -> Optional[int]:
        m = self._live
        return None if m is None else m.version

    @property
    def pinned(self) -> Optional[int]:
        return self._pinned

    def versions(self) -> list:
        with self._lock:
            return list(self._history)

    def state(self, version: int) -> str:
        """Release state of a history entry: "promoted" | "canary"."""
        with self._lock:
            if version not in self._history:
                raise KeyError(f"version {version} not in registry "
                               f"history {list(self._history)}")
            return self._state[version]

    def canaries(self) -> list:
        """Versions still awaiting a release verdict."""
        with self._lock:
            return [v for v in self._history
                    if self._state[v] == "canary"]

    def get(self, version: int) -> ServedModel:
        """The snapshot for ``version`` (shadow replay reads the canary
        without ever touching the live slot)."""
        with self._lock:
            if version not in self._history:
                raise KeyError(f"version {version} not in registry "
                               f"history {list(self._history)}")
            return self._history[version]

    # -- write path ---------------------------------------------------------
    def publish(self, params: Pytree, version: int,
                canary: bool = False) -> bool:
        """Register a new model version; hot-swap it live unless a pin is
        holding an older version.  Returns True when the version was NEW
        (stale/duplicate publishes — e.g. a watcher and a train hook both
        feeding the registry — are ignored, preserving monotonicity).

        ``canary=True`` (the release gate's entry path): the version
        lands in history but NEVER swaps the live slot — it serves only
        shadow traffic until ``promote()`` or ``discard()`` resolves it.
        """
        version = int(version)
        snapshot = ServedModel(params, self._apply_fn, version)
        with self._lock:
            if self._history and version <= next(reversed(self._history)):
                return False
            self._history[version] = snapshot
            self._state[version] = "canary" if canary else "promoted"
            self._evict_locked()
            if not canary and self._pinned is None:
                self._live = snapshot
                self._c_swap.inc()
            if self._live is not None:  # gauge tracks the SERVING version
                self._g_version.set(self._live.version)
        log.info("registry: published version %d%s", version,
                 " (canary, not live)" if canary else
                 (" (pinned, not live)" if self._pinned is not None
                  else ""))
        return True

    def _evict_locked(self) -> None:
        # evict oldest-first but NEVER the pinned, live, or a pending
        # canary version: a long serve-while-train run publishing past a
        # pin must not make the pinned model un-rollback-able, and a
        # canary awaiting its verdict must not vanish mid-evaluation
        while len(self._history) > self._max_history:
            protected = {self._pinned}
            if self._live is not None:
                protected.add(self._live.version)
            protected.update(v for v in self._history
                             if self._state[v] == "canary")
            evict = next((k for k in self._history
                          if k not in protected), None)
            if evict is None:
                break
            del self._history[evict]
            self._state.pop(evict, None)

    def promote(self, version: int) -> int:
        """Resolve a canary as vetted: mark it promoted, swap it live,
        and pin there (the promoted horizon — on the gated path serving
        only ever moves by an explicit verdict).  Idempotent when the
        version is already promoted AND live (the crash-at-
        ``canary_promote`` respawn re-drives the verdict safely).
        The swap is ONE lock-guarded reference assignment, so a process
        killed anywhere around it leaves the registry either fully
        pre-promote or fully post-promote — never between."""
        with self._lock:
            if version not in self._history:
                raise KeyError(f"version {version} not in registry "
                               f"history {list(self._history)}; cannot "
                               f"promote")
            if self._state[version] == "promoted":
                if self._live is not None \
                        and self._live.version == version:
                    return version  # respawn replay: already done
                raise RuntimeError(
                    f"version {version} is promoted but not live "
                    f"(live={None if self._live is None else self._live.version}); "
                    f"promote() resolves canaries — use pin() to move "
                    f"serving between vetted versions")
            self._state[version] = "promoted"
            self._pinned = version
            self._live = self._history[version]
            self._c_swap.inc()
            self._g_version.set(version)
        log.info("registry: PROMOTED canary version %d (live, pinned)",
                 version)
        return version

    def discard(self, version: int) -> None:
        """Resolve a canary as rejected: drop it from history.  The live
        slot never moved for a canary, so this IS the rollback — serving
        stays on the last promoted version.  Promoted versions cannot be
        discarded (serving history is the rollback chain)."""
        with self._lock:
            if version not in self._history:
                raise KeyError(f"version {version} not in registry "
                               f"history {list(self._history)}; cannot "
                               f"discard")
            if self._state[version] != "canary":
                raise RuntimeError(
                    f"version {version} is promoted; discard() resolves "
                    f"canaries only — promoted history is the rollback "
                    f"chain")
            del self._history[version]
            del self._state[version]
        log.warning("registry: discarded canary version %d", version)

    def pin(self, version: int) -> None:
        """Freeze serving on ``version`` (must still be in history and
        promoted — a pin can never put an unvetted canary live).
        Publishes keep landing in history but stop swapping live."""
        with self._lock:
            if version not in self._history:
                raise KeyError(
                    f"version {version} not in registry history "
                    f"{list(self._history)}; cannot pin")
            if self._state[version] != "promoted":
                raise RuntimeError(
                    f"version {version} is an unvetted canary; pin() "
                    f"serves promoted versions only — resolve it via "
                    f"promote()/discard() first")
            self._pinned = version
            self._live = self._history[version]
            self._g_version.set(version)

    def unpin(self) -> None:
        """Resume following the newest PROMOTED version (a pending
        canary is never served by unpinning past it)."""
        with self._lock:
            self._pinned = None
            newest = next(
                (v for v in reversed(self._history)
                 if self._state[v] == "promoted"), None)
            if newest is not None:
                self._live = self._history[newest]
                self._g_version.set(newest)

    def rollback(self) -> int:
        """Step the live model back to the previous PROMOTED version and
        pin there (so the next publish doesn't instantly re-roll).
        Canary entries are skipped — rollback must never land serving on
        an unvetted model — and rolling past the promoted horizon (no
        older promoted version in history) fails loudly instead of
        serving whatever happens to be oldest.  Returns the version now
        live."""
        with self._lock:
            if self._live is None:
                raise RuntimeError("rollback before any publish")
            versions = list(self._history)
            idx = versions.index(self._live.version)
            target = next(
                (v for v in reversed(versions[:idx])
                 if self._state[v] == "promoted"), None)
            if target is None:
                promoted = [v for v in versions
                            if self._state[v] == "promoted"]
                raise RuntimeError(
                    f"no promoted version older than {self._live.version} "
                    f"in history {versions} (promoted horizon: "
                    f"{promoted}); cannot rollback onto an unvetted "
                    f"canary")
            self._pinned = target
            self._live = self._history[target]
            self._g_version.set(target)
            self._c_rollback.inc()
        log.warning("registry: rolled back to version %d (pinned)", target)
        return target


def _list_steps(ckpt_dir: str) -> list:
    """Integer-named child dirs = completed orbax steps (orbax writes to a
    tmp-named dir and renames, so a digit-named dir is a durable step)."""
    try:
        names = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return []
    return sorted(int(n) for n in names if n.isdigit())


class CheckpointWatcher:
    """Background thread: poll a `RoundCheckpointer` directory, publish
    new rounds into a `ModelRegistry`.

    Each load opens a FRESH read-side `RoundCheckpointer` so the live
    writer's orbax manager (possibly mid-async-save in another process)
    is never shared.  A step that vanishes between list and load — the
    checkpointer's ``keep_last_n`` GC racing us — is counted and skipped,
    never fatal; it is marked seen so the watcher doesn't spin on it.

    Torn-file hardening (ISSUE 16): the writer stamps every step with a
    checksum manifest (`utils.checkpoint.manifest_path`, atomic-rename
    via `utils.journal.atomic_write`).  When a manifest exists, the
    loaded params must match its crc32 — a truncated orbax file, a
    half-written manifest, or any torn read skips-and-warns
    (``outcome="corrupt"``) instead of crashing the watcher or serving
    garbage.  A step with NO manifest takes the pre-manifest load path
    unverified (old checkpoint trees keep serving).
    """

    def __init__(self, registry: ModelRegistry, ckpt_dir: str,
                 poll_s: float = 0.5, param_key: str = "params"):
        self.registry = registry
        self.ckpt_dir = ckpt_dir
        self.poll_s = poll_s
        self.param_key = param_key
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seen = -1  # highest step already published or skipped
        reg = telemetry.get_registry()
        self._c_loads = reg.counter("fedml_serve_checkpoint_load_total",
                                    outcome="ok")
        self._c_vanished = reg.counter("fedml_serve_checkpoint_load_total",
                                       outcome="vanished")
        self._c_corrupt = reg.counter("fedml_serve_checkpoint_load_total",
                                      outcome="corrupt")

    def poll_once(self) -> int:
        """One list-and-load sweep (the thread's loop body; also the
        deterministic test surface).  Returns how many new versions were
        published."""
        published = 0
        for step in _list_steps(self.ckpt_dir):
            if step <= self._seen:
                continue
            params = self._load(step)
            self._seen = max(self._seen, step)
            if params is not None:
                self.registry.publish(params, step)
                self._c_loads.inc()
                published += 1
        return published

    def _load(self, step: int):
        from fedml_tpu.utils.checkpoint import (RoundCheckpointer,
                                                _pack_keys, manifest_path)
        from fedml_tpu.utils.journal import tree_crc
        # the atomic-rename + checksum contract, verified BEFORE serving:
        # a manifest that exists but cannot be parsed is a torn write —
        # the step is suspect, never loaded (fail safe, keep serving)
        want_crc = None
        mpath = manifest_path(self.ckpt_dir, step)
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    manifest = json.load(f)
                want_crc = int(manifest["crc"][self.param_key])
            except (OSError, ValueError, KeyError, TypeError) as e:
                self._c_corrupt.inc()
                log.warning("watcher: step %d manifest torn/unreadable "
                            "(%s: %s); skipping the step",
                            step, type(e).__name__, e)
                return None
        try:
            ck = RoundCheckpointer(self.ckpt_dir)
            try:
                state = ck.restore(step)
            finally:
                ck.close()
            params = state[self.param_key]
        except (FileNotFoundError, KeyError) as e:
            # the step was GC'd between list and load, or is from a
            # different state schema — skip it, keep serving
            self._c_vanished.inc()
            log.warning("watcher: step %d unreadable (%s: %s); skipping",
                        step, type(e).__name__, e)
            return None
        except Exception as e:  # noqa: BLE001 — a truncated orbax file
            # raises whatever its decoder hits (ValueError, OSError,
            # struct/msgpack errors...); every flavor of half-written
            # checkpoint must skip-and-warn, never crash or serve garbage
            self._c_corrupt.inc()
            log.warning("watcher: step %d failed to load (%s: %s); "
                        "skipping the step", step, type(e).__name__, e)
            return None
        if want_crc is not None:
            got = tree_crc(_pack_keys(params))
            if got != want_crc:
                self._c_corrupt.inc()
                log.warning("watcher: step %d params crc %d != manifest "
                            "%d (torn/partial checkpoint); skipping",
                            step, got, want_crc)
                return None
        return params

    def start(self) -> "CheckpointWatcher":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-ckpt-watcher")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watcher must outlive
                log.exception("watcher: poll failed; retrying")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
