"""Actor layer: handler-registry node managers for cross-silo federation.

Reference equivalent: ``ClientManager``
(fedml_core/distributed/client/client_manager.py:13-62) and ``ServerManager``
(fedml_core/distributed/server/server_manager.py:13-59): an event loop plus a
``message_handler_dict`` keyed by message type.

Differences: transports are injected (no backend-string switch with hardcoded
MQTT broker IPs, client_manager.py:20-30); ``finish()`` is a clean transport
stop, not ``MPI.COMM_WORLD.Abort()`` (server_manager.py:64).  On-pod
federation never instantiates these — the whole round is one jit program;
actors exist only for host-edge (cross-silo gRPC / device) deployments.
"""

from __future__ import annotations

import abc
import logging
import threading
from typing import Callable, Dict

from fedml_tpu.comm.message import Message, build_fanout
from fedml_tpu.comm.transport import Transport
from fedml_tpu.obs import telemetry, trace

log = logging.getLogger(__name__)


class SelfMessageTimer:
    """One-shot daemon timer for actor watchdogs (straggler timeout,
    async re-tasking).

    The callback is expected to ENQUEUE a self-message so all policy
    logic stays single-threaded on the transport's event loop; this
    class owns the thread-lifecycle subtleties both server actors need:

    * re-``arm()`` cancels the previous timer first;
    * ``cancel(join=True)`` (the finish/abort path) joins every timer
      thread still exiting its wait, so no timer outlives the federation
      (no late fire, no leaked-thread warning under ``-W error``), and
      permanently closes the timer — a fire racing the teardown is
      suppressed, and send errors from a mid-shutdown transport are
      swallowed.
    """

    def __init__(self):
        self._timer: threading.Timer | None = None
        self._spent: list = []  # cancelled, possibly still exiting
        self._closed = False

    @property
    def pending(self) -> bool:
        return self._timer is not None

    def arm(self, delay_s: float, fire: Callable[[], None]) -> None:
        self.cancel()
        if self._closed:
            return

        def wrapped():
            if self._closed:
                return
            try:
                fire()
            except Exception:  # noqa: BLE001 — transport mid-shutdown
                pass

        timer = threading.Timer(delay_s, wrapped)
        timer.daemon = True
        self._timer = timer
        timer.start()

    def cancel(self, join: bool = False) -> None:
        timer = self._timer
        if timer is not None:
            self._timer = None
            timer.cancel()
            # a cancelled Timer thread still takes a beat to exit its
            # wait; remember it so the join pass can reap every one
            self._spent = [t for t in self._spent if t.is_alive()]
            self._spent.append(timer)
        if join:
            self._closed = True
            for t in self._spent:
                if t is not threading.current_thread():
                    t.join(timeout=5)
            self._spent = [t for t in self._spent if t.is_alive()]


class NodeManager(abc.ABC):
    """Event-loop node with a message-type → handler registry.

    Tracing: when the process tracer is enabled (obs/trace.py), every
    ``send()`` inside an active span stamps the span's context onto the
    message, and every inbound message CARRYING a context is handled
    under a ``recv:<type>`` child span — so one federated round stitches
    into a single cross-node trace with no per-algorithm code.  Handler
    spans use deterministic ids, so a chaotic wire delivering a frame
    twice collapses to one span.  Disabled (``_tracer is None``) both
    paths are a single branch."""

    def __init__(self, node_id: int, transport: Transport):
        self.node_id = node_id
        self.transport = transport
        self.transport.add_observer(self)
        self._handlers: Dict[object, Callable[[Message], None]] = {}
        self._tracer = trace.get_tracer()
        self._m_fanout = telemetry.get_registry().counter(
            "fedml_wire_fanout_total")

    def _span(self, name: str, **kw):
        """A span context-manager on this node's track, or the SHARED
        null context when tracing is disabled — call sites stay
        single-path and the disabled branch allocates nothing (the
        zero-allocation pin in tests/test_critical_path.py)."""
        if self._tracer is None:
            return trace.NULL_CONTEXT
        return self._tracer.span(name, node=self.node_id, **kw)

    def _root_span(self, name: str, hint: str = "", **kw):
        """Like `_span` but starts a NEW trace (ignores any active span)
        — for the spans that root a round/version/re-task tree."""
        if self._tracer is None:
            return trace.NULL_CONTEXT
        return self._tracer.span(
            name, parent=None, node=self.node_id,
            trace_id=self._tracer.new_trace_id(hint or name), **kw)

    # -- registry (reference client_manager.py:58-62) ------------------------
    def register_handler(self, msg_type, fn: Callable[[Message], None]) -> None:
        self._handlers[msg_type] = fn

    @abc.abstractmethod
    def register_handlers(self) -> None:
        """Subclasses register their message handlers here."""

    # -- observer ------------------------------------------------------------
    def receive_message(self, msg_type, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            log.warning("node %d: no handler for message type %r",
                        self.node_id, msg_type)
            return
        if self._tracer is not None:
            ctx = trace.extract(msg)
            if ctx is not None:
                # deterministic id: a duplicated delivery of the same frame
                # re-runs the handler but records only one span
                with self._tracer.span(f"recv:{msg_type}", parent=ctx,
                                       node=self.node_id,
                                       deterministic=True):
                    handler(msg)
                return
        handler(msg)

    # -- lifecycle (reference client_manager.py:34-36) -----------------------
    def run(self) -> None:
        self.register_handlers()
        self.transport.run()

    def send(self, msg_type, receiver_id: int, **params) -> None:
        msg = Message(msg_type, self.node_id, receiver_id)
        for k, v in params.items():
            msg.add(k, v)
        if self._tracer is not None:
            ctx = self._tracer.current_context()
            if ctx is not None:
                trace.inject(msg, ctx)
        self.transport.send_message(msg)

    def send_many(self, msg_type, receivers, shared_params=None,
                  per_receiver_params=None) -> None:
        """Encode-once fan-out: serialize ``shared_params`` a single time
        and deliver one message per receiver, varying only the small
        per-receiver header (``per_receiver_params[r]``).  The trace
        context rides each receiver's own header, so per-silo recv spans
        stitch exactly as with single sends."""
        messages = build_fanout(msg_type, self.node_id, receivers,
                                shared_params, per_receiver_params)
        if self._tracer is not None:
            ctx = self._tracer.current_context()
            if ctx is not None:
                for msg in messages:
                    trace.inject(msg, ctx)
        self._m_fanout.inc(len(messages))
        self.transport.send_many(messages)

    def finish(self) -> None:
        self.transport.stop()


class ClientManager(NodeManager):
    """Cross-silo client actor (reference ClientManager, client_manager.py:13)."""


class ServerManager(NodeManager):
    """Cross-silo server actor (reference ServerManager, server_manager.py:13)."""

    #: optional `fedml_tpu.obs.perf.PerfRecorder` — subclasses accepting a
    #: ``perf=`` parameter assign it; `_perf_phase` is the shared span helper
    perf = None

    def _perf_phase(self, name: str):
        """Flight-recorder phase span (the shared null context when no
        recorder — one branch, zero allocations)."""
        if self.perf is not None:
            return self.perf.phase(name)
        return trace.NULL_CONTEXT

    def _note_arrival(self) -> None:
        """Stamp one upload arrival on the round's critical-path
        timeline (one branch when the recorder is off)."""
        if self.perf is not None:
            self.perf.note_arrival()
