"""FedAC accelerated federated SGD (algorithms/fedac.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.algorithms import FedAvg, FedAvgConfig
from fedml_tpu.algorithms.fedac import (FedAC, FedACConfig, fedac_coupling)
from fedml_tpu.data.stacking import FederatedData, stack_client_data
from fedml_tpu.models import LogisticRegression
from fedml_tpu.trainer.workload import ClassificationWorkload


def _ill_conditioned_clients(n_clients=4, dim=8, per=32, seed=0):
    """Feature scales spanning 100x: the ill-conditioned regime where
    acceleration beats plain SGD at the same budget."""
    rng = np.random.RandomState(seed)
    scales = np.logspace(0, -2, dim).astype(np.float32)
    w_true = rng.randn(dim, 2).astype(np.float32)
    xs, ys = [], []
    for _ in range(n_clients):
        x = (rng.randn(per, dim) * scales).astype(np.float32)
        y = (x @ w_true).argmax(axis=1).astype(np.int32)
        xs.append(x)
        ys.append(y)
    return xs, ys


def _fed(xs, ys, batch=8, classes=2):
    train = stack_client_data(xs, ys, batch)
    return FederatedData(client_num=len(xs), class_num=classes,
                         train=train, test=train)


def _wl(dim=8, classes=2):
    return ClassificationWorkload(LogisticRegression(dim, classes),
                                  num_classes=classes, grad_clip_norm=None)


def test_degenerate_coupling_is_exactly_fedavg():
    """(alpha=1, beta=1, gamma=lr) collapses both sequences onto plain
    local SGD — bit-identical to FedAvg on the same rng chain."""
    xs, ys = _ill_conditioned_clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=3, client_num_per_round=4, epochs=2,
               batch_size=8, lr=0.1, frequency_of_the_test=100)
    fa = FedAvg(_wl(), data, FedAvgConfig(**cfg))
    ac = FedAC(_wl(), data, FedACConfig(
        fedac_alpha=1.0, fedac_beta=1.0, fedac_gamma=0.1, **cfg))
    p0 = fa.init_params(jax.random.key(3))
    out_fa = fa.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    out_ac = ac.run(params=jax.tree.map(jnp.copy, p0),
                    rng=jax.random.key(4))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 out_fa, out_ac)


def test_acceleration_beats_fedavg_on_ill_conditioned_problem():
    """The paper's point: at the SAME rounds/local-steps budget, the
    accelerated coupling reaches a lower global train loss than plain
    FedAvg on an ill-conditioned objective."""
    xs, ys = _ill_conditioned_clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=15, client_num_per_round=4, epochs=2,
               batch_size=8, lr=0.05, frequency_of_the_test=14)
    fa = FedAvg(_wl(), data, FedAvgConfig(**cfg))
    ac = FedAC(_wl(), data, FedACConfig(fedac_mu=0.05, **cfg))
    fa.run(rng=jax.random.key(0))
    ac.run(rng=jax.random.key(0))
    loss_fa = fa.history[-1]["train_loss"]
    loss_ac = ac.history[-1]["train_loss"]
    assert loss_ac < loss_fa, (loss_ac, loss_fa)


def test_coupling_formula():
    gamma, alpha, beta = fedac_coupling(lr=0.1, mu=0.1, k_steps=16)
    assert gamma == pytest.approx(max(np.sqrt(0.1 / (0.1 * 16)), 0.1))
    assert alpha == pytest.approx(1.0 / (gamma * 0.1))
    assert beta == pytest.approx(alpha + 1.0)
    # large mu with k=1: gamma -> lr, alpha -> 1/(lr*mu)
    g2, a2, b2 = fedac_coupling(lr=0.1, mu=100.0, k_steps=1)
    assert g2 == pytest.approx(0.1)
    assert a2 == pytest.approx(1.0 / (0.1 * 100.0))


def test_checkpoint_roundtrip_and_rerun(tmp_path):
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    xs, ys = _ill_conditioned_clients()
    data = _fed(xs, ys)
    cfg = dict(comm_round=4, client_num_per_round=2, epochs=1,
               batch_size=8, lr=0.05, frequency_of_the_test=100)
    straight = FedAC(_wl(), data, FedACConfig(fedac_mu=0.1, **cfg))
    w_straight = straight.run(rng=jax.random.key(0))

    half = FedAC(_wl(), data, FedACConfig(
        fedac_mu=0.1, **{**cfg, "comm_round": 2}))
    ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
    half.run(rng=jax.random.key(0), checkpointer=ck)
    resumed = FedAC(_wl(), data, FedACConfig(fedac_mu=0.1, **cfg))
    w_resumed = resumed.run(
        rng=jax.random.key(0),
        checkpointer=RoundCheckpointer(str(tmp_path / "ck"), save_every=1))
    for a, b in zip(jax.tree.leaves(w_straight),
                    jax.tree.leaves(w_resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # rerun on the same instance re-couples x to the fresh x^ag
    again = straight.run(rng=jax.random.key(0))
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 w_straight, again)


def test_rejects_unsupported_configs():
    xs, ys = _ill_conditioned_clients()
    data = _fed(xs, ys)
    base = dict(comm_round=1, client_num_per_round=2, epochs=1,
                batch_size=8, lr=0.1)
    with pytest.raises(ValueError, match="sgd only"):
        FedAC(_wl(), data, FedACConfig(client_optimizer="adam", **base))
    with pytest.raises(ValueError, match="alpha >= 1"):
        FedAC(_wl(), data, FedACConfig(fedac_alpha=0.5, **base))


def test_mesh_sharded_fedac_equals_single_chip():
    """Mesh == single-chip to float tolerance for x^ag AND the coupled x
    sequence, full and padded cohorts (second case: 4 live clients in 8
    slots over 4 devices)."""
    from fedml_tpu.parallel.mesh import make_mesh
    for n_clients, m, axis in ((4, 4, 4), (4, 8, 4)):
        xs, ys = _ill_conditioned_clients(n_clients=n_clients)
        data = _fed(xs, ys)
        cfg = dict(fedac_mu=0.1, comm_round=2, client_num_per_round=m,
                   epochs=2, batch_size=8, lr=0.05,
                   frequency_of_the_test=100)
        single = FedAC(_wl(), data, FedACConfig(**cfg))
        meshed = FedAC(_wl(), data, FedACConfig(**cfg),
                       mesh=make_mesh(client_axis=axis,
                                      devices=jax.devices()[:axis]))
        out_s = single.run(rng=jax.random.key(0))
        out_m = meshed.run(rng=jax.random.key(0))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6), out_s, out_m)
        for a, b in zip(jax.tree.leaves(single._x_state),
                        jax.tree.leaves(meshed._x_state)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


def test_cli_fedac_end_to_end():
    from fedml_tpu.experiments.main import main
    summary = main(["--algo", "fedac", "--model", "lr", "--dataset",
                    "mnist", "--client_num_in_total", "8",
                    "--client_num_per_round", "4", "--comm_round", "2",
                    "--frequency_of_the_test", "1", "--batch_size", "4",
                    "--fedac_mu", "0.1", "--log_stdout", "false"])
    assert np.isfinite(summary["train_loss"])


def test_mu_over_limit_error_names_the_knob():
    xs, ys = _ill_conditioned_clients()
    data = _fed(xs, ys)
    with pytest.raises(ValueError, match="fedac_mu"):
        FedAC(_wl(), data, FedACConfig(
            fedac_mu=40.0, comm_round=1, client_num_per_round=2,
            epochs=1, batch_size=8, lr=0.03))
