#!/usr/bin/env bash
# Full TPU perf capture — run when the tunnel is alive and the machine is
# otherwise IDLE (concurrent work contaminates both the TPU timings and
# the torch CPU baseline; verify skill).  One command covers every
# VERDICT-r02 pending item:
#   1. bf16 comparison run   -> BENCH_DETAILS_bf16.json
#   2. resnet56 repeat runs  -> BENCH_R56_SPREAD.json (variance methodology)
#   3. clean full f32 bench  -> BENCH_DETAILS.json (honest FLOPs,
#      device_kind, per-round spread medians, flash + blockwise T=2048)
# Ordered so the committed artifact (BENCH_DETAILS.json) is written LAST
# by the canonical f32 run.  Aborts before touching anything if the
# backend probe fails.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== backend probe (120s watchdog) =="
timeout 120 python - <<'EOF'
import jax, jax.numpy as jnp
jax.block_until_ready(jax.jit(lambda a: a + 1)(jnp.ones(8)))
d = jax.devices()[0]
print("alive:", d.platform, getattr(d, "device_kind", "?"))
EOF

echo "== 1/4 bf16 comparison =="
BENCH_DTYPE=bfloat16 BENCH_SCALING=0 python bench.py
cp BENCH_DETAILS.json BENCH_DETAILS_bf16.json
echo "bf16 details -> BENCH_DETAILS_bf16.json"

echo "== 2/4 resnet56 investigation: spreads + client-axis x dtype grid =="
python - <<'EOF'
import json
import os
import jax
import bench

# resolve the attached chip's peak once; _mfu reads this module global
bench.PEAK_TFLOPS = bench._peak_for_device(jax.devices()[0])
out = {"spread_reps": [], "grid": {},
       "device_kind": jax.devices()[0].device_kind,
       "peak_tflops": bench.PEAK_TFLOPS}
for rep in range(3):
    round_s, flops, steps, spread = bench.bench_resnet56_cifar10(8)
    out["spread_reps"].append(
        {"rep": rep, "round_s": round_s, "spread": spread,
         "step_time_ms": 1e3 * round_s / steps})
    print("rep", rep, out["spread_reps"][-1])

# vmap lowers per-client conv kernels to grouped convs (MXU sliver per
# group at 16/32/64 channels); scan keeps dense convs.  Grid pins which
# engine + dtype the flagship should ship with, and the E=20 row scales
# the winner to the published config (benchmark/README.md:105).
for axis in ("vmap", "scan"):
    for dtype in ("", "bfloat16"):
        os.environ["BENCH_DTYPE"] = dtype
        round_s, flops, steps, spread = bench.bench_resnet56_cifar10(
            6, client_axis=axis)
        key = f"{axis}_{dtype or 'f32'}"
        out["grid"][key] = {
            "round_s": round_s, "steps": steps,
            "step_time_ms": 1e3 * round_s / steps,
            "mfu": bench._mfu(flops, round_s), "spread": spread}
        print(key, out["grid"][key])
os.environ["BENCH_DTYPE"] = ""

# published-config row: E=20 with the winning engine
best = min(out["grid"], key=lambda k: out["grid"][k]["round_s"])
axis, dtype = best.rsplit("_", 1)
os.environ["BENCH_DTYPE"] = "" if dtype == "f32" else dtype
round_s, flops, steps, spread = bench.bench_resnet56_cifar10(
    3, epochs=20, client_axis=axis)
out["e20_published_config"] = {
    "engine": best, "round_s": round_s, "steps": steps,
    "step_time_ms": 1e3 * round_s / steps,
    "mfu": bench._mfu(flops, round_s), "spread": spread}
os.environ["BENCH_DTYPE"] = ""
print("E=20:", out["e20_published_config"])
with open("BENCH_R56_SPREAD.json", "w") as f:
    json.dump(out, f, indent=2)
print("wrote BENCH_R56_SPREAD.json")
EOF

echo "== 3/4 full clean f32 bench (canonical BENCH_DETAILS.json) =="
BENCH_MODE=full python bench.py

echo "== 4/4 profiler traces (resnet56 + shakespeare rounds) =="
for cfg in "resnet56 cifar10" "rnn shakespeare"; do
  set -- $cfg
  if ! python -m fedml_tpu --algo fedavg --model "$1" --dataset "$2" \
      --client_num_in_total 10 --client_num_per_round 10 --comm_round 3 \
      --batch_size 64 --frequency_of_the_test 3 --log_stdout false \
      --profile_dir "profiles/$1"; then
    echo "WARNING: profiled $1 run FAILED — profiles/$1 is empty/partial"
  fi
done

echo "done — inspect BENCH_DETAILS.json / BENCH_DETAILS_bf16.json /"
echo "BENCH_R56_SPREAD.json + profiles/, then commit the clean artifacts"
echo "(profiles/ stays local — gitignored)."
