"""TurboAggregate: multi-group ring secure aggregation (So et al. 2020).

Reference equivalent: ``fedml_api/{distributed,standalone}/turboaggregate/``.
The reference's runnable path is the standalone trainer
(TA_trainer.py:38-97): FedAvg where clients are arranged in a ring of groups
(``TA_topology_vanilla`` :87-97) and aggregation proceeds group-to-group; its
distributed worker is a skeleton (TA_decentralized_worker.py:27-29 trains a
constant).  The cryptographic kernel is mpc_function.py — reimplemented
vectorized in `fedml_tpu.secure.field`.

TPU-native composition:

- **in-group privacy**: each group's cohort sum runs through the uint32
  pairwise-masking aggregator (`fedml_tpu.secure.secagg`) inside the jit
  round program — the server/ring never sees an individual update;
- **cross-group redundancy**: each group's (quantized) partial aggregate is
  LCC-encoded (`lcc_encode`) into shares held by the next group's members,
  so up to T straggler/dropout members per hop are tolerated — the decode
  (`lcc_decode`) needs any K+T surviving shares, mirroring TurboAggregate's
  dropout story;
- training itself is the standard cohort engine (local SGD under vmap).
"""

from __future__ import annotations

import dataclasses
import secrets
from typing import Any, List, Optional

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.data.stacking import FederatedData, gather_cohort
from fedml_tpu.secure.field import lcc_encode, lcc_decode, P_DEFAULT
from fedml_tpu.secure.secagg import SecureCohortAggregator
from fedml_tpu.trainer.local_sgd import make_local_trainer
from fedml_tpu.trainer.workload import Workload, make_client_optimizer

Pytree = Any


@dataclasses.dataclass
class TurboAggregateConfig:
    comm_round: int = 10
    group_num: int = 4            # ring length L (TA_topology_vanilla :87-97)
    clients_per_group: int = 4
    drop_tolerance: int = 1       # T: tolerated dropouts per hop
    epochs: int = 1
    lr: float = 0.03
    client_optimizer: str = "sgd"
    seed: int = 0
    # clip * scale must stay within the centered field range P//2, or a
    # saturated element decodes with flipped sign (see __init__ assert) —
    # AND clients_per_group * clip * scale must stay within the uint32
    # ring (secagg.validate_ring_budget), or a group's masked sum wraps.
    # None = auto-derive the largest power-of-two scale satisfying both.
    quant_scale: Optional[float] = None
    quant_clip: float = 2.0**14
    secagg_backend: str = "xla"   # "pallas": fused quantize+mask kernel
    # secret entropy for the LCC masking chunks; None = fresh per instance.
    # MUST stay secret from share holders — seeding from public values (e.g.
    # the group index) voids T-privacy entirely.
    privacy_key: Optional[int] = None


class TurboAggregate:
    """Group-ring secure FedAvg simulator (one jit per group cohort)."""

    def __init__(self, workload: Workload, data: FederatedData,
                 config: TurboAggregateConfig):
        self.workload = workload
        self.data = data
        self.cfg = config
        if config.quant_scale is None:
            # auto: the largest power-of-two scale the GROUP's uint32 ring
            # budget allows (N clipped group members must sum without
            # wrapping — the ISSUE 11 satellite bug), further bounded by
            # the LCC field range below.  Derived into THIS instance, not
            # written back into the (possibly shared) config.
            from fedml_tpu.secure.secagg import ring_budget_scale
            self.quant_scale = ring_budget_scale(config.clients_per_group,
                                                 config.quant_clip)
            while config.quant_clip * self.quant_scale > P_DEFAULT // 2:
                self.quant_scale /= 2.0
            if self.quant_scale < 1.0:
                raise ValueError(
                    f"no usable fixed-point scale: clients_per_group="
                    f"{config.clients_per_group} at clip="
                    f"{config.quant_clip} cannot satisfy both the uint32 "
                    f"ring and the LCC field range")
        else:
            from fedml_tpu.secure.secagg import validate_ring_budget
            validate_ring_budget(config.clients_per_group,
                                 config.quant_clip, config.quant_scale)
            self.quant_scale = config.quant_scale
        assert config.quant_clip * self.quant_scale <= P_DEFAULT // 2, (
            "quant_clip*quant_scale exceeds the centered field range "
            f"P//2={P_DEFAULT // 2}: a clipped element at +clip would decode "
            "with flipped sign on the dropout-recovery path")
        self._privacy_key = (config.privacy_key if config.privacy_key
                             is not None else secrets.randbits(63))
        opt = make_client_optimizer(config.client_optimizer, config.lr)
        self._local = jax.jit(jax.vmap(
            make_local_trainer(workload, opt, config.epochs),
            in_axes=(None, 0, 0)))
        self.secagg = SecureCohortAggregator(
            config.clients_per_group, self.quant_scale, config.quant_clip,
            backend=config.secagg_backend)
        self._masked_group_sum = jax.jit(self._masked_group_sum_impl)

    # -- one group's secure cohort aggregate --------------------------------
    def _masked_group_sum_impl(self, params, cohort, round_key):
        batches = {k: v for k, v in cohort.items() if k != "num_samples"}
        n = jax.tree.leaves(batches)[0].shape[0]
        rngs = jax.vmap(lambda i: jax.random.fold_in(round_key, i))(
            jnp.arange(n))
        trained, _ = self._local(params, batches, rngs)
        num = cohort["num_samples"].astype(jnp.float32)
        summed = self.secagg.aggregate_stacked(trained, num, round_key)
        # aggregate_stacked returns the weighted mean of the group
        return summed, jnp.sum(num)

    def train_round(self, params: Pytree, round_idx: int,
                    dropped_groups: Optional[List[int]] = None) -> Pytree:
        """One ring pass: every group securely aggregates, group partials are
        LCC-coded for redundancy, then combined sample-weighted.

        ``dropped_groups`` simulates hop failures: those groups' direct
        partials are discarded and reconstructed from surviving LCC shares.
        """
        cfg = self.cfg
        dropped = set(dropped_groups or ())
        assert len(dropped) <= cfg.drop_tolerance, "beyond design tolerance"
        group_means: List[Pytree] = []
        group_weights: List[float] = []
        rng_round = jax.random.fold_in(jax.random.key(cfg.seed), round_idx)
        cohort_size = cfg.group_num * cfg.clients_per_group
        ids = sample_clients(round_idx, self.data.client_num, cohort_size)
        for g in range(cfg.group_num):
            gids = ids[g * cfg.clients_per_group:(g + 1) * cfg.clients_per_group]
            if len(gids) == 0:
                continue  # sample_clients caps the cohort at client_num —
                # an empty (all-padding) group carries no weight and would
                # only add a zero-weight entry to the ring
            cohort = gather_cohort(self.data.train, gids,
                                   pad_to=cfg.clients_per_group)
            gkey = jax.random.fold_in(rng_round, g)
            mean, n = self._masked_group_sum(params, cohort, gkey)
            group_means.append(mean)
            group_weights.append(float(n))

        # ring redundancy: flatten each group partial, LCC-encode into
        # clients_per_group shares "held by the next group", decode from
        # survivors when the direct partial is lost
        recovered: List[Pytree] = []
        for g, mean in enumerate(group_means):
            if g not in dropped:
                recovered.append(mean)
                continue
            vec_j, unravel = jax.flatten_util.ravel_pytree(mean)
            vec = np.asarray(vec_j, np.float64)
            q = np.mod(np.round(vec * self.quant_scale).astype(np.int64),
                       P_DEFAULT)
            pad = (-len(q)) % 2
            q2 = np.pad(q, (0, pad)).reshape(-1, 2)
            N = cfg.clients_per_group
            K, T = 2, cfg.drop_tolerance
            # after T member dropouts, the surviving N-T shares must still
            # reach the K+T needed to interpolate the coding polynomial
            assert N - T >= K + T, (
                f"clients_per_group={N} cannot tolerate T={T} dropouts with "
                f"K={K} data chunks (need N >= K + 2T = {K + 2 * T})")
            # fresh SECRET randomness per (round, group): the T masking
            # chunks must be unpredictable to share holders and never reused
            # across rounds (reuse lets two rounds' shares cancel the mask)
            share_rng = np.random.RandomState(np.random.MT19937(
                np.random.SeedSequence([self._privacy_key, round_idx, g])))
            shares = lcc_encode(q2.T, N, K, T, p=P_DEFAULT, rng=share_rng)
            survivors = list(range(T, N))
            decoded = lcc_decode(shares[survivors], N, K, T, survivors,
                                 p=P_DEFAULT)
            # decoded rows are the K interleaved chunks (row i = q[i::K]);
            # transpose restores the original element order
            vec_q = decoded.T.reshape(-1)[:len(q)]
            # undo centered field representation (values may encode negatives)
            signed = np.where(vec_q > P_DEFAULT // 2, vec_q - P_DEFAULT, vec_q)
            vec_rec = signed.astype(np.float64) / self.quant_scale
            recovered.append(unravel(jnp.asarray(vec_rec, jnp.float32)))

        return tree_weighted_mean(recovered,
                                  np.asarray(group_weights, np.float32))

    def run(self, params: Pytree) -> Pytree:
        for r in range(self.cfg.comm_round):
            params = self.train_round(params, r)
        return params
