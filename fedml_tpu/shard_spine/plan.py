"""Deterministic leaf→shard layout for the sharded global-model spine.

Every live path used to assume the global model fits one host buffer
(ROADMAP item 2).  The plan is the contract that breaks that assumption
without breaking determinism: given ONLY the template's leaf shapes, the
shard count ``S``, and the split threshold, it derives — identically on
every process, every restart, and every checkpoint resume — which piece
of the model each shard owns:

* a leaf with a dimension divisible by ``S`` (and at least
  ``min_split_elems`` elements) is **split** along the first such
  dimension: shard ``s`` owns the ``s``-th contiguous block.  Following
  "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
  Training" (arXiv 2004.13336), it is the *update/aggregation state*
  that is laid out this way, not just the forward pass;
* a small (or indivisible) leaf is **replicated** for placement —
  `NamedSharding` ``P()`` on the mesh's ``model`` axis — but owned by
  exactly ONE shard for the wire/fold partition (greedy
  lightest-shard-first, ties to the lowest shard id), so no leaf is
  ever folded twice.

The plan is pure metadata: O(#leaves), JSON-able (`spec()`), and
fingerprinted (`fingerprint()`) so checkpoints and journal snapshots can
record the layout and a resume can *verify* it re-derived the identical
one instead of silently folding restored state into the wrong slots.

Wire form of one shard's slice::

    {"s<idx>": {"00007": <piece of leaf 7>, ...}}

The shard id is part of the screened STRUCTURE (the outer key), so the
admission fingerprint rejects a wrong-shard upload even when two shards'
pieces happen to share shapes (an even split of every leaf makes all
``S`` slices shape-identical — the key is what tells them apart).

Leaf order: the plan flattens with ``jax.tree`` (sorted dict keys,
positional lists/tuples).  The wire codec (`comm/message.py
_flatten_arrays`) canonicalizes identically for the plain-container
trees model params actually are, and `from_spec` + the codec's
``structure`` spec let a SILO rebuild split/join from the sync frame
alone — zero client-side shard configuration, like the secagg sync.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

Pytree = Any

# wire slice keys: zero-padded so string sort order == leaf order
_LEAF_KEY_DIGITS = 5


def _leaf_key(i: int) -> str:
    return f"{i:0{_LEAF_KEY_DIGITS}d}"


def _shard_key(s: int) -> str:
    return f"s{s}"


@dataclasses.dataclass(frozen=True)
class LeafPlan:
    """One leaf's layout: ``mode`` is ``"split"`` (shard ``s`` owns the
    ``s``-th block of ``dim``) or ``"rep"`` (whole leaf owned by
    ``owner``, replicated for placement)."""
    index: int
    path: str
    shape: tuple
    dtype: str
    is_weight: bool          # counts toward the clip norm (core/robust.py)
    mode: str                # "split" | "rep"
    dim: int = -1            # split dimension (mode == "split")
    owner: int = 0           # owning shard (mode == "rep")

    def to_json(self) -> dict:
        return {"i": self.index, "path": self.path,
                "shape": list(self.shape), "dtype": self.dtype,
                "w": int(self.is_weight), "mode": self.mode,
                "dim": self.dim, "owner": self.owner}

    @classmethod
    def from_json(cls, d: dict) -> "LeafPlan":
        return cls(index=int(d["i"]), path=str(d["path"]),
                   shape=tuple(int(x) for x in d["shape"]),
                   dtype=str(d["dtype"]), is_weight=bool(d["w"]),
                   mode=str(d["mode"]), dim=int(d["dim"]),
                   owner=int(d["owner"]))


def _path_str(path) -> str:
    from jax.tree_util import DictKey, SequenceKey
    parts = []
    for p in path:
        if isinstance(p, DictKey):
            parts.append(str(p.key))
        elif isinstance(p, SequenceKey):
            parts.append(str(p.idx))
        else:
            parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


class ShardPlan:
    """The derived layout.  Build with `build_shard_plan` (server side,
    from the live template) or `ShardPlan.from_spec` (silo side, from
    the sync frame's descriptor — structure only, no arrays)."""

    def __init__(self, num_shards: int, leaves: Sequence[LeafPlan],
                 min_split_elems: int):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.num_shards = int(num_shards)
        self.leaves: List[LeafPlan] = list(leaves)
        self.min_split_elems = int(min_split_elems)
        # shard -> ordered leaf indices it carries a piece of
        self.members: List[List[int]] = [[] for _ in range(num_shards)]
        for lp in self.leaves:
            if lp.mode == "split":
                for s in range(num_shards):
                    self.members[s].append(lp.index)
            else:
                self.members[lp.owner].append(lp.index)

    # -- identity ------------------------------------------------------------
    def descriptor(self) -> dict:
        """The JSON-able identity of the layout (everything `fingerprint`
        covers; `spec()` adds the client-facing structure)."""
        return {"num_shards": self.num_shards,
                "min_split_elems": self.min_split_elems,
                "leaves": [lp.to_json() for lp in self.leaves]}

    def fingerprint(self) -> int:
        """crc32 of the canonical descriptor — stamped into checkpoints
        and journal snapshots so a resume can verify it re-derived the
        IDENTICAL layout (restoring sharded fold state into a different
        plan would mis-aggregate silently)."""
        blob = json.dumps(self.descriptor(), sort_keys=True).encode()
        return zlib.crc32(blob)

    def spec(self) -> dict:
        """What the sync frame ships (shard 0) so a silo can split/join
        with zero configuration: the descriptor plus the codec-form
        ``structure`` spec `SiloShardCodec` unflattens with."""
        return dict(self.descriptor(), structure=self._structure)

    # populated by build_shard_plan / from_spec
    _structure: Optional[dict] = None

    @classmethod
    def from_spec(cls, spec: dict) -> "ShardPlan":
        plan = cls(int(spec["num_shards"]),
                   [LeafPlan.from_json(d) for d in spec["leaves"]],
                   int(spec["min_split_elems"]))
        plan._structure = spec.get("structure")
        return plan

    # -- leaf-list split / join ----------------------------------------------
    def _piece(self, lp: LeafPlan, arr, shard: int):
        if lp.mode != "split":
            return arr
        n = arr.shape[lp.dim] // self.num_shards
        idx = [slice(None)] * arr.ndim
        idx[lp.dim] = slice(shard * n, (shard + 1) * n)
        return arr[tuple(idx)]

    def piece_shape(self, lp: LeafPlan) -> tuple:
        if lp.mode != "split":
            return lp.shape
        shape = list(lp.shape)
        shape[lp.dim] //= self.num_shards
        return tuple(shape)

    def split_leaves(self, leaves: Sequence) -> List[Dict[str, dict]]:
        """Ordered leaf list → one wire slice dict per shard.  Split
        pieces are VIEWS of the input arrays (numpy basic slicing) — the
        single copy per piece happens where the wire encodes it."""
        if len(leaves) != len(self.leaves):
            raise ValueError(
                f"shard plan covers {len(self.leaves)} leaves but the "
                f"tree has {len(leaves)} — the model does not match the "
                f"plan's template")
        out: List[Dict[str, dict]] = [
            {_shard_key(s): {}} for s in range(self.num_shards)]
        for lp, leaf in zip(self.leaves, leaves):
            arr = np.asarray(leaf)
            if tuple(arr.shape) != lp.shape:
                raise ValueError(
                    f"leaf {lp.index} ({lp.path}) has shape {arr.shape} "
                    f"but the plan expects {lp.shape}")
            if lp.mode == "split":
                for s in range(self.num_shards):
                    out[s][_shard_key(s)][_leaf_key(lp.index)] = \
                        self._piece(lp, arr, s)
            else:
                out[lp.owner][_shard_key(lp.owner)][
                    _leaf_key(lp.index)] = arr
        return out

    def join_slices(self, slices: Sequence[Dict[str, dict]]) -> List:
        """One wire slice per shard → the ordered full leaf list
        (np.concatenate along the split dim; exact — concatenation does
        no arithmetic)."""
        if len(slices) != self.num_shards:
            raise ValueError(f"join_slices needs {self.num_shards} "
                             f"slices, got {len(slices)}")
        inner = []
        for s, sl in enumerate(slices):
            body = sl.get(_shard_key(s))
            if body is None:
                raise ValueError(
                    f"slice {s} does not carry the '{_shard_key(s)}' "
                    f"shard key (wrong-shard or malformed slice)")
            inner.append(body)
        leaves: List = []
        for lp in self.leaves:
            key = _leaf_key(lp.index)
            if lp.mode == "split":
                pieces = [np.asarray(inner[s][key])
                          for s in range(self.num_shards)]
                leaves.append(np.concatenate(pieces, axis=lp.dim)
                              if self.num_shards > 1 else pieces[0])
            else:
                leaves.append(np.asarray(inner[lp.owner][key]))
        return leaves

    def slice_weight_flags(self, shard: int) -> tuple:
        """Per-piece is_weight flags in the shard slice's KEY ORDER (the
        order `jax.tree` flattens the slice dict — zero-padded keys sort
        numerically), for the clip mask inside the per-shard fold jit."""
        idxs = sorted(self.members[shard])
        by_index = {lp.index: lp for lp in self.leaves}
        return tuple(by_index[i].is_weight for i in idxs)

    def slice_ref_dtypes(self, shard: int) -> tuple:
        idxs = sorted(self.members[shard])
        by_index = {lp.index: lp for lp in self.leaves}
        return tuple(by_index[i].dtype for i in idxs)

    def slice_nbytes(self, shard: int) -> int:
        """Bytes of one shard's slice (the O(model/S) evidence)."""
        total = 0
        by_index = {lp.index: lp for lp in self.leaves}
        for i in self.members[shard]:
            lp = by_index[i]
            total += int(np.prod(self.piece_shape(lp) or (1,))
                         * np.dtype(lp.dtype).itemsize)
        return total

    # -- placement (NamedSharding over the mesh's model axis) ----------------
    def leaf_partition_specs(self, axis: str = "model") -> List:
        """One `PartitionSpec` per leaf for laying the ASSEMBLED global
        out sharded on a mesh: split leaves put their split dim on
        ``axis``, replicated leaves are ``P()`` — the `NamedSharding`
        form of this plan."""
        from jax.sharding import PartitionSpec as P
        specs = []
        for lp in self.leaves:
            if lp.mode == "split":
                spec = [None] * len(lp.shape)
                spec[lp.dim] = axis
                specs.append(P(*spec))
            else:
                specs.append(P())
        return specs

    def place_global(self, tree: Pytree, mesh, axis: str = "model"):
        """Lay the assembled global out as `NamedSharding` shards over
        ``mesh``'s ``axis`` per this plan (the pjit-visible round
        state).  Identity when ``mesh`` is None."""
        if mesh is None:
            return tree
        import jax
        from jax.sharding import NamedSharding
        leaves, treedef = jax.tree.flatten(tree)
        specs = self.leaf_partition_specs(axis)
        placed = [jax.device_put(leaf, NamedSharding(mesh, spec))
                  for leaf, spec in zip(leaves, specs)]
        return jax.tree.unflatten(treedef, placed)

    def shard_devices(self, mesh, axis: str = "model") -> Optional[list]:
        """Device of each shard on ``mesh``'s ``axis`` (slice/fold state
        placement: shard ``s``'s pieces live wholly on device ``s``).
        None when no mesh — everything stays on the default device."""
        if mesh is None:
            return None
        if mesh.shape[axis] != self.num_shards:
            raise ValueError(
                f"mesh {axis} axis has {mesh.shape[axis]} devices but "
                f"the plan has {self.num_shards} shards")
        import numpy as _np
        arr = _np.asarray(mesh.devices)
        axis_index = mesh.axis_names.index(axis)
        return [arr.take(s, axis=axis_index).ravel()[0]
                for s in range(self.num_shards)]


def build_shard_plan(template: Pytree, num_shards: int,
                     min_split_elems: int = 1024) -> ShardPlan:
    """Derive the plan from a live template tree.  Deterministic in
    (leaf shapes/dtypes, ``num_shards``, ``min_split_elems``) only — a
    restart re-derives the identical plan, which `fingerprint()` lets
    checkpoints verify."""
    import jax
    from fedml_tpu.core.robust import default_is_weight_param

    flat = jax.tree_util.tree_leaves_with_path(template)
    leaves: List[LeafPlan] = []
    rep_bytes = [0] * num_shards
    split_bytes = 0
    for i, (path, leaf) in enumerate(flat):
        arr = np.asarray(leaf)
        shape = tuple(int(d) for d in arr.shape)
        is_w = bool(default_is_weight_param(path))
        dim = -1
        if num_shards > 1 and arr.size >= min_split_elems:
            for d, n in enumerate(shape):
                if n >= num_shards and n % num_shards == 0:
                    dim = d
                    break
        if dim >= 0:
            leaves.append(LeafPlan(i, _path_str(path), shape,
                                   arr.dtype.str, is_w, "split", dim=dim))
            split_bytes += arr.nbytes
        else:
            # greedy balance: lightest shard first, ties to the lowest
            # id — deterministic given the canonical leaf order
            owner = int(np.argmin(rep_bytes))
            rep_bytes[owner] += arr.nbytes
            leaves.append(LeafPlan(i, _path_str(path), shape,
                                   arr.dtype.str, is_w, "rep", owner=owner))
    plan = ShardPlan(num_shards, leaves, min_split_elems)
    # the client-facing structure: the wire codec's flatten spec of the
    # template, so a silo can unflatten joined leaves into the params
    # tree (and flatten its trained tree back) with zero configuration.
    # The codec and jax.tree canonicalize plain-container trees the same
    # way; verify leaf-for-leaf here so a tree they'd disagree on fails
    # at plan build, not as silently-permuted params on a silo
    from fedml_tpu.comm.message import _flatten_arrays
    codec_leaves, structure = _flatten_arrays(
        jax.tree.map(np.asarray, template))
    if codec_leaves is None or len(codec_leaves) != len(flat) or any(
            np.asarray(a).shape != np.asarray(b).shape
            or np.asarray(a).dtype != np.asarray(b).dtype
            for a, (_, b) in zip(codec_leaves, flat)):
        raise ValueError(
            "the model's parameter tree does not canonicalize identically "
            "through jax.tree and the wire codec; --model_shards needs "
            "plain dict/list/tuple params (every in-tree model qualifies)")
    plan._structure = structure
    return plan


class SiloShardCodec:
    """Silo-side split/join built purely from the sync frame's plan
    spec: ``join(slices) -> params tree`` for training, ``split(tree) ->
    slices`` for the upload.  Cached per spec fingerprint by the client
    actor — the spec is static across rounds."""

    def __init__(self, spec: dict):
        self.plan = ShardPlan.from_spec(spec)
        self._structure = spec.get("structure")
        if self._structure is None:
            raise ValueError("shard spec carries no structure; the silo "
                             "cannot rebuild the params tree from slices")
        self.fingerprint = self.plan.fingerprint()

    @property
    def num_shards(self) -> int:
        return self.plan.num_shards

    def join(self, slices: Sequence[dict]):
        from fedml_tpu.comm.message import _unflatten_arrays
        return _unflatten_arrays(self._structure,
                                 self.plan.join_slices(slices))

    def split(self, tree: Pytree) -> List[dict]:
        from fedml_tpu.comm.message import _flatten_arrays
        leaves, _ = _flatten_arrays(tree)
        if leaves is None:
            raise ValueError("cannot split a tree with no array leaves")
        return self.plan.split_leaves(leaves)
