"""The cohort engine: one FL round as ONE compiled XLA program.

This is the centerpiece replacement for the reference's entire distributed
runtime.  In the reference, a round is a message choreography —
S2C_SYNC_MODEL to every client process, per-client torch training, C2S
uploads, an all-received barrier, then a Python aggregation loop
(FedAvgServerManager.py:45-82, FedAVGAggregator.py:50-87).  Here:

* single chip: `vmap` the local trainer over a stacked client axis — the
  whole cohort trains in parallel in one jit (what the reference's
  *sequential* standalone simulator, fedavg_api.py:56-66, wished it could do);
* multi chip: `shard_map` over the mesh's ``clients`` axis — each device
  trains its shard of the cohort (vmap within), and the weighted aggregation
  is a `lax.psum` riding ICI.  No threads, queues, pickling, or barriers:
  the collective IS the barrier.

Cohort sizes are static per jit (pad the sampled cohort with weight-0
clients; see fedml_tpu.data.stacking.gather_cohort), so re-jit pressure is
zero after the first round.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu.core.pytree import tree_weighted_mean

Pytree = Any
CohortData = Dict[str, jax.Array]  # leaves [C, S, B, ...]; "num_samples" [C]
CohortStep = Callable[..., Tuple[Pytree, Dict[str, jax.Array]]]


def compat_shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """`jax.shard_map` where available, else the experimental spelling
    older toolchains ship.  ``check_vma=None`` leaves the new API's
    default checking on; the old API's `check_rep` (its analog) is
    disabled — it predates the pcast annotations these bodies use to
    satisfy the checker."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as esm
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def compat_is_legacy_shard_map() -> bool:
    """True on toolchains without `jax.shard_map` (the experimental
    fallback runs instead).  Two surfaces are UNSUPPORTED there and must
    refuse loudly rather than misbehave: gradients THROUGH a psum inside
    the mapped body (the old API's transpose is wrong without the
    replication tracking pcast feeds — sequence-parallel training), and
    the MoE pipeline schedule (its scalar balance output trips the old
    spec checker at trace time)."""
    return getattr(jax, "shard_map", None) is None


def compat_axis_size(axis_name):
    """`jax.lax.axis_size` where available (a STATIC python int —
    callers build ppermute tables from it); older jax reads the same
    static size off the tracing-time axis env (private API, guarded —
    the traced psum-of-ones fallback serves only callers that never
    need a concrete int)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    try:
        from jax._src.core import get_axis_env
        return get_axis_env().axis_size(axis_name)
    except (ImportError, AttributeError):
        import jax.numpy as _jnp
        return jax.lax.psum(_jnp.int32(1), axis_name)


def compat_pcast_varying(x, axes):
    """`jax.lax.pcast(..., to="varying")` marks replicated args
    device-varying for the new shard_map's VMA checker; older jax has
    no VMA tracking (and no pcast) — identity there."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axes, to="varying")


def train_cohort(local_train, params: Pytree, data: CohortData,
                 rng: jax.Array, index_offset=0, transform_update=None,
                 client_axis: str = "vmap"):
    """Run ``local_train`` over the stacked client axis.

    Per-client rng = fold_in(rng, global cohort slot), so single-chip and
    mesh-sharded runs are bit-identical even with dropout.  This is the one
    shared preamble for every cohort-training algorithm (FedAvg cohort step,
    FedNova, gossip) — keep rng/num_samples conventions here only.

    ``client_axis`` picks the execution of that axis; both produce
    identical stacked outputs:

    * ``"vmap"`` (default) — all clients train concurrently.  For conv
      models this batches per-client KERNELS too, which XLA lowers to
      grouped convolutions: at CIFAR-ResNet channel widths (16/32/64)
      each group occupies a sliver of the 128-wide MXU tile, so the
      grouping can dominate the step time.
    * ``"scan"`` — clients train sequentially via ``lax.scan``; every
      conv stays a dense, full-batch conv (better MXU tiling per call,
      no cross-client parallelism).  The right choice is empirical —
      bench.py measures both for the resnet56 flagship (BENCH_R56 table).
    """
    if client_axis not in ("vmap", "scan"):
        raise ValueError(f"client_axis must be 'vmap' or 'scan', "
                         f"got {client_axis!r}")
    n_clients = data["num_samples"].shape[0]
    idx = jnp.arange(n_clients) + index_offset
    rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(idx)
    client_batches = {k: v for k, v in data.items() if k != "num_samples"}
    if client_axis == "scan":
        def _one(_, xs):
            batches, r = xs
            return _, local_train(params, batches, r)
        _, (new_params, metrics) = jax.lax.scan(
            _one, 0, (client_batches, rngs))
    else:
        new_params, metrics = jax.vmap(
            local_train, in_axes=(None, 0, 0))(params, client_batches, rngs)
    if transform_update is not None:
        t_rng = jax.random.fold_in(rng, 0x7FFFFFFF)  # distinct stream
        t_rngs = jax.vmap(lambda i: jax.random.fold_in(t_rng, i))(idx)
        new_params = jax.vmap(
            transform_update, in_axes=(0, None, 0))(new_params, params, t_rngs)
    return new_params, metrics


def _call_aggregate(aggregate, stacked, weights, global_params, rng):
    """Aggregates normally take (stacked, weights); fused kernels that also
    need the round context (e.g. core.pallas_agg — clip is relative to the
    global params, noise is keyed by the round rng) set ``needs_global``."""
    if getattr(aggregate, "needs_global", False):
        return aggregate(stacked, weights, global_params, rng)
    return aggregate(stacked, weights)


def make_cohort_step(local_train, mesh: Optional[Mesh] = None,
                     aggregate=tree_weighted_mean,
                     transform_update=None,
                     client_axis: str = "vmap") -> CohortStep:
    """Build ``step(global_params, cohort_data, rng) -> (new_global, aux)``.

    ``local_train(params, client_data, rng) -> (params', metrics)`` is the
    jit-able per-client trainer (fedml_tpu.trainer.local_sgd).

    ``transform_update(client_params, global_params, rng) -> client_params``
    is an optional per-client hook applied before aggregation — the seam
    where robust defenses (clip / weak-DP, fedml_tpu.core.robust) plug in,
    exactly where the reference hooks them (FedAvgRobustAggregator.py:179-207).

    ``aggregate(stacked_params, weights) -> params`` defaults to the
    sample-weighted FedAvg mean; FedOpt/FedNova swap in their own.

    ``client_axis`` ("vmap" | "scan") — see train_cohort: concurrent
    clients (grouped convs) vs sequential clients (dense convs).
    """

    def _train_cohort(params, data, rng, index_offset=0):
        return train_cohort(local_train, params, data, rng,
                            index_offset=index_offset,
                            transform_update=transform_update,
                            client_axis=client_axis)

    if mesh is None:
        def step(global_params, cohort_data, rng):
            stacked, metrics = _train_cohort(global_params, cohort_data, rng)
            new_global = _call_aggregate(aggregate, stacked,
                                         cohort_data["num_samples"],
                                         global_params, rng)
            return new_global, metrics
        return jax.jit(step)

    # ---- sharded path: clients axis split across the mesh ----------------
    def _sharded(global_params, cohort_data, rng):
        # runs per-device: cohort_data leaves are the local shard [C/D, ...]
        # params/rng arrive replicated (unvarying); mark them device-varying so
        # the local-train scan carry (which mixes in varying data) typechecks
        global_params = compat_pcast_varying(global_params, ("clients",))
        rng = compat_pcast_varying(rng, ("clients",))
        local_c = cohort_data["num_samples"].shape[0]
        offset = jax.lax.axis_index("clients") * local_c
        stacked, metrics = _train_cohort(global_params, cohort_data, rng, offset)
        # local partial weighted sums, then one psum pair over ICI
        w = cohort_data["num_samples"].astype(jnp.float32)
        total = jax.lax.psum(jnp.sum(w), "clients")
        ratio = w / total
        new_global = jax.tree.map(
            lambda x: jax.lax.psum(
                jnp.sum(x * ratio.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype),
                        axis=0), "clients"),
            stacked)
        return new_global, metrics

    data_spec = P("clients")
    sharded = compat_shard_map(
        _sharded, mesh=mesh,
        in_specs=(P(), data_spec, P()),
        out_specs=(P(), data_spec))

    n_dev = mesh.shape["clients"]

    @jax.jit
    def step(global_params, cohort_data, rng):
        C = cohort_data["num_samples"].shape[0]
        if C % n_dev:  # static shape — checked at trace time
            raise ValueError(
                f"cohort size {C} not divisible by the mesh clients axis "
                f"({n_dev}); pad the cohort (gather_cohort pad_to=) to a "
                f"multiple of the device count")
        return sharded(global_params, cohort_data, rng)

    return step


def make_device_round(local_train, clients_per_round: int,
                      aggregate=tree_weighted_mean, transform_update=None,
                      client_axis: str = "vmap"):
    """Fully-on-device round: the ENTIRE stacked dataset lives in HBM and
    the sampled cohort is gathered by ids INSIDE the jit — zero per-round
    host<->device traffic (only the [m] ids array crosses).

    This is the TPU answer to SURVEY.md hard part (f): the reference's
    "process k plays sampled client i" re-pointing (FedAVGTrainer.py:25-29)
    becomes one XLA gather.  At large cohorts the host-gather path
    (gather_cohort + re-upload) is bandwidth-bound and collapses — see
    BENCH_DETAILS.json cohort_scaling; this path keeps the chip fed.

    Returns ``round_fn(params, stacked_dev, ids, live, rng)`` where
    ``stacked_dev`` is the device-resident ``{x, y, mask, num_samples}``
    tree, ``ids`` an int32[m] cohort (padded with any valid id), and
    ``live`` a float32[m] 1/0 mask of real (non-padding) cohort slots.
    """

    body = _device_round_body(local_train, aggregate, transform_update,
                              client_axis)
    return jax.jit(body)


def gather_live_cohort(stacked: CohortData, ids, live) -> CohortData:
    """In-jit cohort materialization from the HBM-resident dataset: gather
    by ``ids`` and zero out padded slots via the ``live`` mask.  THE one
    definition of the live-masking convention — every HBM fast path
    (make_device_round, make_scanned_rounds, FedNova's device round) calls
    this, so the convention cannot drift between them."""
    cohort = jax.tree.map(lambda v: jnp.take(v, ids, axis=0), stacked)
    cohort["mask"] = cohort["mask"] * live[:, None, None]
    cohort["num_samples"] = cohort["num_samples"] * live
    return cohort


def _device_round_body(local_train, aggregate, transform_update,
                       client_axis: str = "vmap"):
    """One HBM-resident round: in-jit id gather + live masking + cohort
    train + aggregate.  Shared by make_device_round (K=1, jitted directly)
    and make_scanned_rounds (the lax.scan body), so the two fast paths can
    never drift apart."""

    def body(params, stacked, ids, live, rng):
        cohort = gather_live_cohort(stacked, ids, live)
        stacked_out, metrics = train_cohort(
            local_train, params, cohort, rng,
            transform_update=transform_update, client_axis=client_axis)
        return _call_aggregate(aggregate, stacked_out,
                               cohort["num_samples"], params, rng), metrics

    return body


def make_scanned_rounds(local_train, clients_per_round: int,
                        aggregate=tree_weighted_mean,
                        transform_update=None, client_axis: str = "vmap"):
    """K federated rounds per dispatch: `lax.scan` over per-round cohort ids
    with the dataset HBM-resident (make_device_round's gather, iterated on
    device).

    Why: at cross-device scale a round is sub-millisecond on the MXU, so a
    host loop pays more in dispatch latency than in compute — the reference
    pays a full MPI broadcast/barrier per round (FedAvgServerManager.py:45-82);
    even our own jit-per-round path pays one host->device dispatch.  Scanning
    K rounds amortises that to one dispatch per K rounds; eval cadence picks
    K (run K = frequency_of_the_test rounds, then eval).

    Returns ``rounds_fn(params, stacked_dev, ids [K, m] int32,
    live [K, m] float32, rng) -> (params, per_round_metrics)``.
    """

    body = _device_round_body(local_train, aggregate, transform_update,
                              client_axis)

    @jax.jit
    def rounds_fn(params, stacked, ids, live, rng):
        def one_round(p, xs):
            ids_r, live_r, i = xs
            return body(p, stacked, ids_r, live_r,
                        jax.random.fold_in(rng, i))

        K = ids.shape[0]
        return jax.lax.scan(one_round, params,
                            (ids, live, jnp.arange(K)))

    return rounds_fn


def make_sharded_stateful_round(core, mesh: Mesh, in_specs, out_specs):
    """Wrap a shared round body ``core(params, cohort, rng, *state,
    psum_axis=, index_offset=)`` as a jitted shard_map over the mesh's
    ``clients`` axis — THE one home for the stateful-algorithm mesh-wrap
    convention (FedNova/SCAFFOLD/FedDyn share it): the per-device wrapper
    derives the shard's GLOBAL cohort-slot offset from the cohort arg
    (second positional, leaves [C/D, ...]) so per-client rng folding
    matches single-chip exactly, and ``check_vma`` is off because the
    local trainers' scans carry scalar counters that start unvarying
    (semantics unaffected).

    MULTI-PROCESS (after ``init_distributed``) is handled here, once, for
    every stateful algorithm (round-4 verdict item 4 — the reference's
    MPI mode is inherently multi-process, FedAvgAPI.py:20-28):

    * inputs: every positional arg is staged to a global jax.Array per
      its ``in_specs`` entry (``stage_global`` is idempotent, so args the
      run loop already staged — params/cohort/rng — pass through);
    * outputs: state sharded ``P("clients")`` is ``all_gather``-ed over
      the clients axis INSIDE the shard_map so it comes out fully
      replicated — every process then reads the complete cohort rows and
      scatters them into its own host-resident state mirror.  This keeps
      the framework's every-host-mirrors-the-state convention (the same
      one the data layer uses, mesh.stage_global docstring) instead of
      sharding state by process; the gather is cohort-sized, so the DCN
      cost is one small collective per round.
    """
    multiproc = jax.process_count() > 1

    def _spec_tuple(specs):
        return specs if isinstance(specs, tuple) else (specs,)

    def _gathered(out):
        """all_gather the P("clients")-sharded outputs (tuple-positional,
        matching out_specs) so they land replicated on every process."""
        outs = out if isinstance(out_specs, tuple) else (out,)
        gathered = tuple(
            jax.tree.map(lambda x: jax.lax.all_gather(
                x, "clients", axis=0, tiled=True), o)
            if "clients" in s else o
            for o, s in zip(outs, _spec_tuple(out_specs)))
        return gathered if isinstance(out_specs, tuple) else gathered[0]

    def per_device(params, cohort, rng, *state):
        local_c = cohort["num_samples"].shape[0]
        offset = jax.lax.axis_index("clients") * local_c
        out = core(params, cohort, rng, *state,
                   psum_axis="clients", index_offset=offset)
        return _gathered(out) if multiproc else out

    if multiproc:
        eff_out = jax.tree.map(
            lambda s: P() if "clients" in s else s, out_specs,
            is_leaf=lambda s: isinstance(s, P))
    else:
        eff_out = out_specs
    fn = jax.jit(compat_shard_map(per_device, mesh=mesh, in_specs=in_specs,
                                  out_specs=eff_out, check_vma=False))
    if not multiproc:
        return fn

    from fedml_tpu.parallel.mesh import stage_global

    def staged(*args):
        return fn(*(stage_global(a, mesh, s)
                    for a, s in zip(args, _spec_tuple(in_specs))))

    return staged


def pad_clients(data: CohortData, n_dev: int) -> CohortData:
    """Zero-pad the leading clients axis to a multiple of ``n_dev``; padded
    rows carry mask 0 / weight 0, so they contribute nothing to training or
    metrics."""
    C = next(iter(data.values())).shape[0]
    if C % n_dev == 0:
        return data
    pad = n_dev - C % n_dev
    return jax.tree.map(
        lambda x: jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)]), data)


def cohort_eval(evaluate, mesh: Optional[Mesh] = None):
    """Evaluate a (global) model over a stacked cohort of datasets; returns
    summed metric dicts.  Replaces the server's sequential per-client eval
    sweep (FedAVGAggregator.test_on_server_for_all_clients, :109-163)."""

    def _eval_cohort(params, data):
        client_batches = {k: v for k, v in data.items() if k != "num_samples"}
        per_client = jax.vmap(evaluate, in_axes=(None, 0))(params, client_batches)
        return jax.tree.map(lambda x: jnp.sum(x, axis=0), per_client)

    if mesh is None:
        return jax.jit(_eval_cohort)

    def _sharded(params, data):
        local = _eval_cohort(params, data)
        return jax.tree.map(lambda x: jax.lax.psum(x, "clients"), local)

    sharded = compat_shard_map(
        _sharded, mesh=mesh, in_specs=(P(), P("clients")), out_specs=P())
    n_dev = mesh.shape["clients"]

    @jax.jit
    def padded(params, data):
        # zero-mask padding so ANY client count shards
        return sharded(params, pad_clients(data, n_dev))

    return padded
