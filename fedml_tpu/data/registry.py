"""Dataset registry — the TPU-native replacement for the ``load_data`` switch
in every reference entry point (``fedml_experiments/distributed/fedavg/
main_fedavg.py:115-221``: a 100-line if/elif over dataset names).

``load_data(name, data_dir=..., **kw)`` dispatches to the right loader and
returns `FederatedData`.  When ``data_dir`` is None or missing and the
dataset has no on-disk requirement, loaders fall back to hermetic synthetic
twins with the real dataset's shapes so every pipeline runs air-gapped
(``synthetic_ok=False`` disables the fallback for production runs).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

from .stacking import FederatedData
from .synthetic import load_synthetic, synthetic_federated_dataset

# name -> (real loader kwargs-adapter, synthetic twin)
_REGISTRY: Dict[str, Dict] = {}


def register_dataset(name: str, loader: Callable,
                     synthetic_twin: Optional[Callable] = None,
                     **defaults) -> None:
    _REGISTRY[name] = {"loader": loader, "twin": synthetic_twin,
                       "defaults": defaults}


def dataset_names():
    return sorted(_REGISTRY)


def _accepted_kwargs(fn, kw: Dict) -> Dict:
    """Keep only kwargs ``fn`` can accept (twins and loaders have different
    signatures; a real-loader option must not crash the hermetic path)."""
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return kw
    if any(p.kind is inspect.Parameter.VAR_KEYWORD
           for p in sig.parameters.values()):
        return kw
    return {k: v for k, v in kw.items() if k in sig.parameters}


def load_data(name: str, data_dir: Optional[str] = None,
              synthetic_ok: bool = True, **kw) -> FederatedData:
    if name not in _REGISTRY:
        raise KeyError(f"unknown dataset {name!r}; have {dataset_names()}")
    entry = _REGISTRY[name]
    if data_dir is not None:
        # an explicitly named data_dir that is missing is a user error, not a
        # request for hermetic mode — never silently train on noise
        if not os.path.isdir(data_dir):
            raise FileNotFoundError(
                f"dataset {name!r}: data_dir {data_dir!r} does not exist")
        merged = {**entry["defaults"], **kw}
        accepted = _accepted_kwargs(entry["loader"], merged)
        # twin-only kwargs (e.g. num_clients) are dropped quietly; anything
        # NEITHER callable accepts is a typo and must fail loudly
        dropped = set(merged) - set(accepted)
        twin_ok = set(_accepted_kwargs(entry["twin"], merged)) \
            if entry["twin"] is not None else set()
        unknown = dropped - twin_ok
        if unknown:
            raise TypeError(
                f"dataset {name!r}: unknown option(s) {sorted(unknown)}")
        return entry["loader"](data_dir=data_dir, **accepted)
    if synthetic_ok and entry["twin"] is not None:
        return entry["twin"](**_accepted_kwargs(entry["twin"], kw))
    raise FileNotFoundError(
        f"dataset {name!r}: no data_dir given and synthetic fallback "
        f"disabled/unavailable")


def _register_all() -> None:
    from . import leaf, tff_h5, cifar
    from functools import partial

    img_twin = lambda shape, classes: partial(
        synthetic_federated_dataset, sample_shape=shape, class_num=classes)

    register_dataset("mnist", leaf.load_mnist,
                     img_twin((784,), 10))
    # the CONVERGENCE-grade MNIST stand-in (class prototypes + noise,
    # LEAF power-law sizes): unlike the shape-only noise twin above, a
    # model actually learns on it, so benches that gate on
    # rounds-to-target accuracy (scripts/opt_bench.py) can run hermetic
    from .synthetic import mnist_learnable_twin
    register_dataset("mnist_learnable_twin", leaf.load_mnist,
                     mnist_learnable_twin)
    register_dataset("shakespeare", leaf.load_shakespeare_leaf,
                     partial(synthetic_federated_dataset,
                             sample_shape=(80,), sequence_vocab=90,
                             class_num=90))
    register_dataset("synthetic", lambda data_dir=None, **kw:
                     leaf.load_synthetic_leaf(data_dir, **kw),
                     load_synthetic)
    register_dataset("femnist", tff_h5.load_federated_emnist,
                     img_twin((28, 28, 1), 62))
    register_dataset("fed_cifar100", tff_h5.load_fed_cifar100,
                     img_twin((32, 32, 3), 100))
    register_dataset("fed_shakespeare", tff_h5.load_fed_shakespeare,
                     partial(synthetic_federated_dataset,
                             sample_shape=(80,), sequence_vocab=90,
                             class_num=90))
    register_dataset("stackoverflow_nwp", tff_h5.load_stackoverflow_nwp,
                     partial(synthetic_federated_dataset,
                             sample_shape=(20,), sequence_vocab=10004,
                             class_num=10004))
    register_dataset("stackoverflow_lr", tff_h5.load_stackoverflow_lr,
                     partial(synthetic_federated_dataset,
                             sample_shape=(10000,), class_num=500,
                             multilabel=True))
    for ds in ("cifar10", "cifar100", "cinic10"):
        register_dataset(
            ds,
            partial(cifar.load_cifar_partitioned, ds),
            img_twin((32, 32, 3), 100 if ds == "cifar100" else 10),
            client_num=10)

    from . import imagenet
    register_dataset("ilsvrc2012", imagenet.load_imagenet,
                     img_twin((224, 224, 3), 1000))
    # per-name mapping-csv defaults (Landmarks/data_loader.py docstring:
    # data_user_dict/gld{23k,160k}_user_dict_train.csv under the data root)
    register_dataset(
        "gld23k", imagenet.load_landmarks, img_twin((224, 224, 3), 203),
        mapping_csv="data_user_dict/gld23k_user_dict_train.csv")
    register_dataset(
        "gld160k", imagenet.load_landmarks, img_twin((224, 224, 3), 2028),
        mapping_csv="data_user_dict/gld160k_user_dict_train.csv")


_register_all()
