"""``python -m fedml_tpu`` — the experiments layer.

Replaces the reference's 5,550-LoC ``fedml_experiments/`` tree (one
``main_*.py`` + shell launcher per algorithm×paradigm) with ONE entry point:
every algorithm in the framework runs end-to-end from a shell, with hermetic
synthetic data when no ``--data_dir`` is given, and the same flag surface as
``main_fedavg.py:46-112`` where flags carry over.

Launch story parity:

* reference: ``sh run_fedavg_distributed_pytorch.sh 10 10 lr mnist ...`` →
  ``mpirun -np 11 -hostfile mpi_host_file python3 main_fedavg.py ...``
* here: ``python -m fedml_tpu --algo fedavg --model lr --dataset mnist
  --client_num_per_round 10 ...`` — on-pod "processes" are mesh shards
  (``--mesh_clients N``); multi-host pods add ``--coordinator_address
  host:port --num_processes P --process_id i`` per host
  (jax.distributed.initialize, fedml_tpu/parallel/mesh.py).

Every run writes ``metrics.jsonl`` + ``summary.json`` into ``--run_dir``
(the wandb-equivalent stream the reference CI asserts on,
CI-script-fedavg.sh:43-48) and prints one final JSON summary line.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Callable, Dict, Optional

import numpy as np

from fedml_tpu.experiments.config import ExperimentConfig, config_from_argv
from fedml_tpu.experiments.models import create_workload, sample_shape_of
from fedml_tpu.utils.metrics import MetricsSink, profiler_trace

logger = logging.getLogger("fedml_tpu")

RUNNERS: Dict[str, Callable] = {}


def runner(name: str):
    def deco(fn):
        RUNNERS[name] = fn
        return fn
    return deco


# --------------------------------------------------------------------------
# shared plumbing
# --------------------------------------------------------------------------

def load_experiment_data(cfg: ExperimentConfig):
    """Registry dispatch with per-dataset kwargs (the load_data switch,
    main_fedavg.py:115-221)."""
    from fedml_tpu.data import load_data
    kw: Dict[str, Any] = {"batch_size": cfg.batch_size}
    if cfg.dataset in ("cifar10", "cifar100", "cinic10"):
        kw.update(client_num=cfg.client_num_in_total,
                  partition_method=cfg.partition_method,
                  partition_alpha=cfg.partition_alpha,
                  seed=cfg.seed)
    else:
        # twin-only knob; real loaders carry their own client counts
        kw.update(num_clients=cfg.client_num_in_total, seed=cfg.seed)
    return load_data(cfg.dataset, data_dir=cfg.data_dir, **kw)


def _fedavg_cfg_kwargs(cfg: ExperimentConfig) -> Dict[str, Any]:
    freq = cfg.frequency_of_the_test
    if cfg.ci:
        # CI mode restricts eval to round 0 + the final round (the gate
        # `round_idx % freq == 0` always fires at 0, reference parity:
        # FedAVGAggregator.py:126-131 shrinks eval rather than skipping it)
        freq = max(cfg.comm_round, 1)
    return dict(comm_round=cfg.comm_round,
                client_num_per_round=cfg.client_num_per_round,
                epochs=cfg.epochs, batch_size=cfg.batch_size, lr=cfg.lr,
                client_optimizer=cfg.client_optimizer, wd=cfg.wd,
                frequency_of_the_test=freq, seed=cfg.seed,
                rounds_per_dispatch=cfg.rounds_per_dispatch,
                client_axis=cfg.client_axis,
                eval_chunk_clients=cfg.eval_chunk_clients)


def _make_workload(cfg: ExperimentConfig, data):
    """The one place runner code constructs the model workload (threading a
    new construction knob is a one-line change here, not 9 edits)."""
    return create_workload(cfg.model, cfg.dataset, data.class_num,
                           sample_shape_of(data),
                           compute_dtype=cfg.compute_dtype,
                           attn_block_size=cfg.attn_block_size,
                           attn_flash=cfg.attn_flash,
                           moe_experts=cfg.moe_experts)


def _make_checkpointer(cfg: ExperimentConfig):
    if not cfg.checkpoint_dir:
        return None
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    return RoundCheckpointer(cfg.checkpoint_dir,
                             save_every=cfg.checkpoint_every,
                             async_save=cfg.checkpoint_async,
                             keep_last_n=cfg.checkpoint_keep_last_n)


def _make_perf(cfg: ExperimentConfig):
    """Performance flight recorder (obs/perf.py) for the live actor
    modes: a per-round ``perf.jsonl`` ledger at ``--perf_ledger`` (or
    ``run_dir/perf.jsonl`` under ``--perf``).  Only the SERVER node
    records — silo processes return None.  The runner owns ``close()``
    (stops the RSS sampler thread)."""
    # perf_strict/device_obs imply the recorder: a strict sentry (or a
    # device observatory) with no recorder to own it would be the exact
    # "flag parses then silently never enforces" condition the algo gate
    # in main() rejects
    if not (cfg.perf or cfg.perf_ledger or cfg.perf_strict
            or cfg.device_obs):
        return None
    if cfg.silo_backend != "local" and cfg.node_id != 0:
        return None  # a gRPC silo has no round lifecycle to ledger
    import os
    from fedml_tpu.obs import PerfRecorder
    device = None
    if cfg.device_obs:
        # device & compile observatory (obs/device.py): every ledger
        # line gains a device section; the hot jits built below wrap
        # through PerfRecorder.instrument_jit / the device= seams
        from fedml_tpu.obs import DeviceRecorder
        device = DeviceRecorder()
    path = cfg.perf_ledger or os.path.join(
        cfg.metrics_dir or cfg.run_dir or ".", "perf.jsonl")
    return PerfRecorder(path, node=f"node{cfg.node_id}",
                        strict_recompiles=cfg.perf_strict, device=device)


def _make_health(cfg: ExperimentConfig, kind: str,
                 suppress_payload=None):
    """Federation health observatory (obs/health.py) for the live actor
    modes: streaming learning-health stats + a ``health.jsonl`` ledger
    at ``--health_ledger`` (or ``run_dir/health.jsonl`` under
    ``--health``).  Only the SERVER node accumulates.  Drift-alarm
    thresholds ride the same ``--slo`` spec as every other objective
    (health_misalignment_ratio / health_norm_cv_ratio /
    health_starvation_ratio); non-health names in the spec are simply
    not thresholds here."""
    if not (cfg.health or cfg.health_ledger):
        return None
    if cfg.silo_backend != "local" and cfg.node_id != 0:
        return None  # a gRPC silo has no round lifecycle to observe
    import os
    from fedml_tpu.obs import HealthAccumulator
    from fedml_tpu.obs.health import HEALTH_SLOS
    from fedml_tpu.obs.perf import parse_slo_spec
    path = cfg.health_ledger or os.path.join(
        cfg.metrics_dir or cfg.run_dir or ".", "health.jsonl")
    spec = parse_slo_spec(cfg.slo) if cfg.slo else {}
    thresholds = {k: v for k, v in spec.items() if k in HEALTH_SLOS}
    return HealthAccumulator(kind=kind, node=f"node{cfg.node_id}",
                             ledger_path=path, thresholds=thresholds,
                             suppress_payload=suppress_payload)


def _make_journal(cfg: ExperimentConfig, subdir: Optional[str] = None):
    """Durable round journal (utils/journal.py) for the live actor
    modes: crash-safe per-accept records + periodic atomic fold-state
    snapshots under ``--journal_dir`` (or ``run_dir/journal`` under
    ``--journal``).  Only the SERVER node journals; under the edge
    topology each edge gets its own ``edge{e}`` subdirectory."""
    if not (cfg.journal or cfg.journal_dir):
        return None
    if cfg.silo_backend != "local" and cfg.node_id != 0:
        return None  # a gRPC silo has no fold state to journal
    import os
    from fedml_tpu.utils.journal import RoundJournal
    base = cfg.journal_dir or os.path.join(
        cfg.metrics_dir or cfg.run_dir or ".", "journal")
    path = os.path.join(base, subdir) if subdir else base
    if not cfg.checkpoint_dir:
        logger.warning("--journal without --checkpoint_dir: mid-round "
                       "recovery needs the round-boundary checkpoint to "
                       "resume against; the journal will record but a "
                       "restarted server starts from round 0")
    elif cfg.checkpoint_every != 1:
        logger.warning("--journal with --checkpoint_every %d: mid-round "
                       "recovery only engages when the crashed round "
                       "directly follows a checkpointed one; set "
                       "--checkpoint_every 1 for full coverage",
                       cfg.checkpoint_every)
    return RoundJournal(path, snapshot_every=cfg.journal_snapshot_every,
                        node=subdir or f"node{cfg.node_id}")


def _compose_extra_state(named):
    """Fold several named ``(get_fn, set_fn)`` pairs into the one
    ``extra_state`` checkpoint hook: the saved tree is a dict keyed by
    name (fixed shapes per entry, so the whole composite still doubles
    as the orbax restore template).  A restored tree missing a name (a
    checkpoint from before that subsystem existed) warns and restores
    what is there."""
    named = [(n, gs) for n, gs in named if gs is not None]
    if not named:
        return None

    def get():
        return {name: g() for name, (g, _) in named}

    def set_(tree):
        if not hasattr(tree, "get"):
            logger.warning("checkpoint extra-state is not the named-dict "
                           "schema (pre-composition checkpoint?); "
                           "skipping extra-state restore")
            return
        for name, (_, s) in named:
            sub = tree.get(name)
            if sub is None:
                logger.warning("checkpoint extra-state has no %r entry; "
                               "that subsystem starts fresh", name)
                continue
            s(sub)

    return (get, set_)


def _make_server_opt(cfg: ExperimentConfig, template, *, plan=None,
                     sentry=None, device=None):
    """The live server-optimizer seam (fedml_tpu/server_opt, ISSUE 18).
    ``plain`` returns None — the actors then keep the pre-seam
    ``params = finalize(...)`` assignment byte-for-byte, which IS the
    bit-identity parity contract."""
    if cfg.server_opt == "plain":
        return None
    from fedml_tpu.server_opt import ServerOptimizer
    return ServerOptimizer(
        cfg.server_opt, template, lr=cfg.server_lr,
        momentum=cfg.server_momentum,
        beta1=cfg.server_adam_beta1, beta2=cfg.server_adam_beta2,
        eps=cfg.server_adam_eps,
        fedac_mu=cfg.fedac_mu, fedac_gamma=cfg.fedac_gamma,
        fedac_alpha=cfg.fedac_alpha, fedac_beta=cfg.fedac_beta,
        local_steps=cfg.epochs, plan=plan, sentry=sentry, device=device)


def _make_controller(cfg: ExperimentConfig, *, cohort, epochs,
                     wave_size=0, max_cohort=None, epochs_live=False):
    """The health-driven adaptive round controller (--adaptive)."""
    if not cfg.adaptive:
        return None
    from fedml_tpu.server_opt import AdaptiveController
    return AdaptiveController(
        cohort=cohort, epochs=epochs, wave_size=wave_size,
        min_cohort=cfg.adapt_min_cohort, max_cohort=max_cohort,
        patience=cfg.adapt_patience, epochs_live=epochs_live)


def _degrade_setup(cfg: ExperimentConfig, n_silos: int,
                   mode: str = "sync"):
    """The sustained-degradation spine (--min_quorum /
    --adaptive_deadline / --partition_frac → robust/degrade.py,
    ISSUE 19), with fail-loud config gates: every misconfiguration is a
    NAMED error at startup, never a silently-ignored flag.  ``mode``:
    "sync" (cross_silo round barrier), "async" (the watchdog is the
    deadline analog; barrier flags are refused by name)."""
    wanted = (cfg.min_quorum > 0 or cfg.adaptive_deadline
              or cfg.partition_frac > 0)
    if not wanted:
        return None
    if not 0.0 < cfg.min_quorum <= 1.0 and cfg.min_quorum != 0.0:
        raise ValueError(
            f"--min_quorum must be in (0, 1] (a cohort fraction), got "
            f"{cfg.min_quorum}")
    if mode == "async":
        if cfg.min_quorum > 0 or cfg.partition_frac > 0:
            raise ValueError(
                "--min_quorum/--partition_frac adjudicate the sync round "
                "barrier; the async server has no barrier to close — "
                "only --adaptive_deadline (the watchdog analog) applies")
        if not cfg.retask_timeout_s:
            raise ValueError(
                "--adaptive_deadline under --algo async_fl adapts the "
                "re-task watchdog and needs --retask_timeout_s > 0 (the "
                "ceiling and cold-start fallback)")
    elif mode == "sync":
        if cfg.straggler_policy != "drop":
            raise ValueError(
                "--min_quorum/--adaptive_deadline/--partition_frac "
                "adjudicate the close-early deadline, which only the "
                "'drop' straggler policy has; use --straggler_policy "
                "drop (wait never closes early, abort never degrades "
                "gracefully)")
        if (cfg.adaptive_deadline or cfg.partition_frac > 0) \
                and not cfg.round_timeout_s:
            raise ValueError(
                "--adaptive_deadline/--partition_frac need "
                "--round_timeout_s > 0: the static timeout is the "
                "deadline's ceiling and the cold-start fallback, and "
                "without a timer the deadline can never fire")
    if cfg.partition_frac > 0 and not 0.0 < cfg.partition_frac <= 1.0:
        raise ValueError(
            f"--partition_frac must be in (0, 1] (a cohort fraction), "
            f"got {cfg.partition_frac}")
    if cfg.partition_frac > 0 and cfg.min_quorum > 0 \
            and cfg.partition_frac > 1.0 - cfg.min_quorum + 1e-9:
        raise ValueError(
            f"--partition_frac {cfg.partition_frac} exceeds the quorum "
            f"gap 1 - min_quorum = {1.0 - cfg.min_quorum:.3f}: a miss "
            f"that large already blocks the quorum, so the partition "
            f"hold would be unreachable dead code — lower "
            f"--partition_frac or --min_quorum")
    from fedml_tpu.robust.degrade import ReliabilityTracker
    return ReliabilityTracker(
        n_silos,
        min_quorum=cfg.min_quorum,
        adaptive_deadline=cfg.adaptive_deadline,
        deadline_floor_s=cfg.deadline_floor_s,
        deadline_quantile=cfg.deadline_quantile,
        deadline_slack=cfg.deadline_slack,
        partition_frac=cfg.partition_frac,
        partition_max_holds=cfg.partition_max_holds)


def _make_slo(cfg: ExperimentConfig):
    """SLO evaluator over the telemetry registry (obs/perf.py) backing
    the serve frontend's ``/healthz?deep=1``; ``--slo`` overrides the
    default objectives.  Needs live telemetry — with the registry
    disabled every objective would read vacuously healthy, so return
    None (the frontend then answers ``deep: unconfigured``)."""
    from fedml_tpu.obs import telemetry as _tel
    if not _tel.get_registry().enabled:
        if cfg.slo:
            logger.warning("--slo given but telemetry is disabled; the "
                           "deep health check needs --telemetry true")
        return None
    from fedml_tpu.obs.perf import SloEvaluator, parse_slo_spec
    thresholds = parse_slo_spec(cfg.slo) if cfg.slo else None
    return SloEvaluator(thresholds=thresholds)


def _eval_global(workload, params, data) -> Dict[str, float]:
    """Train/test accuracy over all clients (the per-runner summary for
    algorithms that don't track their own history)."""
    import jax
    from fedml_tpu.parallel.cohort import cohort_eval
    from fedml_tpu.trainer.local_sgd import make_evaluator
    ev = cohort_eval(make_evaluator(workload))
    out = {}
    for split, stacked in (("train", data.train), ("test", data.test)):
        if stacked is None:
            continue
        from fedml_tpu.utils.metrics import stats_from_metrics
        m = ev(params, {k: jax.numpy.asarray(v) for k, v in stacked.items()})
        out.update(stats_from_metrics(m, prefix=f"{split}_"))
    return out


def _release_eval_fn(workload, data):
    """Held-out scorer for the release gate: test accuracy, higher is
    better.  None when the dataset has no test split — the eval signal
    then passes vacuously (and says so in the verdict) instead of
    scoring candidates on training data."""
    if data.test is None:
        return None
    import jax
    from fedml_tpu.parallel.cohort import cohort_eval
    from fedml_tpu.trainer.local_sgd import make_evaluator
    from fedml_tpu.utils.metrics import stats_from_metrics
    ev = cohort_eval(make_evaluator(workload))
    test = {k: jax.numpy.asarray(v) for k, v in data.test.items()}

    def score(params):
        return stats_from_metrics(ev(params, test))["acc"]

    return score


def _first_cohort(data, n: int):
    """Deterministic cohort of the first n clients (for cohort-input
    algorithms: FedNAS / FedGKT / FedGAN)."""
    from fedml_tpu.data.stacking import gather_cohort
    ids = np.arange(min(n, data.client_num))
    return gather_cohort(data.train, ids, pad_to=n)


def _image_sample_shape(cfg, data, algo: str):
    shape = sample_shape_of(data)
    if len(shape) != 3:
        raise ValueError(
            f"--algo {algo} needs image-shaped data [H, W, C]; dataset "
            f"{cfg.dataset!r} yields {shape}. Try --dataset femnist or "
            f"cifar10.")
    return shape


# --------------------------------------------------------------------------
# FedAvg family
# --------------------------------------------------------------------------

@runner("fedavg")
def run_fedavg(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
    wl = _make_workload(cfg, data)
    if cfg.mesh_sequence > 0:
        # dp x sp: long-context federated training over a [clients,
        # sequence] mesh (parallel/sequence.py) — ring attention + psum'd
        # loss/grads inside each client, weighted psum across the cohort.
        # The dense workload still drives init + eval (params identical).
        from fedml_tpu.models import TransformerLM
        from fedml_tpu.parallel.sequence import (
            make_sp_cohort_step, make_sp_mesh, make_sp_nwp_workload)
        from fedml_tpu.trainer.workload import make_client_optimizer
        if cfg.model != "transformer":
            raise ValueError("--mesh_sequence requires --model transformer "
                             "(the ring-attention-capable model)")
        if cfg.moe_experts:
            raise ValueError(
                "--moe_experts with --mesh_sequence is not supported: the "
                "sequence-parallel loss path does not capture the Switch "
                "load-balance loss (it would silently train with zero "
                "balancing pressure); drop one of the flags")
        if not cfg.attn_block_size:
            logging.getLogger(__name__).warning(
                "--mesh_sequence without --attn_block_size: init/eval run "
                "single-chip attention (auto-blockwise past 1024 tokens "
                "when a block of 64-512 divides T, DENSE O(T^2) scores "
                "otherwise); set --attn_block_size to pin the "
                "memory-efficient path")
        if mesh is not None:
            raise ValueError("--mesh_sequence and --mesh_clients build one "
                             "combined [clients, sequence] mesh; pass "
                             "--mesh_sequence S with client sharding "
                             "implied by the remaining devices")
        import jax
        n_dev = len(jax.devices())
        n_cli = max(1, n_dev // cfg.mesh_sequence)
        algo = FedAvg(wl, data, FedAvgConfig(**_fedavg_cfg_kwargs(cfg)),
                      mesh=None, sink=sink)
        sp_wl = make_sp_nwp_workload(wl.model)
        algo.cohort_step = make_sp_cohort_step(
            sp_wl, make_client_optimizer(cfg.client_optimizer, cfg.lr,
                                         cfg.wd),
            cfg.epochs, mesh=make_sp_mesh(
                n_cli, cfg.mesh_sequence,
                devices=jax.devices()[:n_cli * cfg.mesh_sequence]))
    else:
        algo = FedAvg(wl, data, FedAvgConfig(**_fedavg_cfg_kwargs(cfg)),
                      mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("fedprox")
def run_fedprox(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.fedprox import FedProx, FedProxConfig
    wl = _make_workload(cfg, data)
    algo = FedProx(wl, data,
                   FedProxConfig(mu=cfg.mu, **_fedavg_cfg_kwargs(cfg)),
                   mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("fedopt")
def run_fedopt(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.fedopt import FedOpt, FedOptConfig
    wl = _make_workload(cfg, data)
    algo = FedOpt(wl, data, FedOptConfig(
        server_optimizer=cfg.server_optimizer, server_lr=cfg.server_lr,
        server_momentum=cfg.server_momentum, **_fedavg_cfg_kwargs(cfg)),
        mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("fednova")
def run_fednova(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.fednova import FedNova, FedNovaConfig
    wl = _make_workload(cfg, data)
    algo = FedNova(wl, data, FedNovaConfig(
        mu=cfg.mu if cfg.mu else 0.0, gmf=cfg.gmf,
        **_fedavg_cfg_kwargs(cfg)), mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("fedavg_robust")
def run_fedavg_robust(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.fedavg_robust import (FedAvgRobust,
                                                    FedAvgRobustConfig)
    wl = _make_workload(cfg, data)
    targeted = None
    if cfg.backdoor:
        # poison the first K clients' shards + track targeted-task accuracy
        # (FedAvgRobustAggregator.test_target_accuracy:270)
        from fedml_tpu.algorithms.backdoor import (make_targeted_test_set,
                                                   poison_federated_data)
        shape = _image_sample_shape(cfg, data, "fedavg_robust --backdoor")
        del shape
        attackers = list(range(min(cfg.attacker_num, data.client_num)))
        eval_src = data.test if data.test is not None else data.train
        honest = np.arange(len(attackers), data.client_num)
        x_eval = np.asarray(eval_src["x"])[honest]
        y_eval = np.asarray(eval_src["y"])[honest]
        m_eval = np.asarray(eval_src["mask"])[honest].reshape(-1) > 0
        x_eval = x_eval.reshape((-1,) + x_eval.shape[3:])[m_eval]
        y_eval = y_eval.reshape(-1)[m_eval]
        targeted = make_targeted_test_set(
            x_eval, y_eval, cfg.target_label, trigger_size=cfg.trigger_size)
        data = poison_federated_data(
            data, attackers, cfg.target_label, cfg.poison_frac,
            cfg.trigger_size, seed=cfg.seed)
    algo = FedAvgRobust(wl, data, FedAvgRobustConfig(
        defense=cfg.defense, norm_bound=cfg.norm_bound, stddev=cfg.stddev,
        defense_backend=cfg.defense_backend, trim_frac=cfg.trim_frac,
        byz_f=cfg.byz_f, krum_m=cfg.krum_m,
        gm_iters=cfg.gm_iters, gm_eps=cfg.gm_eps,
        **_fedavg_cfg_kwargs(cfg)), mesh=mesh, sink=sink)
    params = algo.run(checkpointer=_make_checkpointer(cfg))
    out = dict(algo.history[-1]) if algo.history else {}
    if targeted is not None:
        from fedml_tpu.algorithms.backdoor import targeted_accuracy
        out["backdoor_acc"] = targeted_accuracy(wl, params, targeted)
        sink.log({"backdoor_acc": out["backdoor_acc"]},
                 step=cfg.comm_round - 1)
    return out


@runner("hierarchical")
def run_hierarchical(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.hierarchical import (HierarchicalConfig,
                                                   HierarchicalFedAvg)
    wl = _make_workload(cfg, data)
    algo = HierarchicalFedAvg(wl, data, HierarchicalConfig(
        group_num=cfg.group_num, group_comm_round=cfg.group_comm_round,
        **_fedavg_cfg_kwargs(cfg)), mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


# --------------------------------------------------------------------------
# other paradigms
# --------------------------------------------------------------------------

@runner("centralized")
def run_centralized(cfg, data, mesh, sink):
    import jax
    from fedml_tpu.algorithms.centralized import CentralizedTrainer
    wl = _make_workload(cfg, data)
    trainer = CentralizedTrainer(wl, lr=cfg.lr,
                                 client_optimizer=cfg.client_optimizer,
                                 wd=cfg.wd, epochs_per_call=cfg.epochs)
    train = {k: jax.numpy.asarray(v) for k, v in data.train_global.items()}
    sample = jax.tree.map(lambda v: v[0], train)
    params = wl.init(jax.random.key(cfg.seed), sample)
    rng = jax.random.key(cfg.seed)
    for r in range(cfg.comm_round):
        rng, rr = jax.random.split(rng)
        params = trainer.train_rounds(params, train, 1, rr)
        if r % cfg.frequency_of_the_test == 0 or r == cfg.comm_round - 1:
            stats = {"train_" + k: v
                     for k, v in trainer.metrics(params, train).items()}
            if data.test_global is not None:
                stats.update({"test_" + k: v for k, v in trainer.metrics(
                    params, data.test_global).items()})
            stats["round"] = r
            sink.log(stats, step=r)
    return stats


@runner("decentralized")
def run_decentralized(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.decentralized import (DecentralizedConfig,
                                                    DecentralizedGossip)
    wl = _make_workload(cfg, data)
    algo = DecentralizedGossip(wl, data, DecentralizedConfig(
        comm_round=cfg.comm_round, epochs=cfg.epochs,
        batch_size=cfg.batch_size, lr=cfg.lr,
        client_optimizer=cfg.client_optimizer, wd=cfg.wd,
        neighbor_num=cfg.neighbor_num,
        frequency_of_the_test=cfg.frequency_of_the_test, seed=cfg.seed),
        mesh=mesh)
    algo.run()
    for h in algo.history:
        sink.log(h, step=h.get("round"))
    return algo.history[-1] if algo.history else {}


@runner("decentralized_online")
def run_decentralized_online(cfg, data, mesh, sink):
    """DSGD / PushSum online learning on streaming UCI data (standalone/
    decentralized main_dol.py surface: --mode --iteration_number --beta
    --b_symmetric --time_varying --topology_neighbors_num_*)."""
    import os
    from fedml_tpu.algorithms.decentralized_online import (
        DecentralizedOnlineConfig, run_decentralized_online as run_dol)
    from fedml_tpu.data.uci import load_streaming_uci, synthetic_stream
    n = min(cfg.client_num_in_total, 128)
    total = cfg.iteration_number * n
    if cfg.data_dir and cfg.dataset.upper() in ("SUSY", "RO"):
        path = cfg.data_dir if os.path.isfile(cfg.data_dir) else os.path.join(
            cfg.data_dir, "SUSY.csv" if cfg.dataset.upper() == "SUSY"
            else "datatraining.txt")
        stream = load_streaming_uci(cfg.dataset, path, list(range(n)),
                                    total, cfg.beta, seed=cfg.seed)
    else:
        stream = synthetic_stream(num_clients=n, total=total,
                                  beta=cfg.beta, seed=cfg.seed)
    out = run_dol(stream, DecentralizedOnlineConfig(
        mode=cfg.mode, iteration_number=cfg.iteration_number,
        epochs=cfg.epochs, learning_rate=cfg.lr, weight_decay=cfg.wd,
        b_symmetric=cfg.b_symmetric,
        topology_neighbors_num_undirected=cfg.topology_neighbors_num_undirected,
        topology_neighbors_num_directed=cfg.topology_neighbors_num_directed,
        time_varying=cfg.time_varying, seed=cfg.seed))
    for h in out["history"][:: max(len(out["history"]) // 50, 1)]:
        sink.log(h, step=h["iteration"])
    return {"final_regret": out["final_regret"],
            "accuracy": out["accuracy"]}


@runner("scaffold")
def run_scaffold(cfg, data, mesh, sink):
    """SCAFFOLD control-variate FL (beyond the reference's list —
    algorithms/scaffold.py)."""
    from fedml_tpu.algorithms.scaffold import Scaffold, ScaffoldConfig
    wl = _make_workload(cfg, data)
    algo = Scaffold(wl, data, ScaffoldConfig(**_fedavg_cfg_kwargs(cfg)),
                    mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("ditto")
def run_ditto(cfg, data, mesh, sink):
    """Ditto personalized FL (beyond the reference's list —
    algorithms/ditto.py): the FedAvg global stream unchanged, plus
    per-client personalized models trained with a λ proximal pull toward
    the globals; history carries personal_{train,test}_acc columns."""
    from fedml_tpu.algorithms.ditto import Ditto, DittoConfig
    wl = _make_workload(cfg, data)
    algo = Ditto(wl, data, DittoConfig(
        ditto_lambda=cfg.ditto_lambda, personal_lr=cfg.personal_lr,
        personal_epochs=cfg.personal_epochs, **_fedavg_cfg_kwargs(cfg)),
        mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("feddyn")
def run_feddyn(cfg, data, mesh, sink):
    """FedDyn dynamic regularization (beyond the reference's list —
    algorithms/feddyn.py): per-client λ corrections make the federated
    fixed point coincide with the centralized optimum under drift."""
    from fedml_tpu.algorithms.feddyn import FedDyn, FedDynConfig
    wl = _make_workload(cfg, data)
    algo = FedDyn(wl, data, FedDynConfig(
        feddyn_alpha=cfg.feddyn_alpha, **_fedavg_cfg_kwargs(cfg)),
        mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("fedac")
def run_fedac(cfg, data, mesh, sink):
    """FedAC accelerated federated SGD (beyond the reference —
    algorithms/fedac.py, arXiv:2006.08950): Nesterov-coupled local steps;
    --fedac_mu derives the paper's (gamma, alpha, beta) coupling."""
    from fedml_tpu.algorithms.fedac import FedAC, FedACConfig
    wl = _make_workload(cfg, data)
    algo = FedAC(wl, data, FedACConfig(
        fedac_mu=cfg.fedac_mu, fedac_gamma=cfg.fedac_gamma,
        fedac_alpha=cfg.fedac_alpha, fedac_beta=cfg.fedac_beta,
        **_fedavg_cfg_kwargs(cfg)), mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("dp_fedavg")
def run_dp_fedavg(cfg, data, mesh, sink):
    """User-level DP FedAvg with a real RDP accountant (beyond the
    reference's unaccounted weak DP, robust_aggregation.py:51-55 —
    algorithms/dp_fedavg.py): clipped uniform mean + central Gaussian
    noise; every eval row reports the (ε, δ) actually spent."""
    from fedml_tpu.algorithms.dp_fedavg import DPFedAvg, DPFedAvgConfig
    wl = _make_workload(cfg, data)
    algo = DPFedAvg(wl, data, DPFedAvgConfig(
        dp_clip=cfg.dp_clip,
        dp_noise_multiplier=cfg.dp_noise_multiplier,
        dp_delta=cfg.dp_delta, dp_accounting=cfg.dp_accounting,
        **_fedavg_cfg_kwargs(cfg)),
        mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


def _pp_workload(cfg, data):
    """--mesh_stages: silo-local GPipe pipeline over the transformer block
    stack (parallel/pipeline.py) — the deployment for silos whose model is
    too deep for one chip.  Same TransformerLM hyperparameters as
    create_workload's dense path; composes with --moe_experts (the Switch
    balance loss rides the schedule's scan carry, pipeline.py)."""
    import jax
    from fedml_tpu.parallel.pipeline import (PipelineLM, make_pp_nwp_workload,
                                             make_stage_mesh)
    if cfg.model != "transformer":
        raise ValueError("--mesh_stages requires --model transformer "
                         "(the stacked-block PipelineLM)")
    shape = sample_shape_of(data)
    if len(shape) != 1:
        raise ValueError(f"--mesh_stages needs a sequence dataset "
                         f"(next-word prediction); got sample shape {shape}")
    n_dev = len(jax.devices())
    if n_dev < cfg.mesh_stages:
        raise ValueError(f"--mesh_stages {cfg.mesh_stages} exceeds the "
                         f"{n_dev} available devices")
    # TransformerLM's dense defaults (experiments/models.py) in stacked
    # form; the block count grows to one-per-stage past the default 2
    plm = PipelineLM(vocab_size=data.class_num, d_model=128, n_heads=4,
                     n_layers=max(2, cfg.mesh_stages), d_ff=512,
                     max_len=2048, moe_experts=cfg.moe_experts)
    mesh = make_stage_mesh(cfg.mesh_stages,
                           devices=jax.devices()[:cfg.mesh_stages])
    n_micro = cfg.pp_microbatches or cfg.mesh_stages
    if cfg.batch_size % n_micro:
        raise ValueError(f"--batch_size {cfg.batch_size} must divide into "
                         f"{n_micro} GPipe microbatches (--pp_microbatches)")
    return make_pp_nwp_workload(plm, mesh, n_micro=n_micro)


def _silo_training_setup(cfg, data, wl, perf=None):
    """Shared silo-side machinery for the sync (cross_silo) and async
    (async_fl) actor modes: the initial global params and the per-silo
    ``train_fn(params, client_idx, round_idx)`` factory.

    The rng chain reproduces FedAvg.run exactly (key(seed) -> init split
    -> one split per round -> per-cohort-slot fold_in) so the message
    choreography lands bit-comparably with the in-jit cohort engine —
    every node derives the chain deterministically from (seed, round).
    The chain advances incrementally (O(R) total, not O(R^2)); a
    backwards query (never happens in a normal run) restarts it."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.trainer.local_sgd import (instrument_train_fn,
                                             make_local_trainer)
    from fedml_tpu.trainer.workload import make_client_optimizer

    jitted = jax.jit(make_local_trainer(
        wl, make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd),
        cfg.epochs))
    if perf is not None:
        # flight recorder: the local trainer jit is a registered hot
        # function — the sentry counts any round that grows its cache,
        # and under --device_obs instrument_jit wraps it so each compile
        # lands in the named compile ledger (wall time + arg signature)
        # and its cost-analysis FLOPs feed the live MFU gauge
        jitted = perf.instrument_jit("train_fn", jitted)
    # instrument_train_fn is the identity when telemetry is disabled;
    # it composes OUTSIDE the device wrapper (both forward _cache_size)
    local = instrument_train_fn(jitted, epochs=cfg.epochs)
    import threading
    _chain = {"next_round": 0,
              "rng": jax.random.split(jax.random.key(cfg.seed))[0]}
    # the chaos CLI mode drives silos on separate THREADS sharing this
    # chain; an unlocked advance would over-step next_round and silently
    # break the (seed, round) determinism contract
    _chain_lock = threading.Lock()

    def _round_rng(round_idx):
        with _chain_lock:
            if round_idx < _chain["next_round"] - 1:
                _chain["next_round"] = 0
                _chain["rng"] = jax.random.split(jax.random.key(cfg.seed))[0]
            if round_idx == _chain["next_round"] - 1:
                return _chain["last"]
            while _chain["next_round"] <= round_idx:
                _chain["rng"], _chain["last"] = \
                    jax.random.split(_chain["rng"])
                _chain["next_round"] += 1
            return _chain["last"]

    def make_train_fn(silo_id, shard_transform=None):
        # shard_transform(shard, client_idx, round_idx) -> shard: the
        # adversary harness's data-poisoning seam (robust/adversary.py
        # backdoor) — the silo genuinely trains on the transformed shard
        def train_fn(params, client_idx, round_idx):
            shard = {k: data.train[k][client_idx]
                     for k in ("x", "y", "mask")}
            if shard_transform is not None:
                shard = shard_transform(shard, client_idx, round_idx)
            shard = {k: jnp.asarray(v) for k, v in shard.items()}
            rng = jax.random.fold_in(_round_rng(round_idx), silo_id - 1)
            new, _ = local(params, shard, rng)
            return new, float(data.train["num_samples"][client_idx])
        return train_fn

    sample = jax.tree.map(lambda v: jnp.asarray(v[0, 0]),
                          {k: data.train[k] for k in ("x", "y", "mask")})
    _, init_rng = jax.random.split(jax.random.key(cfg.seed))
    return wl.init(init_rng, sample), make_train_fn


def _robust_setup(cfg: ExperimentConfig, template, kind: str, sentry=None,
                  device=None):
    """Payload-defense wiring shared by the sync and async actor modes
    (fedml_tpu/robust): the admission pipeline (``--admission`` — 'auto'
    arms it whenever any defense flag is set) and the aggregation
    regime.  Returns ``(admission, defended_aggregate, stream_agg)``:
    ``--agg_mode stack`` yields the jit-once defended aggregate over the
    staged ``[cohort, ...]`` buffer (``defended_aggregate``; None when
    every defense flag is off — the legacy exact weighted mean runs);
    ``--agg_mode stream`` yields a `StreamingAggregator` instead
    (``stream_agg``, ALWAYS set — plain mean streams too; that is the
    O(model)-memory point), and ``defended_aggregate`` stays None.
    ``sentry``: the flight recorder's RecompileSentry — the hot
    aggregation jit registers so a retracing round is counted/failed.
    ``device``: the flight recorder's DeviceRecorder (--device_obs) —
    the hot aggregation jits wrap through its compile-ledger/FLOPs
    instrumentation."""
    if cfg.admission not in ("auto", "on", "off"):
        raise ValueError(f"--admission must be auto|on|off, "
                         f"got {cfg.admission!r}")
    from fedml_tpu.core.stream_agg import STREAM_MODES
    if cfg.agg_mode not in STREAM_MODES:
        raise ValueError(f"--agg_mode must be one of {STREAM_MODES}, "
                         f"got {cfg.agg_mode!r}")
    robust_on = (cfg.robust_agg != "mean" or cfg.norm_clip > 0
                 or cfg.agg_noise_std > 0)
    # 'auto' also arms the screen under payload corruption: a corrupted
    # compressed frame can make the DECODER itself throw, and without
    # admission that exception kills the server event loop mid-run
    # (adversary flags alone do NOT arm it — the undefended-under-attack
    # baseline must stay runnable)
    screen_on = robust_on or cfg.chaos_corrupt > 0
    admission = defended = None
    if cfg.admission == "on" or (cfg.admission == "auto" and screen_on):
        from fedml_tpu.robust import AdmissionPipeline, TrustTracker
        admission = AdmissionPipeline(
            template, kind=kind, max_num_samples=cfg.max_num_samples,
            norm_k=cfg.norm_screen_k, norm_window=cfg.norm_screen_window,
            norm_min_history=cfg.norm_screen_min_history,
            trust=TrustTracker(
                strikes_to_quarantine=cfg.strikes_to_quarantine,
                quarantine_rounds=cfg.quarantine_rounds,
                probation_rounds=cfg.probation_rounds))
    if cfg.agg_mode == "stream":
        from fedml_tpu.core.stream_agg import StreamingAggregator
        stream = StreamingAggregator(
            template, method=cfg.robust_agg, kind=kind,
            norm_clip=cfg.norm_clip, noise_std=cfg.agg_noise_std,
            seed=cfg.seed, reservoir_k=cfg.stream_reservoir,
            trim_frac=cfg.trim_frac, byz_f=cfg.byz_f, krum_m=cfg.krum_m,
            gm_iters=cfg.gm_iters, gm_eps=cfg.gm_eps, sentry=sentry,
            device=device)
        return admission, None, stream
    if robust_on:
        from fedml_tpu.robust import make_defended_aggregate
        defended = make_defended_aggregate(
            cfg.robust_agg, trim_frac=cfg.trim_frac, byz_f=cfg.byz_f,
            krum_m=cfg.krum_m, gm_iters=cfg.gm_iters, gm_eps=cfg.gm_eps,
            norm_clip=cfg.norm_clip, noise_std=cfg.agg_noise_std,
            seed=cfg.seed, sentry=sentry, device=device)
    return admission, defended, None


def _adversary_train_fns(cfg: ExperimentConfig, data, make_train_fn,
                         n_silos: int):
    """Wrap the silo train-fn factory with the ``--adversary`` spec
    (fedml_tpu/robust/adversary.py): listed silos run their seeded attack
    over the real message path; everyone else is untouched."""
    if not cfg.adversary:
        return make_train_fn
    from fedml_tpu.robust import (make_backdoor_shard_transform,
                                  make_malicious_train_fn,
                                  parse_adversary_spec)
    adversaries = parse_adversary_spec(cfg.adversary)
    bad = sorted(s for s in adversaries if s > n_silos)
    if bad:
        raise ValueError(f"--adversary names silos {bad} but the "
                         f"deployment has only {n_silos} silos (ids 1.."
                         f"{n_silos})")

    def wrapped(silo_id):
        atk = adversaries.get(silo_id)
        if atk is None:
            return make_train_fn(silo_id)
        transform = None
        if atk.kind == "backdoor":
            _image_sample_shape(cfg, data,
                                f"--adversary backdoor (silo {silo_id})")
            target = int(atk.param) if atk.param >= 0 else cfg.target_label
            transform = make_backdoor_shard_transform(
                target, trigger_size=cfg.trigger_size,
                poison_frac=cfg.poison_frac, seed=cfg.seed)
        return make_malicious_train_fn(atk, make_train_fn(silo_id,
                                                          transform),
                                       silo_id, seed=cfg.seed)

    return wrapped


@runner("async_fl")
def run_async_fl(cfg, data, mesh, sink):
    """FedBuff-style asynchronous federation (algorithms/async_fl.py):
    no barrier — the server aggregates every --async_goal uploads with
    (1+staleness)^-alpha discounts and immediately re-tasks the consumed
    silos.  --comm_round counts server VERSIONS (aggregations).  Local
    hub deployment (the async protocol is transport-agnostic; the gRPC
    path would reuse the same actors)."""
    from fedml_tpu.algorithms.async_fl import (AsyncFedServerActor,
                                               delta_encoder)
    from fedml_tpu.algorithms.cross_silo import FedAvgClientActor
    from fedml_tpu.comm.local import LocalHub

    if mesh is not None:
        raise ValueError("--mesh_clients does not apply to the async "
                         "actor mode (each silo trains single-chip)")
    if cfg.wire_compression != "none" or cfg.error_feedback:
        raise ValueError(
            "--wire_compression/--error_feedback are not wired into "
            "--algo async_fl yet (the async server consumes raw deltas); "
            "running on would silently send uncompressed uploads")
    if cfg.silo_backend != "local":
        raise ValueError(
            "--algo async_fl currently deploys over the local hub only; "
            f"--silo_backend {cfg.silo_backend!r} would silently be "
            "ignored (the actors are transport-agnostic — the gRPC "
            "wiring mirrors cross_silo's when needed)")
    perf = _make_perf(cfg)
    # async has no serve frontend, but `--slo` must still evaluate: the
    # rolling objectives ride on_version below (gauges + breach counters)
    slo = _make_slo(cfg)
    # async deltas ARE updates: health norms/alignment read them raw
    health = _make_health(cfg, kind="delta")
    wl = _make_workload(cfg, data)
    init, make_train_fn = _silo_training_setup(cfg, data, wl, perf=perf)
    n_silos = min(cfg.client_num_per_round, data.client_num)
    goal = cfg.async_goal or max(1, n_silos // 2)
    make_train_fn = _adversary_train_fns(cfg, data, make_train_fn, n_silos)
    if cfg.edge_aggregators > 0:
        raise ValueError("--edge_aggregators is a cross_silo (sync barrier) "
                         "topology; the async server consumes per-silo "
                         "deltas directly")
    # async uploads are deltas — the admission screen fingerprints them
    # against the params template (same treedef/shapes/dtypes) and
    # screens the raw delta norm
    admission, defended, stream = _robust_setup(
        cfg, init, kind="delta", sentry=perf.sentry if perf else None,
        device=perf.device if perf else None)

    history = []

    def on_version(version, params):
        if slo is not None:
            slo.evaluate()  # rolling: gauges update, breaches count
        if (version % cfg.frequency_of_the_test == 0
                or version == cfg.comm_round):
            stats = _eval_global(wl, params, data)
            stats["version"] = version
            history.append(stats)
            sink.log(stats, step=version)

    # the staleness-aware server-optimizer seam (ISSUE 18): the
    # discounted buffer mean becomes the pseudo-gradient
    server_opt = _make_server_opt(
        cfg, init, sentry=perf.sentry if perf else None,
        device=perf.device if perf else None)

    # version-checkpoint extra state: the trust ledger survives crashes
    # (the sync runner's composition, mirrored)
    trust_extra = None
    if admission is not None:
        trust_extra = (lambda: admission.trust.state_dict(n_silos),
                       admission.trust.load_state_dict)
    srv_opt_extra = None
    if server_opt is not None:
        srv_opt_extra = (server_opt.state_dict, server_opt.load_state_dict)
    # the degrade tracker's async role (ISSUE 19): the observed
    # task→upload latency adapts the re-task watchdog's quiet threshold
    degrade = _degrade_setup(cfg, n_silos, mode="async")
    degrade_extra = None
    if degrade is not None:
        degrade_extra = (degrade.state_dict, degrade.load_state_dict)
    extra_state = _compose_extra_state([("trust", trust_extra),
                                        ("srv_opt", srv_opt_extra),
                                        ("degrade", degrade_extra)])

    # zero-copy pipelined ingest (comm/ingest.py, ISSUE 20): one fold
    # worker consumes the buffer-fold queue in arrival order.  No decode
    # arena here — async uploads are DELTAS screened against the delta
    # template, and the staleness-discounted buffer path keeps the host
    # decode (the arena rides the sync paths); what pipelining buys is
    # decode+screen+fold off the transport thread.
    ingest = None
    if cfg.ingest_pipeline:
        from fedml_tpu.comm.ingest import IngestPipeline
        ingest = IngestPipeline(
            num_shards=1, depth=cfg.ingest_queue_depth,
            fault_feed=((lambda reason, detail:
                         degrade.note_dead_letter(reason))
                        if degrade is not None else None))

    hub = LocalHub(codec_roundtrip=True)  # exercise the wire codec
    server = AsyncFedServerActor(
        hub.transport(0), init, data.client_num, n_silos,
        num_versions=cfg.comm_round, aggregation_goal=goal,
        staleness_exponent=cfg.staleness_exponent,
        server_lr=cfg.async_server_lr, on_version=on_version,
        seed=cfg.seed, checkpointer=_make_checkpointer(cfg),
        retask_timeout_s=cfg.retask_timeout_s or None,
        admission=admission, defended_aggregate=defended,
        stream_agg=stream, perf=perf, health=health,
        extra_state=extra_state, journal=_make_journal(cfg),
        server_opt=server_opt, degrade=degrade, ingest=ingest)
    server.register_handlers()
    silos = [FedAvgClientActor(i, hub.transport(i), make_train_fn(i),
                               encode_upload=delta_encoder)
             for i in range(1, n_silos + 1)]
    for s in silos:
        s.register_handlers()
    try:
        server.start()
        hub.pump(idle_hook=(ingest.drain if ingest is not None else None))
    finally:
        if perf is not None:
            perf.close()  # join the RSS sampler thread
    out = dict(history[-1]) if history else {}
    if server.staleness_seen:
        out["mean_staleness"] = float(np.mean(server.staleness_seen))
    return out


@runner("cross_silo")
def run_cross_silo(cfg, data, mesh, sink):
    """Distributed FedAvg over the host-edge actor/transport layer — the
    reference's ``mpirun -np N+1 main_fedavg.py`` deployment
    (run_fedavg_distributed_pytorch.sh:17-21).

    ``--silo_backend local`` runs server + N silo actors in-process over the
    deterministic hub (the reference's localhost-MPI CI analog);
    ``--silo_backend grpc`` runs THIS process as ``--node_id`` k (0=server,
    1..N=silos) with peers from ``--ip_config`` (the reference's
    grpc_ipconfig.csv format, ip_config_utils.py:4-14) at
    ``--base_port``+rank.  Each silo trains its sampled client's shard with
    a jit'd local-SGD program; only aggregation rides messages.
    """
    import jax
    from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                                 FedAvgServerActor)

    if mesh is not None:
        raise ValueError("--mesh_clients does not apply to the cross-silo "
                         "actor mode (each silo trains single-chip); drop "
                         "the flag or use --algo fedavg for on-pod sharding")

    perf = _make_perf(cfg)
    # built once per run, evaluated EVERY round below — not only behind
    # the serve frontend, so `--slo` without --serve_port still exports
    # the fedml_slo_* gauges and ticks breach counters instead of
    # silently never evaluating the configured objectives
    slo = _make_slo(cfg)
    # the privacy↔observability trade, stated in the ledger: under flat
    # (--secagg pairwise) masking the root sees only ciphertext, so the
    # payload-derived health stats are SUPPRESSED BY NAME; under grouped
    # masking the root receives plaintext edge MEANS and its block-level
    # stats keep working (the edges' own accumulators are the suppressed
    # ones)
    health = _make_health(
        cfg, kind="params",
        suppress_payload=("secagg_pairwise_masking"
                          if cfg.secagg == "pairwise" else None))
    wl = (_pp_workload(cfg, data) if cfg.mesh_stages > 0
          else _make_workload(cfg, data))
    init, make_train_fn = _silo_training_setup(cfg, data, wl, perf=perf)
    n_silos = min(cfg.client_num_per_round, data.client_num)
    timeout = cfg.round_timeout_s or None
    make_train_fn = _adversary_train_fns(cfg, data, make_train_fn, n_silos)
    shard_spine = None
    if cfg.model_shards > 0:
        # sharded global-model spine (fedml_tpu/shard_spine): the
        # spine's ShardAdmission + ShardedStreamingAggregator replace
        # the whole-model screen and fold wholesale — per-shard wire
        # slices, per-shard fold state, per-shard defended finalize
        from fedml_tpu.robust import TrustTracker
        from fedml_tpu.shard_spine import build_shard_spine
        admission = defended = None
        shard_spine = build_shard_spine(
            init, num_shards=cfg.model_shards,
            norm_clip=cfg.norm_clip, noise_std=cfg.agg_noise_std,
            seed=cfg.seed, fused=cfg.fused_finalize,
            max_num_samples=cfg.max_num_samples,
            norm_k=cfg.norm_screen_k,
            norm_window=cfg.norm_screen_window,
            norm_min_history=cfg.norm_screen_min_history,
            trust=TrustTracker(
                strikes_to_quarantine=cfg.strikes_to_quarantine,
                quarantine_rounds=cfg.quarantine_rounds,
                probation_rounds=cfg.probation_rounds),
            sentry=perf.sentry if perf else None,
            device=perf.device if perf else None)
        stream = shard_spine.agg
    else:
        admission, defended, stream = _robust_setup(
            cfg, init, kind="params",
            sentry=perf.sentry if perf else None,
            device=perf.device if perf else None)

    # live secure aggregation (secure/protocol.py, --secagg): masked
    # uploads over the real transport.  pairwise = the whole cohort is
    # one masking group served by the ROOT's SecAggServer; grouped =
    # masking scoped per edge block (each edge runs the protocol for its
    # silos and ships a plaintext partial mean to an UNMODIFIED root).
    secagg_root = None
    make_edge_secagg = None
    make_silo_secagg = lambda g: None  # noqa: E731
    if cfg.secagg != "off":
        from fedml_tpu.robust import AdmissionPipeline, TrustTracker
        from fedml_tpu.secure.protocol import (SecAggClient, SecAggServer,
                                               masked_template)
        # the weight normalizer every silo and server must agree on:
        # each silo masks n_i/weight_cap <= 1 so the ring budget holds;
        # the normalizer cancels in the recovered sum/weight ratio
        weight_cap = float(np.max(data.train["num_samples"]))
        host_init = jax.tree.map(np.asarray, init)

        def _masked_admission():
            # the PRE-mask-removal screens: structural fingerprint vs
            # the MASKED template + num_samples validation.  Norm
            # screening moves to the post-unmask sum (the protocol's
            # SumNormScreen) — a ciphertext norm is PRG noise.
            return AdmissionPipeline(
                masked_template(host_init), kind="masked",
                max_num_samples=cfg.max_num_samples,
                trust=TrustTracker(
                    strikes_to_quarantine=cfg.strikes_to_quarantine,
                    quarantine_rounds=cfg.quarantine_rounds,
                    probation_rounds=cfg.probation_rounds))

        def _secagg_server(node, noise_std):
            return SecAggServer(
                threshold=cfg.secagg_threshold, clip=cfg.secagg_clip,
                weight_cap=weight_cap, norm_clip=cfg.norm_clip,
                noise_std=noise_std, seed=cfg.seed,
                norm_screen_k=cfg.norm_screen_k,
                norm_screen_window=cfg.norm_screen_window,
                norm_screen_min_history=cfg.norm_screen_min_history,
                node=node)

        make_silo_secagg = lambda g: SecAggClient(g)  # noqa: E731
        if cfg.secagg == "pairwise":
            secagg_root = _secagg_server("server", cfg.agg_noise_std)
            admission = (_masked_admission()
                         if cfg.admission != "off" else None)
            defended = stream = None  # the ring fold replaces both
        else:
            # grouped: edges mask, the root stays plaintext.  The DP
            # noise is injected ONCE, by the root's streaming finalize
            # over the edge means — an edge-side injection would add
            # E+1 draws and make grouped runs systematically noisier
            # than flat ones (the plaintext edge topology's convention,
            # mirrored: edges clip, the root alone adds noise)
            make_edge_secagg = lambda node: _secagg_server(  # noqa: E731
                node, 0.0)

    # multi-level aggregator topology (--edge_aggregators E): E edge
    # actors sit between the silos and the root, each folding its block
    # of silos' uploads at arrival and shipping ONE pre-reduced
    # (mean, weight, count) update per round — the root is this same
    # FedAvgServerActor whose "silos" are the edges
    n_edges = cfg.edge_aggregators
    if n_edges > 0:
        if cfg.silo_backend != "local":
            raise ValueError("--edge_aggregators deploys over the local "
                             "hub only for now (the actors are transport-"
                             "agnostic; gRPC wiring mirrors the flat one)")
        if not 1 <= n_edges <= n_silos:
            raise ValueError(f"--edge_aggregators {n_edges} must be in "
                             f"1..{n_silos} (every edge needs a silo)")
        if cfg.wire_compression != "none" or cfg.error_feedback:
            raise ValueError("--wire_compression/--error_feedback are not "
                             "wired through the edge tier (the root would "
                             "try to decompress an edge's raw mean)")
        if cfg.dead_after_s > 0:
            raise ValueError("--dead_after_s: silo heartbeats terminate at "
                             "their edge; the root failure detector would "
                             "declare every edge dead")
        if admission is not None and admission.max_num_samples > 0:
            # the per-UPLOAD sample cap screens silo claims at the edge
            # tier; the root sees pre-reduced edges whose num_samples is
            # the SUM over their block — scale the root's cap by the
            # largest block so an honest edge is never struck as weight
            # inflation (the edge pipelines below keep the per-silo cap)
            admission.max_num_samples *= -(-n_silos // n_edges)

    # optional lossy upload compression (comm/compress.py): silos send the
    # compressed DELTA to the global model; the server reconstructs.  The
    # down-link broadcast stays exact.
    encode = decode = ef_extra = None
    wire_stats = {"bytes": 0}
    if cfg.wire_compression != "none":
        # host-side numpy throughout — compression is a wire-boundary op
        # and must not bounce the model through the accelerator
        from fedml_tpu.comm.compress import (compress_update,
                                             decompress_update, wire_bytes)

        # error feedback (Seide'14 / Karimireddy'19): the part of the delta
        # the compressor dropped is kept silo-side and added to the NEXT
        # round's delta, so small topk fractions stop systematically losing
        # the same small coordinates.  Residual settlement is DEFERRED
        # until the server's accepted-silos ack arrives with the next sync
        # (ErrorFeedback.resolve via on_accepted): a dropped upload
        # (straggler policy) carries its FULL delta forward instead of
        # losing the sent part.  State is per-silo — fine for persistent
        # silo processes, intentionally beyond the reference's
        # stateless-client contract (flag-gated).
        from fedml_tpu.comm.compress import ErrorFeedback
        _ef = ErrorFeedback()
        if cfg.error_feedback and cfg.silo_backend == "local":
            # EF residuals are silo-side cross-round state; fold them into
            # the server's round checkpoint (fixed-shape template, so it
            # doubles as the orbax restore skeleton).  LOCAL backend only:
            # one process holds every silo's EF there.  A gRPC server
            # never sees silo residuals — checkpointing its own (empty)
            # EF would bloat every checkpoint with model-sized zero trees
            # while restoring nothing; distributed silos keep their own
            # state and are expected to stay alive across server crashes.
            _ef_template = jax.tree.map(
                lambda v: np.zeros_like(np.asarray(v)), init)
            _ef_silos = tuple(range(1, n_silos + 1))
            ef_extra = (lambda: _ef.state_dict(_ef_silos, _ef_template),
                        _ef.load_state_dict)

        # bandwidth observability (the obs report's "bytes saved per
        # round"): compressed-vs-raw bytes of every accepted upload, plus
        # the per-upload compression ratio (handles cached here — null
        # no-ops when telemetry is disabled)
        from fedml_tpu.obs import telemetry as _tel
        _reg = _tel.get_registry()
        _c_comp = _reg.counter("fedml_comm_compressed_bytes_total")
        _c_raw = _reg.counter("fedml_comm_raw_bytes_total")
        _h_ratio = _reg.histogram(
            "fedml_comm_compression_ratio_total",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0))

        def encode(new_params, global_params, _silo=None):
            from fedml_tpu.algorithms.async_fl import delta_encoder
            delta = delta_encoder(new_params, global_params)
            if cfg.error_feedback:
                delta = _ef.apply(_silo, delta)
            payload = compress_update(delta, cfg.wire_compression,
                                      cfg.topk_frac)
            if cfg.error_feedback:
                _ef.record(_silo, delta, decompress_update(payload, delta))
            return payload

        _decode_cache = {"ref": None, "host": None}

        def decode(payload, global_params):
            # one host copy of the globals per round, not one per silo
            # (cache keyed by object identity; holding "ref" prevents id
            # reuse of a collected params tree)
            if _decode_cache["ref"] is not global_params:
                _decode_cache["host"] = jax.tree.map(np.asarray,
                                                     global_params)
                _decode_cache["ref"] = global_params
            host_global = _decode_cache["host"]
            compressed = wire_bytes(payload)
            wire_stats["bytes"] += compressed
            delta = decompress_update(payload, host_global)
            raw = wire_bytes(delta)
            _c_comp.inc(compressed)
            _c_raw.inc(raw)
            if raw:
                _h_ratio.observe(compressed / raw)
            return jax.tree.map(np.add, host_global, delta)

    def make_encode(silo_id):
        if encode is None:
            return None
        return lambda new, g: encode(new, g, _silo=silo_id)

    def make_on_accepted(silo_id):
        if encode is None or not cfg.error_feedback:
            return None
        return lambda accepted: _ef.resolve(silo_id, accepted)

    history = []

    def on_round_done(r, params):
        if slo is not None:
            slo.evaluate()  # rolling: gauges update, breaches count
        if r % cfg.frequency_of_the_test == 0 or r == cfg.comm_round - 1:
            stats = _eval_global(wl, params, data)
            stats["round"] = r
            if cfg.wire_compression != "none":
                # compressed bytes received since the last eval round
                stats["upload_bytes"] = wire_stats["bytes"]
                wire_stats["bytes"] = 0
            history.append(stats)
            sink.log(stats, step=r)

    detector = None
    if cfg.dead_after_s > 0:
        from fedml_tpu.algorithms.cross_silo import FailureDetector
        detector = FailureDetector(
            suspect_after_s=cfg.suspect_after_s or cfg.dead_after_s / 2,
            dead_after_s=cfg.dead_after_s)

    # serve-while-train (fedml_tpu/serve): the server node publishes each
    # round's global into a hot-swap registry behind an HTTP frontend, so
    # the federation serves its own model live.  A gRPC SILO process never
    # serves — only rank 0 holds the global.
    frontend = publish = release = None
    if cfg.serve_port > 0 and (cfg.silo_backend == "local"
                               or cfg.node_id == 0):
        from fedml_tpu.serve import (MicroBatcher, ModelRegistry,
                                     ServeFrontend, ServeWorkerPool)
        predict = jax.jit(lambda p, x: wl.apply(p, x))
        registry = ModelRegistry(predict)
        buckets = tuple(int(b) for b in cfg.serve_buckets.split(","))
        batcher_kw = dict(
            buckets=buckets,
            max_delay_s=cfg.serve_batch_delay_ms / 1e3,
            queue_depth=cfg.serve_queue_depth,
            default_deadline_s=cfg.serve_deadline_ms / 1e3,
            best_effort_headroom=cfg.serve_best_effort_headroom)
        shadow = None
        if cfg.release_gate:
            # the shadow tap rides every worker's batcher (one shared
            # sampler), so the gate replays real admitted traffic
            from fedml_tpu.serve import ReleaseController, ShadowSampler
            shadow = ShadowSampler(every=cfg.release_shadow_every,
                                   slots=cfg.release_shadow_slots)
            batcher_kw["shadow"] = shadow
        # deep health check: /healthz?deep=1 evaluates the rolling SLOs
        # (round p95, shed rate, worst-worker queue fill, torn frames,
        # quarantines) and answers 503 on breach so an LB can rotate out
        # a violating instance.  The same evaluator backs tiered
        # admission (TierGate): best-effort sheds exactly while deep
        # health would answer 503.
        if cfg.serve_workers > 1:
            frontend = ServeWorkerPool(
                registry, port=cfg.serve_port,
                workers=cfg.serve_workers, slo=slo, health=health,
                **batcher_kw).start()
        else:
            batcher = MicroBatcher(registry, slo=slo, **batcher_kw)
            frontend = ServeFrontend(registry, batcher,
                                     port=cfg.serve_port,
                                     slo=slo, health=health).start()
        if cfg.release_gate:
            import os as _os
            release = ReleaseController(
                registry, shadow=shadow, health=health,
                eval_fn=_release_eval_fn(wl, data),
                divergence_budget=cfg.release_divergence_budget,
                eval_tolerance=cfg.release_eval_tolerance,
                cooldown_s=cfg.release_cooldown_s,
                backoff=cfg.release_backoff,
                max_cooldown_s=cfg.release_max_cooldown_s,
                journal_path=_os.path.join(
                    cfg.metrics_dir or cfg.run_dir or ".",
                    "release.jsonl"))
        _sample_x = np.asarray(data.train["x"][0, 0, 0])
        _warmed = []

        def publish(params, version):
            if release is not None:
                # the gated path: canary → shadow/health/eval verdict →
                # promote or rollback.  The cross-silo hook's version IS
                # the producing round, which keys the health signal.
                release.offer(params, version, round_idx=version)
            else:
                registry.publish(params, version)
            if registry.current() is None:
                return  # first offer rolled back: nothing to warm yet
            if not _warmed:
                _warmed.append(True)
                # compile every bucket off the round path: without this
                # the FIRST request per bucket size pays the jit compile
                # inside its own deadline and is shed 429 from an
                # otherwise idle server.  The pool warms every worker's
                # batcher (all share one jit cache through predict).
                import threading as _th
                _warm_target = (frontend.warmup
                                if cfg.serve_workers > 1
                                else batcher.warmup)
                _th.Thread(target=lambda: _warm_target(_sample_x),
                           daemon=True, name="serve-warmup").start()

    # the server-optimizer seam + adaptive controller (ISSUE 18): the
    # optimizer's O(model) state shards along the spine's plan when one
    # exists, and both ride the round checkpoint by name below
    server_opt = _make_server_opt(
        cfg, init,
        plan=shard_spine.plan if shard_spine is not None else None,
        sentry=perf.sentry if perf else None,
        device=perf.device if perf else None)
    controller = _make_controller(
        cfg, cohort=(n_edges if n_edges > 0 else n_silos),
        epochs=cfg.epochs)
    # the sustained-degradation spine (ISSUE 19): per-silo reliability
    # tracking drives the adaptive deadline, the quorum-aware close, and
    # network-vs-payload fault attribution; under the edge topology the
    # root's cohort IS the edge tier, so the tracker sizes to it
    degrade = _degrade_setup(cfg, n_edges if n_edges > 0 else n_silos)

    # round-checkpoint extra state, composed by name: silo-side EF
    # residuals (PR 3) + the admission trust ledger (ISSUE 12 — a
    # resumed server must keep strikes, quarantine sentences, and
    # probation clocks, or every crash releases jailed attackers early)
    trust_extra = None
    if admission is not None:
        n_trust = n_edges if n_edges > 0 else n_silos
        trust_extra = (lambda: admission.trust.state_dict(n_trust),
                       admission.trust.load_state_dict)
    elif shard_spine is not None and shard_spine.admission is not None:
        # the sharded spine's trust ledger is just as durable as the
        # flat one — strikes, quarantine sentences, probation clocks
        # all survive a crash (ISSUE 12's contract, unchanged)
        _sh_trust = shard_spine.admission.trust
        trust_extra = (lambda: _sh_trust.state_dict(n_silos),
                       _sh_trust.load_state_dict)
    shard_extra = None
    if shard_spine is not None:
        # the shard LAYOUT is checkpointed state: a resume re-derives
        # the plan and VERIFIES the fingerprint instead of silently
        # restoring sharded fold state into a different layout
        shard_extra = (shard_spine.checkpoint_state,
                       shard_spine.restore_checkpoint_state)
    srv_opt_extra = adapt_extra = None
    if server_opt is not None:
        # bit-exact optimizer-state roundtrip; a restore under a
        # different --server_opt (or shard plan) refuses loudly
        # (ServerOptMismatchError — the PR 14 mode-mismatch mirror)
        srv_opt_extra = (server_opt.state_dict, server_opt.load_state_dict)
    if controller is not None:
        adapt_extra = (controller.state_dict, controller.load_state_dict)
    degrade_extra = None
    if degrade is not None:
        # the reliability history rides the round checkpoint: a resumed
        # server re-derives the SAME adaptive deadline and quorum
        # verdict the crashed process would have (ISSUE 19 determinism)
        degrade_extra = (degrade.state_dict, degrade.load_state_dict)
    extra_state = _compose_extra_state([("ef", ef_extra),
                                        ("trust", trust_extra),
                                        ("shard", shard_extra),
                                        ("srv_opt", srv_opt_extra),
                                        ("adapt", adapt_extra),
                                        ("degrade", degrade_extra)])
    journal = _make_journal(cfg)

    # zero-copy pipelined ingest (comm/ingest.py, ISSUE 20): the
    # transport thread only checks guards and enqueues; one fold worker
    # per shard runs decode -> screen -> fold in arrival order.  Queue
    # overflow dead-letters through the degrade tracker's fault feed as
    # NETWORK evidence (the resilient-transport convention) — never a
    # trust strike, never silent.
    ingest = None
    if cfg.ingest_pipeline:
        from fedml_tpu.comm.ingest import IngestArena, IngestPipeline
        ingest = IngestPipeline(
            num_shards=(shard_spine.num_shards
                        if shard_spine is not None else 1),
            depth=cfg.ingest_queue_depth,
            fault_feed=((lambda reason, detail:
                         degrade.note_dead_letter(reason))
                        if degrade is not None else None))
        if cfg.secagg == "off":
            # pre-pinned decode arenas, one per shard, templated on the
            # exact slice layout the wire ships: a frame's float payload
            # lands via ONE device_put into the flat arena, and the
            # fused finite+sumsq screen replaces the per-upload host
            # norm pass.  Masked (secagg) uploads keep the host decode —
            # a ciphertext norm is PRG noise — but the ring fold still
            # runs on the worker.
            if shard_spine is not None:
                arenas = [IngestArena(sl, name=f"ingest_s{s}", perf=perf)
                          for s, sl in enumerate(
                              shard_spine.broadcast_slices(init))]
            else:
                arenas = [IngestArena(init, perf=perf)]
            ingest.attach_arenas(arenas)

    def make_server(transport):
        # under the edge topology the root's cohort IS the edge tier:
        # straggler policy, admission, trust, and both agg modes apply
        # per edge unchanged
        s = FedAvgServerActor(
            transport, init, data.client_num,
            n_edges if n_edges > 0 else n_silos, cfg.comm_round,
            on_round_done=on_round_done,
            straggler_policy=cfg.straggler_policy,
            round_timeout_s=timeout, min_silo_frac=cfg.min_silo_frac,
            decode_upload=decode, failure_detector=detector,
            checkpointer=_make_checkpointer(cfg),
            publish=publish, extra_state=extra_state,
            admission=admission, aggregate_fn=defended,
            stream_agg=stream, perf=perf, health=health,
            secagg=secagg_root, journal=journal,
            shard_wire=shard_spine,
            server_opt=server_opt, controller=controller,
            degrade=degrade, ingest=ingest)
        s.register_handlers()
        return s

    chaos_on = any((cfg.chaos_drop, cfg.chaos_delay, cfg.chaos_dup,
                    cfg.chaos_reorder, cfg.chaos_corrupt))
    if chaos_on and cfg.silo_backend != "local":
        raise ValueError("--chaos_* injection wraps the local hub only; "
                         "for real wires compose ChaosTransport in code")
    try:
        if cfg.silo_backend == "local":
            import threading
            from fedml_tpu.comm.local import LocalHub
            hub = LocalHub(codec_roundtrip=True)  # exercise the wire codec
            wrap = lambda t: t  # noqa: E731
            if chaos_on:
                from fedml_tpu.algorithms.cross_silo import MsgType
                from fedml_tpu.comm.chaos import (ChaosPlan, ChaosTransport,
                                                  LinkChaos)
                if cfg.chaos_drop > 0 and (cfg.straggler_policy == "wait"
                                           or not timeout):
                    raise ValueError(
                        "--chaos_drop with the strict 'wait' barrier (or no "
                        "--round_timeout_s) would wedge the federation on "
                        "the first lost upload; use --straggler_policy drop "
                        "--round_timeout_s T")
                plan = ChaosPlan(
                    seed=cfg.chaos_seed,
                    default=LinkChaos(drop_prob=cfg.chaos_drop,
                                      delay_prob=cfg.chaos_delay,
                                      max_delay_s=cfg.chaos_max_delay_s,
                                      dup_prob=cfg.chaos_dup,
                                      reorder_prob=cfg.chaos_reorder,
                                      corrupt_prob=cfg.chaos_corrupt),
                    # FINISH: shutdown liveness.  ROUND_TIMEOUT: the
                    # straggler timer's SELF-message rides the server's own
                    # chaotic transport on link (0,0) — dropping it disarms
                    # the only re-arm path and wedges the round
                    immune_types=(MsgType.S2C_FINISH, MsgType.ROUND_TIMEOUT))
                wrap = lambda t: ChaosTransport(t, plan)  # noqa: E731
            server = make_server(wrap(hub.transport(0)))
            # hub address plan: root 0; edges 1..E (the root's "silos");
            # flat silos at E+g, where g is the 1-based GLOBAL cohort
            # slot that seeds the silo's rng stream and client assignment
            # — a silo trains identically under any topology
            edges, edge_of = [], {}
            if n_edges > 0:
                from fedml_tpu.algorithms.hierarchical import (
                    EdgeAggregatorActor)
                from fedml_tpu.core.stream_agg import StreamingAggregator
                blocks = np.array_split(np.arange(1, n_silos + 1), n_edges)
                for e, block in enumerate(blocks, start=1):
                    edge_admission = None
                    if make_edge_secagg is not None:
                        # grouped masking: the edge screens CIPHERTEXT
                        # (masked-template fingerprint + num_samples,
                        # pre-mask-removal) with its own trust ledger
                        if cfg.admission != "off":
                            edge_admission = _masked_admission()
                    elif admission is not None:
                        # each edge screens ITS silos with its own
                        # pipeline/trust ledger (PR 4 composes per-upload
                        # at the edge; the root's screen then sees the
                        # edge means)
                        from fedml_tpu.robust import (AdmissionPipeline,
                                                      TrustTracker)
                        edge_admission = AdmissionPipeline(
                            init, kind="params",
                            max_num_samples=cfg.max_num_samples,
                            norm_k=cfg.norm_screen_k,
                            norm_window=cfg.norm_screen_window,
                            norm_min_history=cfg.norm_screen_min_history,
                            trust=TrustTracker(
                                strikes_to_quarantine=(
                                    cfg.strikes_to_quarantine),
                                quarantine_rounds=cfg.quarantine_rounds,
                                probation_rounds=cfg.probation_rounds))
                    edge_health = None
                    if health is not None:
                        # per-edge statistics-only accumulator: the edge
                        # ships its compact rollup inside its per-round
                        # frame; the root's accumulator owns the
                        # gauges, alarms, and the ledger.  Under grouped
                        # masking the edge sees only ciphertext, so its
                        # payload stats are suppressed BY NAME.
                        from fedml_tpu.obs import HealthAccumulator
                        edge_health = HealthAccumulator(
                            kind="params", node=f"edge{e}", alarms=False,
                            suppress_payload=(
                                "secagg_grouped_masking"
                                if make_edge_secagg is not None else None))
                    # edge folds are plain clipped means — the robust
                    # rule and the DP noise run ONCE, at the root, over
                    # the edge means.  Under grouped masking the edge
                    # instead runs the secure protocol for its block
                    # (ring fold + unmask) and ships the plaintext
                    # PARTIAL MEAN in the same one-frame-per-round format.
                    edges.append(EdgeAggregatorActor(
                        e, wrap(hub.transport(e)),
                        {n_edges + int(g): int(g) for g in block},
                        cohort_total=n_silos,
                        client_num_in_total=data.client_num,
                        stream_agg=(None if make_edge_secagg is not None
                                    else StreamingAggregator(
                                        init, method="mean", kind="params",
                                        norm_clip=cfg.norm_clip,
                                        seed=cfg.seed)),
                        admission=edge_admission,
                        health=edge_health,
                        secagg=(make_edge_secagg(f"edge{e}")
                                if make_edge_secagg is not None else None),
                        journal=_make_journal(cfg, subdir=f"edge{e}"),
                        # the edge must flush its partial fold BEFORE
                        # the root's round timer fires, or an on-time
                        # block is discarded with its one straggler —
                        # half the root timeout leaves the flush margin.
                        # A MASKED edge runs up to three timed stages
                        # (agreement / upload / unmask), so its per-stage
                        # margin is a quarter: two stage timeouts still
                        # land inside the root's window
                        timeout_s=((timeout / 4
                                    if make_edge_secagg is not None
                                    else timeout / 2)
                                   if timeout else None)))
                    for g in block:
                        edge_of[int(g)] = e
            silos = [FedAvgClientActor(
                         n_edges + g, wrap(hub.transport(n_edges + g)),
                         make_train_fn(g),
                         encode_upload=make_encode(g),
                         on_accepted=make_on_accepted(g),
                         heartbeat_interval_s=(cfg.heartbeat_s or None)
                         if chaos_on else None,
                         server_id=edge_of.get(g, 0),
                         # masking identity = the TRANSPORT id (the group
                         # lists in sync frames are transport ids)
                         secagg=make_silo_secagg(n_edges + g))
                     for g in range(1, n_silos + 1)]
            if not chaos_on:
                for a in edges + silos:
                    a.register_handlers()
                for e_actor in edges:
                    # mid-round recovery for a journaled edge: a restart
                    # that left an edge's block mid-flight restores the
                    # durable fold and re-syncs only the missing silos
                    # (no-op without a journal or an open round)
                    e_actor.resume()
                server.start()
                # idle_hook: when every inbox is empty the pump drains
                # queued ingest folds; a truthy processed count means the
                # drain may have enqueued broadcasts, so pumping resumes
                hub.pump(idle_hook=(ingest.drain if ingest is not None
                                    else None))
                return history[-1] if history else {}
            # chaos delivers delayed/reordered frames on wall-clock timers,
            # which the synchronous pump cannot wait for — drive each actor
            # on its own thread like a real deployment
            threads = [threading.Thread(target=a.run, daemon=True,
                                        name=f"node-{a.node_id}")
                       for a in edges + silos]
            for th in threads:
                th.start()
            for e_actor in edges:
                e_actor.resume()
            server.start()
            server.transport.run()  # blocks until the final round's FINISH
            for th in threads:
                th.join(timeout=10)
            return history[-1] if history else {}
        if cfg.silo_backend == "grpc":
            from fedml_tpu.comm.grpc_transport import (GrpcTransport,
                                                       load_ip_table)
            table = (load_ip_table(cfg.ip_config) if cfg.ip_config
                     else {i: "127.0.0.1" for i in range(n_silos + 1)})
            transport = GrpcTransport(cfg.node_id, table,
                                      base_port=cfg.base_port,
                                      max_message_mb=cfg.grpc_max_message_mb,
                                      idle_timeout_s=cfg.silo_idle_timeout_s,
                                      workers=cfg.grpc_workers)
            if cfg.silo_retries > 0:
                # production posture: retried, backed-off, dead-lettered
                # sends with channel re-dial between attempts
                # (comm/resilient.py)
                from fedml_tpu.comm.resilient import (ResilientTransport,
                                                      RetryPolicy)
                transport = ResilientTransport(
                    transport, RetryPolicy(max_attempts=cfg.silo_retries),
                    seed=cfg.seed,
                    # the server's dead letters are NETWORK evidence for
                    # the degrade tracker's partition discrimination —
                    # routed by reason, never a trust strike
                    fault_feed=(
                        (lambda reason, msg:
                         degrade.note_dead_letter(reason))
                        if degrade is not None and cfg.node_id == 0
                        else None))
            if cfg.node_id == 0:
                server = make_server(transport)
                server.start()
                transport.run()   # blocks until the final round's FINISH
                return history[-1] if history else {}
            silo = FedAvgClientActor(
                cfg.node_id, transport, make_train_fn(cfg.node_id),
                encode_upload=make_encode(cfg.node_id),
                on_accepted=make_on_accepted(cfg.node_id),
                heartbeat_interval_s=cfg.heartbeat_s or None)
            # run() (not bare transport.run()) so the heartbeat thread
            # starts
            silo.run()
            return {}
        raise ValueError(f"unknown silo_backend {cfg.silo_backend!r}; "
                         f"available: ('local', 'grpc')")
    finally:
        if perf is not None:
            perf.close()  # join the RSS sampler thread
        if frontend is not None:
            # drain-on-shutdown: queued requests still answer, then the
            # listener closes — training's end never drops live traffic
            frontend.stop(drain=True)


@runner("cross_device")
def run_cross_device(cfg, data, mesh, sink):
    """Mega-cohort cross-device federation (algorithms/cross_device.py):
    the seeded sampler picks 1k-100k clients, static device-sized waves
    each train as ONE compiled program (vmap single-chip, shard_map over
    the --mesh_clients ``clients`` axis), and every wave's stacked
    updates fold device-side into the PR 7 streaming spine at wave
    completion — O(model) server memory at any cohort size, with the
    per-wave admission screens and the perf/health/device observatories
    riding the loop."""
    from fedml_tpu.algorithms.cross_device import (CrossDevice,
                                                   CrossDeviceConfig)
    perf = _make_perf(cfg)
    slo = _make_slo(cfg)
    # wave summaries are params-like trees: health norms/alignment read
    # them against the round's global exactly like cross-silo uploads
    health = _make_health(cfg, kind="params")
    wl = _make_workload(cfg, data)
    server_opt = controller = None
    if cfg.server_opt != "plain" or cfg.adaptive:
        import jax
        # the optimizer template must BE the run's initial global
        # (fedac's coupled x sequence starts at it): reproduce run()'s
        # exact rng chain — same seed, same split, same init
        _, _init_rng = jax.random.split(jax.random.key(cfg.seed))
        _tmpl = wl.init(_init_rng, jax.tree.map(
            lambda v: v[0, 0],
            {k: data.train[k] for k in ("x", "y", "mask")}))
        server_opt = _make_server_opt(
            cfg, _tmpl, sentry=perf.sentry if perf else None,
            device=perf.device if perf else None)
        # cross_device's cohort lever is LIVE: the sampler draws from
        # the full population, so the ceiling is the population itself
        controller = _make_controller(
            cfg, cohort=cfg.client_num_per_round, epochs=cfg.epochs,
            wave_size=cfg.wave_size, max_cohort=data.client_num)
    # zero-copy pipelined ingest (ISSUE 20): the wave loop's pipelining
    # — the main thread keeps launching waves while the fold worker
    # runs admission/fold/health for completed ones.  submit_wait means
    # overflow cannot happen (backpressure paces wave launches), so no
    # fault feed is wired.
    ingest = None
    if cfg.ingest_pipeline:
        from fedml_tpu.comm.ingest import IngestPipeline
        ingest = IngestPipeline(num_shards=1,
                                depth=cfg.ingest_queue_depth)
    algo = CrossDevice(
        wl, data, CrossDeviceConfig(
            wave_size=cfg.wave_size, local_alg=cfg.local_alg,
            sampler=cfg.sampler, mu=cfg.mu, norm_clip=cfg.norm_clip,
            agg_noise_std=cfg.agg_noise_std, admission=cfg.admission,
            norm_screen_k=cfg.norm_screen_k,
            norm_screen_window=cfg.norm_screen_window,
            norm_screen_min_history=cfg.norm_screen_min_history,
            wave_adversary=cfg.wave_adversary,
            **_fedavg_cfg_kwargs(cfg)),
        mesh=mesh, sink=sink, perf=perf, health=health, slo=slo,
        server_opt=server_opt, controller=controller, ingest=ingest)
    try:
        algo.run(checkpointer=_make_checkpointer(cfg))
    finally:
        if perf is not None:
            perf.close()  # join the RSS sampler thread
    return algo.history[-1] if algo.history else {}


@runner("turboaggregate")
def run_turboaggregate(cfg, data, mesh, sink):
    import jax
    from fedml_tpu.algorithms.turboaggregate import (TurboAggregate,
                                                     TurboAggregateConfig)
    wl = _make_workload(cfg, data)
    clients_per_group = max(2, cfg.client_num_per_round // cfg.group_num)
    algo = TurboAggregate(wl, data, TurboAggregateConfig(
        comm_round=cfg.comm_round, group_num=cfg.group_num,
        clients_per_group=clients_per_group,
        drop_tolerance=cfg.drop_tolerance, epochs=cfg.epochs, lr=cfg.lr,
        client_optimizer=cfg.client_optimizer, seed=cfg.seed,
        secagg_backend=cfg.secagg_backend))
    sample = jax.tree.map(lambda v: jax.numpy.asarray(v[0, 0]),
                          {k: data.train[k] for k in ("x", "y", "mask")})
    params = wl.init(jax.random.key(cfg.seed), sample)
    params = algo.run(params)
    stats = _eval_global(wl, params, data)
    sink.log(stats, step=cfg.comm_round - 1)
    return stats


@runner("fednas")
def run_fednas(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.fednas import FedNAS, FedNASConfig
    from fedml_tpu.models import DARTSSearchNetwork
    _image_sample_shape(cfg, data, "fednas")
    net = DARTSSearchNetwork(
        C=cfg.fednas_channels, layers=cfg.fednas_layers,
        steps=cfg.fednas_steps, multiplier=cfg.fednas_steps,
        num_classes=data.class_num)
    algo = FedNAS(net, FedNASConfig(rounds=cfg.comm_round,
                                    epochs=cfg.epochs, seed=cfg.seed))
    cohort = _first_cohort(data, cfg.client_num_per_round)
    # local validation split = the local train data (the reference splits
    # each client's local set; with hermetic twins the halves are iid anyway)
    out = algo.run(cohort, cohort)
    for h in out["history"]:
        sink.log({"round": h["round"], "search_loss": h["search_loss"],
                  "genotype": str(h["genotype"])}, step=h["round"])
    return {"search_loss": out["history"][-1]["search_loss"],
            "genotype": str(out["history"][-1]["genotype"])}


@runner("fedgkt")
def run_fedgkt(cfg, data, mesh, sink):
    from fedml_tpu.algorithms.fedgkt import FedGKT, FedGKTConfig
    from fedml_tpu.models import GKTClientResNet, GKTServerResNet
    _image_sample_shape(cfg, data, "fedgkt")
    client = GKTClientResNet(num_classes=data.class_num)
    server = GKTServerResNet(num_classes=data.class_num)
    algo = FedGKT(client, server, FedGKTConfig(
        rounds=cfg.comm_round, epochs_client=cfg.epochs,
        temperature=cfg.temperature, seed=cfg.seed))
    cohort = _first_cohort(data, cfg.client_num_per_round)
    out = algo.run(cohort)
    for h in out["history"]:
        sink.log(h, step=h["round"])
    ev = algo.evaluate(out["client_params"], out["server_params"], cohort)
    sink.log(ev, step=cfg.comm_round - 1)
    return ev


@runner("fedgan")
def run_fedgan(cfg, data, mesh, sink):
    import jax.numpy as jnp
    from fedml_tpu.algorithms.fedgan import FedGan, FedGanConfig
    from fedml_tpu.models import Discriminator, Generator
    shape = _image_sample_shape(cfg, data, "fedgan")
    H, W, ch = shape
    # G emits 4 * 2^len(widths) px; centre-crop the data to the largest
    # generator-compatible size <= min(H, W)
    n_ups, size = 1, 8
    while size * 2 <= min(H, W):
        n_ups, size = n_ups + 1, size * 2
    widths = tuple(64 // (2 ** i) for i in range(n_ups))
    G = Generator(out_channels=ch, widths=widths)
    D = Discriminator()
    cohort = _first_cohort(data, cfg.client_num_per_round)
    oy, ox = (H - size) // 2, (W - size) // 2
    cohort = {"x": jnp.asarray(
        cohort["x"][:, :, :, oy:oy + size, ox:ox + size, :]),
        "num_samples": jnp.asarray(cohort["num_samples"])}
    algo = FedGan(G, D, FedGanConfig(rounds=cfg.comm_round,
                                     local_epochs=cfg.epochs, seed=cfg.seed))
    out = algo.run(cohort)
    for h in out["history"]:
        sink.log(h, step=h["round"])
    return out["history"][-1]


@runner("asdgan")
def run_asdgan(cfg, data, mesh, sink):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.algorithms.fedgan import AsDGan, AsDGanConfig
    from fedml_tpu.models import CondGenerator, PatchDiscriminator
    shape = _image_sample_shape(cfg, data, "asdgan")
    ch = shape[2]
    cohort = _first_cohort(data, cfg.client_num_per_round)
    # hermetic paired task: conditioning a = noisy image, private b = clean
    # (a denoising translation — AsDGan's server-G never sees b directly)
    b = jnp.asarray(cohort["x"])
    noise = jax.random.normal(jax.random.key(cfg.seed), b.shape) * 0.3
    algo = AsDGan(CondGenerator(out_channels=ch), PatchDiscriminator(),
                  AsDGanConfig(epochs=cfg.comm_round, seed=cfg.seed,
                               lambda_l1=cfg.lambda_l1,
                               lambda_perceptual=cfg.lambda_perceptual))
    out = algo.run({"a": b + noise, "b": b,
                    "num_samples": jnp.asarray(cohort["num_samples"])})
    for h in out["history"]:
        sink.log(h, step=h.get("epoch", 0))
    return out["history"][-1]


@runner("fedseg")
def run_fedseg(cfg, data, mesh, sink):
    import jax.numpy as jnp
    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
    from fedml_tpu.algorithms.fedseg import SegmentationWorkload
    from fedml_tpu.data.stacking import FederatedData
    from fedml_tpu.models import UNet
    shape = _image_sample_shape(cfg, data, "fedseg")
    # hermetic dense-label task: per-pixel class = brightness threshold of
    # the image itself (2 classes) — learnable, and exercises the full
    # ignore-index CE + confusion-matrix mIoU path
    def to_seg(stacked):
        if stacked is None:
            return None
        y = (np.asarray(stacked["x"]).mean(axis=-1) > 0).astype(np.int32)
        return {**stacked, "y": y}
    seg_data = FederatedData(
        client_num=data.client_num, class_num=2,
        train=to_seg(data.train), test=to_seg(data.test))
    wl = SegmentationWorkload(UNet(num_classes=2, widths=(8, 16)),
                              num_classes=2)
    algo = FedAvg(wl, seg_data, FedAvgConfig(**_fedavg_cfg_kwargs(cfg)),
                  mesh=mesh, sink=sink)
    algo.run(checkpointer=_make_checkpointer(cfg))
    return algo.history[-1] if algo.history else {}


@runner("split_nn")
def run_split_nn(cfg, data, mesh, sink):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from fedml_tpu.algorithms.split_nn import (SplitModel, SplitNNConfig,
                                               SplitNNSimulator)
    sample_shape = sample_shape_of(data)

    class Body(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            return nn.relu(nn.Dense(64)(x))

    class Head(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(data.class_num)(x)

    split = SplitModel(Body(), Head())
    sim = SplitNNSimulator(split, SplitNNConfig(
        epochs_per_client=cfg.epochs, rounds=cfg.comm_round,
        client_lr=cfg.lr, server_lr=cfg.lr))
    n = min(cfg.client_num_per_round, data.client_num)
    client_data = [
        {k: jnp.asarray(data.train[k][c]) for k in ("x", "y", "mask")}
        for c in range(n)]
    out = sim.run(client_data, jax.random.key(cfg.seed))
    for h in out["history"]:
        sink.log(h, step=h.get("sweep", 0))
    return out["history"][-1] if out["history"] else {}


@runner("vfl")
def run_vfl(cfg, data, mesh, sink):
    import jax
    from fedml_tpu.algorithms.vertical_fl import VerticalFL, VFLConfig
    from fedml_tpu.data.tabular import synthetic_vfl_parties
    from fedml_tpu.models import VFLPartyNet
    # vertical FL partitions FEATURES, not clients: two-party synthetic
    # standing in for lending_club / NUS-WIDE (tabular.py loaders take a
    # real csv via --data_dir in library use)
    train, test = synthetic_vfl_parties(
        n_samples=max(cfg.batch_size * 4, 256), seed=cfg.seed)
    feature_dims = [x.shape[1] for x in train[:-1]]
    models = [VFLPartyNet(hidden_dim=16) for _ in feature_dims]
    vfl = VerticalFL(models, VFLConfig(
        rounds=cfg.comm_round, batch_size=cfg.batch_size, lr=cfg.lr,
        frequency_of_the_test=cfg.frequency_of_the_test))
    out = vfl.fit(train, test, jax.random.key(cfg.seed))
    for h in out["history"]:
        sink.log(h, step=h.get("round"))
    return out["history"][-1] if out["history"] else {}


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------

def setup_platform(cfg: ExperimentConfig) -> None:
    """Pick the jax platform/devices BEFORE any backend initializes (env
    vars alone don't stick — the PJRT plugin overwrites them)."""
    import os
    if cfg.host_device_count > 0:
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count="
                     f"{cfg.host_device_count}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    if cfg.platform:
        import jax
        jax.config.update("jax_platforms", cfg.platform)


def enable_compile_cache() -> None:
    """Persistent XLA compilation cache, gated on the RESOLVED backend
    (initializes it): TPU first-compiles run 20-40s+ per program
    (multi-minute for the big models), so caching makes every rerun of the
    same config start hot.  CPU backends stay uncached — compiles are
    cheap there and tests churn shapes, which would just grow the cache.
    ``FEDML_TPU_CACHE=path`` overrides the location; empty disables.
    Call AFTER platform selection (setup_platform), at a point where
    backend initialization is acceptable."""
    import os
    import jax
    cache = os.environ.get("FEDML_TPU_CACHE",
                           os.path.expanduser("~/.cache/fedml_tpu_xla"))
    if cache and jax.default_backend() != "cpu":
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)


def main(argv=None) -> Dict[str, Any]:
    cfg = config_from_argv(argv) if not isinstance(argv, ExperimentConfig) \
        else argv
    logging.basicConfig(
        level=logging.INFO,
        format=f"[proc {cfg.process_id}] %(asctime)s %(name)s: %(message)s")
    # --cross_device is shorthand for --algo cross_device (the compiled
    # wave engine); pairing it with any OTHER algorithm would silently
    # pick one of the two — fail instead
    if cfg.cross_device and cfg.algo not in ("fedavg", "cross_device"):
        raise ValueError(
            f"--cross_device IS an algorithm selection (the compiled "
            f"wave engine, --algo cross_device); it cannot combine with "
            f"--algo {cfg.algo}")
    if cfg.cross_device or cfg.algo == "cross_device":
        cfg = dataclasses.replace(cfg, algo="cross_device",
                                  cross_device=True)
    setup_platform(cfg)

    from fedml_tpu.parallel.mesh import init_distributed, make_mesh
    init_distributed(cfg.coordinator_address, cfg.num_processes,
                     cfg.process_id)
    enable_compile_cache()
    mesh = None
    if cfg.mesh_groups > 0:
        if cfg.algo != "hierarchical":
            raise ValueError(
                "--mesh_groups builds the two-level [groups, clients] mesh, "
                "which only the hierarchical algorithm consumes; other "
                f"algorithms (got --algo {cfg.algo}) would silently "
                "duplicate work across the groups axis. Use --mesh_clients.")
        import jax
        from fedml_tpu.parallel.mesh import make_two_level_mesh
        n_dev = len(jax.devices())
        n_cli = cfg.mesh_clients or n_dev // cfg.mesh_groups
        if n_cli < 1:
            raise ValueError(
                f"--mesh_groups {cfg.mesh_groups} exceeds the "
                f"{n_dev} available devices")
        mesh = make_two_level_mesh(
            group_axis=cfg.mesh_groups, client_axis=n_cli,
            devices=jax.devices()[:cfg.mesh_groups * n_cli])
    elif cfg.mesh_clients > 0:
        import jax
        mesh = make_mesh(client_axis=cfg.mesh_clients,
                         devices=jax.devices()[:cfg.mesh_clients])

    if cfg.algo not in RUNNERS:
        raise KeyError(f"unknown --algo {cfg.algo!r}; have {sorted(RUNNERS)}")
    # mixed precision is wired through _make_workload; runners that build
    # their own models (NAS/GKT/GAN/seg/split/vfl/online) would silently
    # train f32 — fail loudly instead of faking a bf16 benchmark
    _DTYPE_RUNNERS = {"fedavg", "fedprox", "fedopt", "fednova",
                      "fedavg_robust", "hierarchical", "centralized",
                      "decentralized", "turboaggregate", "ditto",
                      "feddyn", "dp_fedavg", "fedac", "cross_device"}
    if cfg.compute_dtype and cfg.algo not in _DTYPE_RUNNERS:
        raise ValueError(
            f"--compute_dtype is not wired into --algo {cfg.algo}; "
            f"supported: {sorted(_DTYPE_RUNNERS)}")
    if cfg.mesh_stages > 0 and cfg.algo != "cross_silo":
        raise ValueError(
            "--mesh_stages is silo-local pipeline parallelism: each silo "
            "runs its own [stages] mesh, so it only applies to --algo "
            "cross_silo (the vmapped cohort engine cannot nest a shard_map "
            f"pipeline per client); got --algo {cfg.algo}")
    if cfg.pp_microbatches and not cfg.mesh_stages:
        raise ValueError("--pp_microbatches tunes the GPipe schedule and "
                         "needs --mesh_stages; alone it would be silently "
                         "ignored")
    if cfg.mesh_stages > 0 and (cfg.attn_block_size or cfg.attn_flash):
        raise ValueError(
            "--attn_block_size/--attn_flash are TransformerLM attention "
            "backends; the pipelined PipelineLM (--mesh_stages) runs dense "
            "block attention and would silently drop them")
    # same fail-loudly convention: a silently-ignored EF flag would label
    # uncompressed numbers as EF results
    if cfg.wire_compression != "none" and cfg.algo != "cross_silo":
        raise ValueError("--wire_compression only applies to "
                         "--algo cross_silo (the host-edge wire)")
    if any((cfg.chaos_drop, cfg.chaos_delay, cfg.chaos_dup,
            cfg.chaos_reorder, cfg.chaos_corrupt)) \
            and cfg.algo != "cross_silo":
        raise ValueError(
            f"--chaos_* injection is wired into --algo cross_silo only; "
            f"--algo {cfg.algo} would silently run a CLEAN network and "
            f"label the results as chaos results")
    # the live-path payload defense + adversary harness (fedml_tpu/robust)
    # rides the distributed actor modes only; on the cohort-simulation
    # algorithms the flags would silently do nothing and label plain runs
    # as defended/attacked ones.  cross_device composes the SUBSET that
    # makes sense inside compiled waves (--norm_clip/--agg_noise_std on
    # the streamed mean + the built-in per-wave screens) — its own gates
    # below refuse the rest with reasons.
    if cfg.algo not in ("cross_silo", "async_fl", "cross_device") and (
            cfg.robust_agg != "mean" or cfg.norm_clip or cfg.agg_noise_std
            or cfg.adversary or cfg.admission == "on"):
        raise ValueError(
            f"--robust_agg/--norm_clip/--agg_noise_std/--adversary/"
            f"--admission on are the live distributed defense "
            f"(fedml_tpu/robust) and apply to --algo cross_silo/async_fl "
            f"only; got --algo {cfg.algo}.  For the single-chip cohort "
            f"simulation use --algo fedavg_robust --defense ... instead.")
    # cross-device wave engine: every unsupported combo fails AT CONFIG
    # TIME with its reason — a silently-ignored flag would mislabel the
    # run (the secagg gate convention)
    if cfg.algo == "cross_device":
        if cfg.secagg != "off":
            raise ValueError(
                "--cross_device trains sampled clients INSIDE compiled "
                "wave programs — there are no per-client uploads on a "
                "wire to mask, so --secagg would label an unmasked "
                "simulation as private; secure aggregation lives on the "
                "actor path (--algo cross_silo --secagg ...)")
        if cfg.edge_aggregators > 0:
            raise ValueError(
                "--edge_aggregators is a transport-actor topology; the "
                "cross-device engine's hierarchy is the wave tree itself "
                "(waves pre-reduce on device), so the flag would "
                "silently run a flat engine labeled as an edge tree")
        if cfg.silo_backend != "local":
            raise ValueError(
                f"--cross_device is the compiled single-process engine; "
                f"--silo_backend {cfg.silo_backend!r} (transport actors) "
                f"would be silently ignored — scale out with "
                f"--mesh_clients (+ --coordinator_address on pods) "
                f"instead")
        if cfg.robust_agg != "mean":
            raise ValueError(
                f"--robust_agg {cfg.robust_agg}: order-statistic rules "
                f"need the per-client population, but cross-device waves "
                f"pre-reduce to a weighted partial mean on device.  The "
                f"defenses that compose are the per-wave structure/"
                f"finite/norm screens + --norm_clip/--agg_noise_std on "
                f"the streamed mean; for per-upload robust rules use "
                f"--algo cross_silo --agg_mode stream "
                f"--stream_reservoir K")
        if cfg.adversary:
            raise ValueError(
                "--adversary wraps per-silo train fns over the real "
                "message path (robust/adversary.py); the compiled wave "
                "has no per-silo message seam — run attack scenarios on "
                "--algo cross_silo, or poison wave SUMMARIES here with "
                "--wave_adversary round:wave:kind[:param]")
        if cfg.rounds_per_dispatch > 1:
            raise ValueError(
                "--rounds_per_dispatch is the fedavg HBM-resident "
                "multi-round scan; the cross-device wave loop folds per "
                "wave on the host each round and would silently ignore "
                "it")
    if cfg.error_feedback and cfg.wire_compression == "none":
        raise ValueError("--error_feedback requires --wire_compression "
                         "topk or int8")
    # zero-copy pipelined ingest (comm/ingest.py, ISSUE 20): the
    # bit-parity contract is proven per combination — every combination
    # WITHOUT a parity pin refuses at config time with its reason
    # instead of silently falling back to the inline path
    if cfg.ingest_queue_depth < 1:
        raise ValueError(f"--ingest_queue_depth must be >= 1, got "
                         f"{cfg.ingest_queue_depth}")
    if cfg.ingest_pipeline:
        if cfg.algo not in ("cross_silo", "async_fl", "cross_device"):
            raise ValueError(
                f"--ingest_pipeline pipelines the SERVER receive path "
                f"(cross_silo / async_fl) and the cross_device wave "
                f"loop; --algo {cfg.algo} has no ingest hot path and "
                f"would silently run inline")
        if cfg.wire_compression != "none":
            raise ValueError(
                "--ingest_pipeline x --wire_compression is unproven: "
                "the decompress + error-feedback settlement runs on the "
                "transport thread today, and no bit-parity pin covers "
                "decode-on-worker — drop one flag")
        if cfg.silo_backend != "local" and cfg.algo != "cross_device":
            raise ValueError(
                f"--ingest_pipeline x --silo_backend "
                f"{cfg.silo_backend!r} is unproven: the parity and "
                f"journal-recovery pins drive the local hub; the grpc "
                f"receive path needs its own soak before the pipeline "
                f"rides it")
        if cfg.edge_aggregators > 0:
            raise ValueError(
                "--ingest_pipeline x --edge_aggregators is unproven: "
                "edges fold on their own actors and no pin covers a "
                "pipelined edge tier — drop one flag")
        if any((cfg.chaos_drop, cfg.chaos_delay, cfg.chaos_dup,
                cfg.chaos_reorder, cfg.chaos_corrupt)):
            raise ValueError(
                "--ingest_pipeline x --chaos_* is unproven: chaos "
                "switches the hub to the threaded drive and no parity "
                "pin covers wall-clock chaos timers racing the fold "
                "workers — drop one flag")
        if cfg.algo == "cross_silo" and cfg.agg_mode != "stream" \
                and cfg.secagg == "off":
            raise ValueError(
                "--ingest_pipeline pipelines the STREAMING fold "
                "(decode -> screen -> fold at arrival); --agg_mode "
                "stack banks uploads instead of folding them, so "
                "there is nothing to hide behind the network — use "
                "--agg_mode stream")
    # secure aggregation (secure/protocol.py): every incompatible combo
    # fails AT CONFIG TIME — a silently-ignored privacy flag would label
    # plaintext traffic as masked, the worst possible mislabel
    if cfg.secagg not in ("off", "pairwise", "grouped"):
        raise ValueError(f"--secagg must be off|pairwise|grouped, "
                         f"got {cfg.secagg!r}")
    if cfg.secagg != "off":
        if cfg.algo != "cross_silo":
            raise ValueError(
                f"--secagg is the sync-barrier secure-aggregation protocol "
                f"and applies to --algo cross_silo only; --algo {cfg.algo} "
                f"(including async_fl, whose per-upload staleness discounts "
                f"need plaintext individual deltas) would silently train "
                f"unmasked and label the run as private")
        if cfg.wire_compression != "none" or cfg.error_feedback:
            raise ValueError(
                "--secagg and --wire_compression/--error_feedback are "
                "mutually exclusive: a compressed/EF payload cannot ride "
                "the uint32 masking ring (masks must cancel word-for-word)")
        if cfg.robust_agg != "mean":
            raise ValueError(
                f"--secagg hides individual uploads by construction, so "
                f"order-statistic rules (--robust_agg {cfg.robust_agg}) "
                f"have no population to rank; the defenses that compose "
                f"are the pre-mask structure/num_samples screens and the "
                f"post-unmask sum screen + --norm_clip/--agg_noise_std "
                f"on the sum")
        if cfg.agg_mode != "stream":
            raise ValueError(
                "--secagg folds masked uploads in the uint32 ring at "
                "arrival — there is no stack path; pass --agg_mode stream")
        if cfg.silo_backend != "local":
            raise ValueError("--secagg deploys over the local hub only "
                             "for now (the actors are transport-agnostic; "
                             "gRPC wiring mirrors the flat one)")
        if cfg.secagg == "grouped" and cfg.edge_aggregators < 1:
            raise ValueError(
                "--secagg grouped scopes masking per edge block and needs "
                "--edge_aggregators E >= 1; for a single cohort-wide "
                "masking group use --secagg pairwise")
        if cfg.secagg == "pairwise" and cfg.edge_aggregators > 0:
            raise ValueError(
                "--secagg pairwise masks across the WHOLE cohort, which an "
                "edge cannot partially unmask (cross-block pair masks only "
                "cancel in the root's full sum); use --secagg grouped with "
                "--edge_aggregators")
        if cfg.secagg == "grouped" \
                and cfg.client_num_per_round < 2 * cfg.edge_aggregators:
            raise ValueError(
                f"--secagg grouped needs every edge block to hold >= 2 "
                f"silos (a 1-silo 'masked sum' IS that silo's update): "
                f"{cfg.client_num_per_round} silos over "
                f"{cfg.edge_aggregators} edges leaves a short block")
        if cfg.secagg == "pairwise" and cfg.client_num_per_round < 2:
            raise ValueError("--secagg pairwise needs >= 2 silos per round")
        if cfg.secagg_threshold == 1:
            raise ValueError(
                "--secagg_threshold 1 voids the privacy guarantee: one "
                "share reconstructs every seed; the minimum is 2 (0 = "
                "majority default)")
        # the threshold is a PER-GROUP share count: a t larger than the
        # masking group could never reconstruct, and silently clamping
        # it would rewrite the dropout-tolerance contract the flag
        # documents — fail here, where the group sizes are knowable
        group_min = (cfg.client_num_per_round if cfg.secagg == "pairwise"
                     else cfg.client_num_per_round // cfg.edge_aggregators)
        if cfg.secagg_threshold > group_min:
            raise ValueError(
                f"--secagg_threshold {cfg.secagg_threshold} exceeds the "
                f"smallest masking group ({group_min} silos"
                f"{' per edge block' if cfg.secagg == 'grouped' else ''}): "
                f"reconstruction could never gather that many shares")
    # sharded global-model spine (fedml_tpu/shard_spine): every
    # incompatible combo fails AT CONFIG TIME with its reason — a
    # silently-ignored sharding flag would label a whole-model run as
    # sharded (the secagg gate convention)
    if cfg.model_shards < 0:
        raise ValueError(f"--model_shards must be >= 0, got "
                         f"{cfg.model_shards}")
    if cfg.fused_finalize not in ("auto", "on", "off"):
        raise ValueError(f"--fused_finalize must be auto|on|off, got "
                         f"{cfg.fused_finalize!r}")
    if cfg.fused_finalize != "auto" and cfg.model_shards < 1:
        raise ValueError(
            "--fused_finalize selects the SHARD finalize backend and "
            "needs --model_shards >= 1; alone it would be silently "
            "ignored")
    if cfg.model_shards > 0:
        if cfg.algo != "cross_silo":
            raise ValueError(
                f"--model_shards is the sharded cross-silo spine and "
                f"applies to --algo cross_silo only; --algo {cfg.algo} "
                f"would silently run whole-model and label the run as "
                f"sharded")
        if cfg.agg_mode != "stream":
            raise ValueError(
                "--model_shards shards the STREAMING fold state — pass "
                "--agg_mode stream (the stack path's [cohort, ...] "
                "buffer is whole-model by construction)")
        if cfg.robust_agg != "mean":
            raise ValueError(
                f"--model_shards with --robust_agg {cfg.robust_agg}: "
                f"order-statistic rules need the per-upload population, "
                f"which the sharded fold deliberately never "
                f"materializes; the defenses that compose are the "
                f"per-shard screens + --norm_clip/--agg_noise_std on "
                f"the streamed mean (for robust rules use the "
                f"replicated --agg_mode stream --stream_reservoir K)")
        if cfg.secagg != "off":
            raise ValueError(
                "--model_shards and --secagg are mutually exclusive: a "
                "pairwise-masked uint32 ring word cannot be re-sliced "
                "per shard without breaking mask cancellation")
        if cfg.edge_aggregators > 0:
            raise ValueError(
                "--model_shards and --edge_aggregators are mutually "
                "exclusive for now: an edge folds and ships whole-model "
                "means, which would defeat the per-shard wire (shard "
                "the flat topology, or keep edges replicated)")
        if cfg.wire_compression != "none" or cfg.error_feedback:
            raise ValueError(
                "--model_shards and --wire_compression/--error_feedback "
                "are mutually exclusive: the delta codec reconstructs "
                "against the whole global, not a shard slice")
        if cfg.admission == "off":
            raise ValueError(
                "--model_shards requires the admission screens: the "
                "per-shard structural fingerprint IS the wire protocol "
                "(slices route by screened structure), so --admission "
                "off would leave the sharded fold unprotected against "
                "mis-assembled uploads")
        if cfg.silo_backend != "local":
            raise ValueError(
                "--model_shards deploys over the local hub only for "
                "now (the actors are transport-agnostic; gRPC wiring "
                "mirrors the flat one)")
    # crash consistency (utils/journal.py): the journal snapshots the
    # STREAMING fold state — on a stack-mode (or non-live) run the flag
    # would parse and then silently journal nothing, which is the exact
    # "we thought we were crash-safe" blindness this subsystem ends
    if cfg.journal or cfg.journal_dir:
        if cfg.algo not in ("cross_silo", "async_fl"):
            raise ValueError(
                f"--journal is mid-round crash consistency for the live "
                f"actor modes and applies to --algo cross_silo/async_fl "
                f"only; --algo {cfg.algo} would silently journal nothing "
                f"and label the run as crash-consistent.")
        if cfg.agg_mode != "stream" and cfg.secagg == "off":
            raise ValueError(
                "--journal rides the streaming-fold receive path: pass "
                "--agg_mode stream (the stack path has no incremental "
                "fold state to snapshot).  Secagg rounds journal "
                "abort-only.")
    if cfg.journal_snapshot_every < 1:
        raise ValueError(f"--journal_snapshot_every must be >= 1, got "
                         f"{cfg.journal_snapshot_every}")
    if cfg.serve_port > 0 and cfg.algo != "cross_silo":
        raise ValueError(
            "--serve_port starts the serve-while-train frontend, which is "
            f"wired into --algo cross_silo only; --algo {cfg.algo} would "
            "silently train without serving.  To serve a finished "
            "checkpoint directory, use scripts/serve_bench.py "
            "--ckpt_dir instead.")
    if cfg.serve_workers < 1:
        raise ValueError(f"--serve_workers must be >= 1, got "
                         f"{cfg.serve_workers}")
    if cfg.serve_workers > 1 and cfg.serve_port <= 0:
        raise ValueError(
            "--serve_workers scales the HTTP frontend and needs "
            "--serve_port; without one there is no frontend to scale "
            "and the flag would silently do nothing.")
    if not 0.0 < cfg.serve_best_effort_headroom <= 1.0:
        raise ValueError(
            f"--serve_best_effort_headroom must be in (0, 1], got "
            f"{cfg.serve_best_effort_headroom}")
    if cfg.metrics_port > 0 and cfg.prom_port > 0 \
            and cfg.metrics_port != cfg.prom_port:
        raise ValueError(
            f"--metrics_port is an alias for --prom_port; got both, "
            f"disagreeing ({cfg.metrics_port} vs {cfg.prom_port}) — "
            f"pass one, or the same port for both.")
    # release gate (serve/release.py): gates the serve-while-train
    # publish hook, so without a frontend the flag would silently train
    # ungated while the run is labeled canary-protected
    if cfg.release_gate and cfg.serve_port <= 0:
        raise ValueError(
            "--release_gate gates the serve-while-train publish hook "
            "(canary → shadow/health/eval verdict) and needs "
            "--serve_port; without a frontend there is no serving swap "
            "to gate and the flag would silently do nothing.")
    if cfg.release_gate and (cfg.release_shadow_every < 1
                             or cfg.release_shadow_slots < 1):
        raise ValueError(
            f"--release_shadow_every and --release_shadow_slots must be "
            f">= 1, got {cfg.release_shadow_every} and "
            f"{cfg.release_shadow_slots}")
    if cfg.wave_adversary and cfg.algo != "cross_device":
        raise ValueError(
            f"--wave_adversary poisons compiled wave SUMMARIES and "
            f"applies to --algo cross_device only; --algo {cfg.algo} "
            f"would silently train clean while the run is labeled "
            f"poisoned.  Per-silo attacks on the actor path use "
            f"--adversary.")
    # the flight recorder and the SLO evaluator hook the live actors'
    # round lifecycle; on the cohort-simulation algorithms the flags
    # would parse and then never record/evaluate anything — an empty
    # ledger and un-evaluated objectives masquerading as a healthy run
    if cfg.algo not in ("cross_silo", "async_fl", "cross_device") and (
            cfg.perf or cfg.perf_ledger or cfg.perf_strict or cfg.slo
            or cfg.device_obs or cfg.health or cfg.health_ledger):
        raise ValueError(
            f"--perf/--perf_ledger/--perf_strict/--device_obs/--slo/"
            f"--health/--health_ledger instrument the live round "
            f"lifecycle and apply to --algo cross_silo/async_fl/"
            f"cross_device only; --algo {cfg.algo} would silently write "
            f"no ledger and never evaluate the objectives.")
    # server-optimizer spine (fedml_tpu/server_opt, ISSUE 18): every
    # incompatible combo fails AT CONFIG TIME with its reason — the
    # named ServerOptConfigError, so a mislabeled run never trains
    from fedml_tpu.server_opt import SERVER_OPT_NAMES, ServerOptConfigError
    if cfg.server_opt not in SERVER_OPT_NAMES:
        raise ServerOptConfigError(
            f"unknown --server_opt {cfg.server_opt!r}; available: "
            f"{list(SERVER_OPT_NAMES)}")
    if cfg.server_opt != "plain":
        if cfg.algo not in ("cross_silo", "async_fl", "cross_device"):
            raise ServerOptConfigError(
                f"--server_opt {cfg.server_opt} rides the live finalize "
                f"seam and applies to --algo cross_silo/async_fl/"
                f"cross_device only; --algo {cfg.algo} would silently "
                f"run its own server step and label the run "
                f"{cfg.server_opt}.  The standalone forks stay at "
                f"--algo fedopt/fedac.")
        if cfg.robust_agg != "mean":
            raise ServerOptConfigError(
                f"--server_opt {cfg.server_opt} with --robust_agg "
                f"{cfg.robust_agg}: an order-statistic finalize is a "
                f"selection, not a cohort mean — there is no "
                f"pseudo-gradient Δ = global − finalize whose "
                f"expectation the server optimizer's moments assume; "
                f"use --robust_agg mean (with --norm_clip/"
                f"--agg_noise_std for defense)")
        if cfg.secagg != "off":
            raise ServerOptConfigError(
                f"--server_opt {cfg.server_opt} and --secagg are "
                f"mutually exclusive: the masked-sum protocol yields "
                f"the plain mean by construction; there is no seam to "
                f"re-step it without unmasking intermediate state")
        if cfg.local_alg == "fednova" and cfg.algo == "cross_device":
            raise ServerOptConfigError(
                "--server_opt with --local_alg fednova: fednova's "
                "tau_eff step IS a server update; stacking a second "
                "optimizer on top would silently change its normalized "
                "averaging semantics")
    if cfg.adaptive:
        if not (cfg.health or cfg.health_ledger):
            raise ServerOptConfigError(
                "--adaptive steers pacing from the health observatory's "
                "drift alarms and requires --health (or "
                "--health_ledger); without it every decision would be "
                "a vacuous hold and the run would be labeled adaptive")
        if cfg.algo not in ("cross_silo", "cross_device"):
            raise ServerOptConfigError(
                f"--adaptive steers the per-round cohort sampler and "
                f"applies to --algo cross_silo/cross_device only; "
                f"--algo {cfg.algo} has no round cohort to pace")
    if cfg.adapt_min_cohort < 1:
        raise ServerOptConfigError(
            f"--adapt_min_cohort must be >= 1, got "
            f"{cfg.adapt_min_cohort}")
    if cfg.adapt_patience < 1:
        raise ServerOptConfigError(
            f"--adapt_patience must be >= 1, got {cfg.adapt_patience}")
    # decentralized_online consumes a streaming dataset (UCI SUSY/RO or a
    # synthetic stream) that the registry doesn't serve — its runner builds
    # it; loading here would KeyError on --dataset SUSY
    data = (None if cfg.algo == "decentralized_online"
            else load_experiment_data(cfg))
    logger.info("algo=%s model=%s dataset=%s clients=%s (%s data)",
                cfg.algo, cfg.model, cfg.dataset,
                "stream" if data is None else data.client_num,
                "real" if cfg.data_dir else "synthetic-twin")

    # multi-host: only process 0 writes run artifacts / prints the summary
    # (the reference's rank-0-only wandb, main_fedavg.py:288-296); other
    # processes keep an in-memory sink so runner code is rank-agnostic
    import os

    import jax
    is_main = jax.process_index() == 0
    run_dir = cfg.metrics_dir or cfg.run_dir

    # observability opt-ins, enabled BEFORE the runner constructs any
    # transport/actor (instrumented constructors cache metric handles);
    # exports happen in the finally so a crashed run still leaves its
    # telemetry snapshot and whatever spans were recorded
    from fedml_tpu.obs import telemetry as _telemetry, trace as _trace
    registry = prom_server = tracer = None
    scrape_port = cfg.metrics_port or cfg.prom_port  # gate above pins
    # any disagreement, so first-nonzero is an alias pick, not a choice
    if cfg.telemetry or scrape_port > 0:
        registry = _telemetry.enable()
        if scrape_port > 0:
            prom_server = _telemetry.start_http_server(scrape_port,
                                                       registry)
            if prom_server is not None:  # bind failure warned + returned None
                logger.info("telemetry: serving /metrics on :%d",
                            scrape_port)
    if cfg.trace_dir:
        tracer = _trace.enable(node=f"node{cfg.node_id}")

    try:
        with MetricsSink(run_dir if is_main else None,
                         stdout=cfg.log_stdout and is_main,
                         name=cfg.algo) as sink:
            sink.log({"config": dataclasses.asdict(cfg)})
            with profiler_trace(cfg.profile_dir if is_main else None):
                summary = RUNNERS[cfg.algo](cfg, data, mesh, sink)
            sink.log({"final": summary})
    finally:
        # each teardown step independently: a failing export must not
        # skip the remaining saves, leak the /metrics port, leave the
        # process-global tracer/registry enabled for the next main()
        # call, or mask the run's own exception
        if tracer is not None:
            try:
                tracer.export(os.path.join(
                    cfg.trace_dir,
                    f"trace-node{cfg.node_id}-{os.getpid()}.json"))
            except OSError:
                logger.exception("trace export failed")
            _trace.disable()
        if registry is not None:
            if run_dir is not None and is_main:
                try:
                    registry.save(os.path.join(run_dir, "telemetry.json"))
                    with open(os.path.join(run_dir, "telemetry.prom"),
                              "w") as f:
                        f.write(registry.render_prometheus())
                except OSError:
                    logger.exception("telemetry export failed")
            if prom_server is not None:
                prom_server.shutdown()
                prom_server.server_close()  # release the port now
            _telemetry.disable()
    if is_main:
        line = json.dumps({"algo": cfg.algo, "dataset": cfg.dataset,
                           "model": cfg.model,
                           **{k: v for k, v in summary.items()
                              if isinstance(v, (int, float, str))}})
        print(line)
        # sweep-orchestration completion signal (parity:
        # post_complete_message_to_sweep_process writes to the named
        # pipe ./tmp/fedml, fedavg/utils.py:19-27); works with a FIFO
        # or a plain file.  Gated on a non-empty summary so a gRPC silo
        # process (returns {}) can't prematurely unblock the orchestrator
        # or truncate the server's real summary.
        if cfg.completion_signal and summary:
            with open(cfg.completion_signal, "w") as f:
                f.write(line + "\n")
    return summary


if __name__ == "__main__":
    main()
