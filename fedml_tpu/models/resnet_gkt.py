"""Split ResNets for FedGKT (group knowledge transfer).

Parity targets (``fedml_api/model/cv/resnet56_gkt/``):

* client net ``resnet8_56`` (resnet_client.py:230): CIFAR stem (3x3 conv,
  16 planes) + layer1 only (BasicBlocks at 16 planes) + avgpool + fc.
  Its forward returns ``(logits, extracted_features)`` where the features
  are the PRE-POOL conv maps [B, 32, 32, 16] (resnet_client.py:189-203) —
  those maps are what travels to the server.
* server net ``resnet55/49`` (resnet_server.py): consumes the feature maps
  and runs the remaining stages layer2 (32 planes, stride 2) + layer3
  (64 planes, stride 2) + avgpool + fc.

Norm defaults to GroupNorm (TPU-friendly; see models/norms.py).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.norms import Norm, conv_kernel_init
from fedml_tpu.models.resnet import BasicBlock, Bottleneck, _conv


class GKTClientResNet(nn.Module):
    """Edge-side small net: stem + stage-1 blocks; emits (logits, feature
    maps).  ``blocks=3`` ≈ resnet8_56."""
    blocks: int = 3
    num_classes: int = 10
    norm: str = "group"

    @nn.compact
    def __call__(self, x, train: bool = False
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = _conv(16, 3)(x)
        x = Norm(self.norm)(x, train)
        x = nn.relu(x)
        for _ in range(self.blocks):
            x = BasicBlock(16, 1, self.norm)(x, train)
        feats = x                                  # [B, H, W, 16] to server
        pooled = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes, name="fc")(pooled)
        return logits, feats


class GKTServerResNet(nn.Module):
    """Server-side large net on received feature maps: stages 2-3 + head.
    ``layers=(9, 9)`` with BasicBlock ≈ the resnet55 server half."""
    layers: Sequence[int] = (9, 9)
    num_classes: int = 10
    norm: str = "group"
    block: type = BasicBlock

    @nn.compact
    def __call__(self, feats, train: bool = False) -> jnp.ndarray:
        x = feats
        for planes, n_blocks in zip((32, 64), self.layers):
            for i in range(n_blocks):
                x = self.block(planes, 2 if i == 0 else 1, self.norm)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, name="fc")(x)
