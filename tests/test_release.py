"""Release-gate contracts (ISSUE 16): the canary state machine in the
registry, the three promotion signals and their verdict matrix, shadow
determinism, cooldown/backoff, crash-consistent promote/rollback, the
checkpoint-manifest torn-file guard, wave-summary poisoning, and the
end-to-end poisoned-round containment story.

The load-bearing invariant everywhere: a canary NEVER occupies the live
slot — promotion is the only way in, so a failed (or crashed) release
can never have served a non-shadow response.
"""

import json
import os

import jax
import numpy as np
import pytest

from fedml_tpu.robust.faultline import (ActorKilled, CrashSpec,
                                        DiskFaultInjector, DiskFaultSpec,
                                        Faultline)
from fedml_tpu.serve.batcher import MicroBatcher
from fedml_tpu.serve.registry import CheckpointWatcher, ModelRegistry
from fedml_tpu.serve.release import (ReleaseController, ShadowSampler,
                                     _divergence)

DIM, CLASSES = 6, 4


def _linear_apply():
    return jax.jit(lambda p, x: x.reshape(x.shape[0], -1) @ p["w"] + p["b"])


def _params(version: int):
    """Version-fingerprinted params (the test_serve.py convention): any
    probe response names which version produced it."""
    w = np.zeros((DIM, CLASSES), np.float32)
    w[0, :] = float(version)
    b = np.zeros(CLASSES, np.float32)
    b[version % CLASSES] = 1.0
    return {"w": w, "b": b}


def _registry(*promoted):
    reg = ModelRegistry(_linear_apply(), history=8)
    for v in promoted:
        reg.publish(_params(v), v)
    return reg


def _controller(reg, **kw):
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("max_cooldown_s", 0.0)
    return ReleaseController(reg, **kw)


# -- registry canary state machine -------------------------------------------

class TestRegistryCanaryStates:
    def test_canary_publish_never_swaps_live(self):
        reg = _registry(1)
        assert reg.publish(_params(2), 2, canary=True)
        assert reg.version == 1           # live never moved
        assert reg.state(2) == "canary"
        assert reg.canaries() == [2]
        assert reg.get(2).version == 2    # but shadow replay can read it

    def test_promote_swaps_live_and_pins(self):
        reg = _registry(1)
        reg.publish(_params(2), 2, canary=True)
        assert reg.promote(2) == 2
        assert reg.version == 2 and reg.pinned == 2
        assert reg.state(2) == "promoted"
        # idempotent re-drive (the crash-at-post respawn path)
        assert reg.promote(2) == 2

    def test_promote_promoted_but_not_live_refuses(self):
        reg = _registry(1, 2)
        reg.pin(1)
        with pytest.raises(RuntimeError, match="promoted but not live"):
            reg.promote(2)

    def test_discard_removes_canary_only(self):
        reg = _registry(1)
        reg.publish(_params(2), 2, canary=True)
        reg.discard(2)
        assert reg.versions() == [1] and reg.canaries() == []
        with pytest.raises(RuntimeError, match="promoted"):
            reg.discard(1)
        with pytest.raises(KeyError):
            reg.discard(99)

    def test_discarded_version_number_can_be_republished(self):
        """Monotonicity compares against the newest REMAINING entry, so
        a rolled-back version number is offerable again after a retrain."""
        reg = _registry(1)
        reg.publish(_params(2), 2, canary=True)
        reg.discard(2)
        assert reg.publish(_params(2), 2, canary=True)

    def test_rollback_skips_canaries_to_previous_promoted(self):
        reg = _registry(1, 2)
        # wedge an unvetted canary between the promoted versions: it
        # must be invisible to rollback
        reg.publish(_params(3), 3, canary=True)
        reg.publish(_params(4), 4)
        assert reg.version == 4
        assert reg.rollback() == 2
        assert reg.version == 2

    def test_rollback_past_promoted_horizon_fails_loudly(self):
        reg = ModelRegistry(_linear_apply(), history=8)
        reg.publish(_params(1), 1, canary=True)
        reg.publish(_params(2), 2)        # the only promoted version
        with pytest.raises(RuntimeError, match="promoted horizon"):
            reg.rollback()
        assert reg.version == 2           # serving never moved

    def test_pin_refuses_canary(self):
        reg = _registry(1)
        reg.publish(_params(2), 2, canary=True)
        with pytest.raises(RuntimeError, match="unvetted canary"):
            reg.pin(2)

    def test_unpin_follows_newest_promoted_not_canary(self):
        reg = _registry(1, 2)
        reg.pin(1)
        reg.publish(_params(3), 3, canary=True)
        reg.unpin()
        assert reg.version == 2

    def test_eviction_protects_pending_canaries(self):
        reg = ModelRegistry(_linear_apply(), history=2)
        reg.publish(_params(1), 1, canary=True)
        for v in (2, 3, 4, 5):
            reg.publish(_params(v), v)
        assert 1 in reg.versions()        # canary outlived retention
        reg.discard(1)
        reg.publish(_params(6), 6)
        assert 1 not in reg.versions()


# -- shadow sampler ----------------------------------------------------------

class TestShadowSampler:
    def test_validates(self):
        with pytest.raises(ValueError):
            ShadowSampler(every=0)
        with pytest.raises(ValueError):
            ShadowSampler(slots=0)

    def test_every_nth_and_determinism(self):
        def run():
            s = ShadowSampler(every=3, slots=4)
            for i in range(20):
                s.offer(np.full(2, float(i), np.float32))
            return [r[0] for r in s.snapshot()]
        a, b = run(), run()
        assert a == b                     # same arrivals, same slice
        # every 3rd arrival (0, 3, 6, ...), newest 4 kept, ring order
        assert sorted(a) == [9.0, 12.0, 15.0, 18.0]

    def test_snapshot_copies_are_owned(self):
        s = ShadowSampler(every=1, slots=2)
        x = np.zeros(2, np.float32)
        s.offer(x)
        x[:] = 7.0                        # caller reuses its buffer
        assert s.snapshot()[0][0] == 0.0

    def test_batcher_taps_admitted_traffic(self):
        reg = _registry(1)
        shadow = ShadowSampler(every=2, slots=8)
        b = MicroBatcher(reg, buckets=(1, 2, 4), shadow=shadow,
                         max_delay_s=0.01)
        b.start()
        try:
            futs = [b.submit(np.full(DIM, float(i), np.float32))
                    for i in range(6)]
            for f in futs:
                f.result(10)
        finally:
            b.stop()
        rows = shadow.snapshot()
        assert len(rows) == 3             # arrivals 0, 2, 4


# -- divergence --------------------------------------------------------------

class TestDivergence:
    def test_argmax_heads(self):
        y1 = np.eye(4, dtype=np.float32)
        y2 = y1.copy()
        y2[0] = [0, 9, 0, 0]              # one row's argmax flips
        assert _divergence(y1, y1) == 0.0
        assert _divergence(y1, y2) == 0.25

    def test_scalar_outputs_use_relative_tolerance(self):
        y1 = np.ones((8, 1), np.float32) * 100
        assert _divergence(y1, y1 * (1 + 1e-6)) == 0.0
        assert _divergence(y1, y1 * 1.5) == 1.0

    def test_nonfinite_canary_rows_count_as_divergent(self):
        y1 = np.ones((4, 1), np.float32)
        y2 = y1.copy()
        y2[1] = np.nan
        assert _divergence(y1, y2) == 0.25


# -- the verdict matrix: each signal failing ALONE ---------------------------

class _FakeHealth:
    def __init__(self, round_idx, ok):
        self._h = {"round": round_idx,
                   "alarms": {"drift": {"value": 1.0, "threshold": 2.0,
                                        "ok": ok}}}

    def healthz(self):
        return self._h


class TestVerdictMatrix:
    def _shadowed(self, reg, rows=8):
        shadow = ShadowSampler(every=1, slots=rows)
        for i in range(rows):
            x = np.zeros(DIM, np.float32)
            x[0] = float(i + 1)
            shadow.offer(x)
        return shadow

    def test_all_pass_promotes(self):
        reg = _registry(1)
        rc = _controller(reg, shadow=self._shadowed(reg),
                         health=_FakeHealth(2, ok=True),
                         eval_fn=lambda p: 0.9)
        # same weights as live under a new version: zero divergence
        v = rc.offer(_params(1), 2, round_idx=2)
        assert v["decision"] == "promote" and reg.version == 2
        assert not any(s["vacuous"] for s in v["signals"].values())
        assert v["signals"]["shadow"]["divergence"] == 0.0

    def test_shadow_fails_alone(self):
        reg = _registry(1)
        rc = _controller(reg, shadow=self._shadowed(reg),
                         health=_FakeHealth(2, ok=True),
                         eval_fn=lambda p: 0.9, divergence_budget=0.0)
        # version-fingerprinted params argmax a different class per
        # version, so every shadow row diverges
        v = rc.offer(_params(2), 2, round_idx=2)
        assert v["decision"] == "rollback"
        assert v["failed_signals"] == ["shadow"]
        assert v["signals"]["shadow"]["divergence"] == 1.0
        assert reg.version == 1 and 2 not in reg.versions()

    def test_health_fails_alone(self):
        reg = _registry(1)
        rc = _controller(reg, health=_FakeHealth(2, ok=False),
                         eval_fn=lambda p: 0.9)
        v = rc.offer(_params(2), 2, round_idx=2)
        assert v["failed_signals"] == ["health"]
        assert reg.version == 1

    def test_eval_fails_alone(self):
        reg = _registry(1)
        scores = iter([0.9, 0.5])
        rc = _controller(reg, health=_FakeHealth(2, ok=True),
                         eval_fn=lambda p: next(scores))
        rc.offer(_params(2), 2, round_idx=2)     # promotes, baseline 0.9
        v = rc.offer(_params(3), 3, round_idx=3)
        assert v["failed_signals"] == ["eval"]
        assert v["signals"]["eval"]["baseline"] == 0.9
        assert reg.version == 2

    def test_eval_within_tolerance_promotes(self):
        reg = _registry(1)
        scores = iter([0.9, 0.89])
        rc = _controller(reg, eval_fn=lambda p: next(scores),
                         eval_tolerance=0.02)
        rc.offer(_params(2), 2, round_idx=2)
        v = rc.offer(_params(3), 3, round_idx=3)
        assert v["decision"] == "promote"

    def test_nonfinite_eval_fails(self):
        reg = _registry(1)
        rc = _controller(reg, eval_fn=lambda p: float("nan"))
        v = rc.offer(_params(2), 2, round_idx=2)
        assert v["failed_signals"] == ["eval"]

    def test_vacuous_passes_are_named(self):
        """No shadow traffic, no health record, no eval_fn: the gate
        degrades to availability but every vacuous pass is visible."""
        reg = _registry(1)
        rc = _controller(reg)
        v = rc.offer(_params(2), 2, round_idx=2)
        assert v["decision"] == "promote"
        assert all(s["vacuous"] for s in v["signals"].values())

    def test_health_round_mismatch_is_vacuous_and_named(self):
        reg = _registry(1)
        rc = _controller(reg, health=_FakeHealth(7, ok=False))
        v = rc.offer(_params(2), 2, round_idx=2)
        assert v["decision"] == "promote"   # alarm is for another round
        assert v["signals"]["health"]["vacuous"]
        assert v["signals"]["health"]["expected_round"] == 2

    def test_first_release_has_no_live_model_shadow_vacuous(self):
        reg = ModelRegistry(_linear_apply(), history=8)
        shadow = ShadowSampler(every=1, slots=4)
        shadow.offer(np.ones(DIM, np.float32))
        rc = _controller(reg, shadow=shadow)
        v = rc.offer(_params(1), 1, round_idx=1)
        assert v["decision"] == "promote"
        assert v["signals"]["shadow"]["vacuous"]  # nothing to diverge FROM

    def test_stale_version_is_refused(self):
        reg = _registry(1, 2)
        rc = _controller(reg)
        v = rc.offer(_params(2), 2, round_idx=2)
        assert v["decision"] == "stale" and reg.version == 2


# -- cooldown / backoff ------------------------------------------------------

class TestCooldownBackoff:
    def test_exponential_backoff_caps_and_resets(self):
        reg = _registry(1)
        clock = [0.0]
        rc = ReleaseController(reg, eval_fn=lambda p: float("nan"),
                               cooldown_s=5.0, backoff=2.0,
                               max_cooldown_s=15.0,
                               clock=lambda: clock[0])
        cooldowns = []
        for i, v in enumerate(range(2, 6)):
            verdict = rc.offer(_params(v), v, round_idx=v)
            assert verdict["decision"] == "rollback"
            cooldowns.append(verdict["cooldown_s"])
            clock[0] += 100.0             # wait out each cooldown
        assert cooldowns == [5.0, 10.0, 15.0, 15.0]   # 2x, capped

        rc.eval_fn = lambda p: 0.9
        clock[0] += 100.0
        assert rc.offer(_params(9), 9, round_idx=9)["decision"] == "promote"
        rc.eval_fn = lambda p: float("nan")
        v = rc.offer(_params(10), 10, round_idx=10)
        assert v["cooldown_s"] == 5.0     # success reset the ladder

    def test_cooldown_refuses_offers_without_publishing(self):
        reg = _registry(1)
        clock = [0.0]
        rc = ReleaseController(reg, eval_fn=lambda p: float("nan"),
                               cooldown_s=30.0, backoff=2.0,
                               max_cooldown_s=60.0,
                               clock=lambda: clock[0])
        rc.offer(_params(2), 2, round_idx=2)           # rollback, arms it
        rc.eval_fn = lambda p: 0.9
        v = rc.offer(_params(3), 3, round_idx=3)
        assert v["decision"] == "cooldown"
        assert 3 not in reg.versions()    # refused BEFORE canary publish
        clock[0] = 31.0
        assert rc.offer(_params(3), 3, round_idx=3)["decision"] == "promote"

    def test_invalid_config_refused(self):
        reg = _registry(1)
        with pytest.raises(ValueError):
            ReleaseController(reg, divergence_budget=1.5)
        with pytest.raises(ValueError):
            ReleaseController(reg, backoff=0.5)
        with pytest.raises(ValueError):
            ReleaseController(reg, cooldown_s=10.0, max_cooldown_s=1.0)


# -- crash consistency -------------------------------------------------------

class TestCrashConsistency:
    def _crc(self, reg):
        from fedml_tpu.utils.journal import tree_crc
        return tree_crc(reg.current().params)

    def test_kill_pre_promote_recovers_to_pre_state(self):
        reg = _registry(1)
        pre = self._crc(reg)
        fl = Faultline([CrashSpec("canary_promote", hit=1)])
        rc = _controller(reg, faultline=fl)
        with pytest.raises(ActorKilled):
            rc.offer(_params(2), 2, round_idx=2)
        # killed between verdict and swap: live is EXACTLY pre-state,
        # the canary lingers unresolved
        assert self._crc(reg) == pre and reg.canaries() == [2]
        fl.respawn()
        rc2 = _controller(reg, faultline=fl)
        r = rc2.recover()
        assert r["discarded"] == [2] and reg.canaries() == []
        assert self._crc(reg) == pre
        # the re-driven offer promotes (the spec fired once)
        assert rc2.offer(_params(2), 2,
                         round_idx=2)["decision"] == "promote"

    def test_kill_post_promote_recovers_to_post_state(self):
        reg = _registry(1)
        fl = Faultline([CrashSpec("canary_promote", hit=2)])
        rc = _controller(reg, faultline=fl)
        with pytest.raises(ActorKilled):
            rc.offer(_params(2), 2, round_idx=2)
        post = self._crc(reg)
        assert reg.version == 2           # swap landed before the kill
        from fedml_tpu.utils.journal import tree_crc
        assert post == tree_crc(_params(2))
        fl.respawn()
        rc2 = _controller(reg, faultline=fl)
        assert rc2.recover()["discarded"] == []   # nothing half-done
        # re-driving the same verdict is idempotent
        assert rc2.offer(_params(2), 2,
                         round_idx=2)["decision"] == "stale"
        assert self._crc(reg) == post

    def test_kill_around_rollback_never_serves_canary(self):
        reg = _registry(1)
        pre = self._crc(reg)
        for hit in (1, 2):
            fl = Faultline([CrashSpec("canary_rollback", hit=hit)])
            rc = _controller(reg, eval_fn=lambda p: float("nan"),
                             faultline=fl)
            with pytest.raises(ActorKilled):
                rc.offer(_params(2), 2, round_idx=2)
            assert self._crc(reg) == pre  # live never moved either way
            fl.respawn()
            _controller(reg).recover()
            assert reg.canaries() == []

    def test_release_journal_survives_disk_fault(self, tmp_path):
        reg = _registry(1)
        path = str(tmp_path / "release.jsonl")
        inj = DiskFaultInjector(
            [DiskFaultSpec("release_journal", hit=2, torn=True)]).install()
        try:
            rc = _controller(reg, journal_path=path)
            rc.offer(_params(2), 2, round_idx=2)
            rc.offer(_params(3), 3, round_idx=3)   # torn write: disables
            rc.offer(_params(4), 4, round_idx=4)
        finally:
            inj.remove()
        assert [v["decision"] for v in rc.verdicts] == ["promote"] * 3
        with open(path) as f:
            lines = f.read().splitlines()
        assert json.loads(lines[0])["version"] == 2
        assert len(lines) == 2            # line 2 is the torn tail
        with pytest.raises(json.JSONDecodeError):
            json.loads(lines[1])


# -- checkpoint watcher: torn/partial file hardening -------------------------

def _ck_state(i):
    rng = np.random.RandomState(i)
    return {"params": {"w": rng.randn(DIM, CLASSES).astype(np.float32),
                       "b": rng.randn(CLASSES).astype(np.float32)},
            "round_idx": np.asarray(i, np.int64)}


class TestWatcherManifest:
    def test_save_writes_manifest_and_watcher_verifies(self, tmp_path):
        from fedml_tpu.utils.checkpoint import (RoundCheckpointer,
                                                manifest_path)
        ck_dir = str(tmp_path / "ck")
        ck = RoundCheckpointer(ck_dir, save_every=1)
        ck.save(0, _ck_state(0))
        ck.close()
        m = json.load(open(manifest_path(ck_dir, 0)))
        assert m["step"] == 0 and m["algo"] == "crc32" and "params" in m["crc"]
        reg = ModelRegistry(_linear_apply(), history=8)
        w = CheckpointWatcher(reg, ck_dir, poll_s=0.05)
        assert w.poll_once() == 1 and reg.version == 0

    def test_crc_mismatch_skips_and_warns(self, tmp_path):
        from fedml_tpu.utils.checkpoint import (RoundCheckpointer,
                                                manifest_path)
        ck_dir = str(tmp_path / "ck")
        ck = RoundCheckpointer(ck_dir, save_every=1)
        ck.save(0, _ck_state(0))
        ck.save(1, _ck_state(1))
        ck.close()
        m = json.load(open(manifest_path(ck_dir, 1)))
        m["crc"]["params"] += 1           # simulate torn orbax payload
        with open(manifest_path(ck_dir, 1), "w") as f:
            json.dump(m, f)
        reg = ModelRegistry(_linear_apply(), history=8)
        w = CheckpointWatcher(reg, ck_dir, poll_s=0.05)
        assert w.poll_once() == 1         # step 1 skipped, step 0 served
        assert reg.version == 0
        assert w.poll_once() == 0         # skip is sticky, no spin

    def test_torn_manifest_skips_step(self, tmp_path):
        from fedml_tpu.utils.checkpoint import (RoundCheckpointer,
                                                manifest_path)
        ck_dir = str(tmp_path / "ck")
        ck = RoundCheckpointer(ck_dir, save_every=1)
        ck.save(0, _ck_state(0))
        ck.close()
        with open(manifest_path(ck_dir, 0), "w") as f:
            f.write('{"step": 0, "algo": "crc32", "crc": {"par')  # torn
        reg = ModelRegistry(_linear_apply(), history=8)
        w = CheckpointWatcher(reg, ck_dir, poll_s=0.05)
        assert w.poll_once() == 0 and reg.version is None

    def test_manifest_write_fault_falls_back_to_unverified(self, tmp_path):
        """ENOSPC on the manifest channel: the checkpoint itself stays
        durable and the watcher serves it on the legacy unverified path."""
        from fedml_tpu.utils.checkpoint import (RoundCheckpointer,
                                                manifest_path)
        ck_dir = str(tmp_path / "ck")
        inj = DiskFaultInjector(
            [DiskFaultSpec("checkpoint_manifest", hit=1)]).install()
        try:
            ck = RoundCheckpointer(ck_dir, save_every=1)
            ck.save(0, _ck_state(0))
            ck.close()
        finally:
            inj.remove()
        assert not os.path.exists(manifest_path(ck_dir, 0))
        reg = ModelRegistry(_linear_apply(), history=8)
        w = CheckpointWatcher(reg, ck_dir, poll_s=0.05)
        assert w.poll_once() == 1 and reg.version == 0

    def test_manifests_pruned_with_retention_gc(self, tmp_path):
        from fedml_tpu.utils.checkpoint import (MANIFEST_DIRNAME,
                                                RoundCheckpointer)
        ck_dir = str(tmp_path / "ck")
        ck = RoundCheckpointer(ck_dir, save_every=1, keep_last_n=2)
        for i in range(5):
            ck.save(i, _ck_state(i))
        ck.close()
        stems = sorted(int(n[:-5]) for n in
                       os.listdir(os.path.join(ck_dir, MANIFEST_DIRNAME)))
        assert stems == [3, 4]


# -- wave-summary poisoning (robust/adversary.py) ----------------------------

class TestWaveAdversary:
    def test_parse_spec(self):
        from fedml_tpu.robust.adversary import parse_wave_adversary_spec
        atks = parse_wave_adversary_spec("0:1:sign_flip,2:0:scale:50")
        assert set(atks) == {(0, 1), (2, 0)}
        assert atks[(2, 0)].kind == "scale" and atks[(2, 0)].param == 50.0
        for bad in ("1:sign_flip", "0:0:nope", "0:0:scale:x",
                    "0:0:scale,0:0:scale"):
            with pytest.raises(ValueError):
                parse_wave_adversary_spec(bad)

    def test_poison_kinds(self):
        from fedml_tpu.robust.adversary import (WaveAttack,
                                                poison_wave_summary)
        g = {"w": np.zeros(4, np.float32)}
        m = {"w": np.ones(4, np.float32)}
        flip = poison_wave_summary(WaveAttack(0, 0, "sign_flip", 1.0), m, g)
        np.testing.assert_allclose(flip["w"], -1.0)
        scale = poison_wave_summary(WaveAttack(0, 0, "scale", 10.0), m, g)
        np.testing.assert_allclose(scale["w"], 10.0)
        nan = poison_wave_summary(WaveAttack(0, 0, "nan_bomb", 1.0), m, g)
        assert np.isnan(nan["w"]).any()

    def test_gauss_is_seeded(self):
        from fedml_tpu.robust.adversary import (WaveAttack,
                                                poison_wave_summary)
        g = {"w": np.zeros(8, np.float32)}
        m = {"w": np.ones(8, np.float32)}
        atk = WaveAttack(1, 2, "gauss", 0.5)
        a = poison_wave_summary(atk, m, g, seed=3)
        b = poison_wave_summary(atk, m, g, seed=3)
        c = poison_wave_summary(atk, m, g, seed=4)
        np.testing.assert_array_equal(a["w"], b["w"])
        assert not np.array_equal(a["w"], c["w"])


# -- end-to-end: poisoned round contained before serving ---------------------

def _cross_device_fixture(**cfg_kw):
    from fedml_tpu.algorithms.cross_device import (CrossDevice,
                                                   CrossDeviceConfig)
    from fedml_tpu.data import load_data
    from fedml_tpu.experiments.models import create_workload, sample_shape_of
    data = load_data("mnist", data_dir=None, batch_size=4, num_clients=24,
                     seed=0)
    wl = create_workload("lr", "mnist", data.class_num,
                         sample_shape_of(data))
    cfg_kw.setdefault("comm_round", 3)
    cfg_kw.setdefault("client_num_per_round", 12)
    cfg_kw.setdefault("epochs", 1)
    cfg_kw.setdefault("batch_size", 4)
    cfg_kw.setdefault("wave_size", 6)
    cfg_kw.setdefault("seed", 0)
    cfg_kw.setdefault("frequency_of_the_test", 10)
    return data, wl, CrossDevice, CrossDeviceConfig(**cfg_kw)


def test_poisoned_round_rolled_back_before_serving():
    """The ISSUE 16 containment story, in miniature: a cross-device run
    publishes every round through the gate with real shadow traffic;
    the seeded poisoned round's version must never reach the live slot,
    and the clean rounds around it must promote.  (Clean rounds move
    ~1.6% of shadow argmaxes on this seed; the scale:1e6 poison moves
    ~97% — the 0.1 budget separates them with margin either way.)"""
    data, wl, CrossDevice, cfg = _cross_device_fixture(
        comm_round=4, wave_adversary="3:0:scale:1000000",
        admission="off")
    apply_fn = jax.jit(lambda p, x: wl.apply(p, x))
    reg = ModelRegistry(apply_fn, history=8)
    shadow = ShadowSampler(every=1, slots=64)
    xt = np.asarray(data.test["x"])
    for row in xt.reshape(-1, xt.shape[-1])[:64]:
        shadow.offer(row)

    rc = ReleaseController(reg, shadow=shadow, divergence_budget=0.1,
                           cooldown_s=0.0, max_cooldown_s=0.0)
    engine = CrossDevice(wl, data, cfg,
                         publish=lambda p, v: rc.offer(
                             jax.tree.map(np.asarray, p), v,
                             round_idx=v - 1))
    engine.run()
    decisions = {v["version"]: v["decision"] for v in rc.verdicts}
    assert decisions == {1: "promote", 2: "promote", 3: "promote",
                         4: "rollback"}, rc.verdicts
    poisoned = rc.verdicts[-1]
    assert poisoned["failed_signals"] == ["shadow"]
    assert poisoned["signals"]["shadow"]["divergence"] > 0.5
    assert 4 not in reg.versions()        # the poisoned global is GONE
    assert reg.version == 3               # serving stayed on clean
    for v in rc.verdicts:
        assert v.get("live_version") != 4  # never live, not for a moment


def test_wave_poison_requires_flag_and_is_exact_when_clean():
    """Without --wave_adversary the engine byte-matches the pre-ISSUE
    path (no attacks parsed, fold_wave untouched)."""
    data, wl, CrossDevice, cfg = _cross_device_fixture(comm_round=1)
    e = CrossDevice(wl, data, cfg)
    assert e._wave_attacks == {}
    import dataclasses as dc
    cfg2 = dc.replace(cfg, wave_adversary="0:0:sign_flip")
    e2 = CrossDevice(wl, data, cfg2)
    assert set(e2._wave_attacks) == {(0, 0)}


# -- config gates ------------------------------------------------------------

class TestConfigGates:
    def test_release_gate_requires_serve_port(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="--release_gate"):
            main(["--release_gate", "true"])

    def test_release_shadow_params_validated(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="release_shadow"):
            main(["--release_gate", "true", "--serve_port", "18099",
                  "--algo", "cross_silo", "--release_shadow_every", "0"])

    def test_wave_adversary_requires_cross_device(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="--wave_adversary"):
            main(["--wave_adversary", "0:0:sign_flip"])

    def test_adversary_on_cross_device_points_at_wave_adversary(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="--wave_adversary"):
            main(["--algo", "cross_device", "--adversary", "1:sign_flip"])
