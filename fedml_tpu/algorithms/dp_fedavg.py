"""DP-FedAvg (McMahan et al. 2018, arXiv:1710.06963) — user-level
differential privacy with a REAL accountant.

The reference's privacy story is "weak DP": per-update clip + Gaussian
noise with a bare stddev knob and no accounting whatsoever
(``fedml_core/robustness/robust_aggregation.py:38-55``; our parity port
is ``--algo fedavg_robust --defense weak_dp``).  This algorithm is the
honest version:

* per-client update Δ_k = θ_k − θ^t clipped to L2 norm ``dp_clip`` (S);
* UNIFORM average over the m live cohort slots — sample-weighted
  averaging (FedAvg's default) has unbounded per-user sensitivity and
  would void the guarantee, so it is deliberately NOT used here;
* one Gaussian draw with std ``S·z/m`` added to the averaged update
  (central model: the server is trusted, the released model sequence is
  what's protected), drawn from a dedicated fold_in stream so the
  training rng chain is untouched;
* SECRET cohort sampling: amplification-by-subsampling assumes the
  adversary cannot tell which users joined a round, so the framework's
  default deterministic, PUBLIC sampling chain
  (core/sampling.sample_clients — the reference's seeded
  client_sampling, identical across all runs) would void the theorem.
  ``_sample_round`` is overridden to draw each cohort from the run rng
  (without replacement; full participation falls back to the exact
  arange, keeping the FedAvg parity case bit-identical);
* an RDP moments accountant (core/privacy.py) composes the subsampled
  Gaussian over rounds with q = cohort/N and reports ε at ``dp_delta``
  in every eval row — the number the reference never computes.  By
  default the accountant uses the fixed-size without-replacement bound
  (``dp_accounting="fixed_size"``) — a rigorous bound that APPLIES to
  the sampler actually used (choice without replacement, replace-one
  adjacency), which the Poisson analysis does not;
  ``dp_accounting="poisson"`` selects the literature-standard Poisson
  approximation instead (optimistic for this sampler — documented in
  core/privacy.py).

The whole defended round stays ONE jit: the per-client clip, the noisy
uniform mean, and the single central noise draw are fused into the
custom ``needs_global`` aggregate (``make_dp_aggregate``) that replaces
the cohort engine's default weighted mean (parallel/cohort.py) — NOT the
``transform_update`` hook, which transforms each client's params but
cannot change the weighting or add one shared draw.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.core.privacy import RdpAccountant
from fedml_tpu.parallel.cohort import make_cohort_step

# distinct fold_in streams: the DP noise draw ("DPNZ") and the secret
# cohort-sampling chain ("DPSG")
_NOISE_STREAM = 0x44504E5A
_SAMPLE_STREAM = 0x44505347


@dataclasses.dataclass
class DPFedAvgConfig(FedAvgConfig):
    dp_clip: float = 1.0             # S: per-user update L2 bound
    dp_noise_multiplier: float = 1.0  # z: noise std = S·z/m on the mean
    dp_delta: float = 1e-5           # δ for the reported ε
    # "fixed_size": rigorous bound for the fixed-size without-
    # replacement sampler actually used (WBK'19, replace-one adjacency);
    # "poisson": the literature-standard approximation (core/privacy.py)
    dp_accounting: str = "fixed_size"


def make_dp_aggregate(clip: float, noise_multiplier: float,
                      psum_axis=None):
    """``aggregate(stacked, weights, global_params, rng)`` — clip each
    client's update, uniform-mean the live slots, add one central
    Gaussian draw calibrated to sensitivity S/m.

    ``psum_axis``: when the cohort is sharded over a mesh axis, the
    per-client clip stays shard-local, the live count and mean cross the
    axis via psum, and the noise key is identical on every device (rng
    is replicated), so the ONE central draw replicates exactly — mesh
    and single-chip runs match even with noise on (parity-tested)."""

    def allsum(v):
        return jax.lax.psum(v, psum_axis) if psum_axis is not None else v

    def aggregate(stacked, weights, global_params, rng):
        live = (weights > 0).astype(jnp.float32)
        m = jnp.maximum(allsum(jnp.sum(live)), 1.0)
        deltas = jax.tree.map(lambda y, x: y - x[None], stacked,
                              global_params)
        # per-client global L2 norm across the whole pytree -> [C]
        sq = sum(jnp.sum(jnp.square(d.astype(jnp.float32)),
                         axis=tuple(range(1, d.ndim)))
                 for d in jax.tree.leaves(deltas))
        scale = jnp.minimum(1.0, clip / jnp.maximum(jnp.sqrt(sq), 1e-12))
        scale = scale * live  # padded slots contribute nothing

        def _mean(d):
            s = scale.reshape((-1,) + (1,) * (d.ndim - 1)).astype(d.dtype)
            return allsum(jnp.sum(d * s, axis=0)) / m.astype(d.dtype)

        mean_delta = jax.tree.map(_mean, deltas)
        nrng = jax.random.fold_in(rng, _NOISE_STREAM)
        leaves, treedef = jax.tree.flatten(mean_delta)
        keys = jax.random.split(nrng, len(leaves))
        std = clip * noise_multiplier / m
        noisy = [d + (std * jax.random.normal(k, d.shape)).astype(d.dtype)
                 for d, k in zip(leaves, keys)]
        mean_delta = jax.tree.unflatten(treedef, noisy)
        return jax.tree.map(lambda x, d: x + d, global_params, mean_delta)

    aggregate.needs_global = True
    return aggregate


class DPFedAvg(FedAvg):
    def __init__(self, workload, data, config: DPFedAvgConfig, mesh=None,
                 sink=None):
        if config.dp_clip <= 0.0:
            raise ValueError("dp_clip must be > 0")
        if config.dp_noise_multiplier < 0.0:
            raise ValueError("dp_noise_multiplier must be >= 0 "
                             "(0 = clipped, non-private FedAvg)")
        if config.dp_accounting not in ("fixed_size", "poisson"):
            raise ValueError(
                f"unknown dp_accounting {config.dp_accounting!r}; use "
                "'fixed_size' (valid for the sampler used) or 'poisson' "
                "(literature approximation)")
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        cfg = config
        # the base class already built the local trainer; only the
        # aggregate differs (clipped uniform mean + central noise)
        if mesh is None:
            self.cohort_step = make_cohort_step(
                self._local_train,
                aggregate=make_dp_aggregate(cfg.dp_clip,
                                            cfg.dp_noise_multiplier),
                client_axis=cfg.client_axis)
        else:
            from jax.sharding import PartitionSpec as P
            from fedml_tpu.parallel.cohort import (
                make_sharded_stateful_round, train_cohort)
            local_train = self._local_train

            def _core(params, cohort, rng, psum_axis=None,
                      index_offset=0):
                stacked, metrics = train_cohort(
                    local_train, params, cohort, rng,
                    index_offset=index_offset,
                    client_axis=cfg.client_axis)
                # aggregate built from the wrapper's axis, so the mesh
                # convention stays defined in ONE place (cohort.py)
                dp_agg = make_dp_aggregate(cfg.dp_clip,
                                           cfg.dp_noise_multiplier,
                                           psum_axis=psum_axis)
                return dp_agg(stacked, cohort["num_samples"], params,
                              rng), metrics

            self.cohort_step = make_sharded_stateful_round(
                _core, mesh,
                in_specs=(P(), P("clients"), P()),
                out_specs=(P(), P("clients")))
        # q for the cohort fraction; z=0 yields eps=inf — reported
        # honestly, not hidden.  The analysis matches the config:
        # fixed_size = valid bound for the choice(replace=False) sampler,
        # poisson = the documented approximation (core/privacy.py)
        q = min(cfg.client_num_per_round, data.client_num) \
            / data.client_num
        self.accountant = RdpAccountant(
            q, cfg.dp_noise_multiplier, cfg.dp_delta,
            sampling=("fixed_size_wor" if cfg.dp_accounting == "fixed_size"
                      else "poisson"))
        base_step = self.cohort_step

        def counted_step(params, cohort, rng):
            out = base_step(params, cohort, rng)
            self.accountant.step()
            return out

        self.cohort_step = counted_step

    def run(self, params=None, rng=None, checkpointer=None):
        self.accountant.steps = 0
        # secret sampling chain, derived from the run rng BEFORE the base
        # loop consumes it (resume replays the same rng -> same cohorts)
        rng = rng if rng is not None else jax.random.key(self.cfg.seed)
        self._sample_base = jax.random.fold_in(rng, _SAMPLE_STREAM)
        return super().run(params=params, rng=rng,
                           checkpointer=checkpointer)

    def _sample_round(self, round_idx: int):
        """SECRET cohorts (see module docstring): drawn without
        replacement from the run rng, not the public round-index chain.
        Full participation needs no subsampling — the exact arange keeps
        the z=0 FedAvg parity case bit-identical."""
        n = self.data.client_num
        m = min(self.cfg.client_num_per_round, n)
        if m >= n:
            return np.arange(n)
        key = jax.random.fold_in(self._sample_base, round_idx)
        return np.asarray(jax.random.choice(key, n, (m,), replace=False))

    def evaluate_global(self, params) -> Dict[str, float]:
        out = super().evaluate_global(params)
        out["dp_epsilon"] = self.accountant.epsilon()
        out["dp_delta"] = self.accountant.delta
        return out

    # the accountant's round count AND the secret sampling chain ride the
    # checkpoint: a resumed run keeps reporting the TOTAL privacy spent,
    # and post-resume cohorts continue the ORIGINAL run's secret schedule
    # even if run() is resumed with a different rng argument (advisor r4:
    # re-deriving _sample_base from the resume rng would silently fork
    # the cohort schedule while the accountant composes as one run).
    # Typed keys pass through as-is — RoundCheckpointer packs/unpacks
    # them (utils/checkpoint.py _pack_keys).
    def _extra_state(self):
        return {"dp_rounds": self.accountant.steps,
                "sample_base": self._sample_base}

    def _extra_state_template(self, params):
        t = {"dp_rounds": 0}
        if not getattr(self, "_legacy_extra", False):
            t["sample_base"] = jax.random.key(0)
        return t

    def _load_extra_state(self, extra) -> None:
        self.accountant.steps = int(extra["dp_rounds"])
        if "sample_base" in extra:
            self._sample_base = extra["sample_base"]
        # legacy checkpoint (pre sample_base): keep the chain run()
        # derived from the rng argument — the pre-change behavior,
        # correct when resume passes the original run's rng

    def _maybe_resume(self, checkpointer, params, rng):
        try:
            return super()._maybe_resume(checkpointer, params, rng)
        except Exception as e:
            # only the legacy-layout mismatch earns the retry: an
            # unrelated restore failure (shape change, corrupt write)
            # must surface as ITSELF, not as a misleading sample_base
            # structure error from the legacy-template attempt
            if (checkpointer is None or checkpointer.latest_round() is None
                    or "sample_base" not in str(e)):
                raise
            # migration: a pre-change checkpoint has no sample_base entry
            # and fails the new restore template — retry with the legacy
            # template and fall back to the rng-derived chain
            self._legacy_extra = True
            try:
                out = super()._maybe_resume(checkpointer, params, rng)
            finally:
                self._legacy_extra = False
            logging.getLogger(__name__).warning(
                "resumed a legacy dp_fedavg checkpoint (no sample_base): "
                "the secret cohort schedule is re-derived from the rng "
                "argument — pass the ORIGINAL run's rng or cohorts fork")
            return out
