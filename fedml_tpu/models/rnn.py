"""LSTM language models (parity: fedml_api/model/nlp/rnn.py:4-70).

Implemented with `flax.linen.RNN` over `OptimizedLSTMCell` — under jit the
recurrence compiles to a `lax.scan`, which XLA pipelines on TPU.  Zero
initial hidden state per batch, exactly as the reference notes
(rnn.py:26-29)."""

import flax.linen as nn
import jax.numpy as jnp


class RNNOriginalFedAvg(nn.Module):
    """Shakespeare next-char model (rnn.py:4-36): embed(8) -> 2x LSTM(256)
    -> dense(vocab) on the final hidden state."""
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, input_seq, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embedding_dim)(input_seq)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(x)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(x)
        final_hidden = x[:, -1]
        return nn.Dense(self.vocab_size)(final_hidden)


class RNNStackOverflow(nn.Module):
    """StackOverflow next-word model (rnn.py:39-70): embed(96) -> LSTM(670)
    -> dense(96) -> dense(extended_vocab); per-position logits.

    Returns [B, T, V] (time-major logits transposed the torch way is [B, V, T];
    our loss consumes [B, T, V] directly)."""
    vocab_size: int = 10000
    num_oov_buckets: int = 1
    embedding_size: int = 96
    latent_size: int = 670
    num_layers: int = 1

    @nn.compact
    def __call__(self, input_seq, train: bool = False):
        extended_vocab = self.vocab_size + 3 + self.num_oov_buckets
        x = nn.Embed(extended_vocab, self.embedding_size)(input_seq)
        for _ in range(self.num_layers):
            x = nn.RNN(nn.OptimizedLSTMCell(self.latent_size))(x)
        x = nn.Dense(self.embedding_size)(x)
        return nn.Dense(extended_vocab)(x)
