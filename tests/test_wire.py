"""The wire hot path: zero-copy codec, encode-once fan-out, incremental
cohort staging — the PR-5 acceptance pins.

* golden-frame interop: the NEW encoder's frames are byte-identical to
  the seed encoder's, and each decoder accepts the other's frames (the
  seed codec is reimplemented verbatim here as the oracle);
* round-trip property over the nasty leaves (0-d, non-contiguous, bool,
  int8-quantized, empty) through BOTH the single-send and the
  ``send_many`` shared-payload paths;
* the encode-once pin: a ``send_many`` fan-out performs EXACTLY ONE
  shared-payload serialization (codec spy counter);
* torn/truncated frames raise ``ValueError`` from every decode entry and
  never kill a transport receive thread;
* incremental staging + donation: bit-identical to the seed
  stack-at-the-barrier path, with the defended jit still compiling once.
"""

import json
import logging
import struct
import threading
import warnings

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                             FedAvgServerActor, MsgType)
from fedml_tpu.comm import message as message_mod
from fedml_tpu.comm.chaos import ChaosPlan, ChaosTransport, LinkChaos
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import (CODEC_COUNTS, Message, SharedPayload,
                                    build_fanout)
from fedml_tpu.comm.resilient import ResilientTransport, RetryPolicy
from fedml_tpu.robust.defense import make_defended_aggregate

_HDR = struct.Struct("<I")


# ---------------------------------------------------------------------------
# the seed codec, reimplemented verbatim (message.py @ PR 4) as the
# golden-frame oracle
# ---------------------------------------------------------------------------

def seed_to_bytes(msg: Message) -> bytes:
    header = {"plain": {}, "arrays": {}}
    buffers = []
    for key, value in msg.params.items():
        leaves, spec = message_mod._flatten_arrays(value)
        if leaves is None:
            header["plain"][key] = value
        else:
            descr = []
            for leaf in leaves:
                src = np.asarray(leaf)
                arr = np.ascontiguousarray(src)
                descr.append({"dtype": arr.dtype.str, "shape": src.shape,
                              "idx": len(buffers)})
                buffers.append(arr)
            header["arrays"][key] = {"spec": spec, "leaves": descr}
    hdr = json.dumps(header).encode()
    parts = [_HDR.pack(len(hdr)), hdr]
    for arr in buffers:
        parts.append(_HDR.pack(arr.nbytes))
        parts.append(arr.tobytes())
    return b"".join(parts)


def seed_from_bytes(data: bytes) -> Message:
    (hlen,) = _HDR.unpack_from(data, 0)
    header = json.loads(data[_HDR.size:_HDR.size + hlen])
    offset = _HDR.size + hlen
    buffers = []
    while offset < len(data):
        (n,) = _HDR.unpack_from(data, offset)
        offset += _HDR.size
        buffers.append(data[offset:offset + n])
        offset += n
    msg = Message.__new__(Message)
    msg._shared = None
    msg.params = dict(header["plain"])
    for key, info in header["arrays"].items():
        leaves = []
        for d in info["leaves"]:
            arr = np.frombuffer(buffers[d["idx"]], dtype=np.dtype(d["dtype"]))
            leaves.append(arr.reshape(d["shape"]))
        msg.params[key] = message_mod._unflatten_arrays(info["spec"], leaves)
    return msg


def _edge_tree(seed=0):
    """Every leaf shape the satellite names: 0-d, non-contiguous, bool,
    int8-quantized, empty — plus ordinary dense layers."""
    rng = np.random.RandomState(seed)
    return {
        "dense": {"kernel": rng.randn(16, 8).astype(np.float32),
                  "bias": rng.randn(8).astype(np.float32)},
        "zero_d": np.float32(3.25),
        "noncontig": rng.randn(6, 6).T,
        "strided": np.arange(20)[::2],
        "flags": np.array([True, False, True]),
        "quantized": {"codes": rng.randint(-128, 128, (32,)).astype(np.int8),
                      "scale": np.float64(0.017)},
        "empty": np.zeros((0, 4), np.float32),
        "half": rng.randn(5).astype(np.float16),
        "mixed": [np.int64(9), ("tag", np.ones((2, 2)))],
    }


def _assert_tree_equal(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and a.shape == b.shape, (a, b)
        np.testing.assert_array_equal(a, b)
    elif isinstance(a, dict):
        assert set(a) == set(b)
        for k in a:
            _assert_tree_equal(a[k], b[k])
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _assert_tree_equal(x, y)
    else:
        assert a == b


def _payload_msg(tree, msg_type=3, sender=1, receiver=0):
    return (Message(msg_type, sender, receiver)
            .add(Message.ARG_MODEL_PARAMS, tree)
            .add(Message.ARG_NUM_SAMPLES, 12)
            .add(Message.ARG_ROUND, 4))


class TestGoldenFrame:
    def test_new_encoder_is_byte_identical_to_seed(self):
        msg = _payload_msg(_edge_tree())
        assert msg.to_bytes() == seed_to_bytes(msg)

    def test_cross_decoding_both_directions(self):
        msg = _payload_msg(_edge_tree(1))
        via_old = seed_from_bytes(msg.to_bytes())
        via_new = Message.from_bytes(seed_to_bytes(msg))
        for out in (via_old, via_new):
            _assert_tree_equal(out.get(Message.ARG_MODEL_PARAMS),
                               msg.get(Message.ARG_MODEL_PARAMS))
            assert out.get(Message.ARG_NUM_SAMPLES) == 12

    def test_seed_decoder_accepts_send_many_frames(self):
        """A fan-out frame (shared block + spliced header) must decode on
        an OLD node: old/new interop is per-frame, not per-path."""
        tree = _edge_tree(2)
        msgs = build_fanout(1, 0, [1, 2],
                            {Message.ARG_MODEL_PARAMS: tree,
                             Message.ARG_ROUND: 7},
                            {1: {Message.ARG_CLIENT_INDEX: 4},
                             2: {Message.ARG_CLIENT_INDEX: 5}})
        for msg, idx in zip(msgs, (4, 5)):
            out = seed_from_bytes(msg.to_bytes())
            _assert_tree_equal(out.get(Message.ARG_MODEL_PARAMS), tree)
            assert out.get(Message.ARG_CLIENT_INDEX) == idx
            assert out.get(Message.ARG_ROUND) == 7


class TestRoundTripProperty:
    @pytest.mark.parametrize("path", ["single", "fanout_bytes",
                                      "fanout_parts"])
    def test_edge_leaves_roundtrip(self, path):
        for seed in range(5):
            tree = _edge_tree(seed)
            if path == "single":
                out = Message.from_bytes(_payload_msg(tree).to_bytes())
            else:
                (msg,) = build_fanout(
                    3, 1, [0], {Message.ARG_MODEL_PARAMS: tree},
                    {0: {Message.ARG_NUM_SAMPLES: 12}})
                if path == "fanout_bytes":
                    out = Message.from_bytes(msg.to_bytes())
                else:
                    out = Message.from_frame_parts(msg.frame_parts())
            _assert_tree_equal(out.get(Message.ARG_MODEL_PARAMS), tree)

    def test_decode_is_zero_copy_readonly_views(self):
        frame = _payload_msg(_edge_tree()).to_bytes()
        out = Message.from_bytes(frame)
        kernel = out.get(Message.ARG_MODEL_PARAMS)["dense"]["kernel"]
        assert not kernel.flags.writeable  # frames are immutable
        assert np.shares_memory(kernel, np.frombuffer(frame, np.uint8))

    def test_encode_pays_one_copy_per_contiguous_leaf(self):
        tree = {"a": np.ones((64, 64), np.float32),
                "b": np.ones(64, np.float32)}
        before = CODEC_COUNTS["leaf_copies"]
        Message(1, 0, 1).add("p", tree).to_bytes()
        assert CODEC_COUNTS["leaf_copies"] - before == 2


class TestEncodeOncePin:
    def test_send_many_serializes_shared_payload_exactly_once(self):
        """THE acceptance pin: an 8-silo fan-out costs ONE payload encode
        (the seed path cost eight)."""
        tree = _edge_tree()
        before = CODEC_COUNTS["payload_encodes"]
        msgs = build_fanout(1, 0, range(1, 9),
                            {Message.ARG_MODEL_PARAMS: tree},
                            {r: {Message.ARG_CLIENT_INDEX: r}
                             for r in range(1, 9)})
        frames = [m.to_bytes() for m in msgs]
        assert CODEC_COUNTS["payload_encodes"] - before == 1
        # and every frame still decodes to its own receiver's view
        for r, frame in enumerate(frames, start=1):
            out = Message.from_bytes(frame)
            assert out.get(Message.ARG_CLIENT_INDEX) == r
            _assert_tree_equal(out.get(Message.ARG_MODEL_PARAMS), tree)

    def test_server_broadcast_is_encode_once_over_the_hub(self):
        """The live path: a FedAvg round over the codec-roundtrip hub
        pays one payload encode per broadcast, not one per silo."""
        hub = LocalHub(codec_roundtrip=True)
        init = {"dense": {"kernel": np.ones((8, 4), np.float32),
                          "bias": np.zeros(4, np.float32)}}

        def train_fn(params, client_idx, round_idx):
            return jax.tree.map(lambda v: np.asarray(v), params), 10

        server = FedAvgServerActor(hub.transport(0), init, 4, 4, 1)
        silos = [FedAvgClientActor(i, hub.transport(i), train_fn)
                 for i in range(1, 5)]
        server.register_handlers()
        for s in silos:
            s.register_handlers()
        before = CODEC_COUNTS["payload_encodes"]
        server.start()  # round-0 broadcast to 4 silos
        # one broadcast encode; each silo's UPLOAD is its own single
        # encode (4), plus nothing else before the pump
        assert CODEC_COUNTS["payload_encodes"] - before == 1
        hub.pump()
        assert server.round_idx == 1

    def test_chaos_corruption_never_mutates_a_sibling_frame(self):
        """Copy-on-corrupt across a shared payload: the corrupted silo's
        frame is rebuilt privately; its siblings' frames and the shared
        block stay byte-identical."""
        tree = {"w": np.zeros((64,), np.float32)}
        hub = LocalHub(codec_roundtrip=True)
        received = {}

        class Collect:
            def __init__(self, node):
                self.node = node

            def receive_message(self, msg_type, msg):
                received[self.node] = msg.get("model_params")["w"]

        transports = {}
        for i in (1, 2):
            t = hub.transport(i)
            t.add_observer(Collect(i))
            transports[i] = t
        plan = ChaosPlan(seed=3, links={(0, 1): LinkChaos(corrupt_prob=1.0)})
        chaotic = ChaosTransport(hub.transport(0), plan)
        msgs = build_fanout(1, 0, [1, 2], {"model_params": tree})
        chaotic.send_many(msgs)
        hub.pump()
        assert not np.array_equal(received[1], tree["w"])  # corrupted
        np.testing.assert_array_equal(received[2], tree["w"])  # untouched
        # the shared source tree itself was never mutated
        np.testing.assert_array_equal(tree["w"], np.zeros(64, np.float32))

    def test_send_many_through_resilient_retries_per_link(self):
        """Per-link retry semantics survive the fan-out: one silo's flaky
        channel retries alone; everyone is delivered exactly once."""
        hub = LocalHub()
        got = []

        class Collect:
            def __init__(self, node):
                self.node = node

            def receive_message(self, msg_type, msg):
                got.append(self.node)

        for i in (1, 2, 3):
            hub.transport(i).add_observer(Collect(i))
        inner = hub.transport(0)
        fails = {"n": 0}
        real_send = inner.send_message

        def flaky(msg):
            if msg.receiver_id == 2 and fails["n"] < 2:
                fails["n"] += 1
                raise ConnectionError("flaky link to silo 2")
            real_send(msg)

        inner.send_message = flaky
        resilient = ResilientTransport(
            inner, RetryPolicy(max_attempts=5, base_backoff_s=0.01,
                               jitter_frac=0.0))
        import time as _t
        try:
            resilient.send_many(build_fanout(
                1, 0, [1, 2, 3], {"model_params": {"w": np.ones(8)}}))
            for _ in range(500):  # sender thread drains asynchronously
                if resilient.sent_ok >= 3:
                    break
                _t.sleep(0.01)
            hub.pump()
        finally:
            resilient.stop()
        assert sorted(got) == [1, 2, 3]
        assert resilient.retries == 2 and resilient.dead_letters == 0

    def test_wire_bytes_counters_match_frames(self):
        """PR-3 semantics hold on the fan-out path: the hub's wire-bytes
        counter per link equals that receiver's standalone frame size."""
        from fedml_tpu.obs import telemetry
        reg = telemetry.enable(telemetry.TelemetryRegistry())
        try:
            hub = LocalHub(codec_roundtrip=True)
            for i in (1, 2):
                hub.transport(i).add_observer(
                    type("N", (), {"receive_message":
                                   lambda self, t, m: None})())
            sender = hub.transport(0)
            msgs = build_fanout(1, 0, [1, 2],
                                {"model_params": _edge_tree()},
                                {1: {Message.ARG_CLIENT_INDEX: 1},
                                 2: {Message.ARG_CLIENT_INDEX: 2}})
            expected = {m.receiver_id: len(m.to_bytes()) for m in msgs}
            sender.send_many(msgs)
            hub.pump()
            snap = reg.snapshot()["counters"]
            for r, nbytes in expected.items():
                key = 'fedml_comm_wire_bytes_total{link="0->%d"}' % r
                assert snap[key] == nbytes, (key, snap)
        finally:
            telemetry.disable()


class TestTornFrames:
    def test_truncations_raise_value_error(self):
        frame = _payload_msg(_edge_tree()).to_bytes()
        cuts = [0, 2, _HDR.size, len(frame) // 2, len(frame) - 1]
        for cut in cuts:
            with pytest.raises(ValueError):
                Message.from_bytes(frame[:cut])

    def test_garbage_and_header_damage_raise_value_error(self):
        frame = bytearray(_payload_msg(_edge_tree()).to_bytes())
        with pytest.raises(ValueError):
            Message.from_bytes(b"\xff" * 64)          # not a frame at all
        frame[6] ^= 0xFF                               # damage header JSON
        with pytest.raises(ValueError):
            Message.from_bytes(bytes(frame))
        with pytest.raises(ValueError):                # huge declared hlen
            Message.from_bytes(_HDR.pack(2 ** 30) + b"xx")

    def test_bad_buffer_index_and_dtype_mismatch_raise(self):
        # header says idx 7, only 1 buffer arrives
        hdr = json.dumps({"plain": {}, "arrays": {
            "p": {"spec": {"k": "leaf"},
                  "leaves": [{"dtype": "<f4", "shape": [2], "idx": 7}]}}}
        ).encode()
        frame = _HDR.pack(len(hdr)) + hdr + _HDR.pack(8) + b"\0" * 8
        with pytest.raises(ValueError):
            Message.from_bytes(frame)
        # declared shape disagrees with the delivered byte count
        hdr = json.dumps({"plain": {}, "arrays": {
            "p": {"spec": {"k": "leaf"},
                  "leaves": [{"dtype": "<f4", "shape": [5], "idx": 0}]}}}
        ).encode()
        frame = _HDR.pack(len(hdr)) + hdr + _HDR.pack(8) + b"\0" * 8
        with pytest.raises(ValueError):
            Message.from_bytes(frame)

    def test_grpc_receive_thread_survives_torn_frame(self):
        grpc = pytest.importorskip("grpc")
        from fedml_tpu.comm.grpc_transport import (_METHOD, _SERVICE,
                                                   GrpcTransport)
        table = {0: "127.0.0.1", 1: "127.0.0.1"}
        a = GrpcTransport(0, table, base_port=56510)
        b = GrpcTransport(1, table, base_port=56510)
        try:
            got = []

            class Collect:
                def receive_message(self, msg_type, msg):
                    got.append(msg_type)
                    b.stop()

            b.add_observer(Collect())
            # fire a torn frame straight at node 1's RPC endpoint
            channel = grpc.insecure_channel("127.0.0.1:56511")
            call = channel.unary_unary(f"/{_SERVICE}/{_METHOD}",
                                       request_serializer=lambda x: x,
                                       response_deserializer=lambda x: x)
            call(b"\xde\xad\xbe\xef" * 3, timeout=10)
            channel.close()
            # the receive loop is alive: a valid frame still delivers
            a.send_message(_payload_msg({"w": np.ones(4, np.float32)},
                                        sender=0, receiver=1))
            b.run()
            assert got == [3]
        finally:
            a.stop()
            b.stop()

    def test_mqtt_callback_survives_torn_frame(self):
        import types
        from fedml_tpu.comm import mqtt_transport as mt
        from fedml_tpu.comm.mqtt_broker import MqttBroker
        with MqttBroker() as broker:
            t = mt.MqttTransport(0, "127.0.0.1", broker.port)
            try:
                t._on_message(None, None, types.SimpleNamespace(
                    topic="fedml_tpu/0", payload=b"\xff" * 9))
                assert t._inbox.empty()  # dropped, no exception
            finally:
                t.stop()


# ---------------------------------------------------------------------------
# incremental staging + donation
# ---------------------------------------------------------------------------

def _drift_train_fn(delta):
    def fn(params, client_idx, round_idx):
        return (jax.tree.map(
            lambda v: np.asarray(v) + np.float32(delta * (client_idx + 1)),
            params), 10 * (client_idx + 1))
    return fn


def _run_federation(encode_once, staging, n_silos=4, rounds=3,
                    defended=None, straggler=False):
    hub = LocalHub(codec_roundtrip=True)
    init = {"dense": {"kernel": np.ones((8, 4), np.float32),
                      "bias": np.zeros(4, np.float32)}}
    defended = defended or make_defended_aggregate("mean", norm_clip=5.0)
    server = FedAvgServerActor(
        hub.transport(0), init, n_silos, n_silos, rounds,
        aggregate_fn=defended, encode_once=encode_once,
        incremental_staging=staging,
        straggler_policy="drop" if straggler else "wait",
        round_timeout_s=0.2 if straggler else None,
        min_silo_frac=0.5 if straggler else 0.5)
    server.register_handlers()
    silos = []
    for i in range(1, n_silos + 1):
        if straggler and i == n_silos:
            class Deaf(FedAvgClientActor):
                def register_handlers(self):
                    self.register_handler(MsgType.S2C_FINISH,
                                          lambda m: self.finish())
            silo = Deaf(i, hub.transport(i), _drift_train_fn(0.01))
        else:
            silo = FedAvgClientActor(i, hub.transport(i),
                                     _drift_train_fn(0.01))
        silos.append(silo)
    for s in silos:
        s.register_handlers()
    if straggler:
        threads = [threading.Thread(target=s.run, daemon=True)
                   for s in silos]
        for th in threads:
            th.start()
        server.start()
        server.transport.run()
        for th in threads:
            th.join(timeout=5)
    else:
        server.start()
        hub.pump()
    assert server.round_idx == rounds
    return jax.tree.map(np.asarray, server.params), server


class TestIncrementalStaging:
    def test_staged_path_matches_seed_stacking_bitwise(self):
        seed_params, _ = _run_federation(encode_once=False, staging=False)
        new_params, server = _run_federation(encode_once=True, staging=True)
        jax.tree.map(np.testing.assert_array_equal, seed_params, new_params)
        # staging ran for every silo every round, and the cohort buffer
        # was RELEASED at round close (RSS returns to baseline between
        # rounds instead of pinning the cohort watermark)
        assert server._staged_seen == 3 * 4
        assert server._staging is None and not server._staged

    def test_staged_path_matches_seed_with_straggler_dropped(self):
        """A dropped silo's slot refills with the global at weight 0 —
        identical to the seed path's stack of the same cohort."""
        seed_params, s1 = _run_federation(encode_once=False, staging=False,
                                          straggler=True)
        new_params, s2 = _run_federation(encode_once=True, staging=True,
                                         straggler=True)
        assert s1.dropped_silos == s2.dropped_silos
        jax.tree.map(np.testing.assert_array_equal, seed_params, new_params)

    def test_jit_once_pin_with_donation_and_staging(self):
        """Acceptance: _cache_size() == 1 across rounds with donation ON
        and incremental staging enabled."""
        with warnings.catch_warnings():
            # CPU backends warn that donation is unimplemented; the pin
            # under test is the trace-cache size, which donation must not
            # perturb on any backend
            warnings.simplefilter("ignore")
            fn = make_defended_aggregate("mean", norm_clip=5.0, donate=True)
            _, server = _run_federation(encode_once=True, staging=True,
                                        rounds=4, defended=fn)
        assert fn._cache_size() == 1
        assert server.round_idx == 4

    def test_host_mirror_shared_across_round_consumers(self):
        """broadcast/checkpoint/staging-fill read ONE device→host copy
        per params value."""
        init = {"w": np.ones(4, np.float32)}
        hub = LocalHub()
        server = FedAvgServerActor(hub.transport(0), init, 2, 2, 3,
                                   aggregate_fn=make_defended_aggregate(
                                       "mean"))
        h1 = server._host_params()
        assert server._host_params() is h1  # memoized
        server.params = {"w": np.zeros(4, np.float32)}
        assert server._host_params() is not h1  # invalidated by identity

    def test_staging_rejects_dtype_drift_loudly(self):
        """A matching treedef with a drifted leaf dtype must fail loudly,
        never silently cast into the template-typed staging buffer."""
        init = {"w": np.ones(4, np.float32)}
        hub = LocalHub()
        server = FedAvgServerActor(hub.transport(0), init, 2, 2, 1,
                                   aggregate_fn=make_defended_aggregate(
                                       "mean"))
        server._num_silos = 2
        with pytest.raises(ValueError, match="dtype"):
            server._stage(1, {"w": np.ones(4, np.float64)})

    def test_build_fanout_rejects_shared_key_override(self):
        with pytest.raises(ValueError, match="override shared"):
            build_fanout(1, 0, [1, 2],
                         {Message.ARG_ROUND: 5},
                         {2: {Message.ARG_ROUND: 6}})

    def test_staging_gauge_tracks_arrivals(self):
        from fedml_tpu.obs import telemetry
        reg = telemetry.enable(telemetry.TelemetryRegistry())
        try:
            _, server = _run_federation(encode_once=True, staging=True,
                                        rounds=2)
            snap = reg.snapshot()["gauges"]
            # the staged-uploads gauge zeroes at round close (the buffer
            # is released with it); the lifetime counter carries the
            # evidence that every arrival staged
            assert snap["fedml_wire_staged_uploads_total"] == 0.0
            assert server._staged_seen == 2 * 4
            counters = reg.snapshot()["counters"]
            # 2 rounds x 4-silo broadcast fan-outs
            assert counters["fedml_wire_fanout_total"] == 8.0
        finally:
            telemetry.disable()


class TestZeroCopyDecode:
    """The decode side never copies: every non-empty array leaf of a
    decoded frame is a read-only view into the inbound frame bytes.
    This is what lets the ingest arena gather frame->device with no
    intermediate host materialization (fedml_tpu/comm/ingest.py)."""

    def test_every_leaf_aliases_the_frame(self):
        data = _payload_msg(_edge_tree(5)).to_bytes()
        frame = np.frombuffer(data, np.uint8)
        out = Message.from_bytes(data)
        leaves = jax.tree.leaves(out.get(Message.ARG_MODEL_PARAMS))
        assert leaves
        for leaf in leaves:
            if not isinstance(leaf, np.ndarray):
                continue   # plain scalars/strings ride the JSON header
            arr = leaf
            if arr.size == 0:
                continue   # empty leaves own no bytes to share
            assert np.shares_memory(arr, frame), arr.dtype
            assert not arr.flags.writeable

    def test_aliasing_covers_awkward_dtypes_and_shapes(self):
        """0-d scalars, bools, int8 codes, float16, and leaves encoded
        from non-contiguous sources all decode as frame views — the
        encode-side ``ascontiguousarray`` is the only copy."""
        rng = np.random.RandomState(11)
        tree = {
            "zero_d": np.float32(3.25),
            "flags": np.array([True, False, True]),
            "codes": rng.randint(-128, 128, (32,)).astype(np.int8),
            "half": rng.randn(5).astype(np.float16),
            "noncontig": rng.randn(6, 6).T,
            "strided": np.arange(20)[::2],
        }
        data = _payload_msg(tree).to_bytes()
        frame = np.frombuffer(data, np.uint8)
        out = Message.from_bytes(data).get(Message.ARG_MODEL_PARAMS)
        _assert_tree_equal(out, jax.tree.map(np.asarray, tree))
        for key, leaf in out.items():
            assert np.shares_memory(np.asarray(leaf), frame), key

    def test_raw_payload_buffers_alias_the_frame(self):
        """``raw_payload`` — the arena's staging input — hands back the
        frame's own buffer views, not copies."""
        tree = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
        data = _payload_msg(tree).to_bytes()
        frame = np.frombuffer(data, np.uint8)
        out = Message.from_bytes(data)
        raw = out.raw_payload(Message.ARG_MODEL_PARAMS)
        assert raw is not None
        descr, spec, buffers = raw
        assert len(descr) == 1
        view = np.frombuffer(buffers[descr[0]["idx"]], np.float32)
        assert np.shares_memory(view, frame)
        np.testing.assert_array_equal(view.reshape(3, 4), tree["w"])

    def test_per_shard_slice_trees_alias_one_frame(self):
        """A sharded upload is several subtrees in ONE frame; each
        shard's decoded slices view the same frame bytes, so per-shard
        staging still costs zero host copies."""
        rng = np.random.RandomState(13)
        shards = {f"shard_{s}": {"w": rng.randn(8, 4).astype(np.float32)}
                  for s in range(3)}
        data = _payload_msg(shards).to_bytes()
        frame = np.frombuffer(data, np.uint8)
        out = Message.from_bytes(data).get(Message.ARG_MODEL_PARAMS)
        for name, sub in out.items():
            assert np.shares_memory(np.asarray(sub["w"]), frame), name
