"""Zero-copy pipelined ingest (ISSUE 20): the `--ingest_pipeline`
receive path is BIT-IDENTICAL to inline — fold order per shard is
deterministic arrival order — while the transport thread only validates
headers and enqueues.

Fast tier: the arena's fused-screen numeric pin against the host path
in `robust/admission.py`, per-shard order preservation under
out-of-order arrivals, the backpressure bound + network-fault
dead-letter attribution, pipelined==inline bit-parity over the live
pump-mode federation (replicated, sharded, secagg ring-fold), the
kill-mid-queue journal-recovery composition (queued-but-unfolded
frames stay un-journaled, so recovery re-tasks exactly those silos),
the config-gate matrix, and the one-ledger-entry compile pin.  The
measured claims (fold overlap >= 0.99, wall clock <= 1.15x network
time, wire speed) ride scripts/ingest_bench.py -> BENCH_ingest.json.
"""

import threading

import jax
import numpy as np
import pytest

from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                             FedAvgServerActor)
from fedml_tpu.comm.ingest import (ArenaScreen, IngestArena,
                                   IngestPipeline, OVERFLOW_REASON)
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import Message
from fedml_tpu.core.stream_agg import StreamingAggregator
from fedml_tpu.obs.telemetry import TelemetryRegistry
from fedml_tpu.robust.admission import AdmissionPipeline
from fedml_tpu.utils.checkpoint import RoundCheckpointer
from fedml_tpu.utils.journal import RoundJournal


def _params(seed=3, big=False):
    rng = np.random.RandomState(seed)
    if big:   # splittable under the shard planner's min_split_elems
        return {"dense": {"kernel": rng.randn(64, 8).astype(np.float32),
                          "bias": rng.randn(8).astype(np.float32)}}
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)}}


def _train_fn(silo):
    def fn(params, client_idx, round_idx):
        rng = np.random.RandomState(1000 * silo + int(round_idx or 0))
        return jax.tree.map(
            lambda v: v + rng.randn(*np.shape(v)).astype(np.float32) * 0.1,
            params), 10 + silo
    return fn


def _leaves_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _make_pipeline(**kw):
    kw.setdefault("registry", TelemetryRegistry())
    return IngestPipeline(**kw)


# ---------------------------------------------------------------------------
# the arena: fused screen vs the host path, structural fingerprint,
# zero-walk frame staging
# ---------------------------------------------------------------------------

class TestArena:
    def test_fused_screen_matches_host_norm(self):
        """The arena's one-reduction screen must agree with the host
        O(model) pass it replaces (`robust/admission.py` computes
        ||upload - global|| leaf-by-leaf in float32)."""
        ref = _params(1)
        upload = jax.tree.map(
            lambda v: v + np.float32(0.25) * np.sign(v), ref)
        arena = IngestArena(ref)
        assert arena.supported
        arena.round_start(ref)
        screen = arena.stage_tree(upload)
        assert screen.structural_ok and screen.finite
        host = float(np.sqrt(sum(
            float(np.sum((np.asarray(u, np.float64)
                          - np.asarray(r, np.float64)) ** 2))
            for u, r in zip(jax.tree.leaves(upload), jax.tree.leaves(ref)))))
        assert screen.norm == pytest.approx(host, rel=1e-5)
        # delta reference (round_start(None)): norm measures the payload
        arena.round_start(None)
        screen = arena.stage_tree(upload)
        flat = np.concatenate([np.asarray(l, np.float64).ravel()
                               for l in jax.tree.leaves(upload)])
        assert screen.norm == pytest.approx(float(np.linalg.norm(flat)),
                                            rel=1e-5)

    def test_nonfinite_flagged(self):
        ref = _params(1)
        arena = IngestArena(ref)
        bad = jax.tree.map(np.copy, ref)
        bad["dense"]["bias"][0] = np.nan
        screen = arena.stage_tree(bad)
        assert screen.structural_ok and not screen.finite

    def test_staged_tree_is_value_identical(self):
        """The device leaves the worker folds must be bit-equal to the
        frame's host views — the whole bit-parity contract rests here."""
        ref = _params(1)
        upload = _params(7)
        arena = IngestArena(ref)
        screen = arena.stage_tree(upload)
        assert _leaves_equal(screen.tree, upload)

    def test_structural_rejects_without_payload_read(self):
        ref = _params(1)
        arena = IngestArena(ref)
        # same shapes, different leaf keys: as strong as the host
        # params_fingerprint — still a reject
        renamed = {"dense": {"kernel2": ref["dense"]["kernel"],
                             "bias": ref["dense"]["bias"]}}
        assert arena.stage_tree(renamed).structural_ok is False
        wrong_shape = {"dense": {"kernel": ref["dense"]["kernel"][:2],
                                 "bias": ref["dense"]["bias"]}}
        assert arena.stage_tree(wrong_shape).structural_ok is False
        assert arena.stage_tree("garbage").structural_ok is False

    def test_stage_message_from_wire_frame(self):
        """The zero-walk path: a decoded frame's raw header + buffer
        views stage without materializing a host tree, and the staged
        values match the payload bit-for-bit."""
        ref = _params(1)
        upload = _params(9)
        arena = IngestArena(ref)
        msg = Message.from_bytes(
            Message(1, 2, 0).add("model_params", upload).to_bytes())
        screen = arena.stage_message(msg, "model_params")
        assert screen is not None and screen.structural_ok
        assert _leaves_equal(screen.tree, upload)
        # a frame whose payload is structurally foreign: reject from the
        # header alone
        other = Message.from_bytes(
            Message(1, 2, 0).add("model_params",
                                 {"w": np.ones(5, np.float32)}).to_bytes())
        assert arena.stage_message(other, "model_params").structural_ok \
            is False
        # an in-process object message has no raw frame: None = caller
        # falls back to stage_tree / the host path
        assert arena.stage_message(Message(1, 2, 0).add(
            "model_params", upload), "model_params") is None

    def test_non_float32_template_unsupported(self):
        arena = IngestArena({"m": np.zeros(4, np.uint32)})
        assert not arena.supported
        assert arena.stage_tree({"m": np.zeros(4, np.uint32)}) is None

    def test_single_compile_ledger_entry(self, tmp_path):
        """The arena split and the fused screen each key exactly ONE
        compile-ledger entry across uploads — the bench's 0-recompile
        gate, pinned in-process."""
        from fedml_tpu.obs.perf import PerfRecorder
        perf = PerfRecorder(str(tmp_path / "perf.jsonl"),
                            registry=TelemetryRegistry())
        arena = IngestArena(_params(1), perf=perf)
        for seed in (5, 6, 7):
            assert arena.stage_tree(_params(seed)).structural_ok
        sizes = perf.sentry.cache_sizes()
        assert sizes.get("ingest_screen") == 1
        assert sizes.get("ingest_arena") == 1


# ---------------------------------------------------------------------------
# the pipeline: per-shard FIFO, backpressure, failure surfacing
# ---------------------------------------------------------------------------

class TestPipeline:
    def test_per_shard_order_preserved_under_out_of_order_arrival(self):
        """Folds within a shard run in exactly arrival order even when
        arrivals interleave across shards arbitrarily — the determinism
        half of the bit-parity contract."""
        pipe = _make_pipeline(num_shards=3, depth=32)
        try:
            folded = {s: [] for s in range(3)}
            pipe.pause()   # hold everything queued, then release at once
            order = [(2, 0), (0, 0), (1, 0), (2, 1), (0, 1), (2, 2),
                     (1, 1), (0, 2), (1, 2), (2, 3)]
            for shard, seq in order:
                assert pipe.submit(
                    shard, (lambda s=shard, q=seq: folded[s].append(q)))
            pipe.resume()
            assert pipe.drain() == len(order)
            for s in range(3):
                want = [q for sh, q in order if sh == s]
                assert folded[s] == want
        finally:
            pipe.stop()

    def test_backpressure_bound_and_network_fault_attribution(self):
        """A full queue bounds memory: the overflow frame is dead-
        lettered (``fedml_comm_dead_letter_total{reason=
        "ingest_overflow"}`` + the fault feed's NETWORK attribution) and
        the task is NEVER silently run or dropped without the books
        knowing."""
        reg = TelemetryRegistry()
        faults = []
        pipe = IngestPipeline(num_shards=1, depth=2, registry=reg,
                              fault_feed=lambda r, d: faults.append((r, d)))
        try:
            gate, started = threading.Event(), threading.Event()
            ran = []

            def _block():
                started.set()
                gate.wait(timeout=30)
                ran.append("head")

            pipe.submit(0, _block)
            assert started.wait(timeout=10)   # worker busy, queue empty
            assert pipe.submit(0, lambda: ran.append("a"))
            assert pipe.submit(0, lambda: ran.append("b"))
            # queue full (depth=2): the next frame is load-shed
            dropped = pipe.submit(0, lambda: ran.append("DROPPED"),
                                  detail="silo 7 round 3")
            assert dropped is False
            assert faults == [(OVERFLOW_REASON, "silo 7 round 3")]
            gate.set()
            pipe.drain()
            assert ran == ["head", "a", "b"]   # the shed task never ran
            counters = reg.snapshot()["counters"]
            dead = [v for k, v in counters.items()
                    if "dead_letter" in k and OVERFLOW_REASON in k]
            assert dead == [1.0]
            over = [v for k, v in counters.items()
                    if "ingest_overflow_total" in k]
            assert over == [1.0]
            enq = [v for k, v in counters.items()
                   if "ingest_enqueued_total" in k]
            assert enq == [3.0]
        finally:
            pipe.stop()

    def test_wave_path_blocks_instead_of_shedding(self):
        """``submit_wait`` (the cross-device producer): backpressure
        means WAIT — a server-produced wave is never a droppable
        network frame."""
        pipe = _make_pipeline(num_shards=1, depth=1)
        try:
            gate, started = threading.Event(), threading.Event()
            pipe.submit(0, lambda: (started.set(), gate.wait(30)))
            assert started.wait(timeout=10)
            pipe.submit(0, lambda: None)   # queue now full
            done = []
            t = threading.Thread(
                target=lambda: (pipe.submit_wait(0, lambda: None),
                                done.append(True)))
            t.start()
            t.join(timeout=0.3)
            assert t.is_alive() and not done   # producer paced, not shed
            gate.set()
            t.join(timeout=10)
            assert done == [True]
            pipe.drain()
        finally:
            pipe.stop()

    def test_worker_exception_fails_the_drain_loudly(self):
        pipe = _make_pipeline(num_shards=1, depth=4)
        try:
            pipe.submit(0, lambda: 1 / 0)
            with pytest.raises(RuntimeError, match="fold worker died"):
                pipe.drain()
        finally:
            pipe.stop()

    def test_construction_and_shard_bounds(self):
        with pytest.raises(ValueError, match="num_shards"):
            _make_pipeline(num_shards=0)
        with pytest.raises(ValueError, match="ingest_queue_depth"):
            _make_pipeline(depth=0)
        pipe = _make_pipeline(num_shards=2)
        try:
            with pytest.raises(ValueError, match="shard 2"):
                pipe.submit(2, lambda: None)
            with pytest.raises(ValueError, match="1 arenas for 2 shard"):
                pipe.attach_arenas([None])
        finally:
            pipe.stop()


# ---------------------------------------------------------------------------
# pipelined == inline bit-parity over the live pump-mode federation
# ---------------------------------------------------------------------------

def _run_replicated(init, rounds, n=3, pipelined=False, jr=None, ck=None):
    hub = LocalHub(codec_roundtrip=True)
    stream = StreamingAggregator(init, method="mean", kind="params",
                                 norm_clip=1.0, seed=0, reservoir_k=8)
    adm = AdmissionPipeline(init, kind="params")
    ing = None
    if pipelined:
        ing = _make_pipeline(num_shards=1, depth=8)
        ing.attach_arenas([IngestArena(init)])
    server = FedAvgServerActor(
        hub.transport(0), init, n, n, rounds, stream_agg=stream,
        admission=adm, journal=jr, checkpointer=ck, ingest=ing)
    silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i))
             for i in range(1, n + 1)]
    server.register_handlers()
    for s in silos:
        s.register_handlers()
    server.start()
    hub.pump(idle_hook=(ing.drain if ing is not None else None))
    if ing is not None:
        ing.stop()
    return server


class TestBitParity:
    def test_replicated_stream(self):
        init = _params(3)
        inline = _run_replicated(init, 3)
        piped = _run_replicated(init, 3, pipelined=True)
        assert piped.round_idx == inline.round_idx == 3
        assert _leaves_equal(piped.params, inline.params)

    def test_sharded_wire(self):
        from fedml_tpu.shard_spine import build_shard_spine
        init = _params(3, big=True)

        def run(pipelined):
            hub = LocalHub(codec_roundtrip=True)
            spine = build_shard_spine(init, num_shards=2, norm_clip=0.0,
                                      fused="off", min_split_elems=64,
                                      mesh=None)
            ing = None
            if pipelined:
                ing = _make_pipeline(num_shards=spine.num_shards, depth=8)
                ing.attach_arenas(
                    [IngestArena(sl, name=f"ingest_s{s}") for s, sl in
                     enumerate(spine.broadcast_slices(init))])
            server = FedAvgServerActor(
                hub.transport(0), init, 3, 3, 2, stream_agg=spine.agg,
                shard_wire=spine, ingest=ing)
            silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i))
                     for i in range(1, 4)]
            server.register_handlers()
            for s in silos:
                s.register_handlers()
            server.start()
            hub.pump(idle_hook=(ing.drain if ing is not None else None))
            if ing is not None:
                ing.stop()
            return server

        inline, piped = run(False), run(True)
        assert piped.round_idx == inline.round_idx == 2
        assert _leaves_equal(piped.params, inline.params)

    def test_secagg_ring_fold(self):
        """Masked uploads ride the pipeline WITHOUT an arena (uint32 by
        construction): the worker ring-folds at arrival in arrival
        order, and the unmasked global is bit-equal to inline."""
        from fedml_tpu.secure.protocol import SecAggClient, SecAggServer

        def run(pipelined):
            init = {"w": np.zeros(6, np.float32),
                    "v": np.zeros(2, np.float32)}
            hub = LocalHub(codec_roundtrip=True)
            ing = _make_pipeline(num_shards=1, depth=8) \
                if pipelined else None
            server = FedAvgServerActor(
                hub.transport(0), init, 4, 4, 2,
                secagg=SecAggServer(threshold=0, clip=8.0,
                                    weight_cap=20.0),
                ingest=ing)
            silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i),
                                       secagg=SecAggClient(i))
                     for i in range(1, 5)]
            server.register_handlers()
            for s in silos:
                s.register_handlers()
            server.start()
            hub.pump(idle_hook=(ing.drain if ing is not None else None))
            if ing is not None:
                ing.stop()
            return server

        inline, piped = run(False), run(True)
        assert piped.round_idx == inline.round_idx == 2
        assert _leaves_equal(piped.params, inline.params)


# ---------------------------------------------------------------------------
# kill-mid-queue: the journal's durable-prefix recovery composes
# ---------------------------------------------------------------------------

class TestKillMidQueue:
    def test_queued_frames_stay_unjournaled_and_recovery_retasks_them(
            self, tmp_path):
        """A kill with frames still QUEUED (validated + enqueued, never
        folded) journals nothing for them — `note_accept` runs on the
        fold worker, after the fold.  Recovery therefore re-tasks
        exactly the un-journaled silos and lands on the uncrashed
        final, bit-identical."""
        init = _params(3)
        want = _run_replicated(init, 2).params

        hub = LocalHub(codec_roundtrip=True)
        stream = StreamingAggregator(init, method="mean", kind="params",
                                     norm_clip=1.0, seed=0, reservoir_k=8)
        ing = _make_pipeline(num_shards=1, depth=8)
        ing.attach_arenas([IngestArena(init)])
        jr = RoundJournal(str(tmp_path / "j"), snapshot_every=1)
        ck = RoundCheckpointer(str(tmp_path / "ck"), save_every=1)
        server = FedAvgServerActor(
            hub.transport(0), init, 3, 3, 2, stream_agg=stream,
            admission=AdmissionPipeline(init, kind="params"),
            journal=jr, checkpointer=ck, ingest=ing)
        silos = [FedAvgClientActor(i, hub.transport(i), _train_fn(i))
                 for i in range(1, 4)]
        server.register_handlers()
        for s in silos:
            s.register_handlers()
        server.start()
        # deliver the 3 broadcasts (each trains its silo and enqueues
        # its upload) plus silo 1's upload, then fold ONLY that one
        hub.pump(max_messages=4)
        ing.drain()
        # hold the workers; the remaining two uploads arrive and sit in
        # the queue — validated, enqueued, NEVER folded
        ing.pause()
        hub.pump()
        # the kill: read what a fresh process would recover.  The
        # durable set is exactly the folded prefix — the queued silos
        # are un-journaled by construction.
        rec = RoundJournal(str(tmp_path / "j")).recover()
        assert rec is not None and rec.resumable
        assert [s for s, _, _ in rec.folded] == [1]
        # resume on fresh actors: the un-journaled silos {2, 3} are
        # re-tasked and the final equals the uncrashed run's, bit-equal
        resumed = _run_replicated(
            init, 2,
            jr=RoundJournal(str(tmp_path / "j"), snapshot_every=1),
            ck=RoundCheckpointer(str(tmp_path / "ck"), save_every=1),
            pipelined=True)
        assert resumed.round_idx == 2
        assert _leaves_equal(resumed.params, want)


# ---------------------------------------------------------------------------
# config gates: every unproven combination refuses loudly by name
# ---------------------------------------------------------------------------

_BASE = ["--model", "lr", "--dataset", "mnist",
         "--client_num_in_total", "4", "--client_num_per_round", "4",
         "--comm_round", "1", "--batch_size", "4", "--epochs", "1",
         "--log_stdout", "false"]


class TestConfigGates:
    @pytest.mark.parametrize("argv,match", [
        (["--algo", "fedavg", "--ingest_pipeline", "true"],
         "no ingest hot path"),
        (["--algo", "cross_silo", "--ingest_pipeline", "true",
          "--wire_compression", "int8"], "wire_compression"),
        (["--algo", "cross_silo", "--ingest_pipeline", "true",
          "--edge_aggregators", "2"], "edge_aggregators"),
        (["--algo", "cross_silo", "--ingest_pipeline", "true",
          "--chaos_drop", "0.1"], "chaos"),
        (["--algo", "cross_silo", "--ingest_pipeline", "true",
          "--agg_mode", "stack"], "stream"),
        (["--algo", "cross_silo", "--ingest_queue_depth", "0"],
         "ingest_queue_depth"),
    ])
    def test_unproven_combination_refused(self, argv, match):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match=match):
            main(argv + _BASE)

    def test_faultline_refused_at_the_actor(self):
        from fedml_tpu.robust.faultline import Faultline
        ing = _make_pipeline(num_shards=1)
        try:
            with pytest.raises(ValueError, match="mutually"):
                FedAvgServerActor(
                    LocalHub().transport(0), _params(), 3, 3, 1,
                    stream_agg=StreamingAggregator(
                        _params(), method="mean", kind="params"),
                    journal=None, faultline=Faultline(), ingest=ing)
        finally:
            ing.stop()
