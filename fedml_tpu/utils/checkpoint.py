"""Round-level checkpoint / resume (orbax) — SURVEY.md §5.4.

The reference has NO checkpointing on the FL path (no torch.save of the
global model in any aggregator); only the GAN BaseModel saves/loads networks
(``fedml_api/model/cv/base_model.py:161-178,277-296``) and ResNets can load
pretrained weights (``cv/resnet.py:202-246``).  Here checkpointing is a
first-class round-level primitive: the tuple (global params, server
optimizer state, round idx, RNG key) is saved every N rounds and a resumed
run continues BIT-IDENTICALLY to an uninterrupted one (tested:
tests/test_checkpoint.py).

Typed PRNG keys are stored as their uint32 key data (orbax serializes
ordinary arrays) and re-wrapped on restore.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

log = logging.getLogger(__name__)

Pytree = Any

MANIFEST_DIRNAME = "manifests"


def manifest_path(ckpt_dir: str, step: int) -> str:
    """The per-step checksum manifest: ``<ckpt_dir>/manifests/<step>.json``
    — a sibling tree, never inside the orbax step dir (orbax owns that
    layout), and never digit-named at the top level (the serving
    watcher's step listing must not mistake it for a round)."""
    return os.path.join(ckpt_dir, MANIFEST_DIRNAME, f"{step}.json")


def _pack_keys(tree: Pytree) -> Pytree:
    """typed PRNG keys -> {"__prng_data__": uint32 array} dicts (orbax
    serializes only arrays/scalars; keys use the default threefry impl)."""
    def pack(x):
        if hasattr(x, "dtype") and jax.dtypes.issubdtype(
                x.dtype, jax.dtypes.prng_key):
            return {"__prng_data__": np.asarray(jax.random.key_data(x))}
        return x
    return jax.tree.map(pack, tree)


def _unpack_keys(tree: Pytree) -> Pytree:
    def is_packed(x):
        return isinstance(x, dict) and "__prng_data__" in x

    def unpack(x):
        if is_packed(x):
            return jax.random.wrap_key_data(x["__prng_data__"])
        return x
    return jax.tree.map(unpack, tree, is_leaf=is_packed)


class RoundCheckpointer:
    """Save/restore the federated training state every ``save_every``
    rounds, keeping ``max_to_keep`` checkpoints."""

    def __init__(self, ckpt_dir: str, save_every: int = 1,
                 max_to_keep: int = 3, async_save: bool = False,
                 keep_last_n: Optional[int] = None):
        """``async_save=True`` lets orbax serialize in a background thread
        so training never blocks on checkpoint I/O (the TPU stays fed).
        Durability semantics: a save is guaranteed on disk only after the
        NEXT save, ``flush()``, ``close()``, or any read (latest_round /
        restore) — a process killed mid-write leaves the previous
        checkpoint intact (orbax writes to a tmp dir and renames).  The
        sync default trades round latency for save-returns-durable.

        ``keep_last_n`` is the retention knob for serve-while-train runs
        (the serving registry watches this directory, so an unbounded
        run would fill the disk it serves from): only the newest N round
        dirs survive each save — older ones are GC'd, and readers (the
        `serve.registry.CheckpointWatcher`) must tolerate a step
        vanishing between list and load.  It overrides ``max_to_keep``
        when set; 0/None keeps the default of 3."""
        import orbax.checkpoint as ocp
        self.save_every = max(1, int(save_every))
        self.async_save = async_save
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        if keep_last_n:
            max_to_keep = int(keep_last_n)
        self.keep_last_n = max_to_keep
        self._mngr = ocp.CheckpointManager(
            self.ckpt_dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))
        self._ocp = ocp

    def maybe_save(self, round_idx: int, state,
                   last_round: bool = False) -> bool:
        """``state`` may be the state dict OR a zero-arg callable building
        it — callers with expensive state (device→host copies, the EF
        fixed-shape serialization) pass a thunk so skipped rounds pay
        nothing for the ``save_every`` gate."""
        if not last_round and (round_idx + 1) % self.save_every:
            return False
        self.save(round_idx, state() if callable(state) else state)
        return True

    def save(self, round_idx: int, state: Dict[str, Any]) -> None:
        state = _pack_keys(state)
        if self.async_save:
            # snapshot MUTABLE host leaves before enqueueing: stacked
            # per-client state (algorithms/fedavg.py stacked-state
            # convention) is numpy and scattered into IN PLACE next round.
            # Current orbax already copies at enqueue (probed empirically;
            # test_ditto.py pins the observable contract), so this is
            # defense-in-depth against that implementation detail changing
            # — a torn save would silently break bit-identical resume.
            # jax arrays are immutable and the sync path blocks until
            # durable, so only async numpy leaves need the copy.
            state = jax.tree.map(
                lambda x: np.array(x) if isinstance(x, np.ndarray) else x,
                state)
        self._mngr.save(round_idx,
                        args=self._ocp.args.StandardSave(state))
        if not self.async_save:
            self._mngr.wait_until_finished()
        self._write_manifest(round_idx, state)

    def _write_manifest(self, round_idx: int, packed_state) -> None:
        """Checksum manifest for the serving watcher's torn-file guard:
        per-top-level-key crc32 over the PACKED leaves, written via the
        atomic tmp+rename contract (and the ``checkpoint_manifest`` disk-
        fault channel, so tests can inject torn/failed manifests).  A
        manifest write failure warns and keeps training — the checkpoint
        itself is durable; only the read-side verification is lost."""
        from fedml_tpu.utils.journal import atomic_write, tree_crc
        items = (packed_state.items() if hasattr(packed_state, "items")
                 else [("state", packed_state)])
        crcs = {str(k): tree_crc(v) for k, v in items}
        path = manifest_path(self.ckpt_dir, round_idx)
        data = json.dumps({"step": int(round_idx), "algo": "crc32",
                           "crc": crcs}, sort_keys=True).encode()
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write(path, data, channel="checkpoint_manifest")
            self._prune_manifests(round_idx)
        except OSError as e:
            log.warning("checkpoint manifest for step %d not written "
                        "(%s); watcher falls back to unverified load",
                        round_idx, e)

    def _prune_manifests(self, current_step: int) -> None:
        """Drop manifests whose step dir the retention GC already took
        (the manifest tree must stay as bounded as the checkpoints).
        Steps >= the one just saved are kept unconditionally — an async
        save's dir is not renamed durable yet when this runs."""
        mdir = os.path.join(self.ckpt_dir, MANIFEST_DIRNAME)
        try:
            live = {n for n in os.listdir(self.ckpt_dir) if n.isdigit()}
            for name in os.listdir(mdir):
                stem = name[:-5] if name.endswith(".json") else name
                if (stem.isdigit() and stem not in live
                        and int(stem) < current_step):
                    os.unlink(os.path.join(mdir, name))
        except OSError:
            pass

    def flush(self) -> None:
        """Block until every pending async save is durable."""
        self._mngr.wait_until_finished()

    def latest_round(self) -> Optional[int]:
        self.flush()  # never report a step whose write is still in flight
        return self._mngr.latest_step()

    def restore(self, round_idx: Optional[int] = None,
                like: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """``like``: a template pytree with the target structure/shapes
        (e.g. a freshly-initialized state) — lets orbax restore to the exact
        dtypes/shardings.  Without it, orbax infers from the saved
        metadata."""
        self.flush()
        step = round_idx if round_idx is not None else self.latest_round()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.ckpt_dir}")
        if like is not None:
            restored = self._mngr.restore(
                step, args=self._ocp.args.StandardRestore(_pack_keys(like)))
        else:
            # explicit StandardRestore: a FRESH manager (the serving
            # watcher opens one read-side per load) has no handler
            # registry from a prior save and a bare restore() refuses
            restored = self._mngr.restore(
                step, args=self._ocp.args.StandardRestore())
        return _unpack_keys(restored)

    def close(self) -> None:
        self.flush()
        self._mngr.close()
