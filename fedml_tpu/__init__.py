"""fedml_tpu — a TPU-native federated learning framework.

A ground-up JAX/XLA re-design with the capabilities of the reference FedML
library (PyTorch + MPI message passing).  The core inversion: on-TPU,
"communication" is an XLA collective inside one jit-compiled program — a
FedAvg round that in the reference is a choreography of MPI messages
(`fedml_api/distributed/fedavg/FedAvgServerManager.py`) collapses here into a
single `shard_map`-ped cohort step whose aggregation is a weighted `lax.psum`
over the ICI mesh.  The message-passing actor layer survives only at the
cross-silo / host edge (gRPC/MQTT transports in `fedml_tpu.comm`).

Layer map (mirrors SURVEY.md §1 of the reference):

    fedml_tpu.experiments   CLI entry points (parity with fedml_experiments/)
    fedml_tpu.algorithms    FedAvg/FedOpt/FedProx/FedNova/... (fedml_api/*)
    fedml_tpu.models        flax model zoo (fedml_api/model/*)
    fedml_tpu.data          dataset loaders + cohort stacking (data_preprocessing/*)
    fedml_tpu.core          kernel: aggregation math, sampling, partition,
                            robustness, topology (fedml_core/*)
    fedml_tpu.parallel      mesh / shard_map cohort engine (replaces MPI runtime)
    fedml_tpu.comm          cross-silo transports: Message protocol, local fake,
                            gRPC, MQTT (fedml_core/distributed/communication/*)
"""

__version__ = "0.1.0"
