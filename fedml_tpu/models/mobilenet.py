"""MobileNet V1 and V3 (parity: fedml_api/model/cv/mobilenet.py:60,
mobilenet_v3.py:137) — the cross-silo CIFAR/CINIC benchmark models.

V1 = Howard'17 depthwise-separable stack; V3 = Howard'19 inverted residuals
with squeeze-excite and hard-swish, in LARGE and SMALL configs.  Norm is
switchable (reference uses BatchNorm; GroupNorm default here, models/norms.py).
NHWC layout.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.norms import Norm, conv_kernel_init


def _conv_norm(x, features, kernel, stride, norm, train, act):
    x = nn.Conv(features, (kernel, kernel), strides=(stride, stride),
                padding="SAME", use_bias=False,
                kernel_init=conv_kernel_init)(x)
    x = Norm(norm)(x, train)
    return act(x)


def _depthwise(x, kernel, stride, norm, train, act):
    ch = x.shape[-1]
    x = nn.Conv(ch, (kernel, kernel), strides=(stride, stride),
                padding="SAME", feature_group_count=ch, use_bias=False,
                kernel_init=conv_kernel_init)(x)
    x = Norm(norm)(x, train)
    return act(x)


class MobileNetV1(nn.Module):
    """13 depthwise-separable blocks (mobilenet.py:60-106).  The
    reference is the CIFAR variant — stride-1 stem and class_num=100
    (mobilenet.py:70-83); ``stem_stride=2`` gives the ImageNet layout."""
    num_classes: int = 100
    width_mult: float = 1.0
    norm: str = "group"
    stem_stride: int = 1

    # (out_channels, stride) after the stem conv
    _blocks: Sequence[Tuple[int, int]] = (
        (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
        (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
        (1024, 1))

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = lambda c: max(8, int(c * self.width_mult))
        x = _conv_norm(x, w(32), 3, self.stem_stride, self.norm, train,
                       nn.relu)
        for out_ch, stride in self._blocks:
            x = _depthwise(x, 3, stride, self.norm, train, nn.relu)
            x = _conv_norm(x, w(out_ch), 1, 1, self.norm, train, nn.relu)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class SqueezeExcite(nn.Module):
    reduce_ch: int

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(self.reduce_ch)(s))
        s = jax.nn.hard_sigmoid(nn.Dense(x.shape[-1])(s))
        return x * s[:, None, None, :]


class InvertedResidual(nn.Module):
    """MBConv block (mobilenet_v3.py:55-100): 1x1 expand -> k x k depthwise
    (+SE) -> 1x1 project, residual when stride 1 and channels match.

    One block serves both MobileNetV3 (relu/hard-swish via ``use_hs``) and
    EfficientNet (``activation=nn.swish``, ``se_reduce_ch`` from input
    channels, per-block stochastic-depth ``drop_rate``)."""
    exp_ch: int
    out_ch: int
    kernel: int
    stride: int
    use_se: bool
    use_hs: bool
    norm: str = "group"
    activation: Callable | None = None  # overrides the use_hs switch
    se_reduce_ch: int | None = None     # default: exp_ch // 4
    drop_rate: float = 0.0              # stochastic depth on the residual

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = self.activation or (jax.nn.hard_swish if self.use_hs
                                  else nn.relu)
        identity = x
        h = x
        if self.exp_ch != x.shape[-1]:
            h = _conv_norm(h, self.exp_ch, 1, 1, self.norm, train, act)
        h = _depthwise(h, self.kernel, self.stride, self.norm, train, act)
        if self.use_se:
            h = SqueezeExcite(self.se_reduce_ch
                              or max(8, self.exp_ch // 4))(h)
        h = nn.Conv(self.out_ch, (1, 1), use_bias=False,
                    kernel_init=conv_kernel_init)(h)
        h = Norm(self.norm)(h, train)
        if self.stride == 1 and x.shape[-1] == self.out_ch:
            if train and self.drop_rate > 0.0:
                rng = self.make_rng("dropout")
                keep = 1.0 - self.drop_rate
                mask = jax.random.bernoulli(
                    rng, keep, (h.shape[0],) + (1,) * (h.ndim - 1))
                h = h * mask / keep
            h = h + identity
        return h


# (kernel, exp, out, SE, HS, stride) — Howard'19 Tables 1 & 2
# (mobilenet_v3.py:137-170 mobilenetv3_large / mobilenetv3_small cfgs).
_V3_LARGE = (
    (3, 16, 16, False, False, 1), (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1), (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1), (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2), (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1), (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1), (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2), (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1))
_V3_SMALL = (
    (3, 16, 16, True, False, 2), (3, 72, 24, False, False, 2),
    (3, 88, 24, False, False, 1), (5, 96, 40, True, True, 2),
    (5, 240, 40, True, True, 1), (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1), (5, 144, 48, True, True, 1),
    (5, 288, 96, True, True, 2), (5, 576, 96, True, True, 1),
    (5, 576, 96, True, True, 1))


class MobileNetV3(nn.Module):
    num_classes: int = 1000
    mode: str = "large"          # "large" | "small"
    norm: str = "group"
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = _V3_LARGE if self.mode == "large" else _V3_SMALL
        x = _conv_norm(x, 16, 3, 2, self.norm, train, jax.nn.hard_swish)
        for k, exp, out, se, hs, s in cfg:
            x = InvertedResidual(exp, out, k, s, se, hs, self.norm)(x, train)
        last_exp = cfg[-1][1]
        x = _conv_norm(x, last_exp, 1, 1, self.norm, train, jax.nn.hard_swish)
        x = jnp.mean(x, axis=(1, 2))
        x = jax.nn.hard_swish(nn.Dense(1280 if self.mode == "large" else 1024)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def mobilenet(num_classes: int = 100, norm: str = "group",
              width_mult: float = 1.0,
              stem_stride: int = 1) -> MobileNetV1:
    """Reference-default CIFAR MobileNet (mobilenet.py:70 class_num=100,
    stride-1 stem); pass stem_stride=2 for the ImageNet stem."""
    return MobileNetV1(num_classes=num_classes, norm=norm,
                       width_mult=width_mult, stem_stride=stem_stride)


def mobilenet_v3(num_classes: int = 1000, mode: str = "large",
                 norm: str = "group") -> MobileNetV3:
    return MobileNetV3(num_classes=num_classes, mode=mode, norm=norm)
