#!/usr/bin/env python
"""Sharded global-model spine bench (ISSUE 14 acceptance) →
BENCH_shard.json.

Three arm families, each in a FRESH SUBPROCESS (allocator/jit history
never leaks between arms):

* **mem S∈{1,4}** — the per-device scaling claim: 4 forced host CPU
  devices, a mostly-splittable ~16 MB template, the spine's live round
  state (per-shard fold accumulators + reference slices + the
  NamedSharding-placed global) after 8 folds; per-device bytes are
  measured from the ACTUAL buffers (``addressable_shards`` /
  ``devices()``), never computed from shapes.  Gate: the busiest
  device's bytes at S=4 ≤ 0.35× S=1 (~1/S + replicated smalls).
* **parity** — S=1 bit-identical to the replicated streaming fold
  (clip included); S>1 unclipped bit-identical, clipped allclose with
  σ=0; the fused Pallas finalize bit-equal to the XLA compose at σ=0.
* **live** — the real CLI (``--model_shards 4 --fused_finalize on
  --perf_strict --device_obs``): the committed ledger lines must show
  0 recompiles after round 0, the ``shard_finalize`` phase and
  ``shards`` field on every line, the compile ledger NAMING the fused
  finalize kernel, and a non-null MFU ≤ 1.0 — the PR 9 gauge finally
  measuring an accelerator-bound hot loop (CPU-labeled here).

CPU-honest contract: every number is host wall-clock / host-device
bytes on ``jax.default_backend()`` — labeled ``backend: cpu``, never
dressed as TPU throughput.  The TPU claim this container cannot test
(fused-kernel HBM traffic) is named, not faked.

  python scripts/shard_bench.py             # full, writes BENCH_shard.json
  python scripts/shard_bench.py --smoke     # CI-sized, /tmp output
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, _ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

MB = 1024 * 1024


def _template(model_mb: float):
    import numpy as np
    # mostly-splittable blocks (dims divisible by 4) + small replicated
    # biases, so the plan exercises both modes
    n_blocks = 8
    per = int(model_mb * MB / 4 / n_blocks)
    rows = max(4, (per // 512) // 4 * 4)
    out = {"blocks": {}}
    for i in range(n_blocks):
        out["blocks"][f"b{i}"] = {
            "w": np.ones((rows, 512), np.float32) * (i + 1),
            "bias": np.zeros((16,), np.float32)}
    return out


def _uploads(tmpl, k: int):
    import jax
    import numpy as np
    ups = []
    for i in range(k):
        rng = np.random.RandomState(i)
        ups.append(jax.tree.map(
            lambda v: (np.asarray(v)
                       + rng.standard_normal(np.shape(v))
                       .astype(np.float32)), tmpl))
    return ups


def _child_mem(num_shards: int, model_mb: float) -> dict:
    import jax
    import numpy as np
    from fedml_tpu.parallel.mesh import make_model_mesh
    from fedml_tpu.shard_spine import (ShardedStreamingAggregator,
                                       build_shard_plan)
    tmpl = _template(model_mb)
    mesh = make_model_mesh(num_shards) if num_shards > 1 else None
    plan = build_shard_plan(tmpl, num_shards)
    agg = ShardedStreamingAggregator(plan, tmpl, norm_clip=2.0,
                                     mesh=mesh)
    agg.reset(tmpl)
    t0 = time.perf_counter()
    for u in _uploads(tmpl, 8):
        agg.fold(u, 10.0)
    fold_s = time.perf_counter() - t0

    per_dev = {}

    def note(arr):
        try:
            shards = list(arr.addressable_shards)
        except AttributeError:
            shards = None
        if shards:
            for sh in shards:
                d = sh.device.id
                per_dev[d] = per_dev.get(d, 0) + int(sh.data.nbytes)
        else:
            for d in arr.devices():
                per_dev[d.id] = per_dev.get(d.id, 0) + int(arr.nbytes)

    # the spine's live round state: fold accumulators + references
    for group in (agg._acc, agg._reference):
        for body in group:
            for v in body.values():
                note(v)
    # the assembled global, laid out per the plan's NamedSharding
    placed = plan.place_global(tmpl, mesh) if mesh is not None \
        else jax.tree.map(jax.numpy.asarray, tmpl)
    for leaf in jax.tree.leaves(placed):
        note(leaf)
    t0 = time.perf_counter()
    out = agg.finalize(0)
    finalize_s = time.perf_counter() - t0
    checksum = float(sum(float(np.sum(np.asarray(x, np.float64)))
                         for x in jax.tree.leaves(out)))
    model_bytes = int(sum(np.asarray(x).nbytes
                          for x in jax.tree.leaves(tmpl)))
    return {"shards": num_shards,
            "devices": len(jax.devices()),
            "model_bytes": model_bytes,
            "per_device_bytes": {str(k): v
                                 for k, v in sorted(per_dev.items())},
            "max_device_bytes": max(per_dev.values()),
            "max_shard_acc_bytes": max(
                plan.slice_nbytes(s) for s in range(num_shards)),
            "fold_s": round(fold_s, 4),
            "finalize_s": round(finalize_s, 4),
            "checksum": checksum,
            "backend": jax.default_backend()}


def _child_parity(model_mb: float) -> dict:
    import jax
    import numpy as np
    from fedml_tpu.core.stream_agg import StreamingAggregator
    from fedml_tpu.shard_spine import (ShardedStreamingAggregator,
                                       build_shard_plan)
    tmpl = _template(model_mb)
    ups = _uploads(tmpl, 6)
    ws = [10.0 * (i + 1) for i in range(6)]

    def run_plain(clip):
        agg = StreamingAggregator(tmpl, method="mean", norm_clip=clip,
                                  seed=0)
        agg.reset(tmpl)
        for u, w in zip(ups, ws):
            agg.fold(u, w)
        return agg.finalize(1)

    def run_shard(S, clip, fused=False):
        plan = build_shard_plan(tmpl, S)
        agg = ShardedStreamingAggregator(plan, tmpl, norm_clip=clip,
                                         seed=0, fused=fused,
                                         interpret=True)
        agg.reset(tmpl)
        for u, w in zip(ups, ws):
            agg.fold(u, w)
        return agg.finalize(1)

    def bits(a, b):
        return all(np.asarray(x).tobytes() == np.asarray(y).tobytes()
                   for x, y in zip(jax.tree.leaves(a),
                                   jax.tree.leaves(b)))

    def close(a, b):
        return all(np.allclose(np.asarray(x), np.asarray(y), rtol=1e-5,
                               atol=1e-6)
                   for x, y in zip(jax.tree.leaves(a),
                                   jax.tree.leaves(b)))

    plain_clip = run_plain(2.0)
    plain_raw = run_plain(0.0)
    s4_xla = run_shard(4, 2.0)
    return {
        "s1_bit_identical_clipped": bits(plain_clip, run_shard(1, 2.0)),
        "s4_bit_identical_unclipped": bits(plain_raw,
                                           run_shard(4, 0.0)),
        "s4_allclose_clipped_sigma0": close(plain_clip, s4_xla),
        "fused_bit_equal_xla_sigma0": bits(s4_xla,
                                           run_shard(4, 2.0,
                                                     fused=True)),
        "backend": jax.default_backend()}


def _run_live(run_dir: str, rounds: int, smoke: bool) -> dict:
    cmd = [sys.executable, "-m", "fedml_tpu",
           "--algo", "cross_silo", "--model", "lr", "--dataset", "mnist",
           "--client_num_in_total", "4", "--client_num_per_round", "4",
           "--comm_round", str(rounds), "--epochs", "1",
           "--batch_size", "8", "--agg_mode", "stream",
           "--model_shards", "4", "--fused_finalize", "on",
           "--norm_clip", "5.0", "--perf", "true", "--perf_strict",
           "true", "--device_obs", "true", "--run_dir", run_dir,
           "--log_stdout", "false"]
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=_ROOT, timeout=1200)
    wall = time.perf_counter() - t0
    if proc.returncode != 0:
        print(proc.stdout[-2000:], file=sys.stderr)
        print(proc.stderr[-4000:], file=sys.stderr)
        raise SystemExit(f"live arm failed rc={proc.returncode}")
    rows = [json.loads(l) for l in
            open(os.path.join(run_dir, "perf.jsonl"))]
    from fedml_tpu.obs.trend import validate_ledger
    problems = validate_ledger(rows)
    fused_fns = sorted({c["fn"] for r in rows
                        for c in (r.get("device") or {})
                        .get("compiles", [])
                        if c["fn"].startswith("fused_finalize")})
    mfus = [r["device"]["mfu"] for r in rows
            if (r.get("device") or {}).get("mfu") is not None]
    return {"rounds": len(rows), "wall_s": round(wall, 2),
            "ledger_problems": problems,
            "recompiles_after_round0": sum(r["recompiles"]
                                           for r in rows[1:]),
            "shard_finalize_on_every_line": all(
                "shard_finalize" in r["phases"] for r in rows),
            "shards_field": sorted({r.get("shards") for r in rows}),
            "fused_finalize_compiles": fused_fns,
            "mfu_values": mfus,
            "mfu_max": max(mfus) if mfus else None,
            "backend": rows[0]["device"]["backend"],
            "ledger_lines": rows}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized arms; output defaults to /tmp so the "
                         "committed artifact is never clobbered")
    ap.add_argument("--out", default=None)
    ap.add_argument("--child", nargs="+", default=None)
    ap.add_argument("--model_mb", type=float, default=None)
    args = ap.parse_args()
    model_mb = args.model_mb if args.model_mb is not None else \
        (1.0 if args.smoke else 16.0)

    if args.child:
        kind = args.child[0]
        if kind == "mem":
            print(json.dumps(_child_mem(int(args.child[1]), model_mb)))
        elif kind == "parity":
            print(json.dumps(_child_parity(model_mb)))
        else:
            raise SystemExit(f"unknown child arm {kind}")
        return 0

    def child(arm_args, force_devices=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if force_devices:
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_"
                                f"count={force_devices}")
        cmd = [sys.executable, os.path.abspath(__file__),
               "--child", *[str(a) for a in arm_args],
               "--model_mb", str(model_mb)]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             env=env, timeout=1200)
        if out.returncode != 0:
            print(out.stderr[-4000:], file=sys.stderr)
            raise SystemExit(f"child {arm_args} failed")
        return json.loads(out.stdout.strip().splitlines()[-1])

    mem = {s: child(["mem", s], force_devices=4) for s in (1, 4)}
    parity = child(["parity"])
    with tempfile.TemporaryDirectory() as d:
        live = _run_live(d, rounds=3 if args.smoke else 5,
                         smoke=args.smoke)

    ratio = mem[4]["max_device_bytes"] / mem[1]["max_device_bytes"]
    acc_ratio = (mem[4]["max_shard_acc_bytes"]
                 / mem[1]["max_shard_acc_bytes"])
    failures = []
    if ratio > 0.35:
        failures.append(f"per-device bytes S=4/S=1 = {ratio:.3f} > 0.35 "
                        f"(expected ~1/S + replicated smalls)")
    if acc_ratio > 0.30:
        failures.append(f"per-shard accumulator S=4/S=1 = "
                        f"{acc_ratio:.3f} > 0.30")
    if abs(mem[4]["checksum"] - mem[1]["checksum"]) > 1e-3 * max(
            1.0, abs(mem[1]["checksum"])):
        failures.append("mem-arm finalize checksums diverge across S")
    for key, want in (("s1_bit_identical_clipped", True),
                      ("s4_bit_identical_unclipped", True),
                      ("s4_allclose_clipped_sigma0", True),
                      ("fused_bit_equal_xla_sigma0", True)):
        if parity.get(key) is not want:
            failures.append(f"parity gate {key} failed")
    if live["ledger_problems"]:
        failures.append(f"live ledger invalid: "
                        f"{live['ledger_problems'][:3]}")
    if live["recompiles_after_round0"] != 0:
        failures.append(f"{live['recompiles_after_round0']} recompiles "
                        f"after round 0 under --perf_strict")
    if not live["shard_finalize_on_every_line"]:
        failures.append("shard_finalize phase missing from a ledger "
                        "line")
    if not live["fused_finalize_compiles"]:
        failures.append("compile ledger never named the fused finalize "
                        "kernel")
    if live["mfu_max"] is None:
        failures.append("MFU gauge null on every ledger line")
    elif live["mfu_max"] > 1.0:
        failures.append(f"mfu {live['mfu_max']} > 1.0 — timing "
                        f"untrusted")

    out_path = args.out
    if out_path is None:
        out_path = ("/tmp/BENCH_shard.json" if args.smoke
                    else os.path.join(_ROOT, "BENCH_shard.json"))
    doc = {
        "bench": "shard_spine",
        "backend": parity["backend"],
        "honesty": ("host CPU container: per-device bytes are measured "
                    "from live buffers over forced host devices; the "
                    "fused kernel runs the Pallas INTERPRETER here — "
                    "its wall time is a correctness artifact, and the "
                    "compiled-kernel HBM-traffic win is the TPU claim "
                    "this container cannot test"),
        "smoke": bool(args.smoke),
        "model_mb": model_mb,
        "mem": {f"S={s}": v for s, v in mem.items()},
        "per_device_bytes_ratio_s4_over_s1": round(ratio, 4),
        "per_shard_acc_bytes_ratio_s4_over_s1": round(acc_ratio, 4),
        "parity": parity,
        "live": {k: v for k, v in live.items() if k != "ledger_lines"},
        "ledger_excerpt": [
            {k: v for k, v in row.items()
             if k in ("round", "phases", "recompiles", "shards")}
            | {"device": {kk: row["device"][kk]
                          for kk in ("backend", "mfu", "flops",
                                     "peak_source")
                          if kk in (row.get("device") or {})},
               "compiles": [c["fn"] for c in
                            (row.get("device") or {})
                            .get("compiles", [])]}
            for row in live["ledger_lines"][:2]],
        "gates": {"failures": failures},
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({"bench": "shard_spine", "out": out_path,
                      "ratio": round(ratio, 4),
                      "mfu_max": live["mfu_max"],
                      "failures": failures}))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
