"""Decentralized online learning — DSGD and PushSum over directed graphs.

Parity with the reference's standalone decentralized stack
(``fedml_api/standalone/decentralized/``):

* ``client_dsgd.py:54-102`` — per iteration each client takes ONE streaming
  sample, computes the BCE gradient at its consensus iterate z, applies it to
  the auxiliary variable x, then mixes x with its neighbors' x using the
  sender-row weights of the mixing matrix (i.e. ``x <- W^T x``);
* ``client_pushsum.py:57-129`` — same gradient step plus push-sum weight
  bookkeeping: ``omega <- W^T omega`` and ``z = x / omega`` (de-biases the
  directed-graph mixing); optionally time-varying topology regenerated each
  iteration from ``seed = t`` (:64-72);
* ``decentralized_fl_api.py:20-99`` — the driver: T*epoch iterations over the
  stream (index wraps mod T), average regret ``sum(losses) / (N * (t+1))``
  logged per iteration;
* the LOCAL baseline (``train_local``, no mixing) is mode ``"LOCAL"``.

TPU-native execution: the reference's client objects, neighbor dicts, and
message passing disappear — client states live stacked on a leading ``nodes``
axis, the per-iteration gradient is a ``vmap`` of ``value_and_grad``, the
neighbor exchange is one ``[N,N] @ [N,D]`` matmul on the MXU, and the ENTIRE
run (T*epoch iterations) is a single ``lax.scan`` inside one jit.  Streaming
sample lookup is a gather on the time axis (index ``t % T``) so multi-epoch
runs don't re-materialise the stream.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.topology import (AsymmetricTopologyManager,
                                     SymmetricTopologyManager)
from fedml_tpu.data.uci import streaming_to_arrays

Pytree = Any

MODES = ("DOL", "PUSHSUM", "LOCAL")


@dataclasses.dataclass
class DecentralizedOnlineConfig:
    """Flag parity with main_dol.py:17-37 (behavioral subset)."""
    mode: str = "DOL"                # "DOL" | "PUSHSUM" | "LOCAL"
    iteration_number: int = 100      # T: stream length per client
    epochs: int = 1                  # total iterations = T * epochs
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    b_symmetric: bool = False
    topology_neighbors_num_undirected: int = 4
    topology_neighbors_num_directed: int = 4
    time_varying: bool = False       # regenerate topology per iteration
    seed: int = 0


# --------------------------------------------------------------------------
# model: online logistic regression (LogisticRegression(input_dim, 1) +
# BCELoss in the reference, main_dol.py:92)
# --------------------------------------------------------------------------

def init_lr_params(input_dim: int) -> Pytree:
    """Zero-init logistic regression (torch Linear starts near zero at this
    scale; zeros make the consensus/oracle tests exact)."""
    return {"w": jnp.zeros((input_dim,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


def lr_predict(params: Pytree, x: jax.Array) -> jax.Array:
    """Single-sample logit (the sigmoid lives inside the stable BCE)."""
    return x @ params["w"] + params["b"]


def bce_with_logits(logit: jax.Array, y: jax.Array) -> jax.Array:
    """Numerically-stable sigmoid + BCE (optax's log_sigmoid formulation —
    smooth everywhere, unlike the max(z,0)-z*y+log1p(exp(-|z|)) form whose
    subgradient is ambiguous at z=0, exactly where zero-init starts)."""
    import optax
    return optax.sigmoid_binary_cross_entropy(logit, y)


# --------------------------------------------------------------------------
# topology
# --------------------------------------------------------------------------

def make_topology(cfg: DecentralizedOnlineConfig, n: int,
                  seed: Optional[int] = None) -> np.ndarray:
    """Row-stochastic mixing matrix W (decentralized_fl_api.py:34-41)."""
    if cfg.b_symmetric:
        mgr = SymmetricTopologyManager(
            n, cfg.topology_neighbors_num_undirected)
        return np.asarray(mgr.generate_topology(), np.float32)
    mgr = AsymmetricTopologyManager(
        n, cfg.topology_neighbors_num_undirected,
        cfg.topology_neighbors_num_directed,
        seed=seed if seed is not None else cfg.seed)
    return np.asarray(mgr.generate_topology(), np.float32)


def _topology_bank(cfg: DecentralizedOnlineConfig, n: int,
                   n_iter: int) -> np.ndarray:
    """[K, N, N] bank of mixing matrices, indexed per iteration by t % K —
    time-varying regenerates with seed = t (client_pushsum.py:64-68, K =
    n_iter); static keeps ONE matrix (K = 1) so the scan doesn't haul
    n_iter copies of W through HBM."""
    if cfg.time_varying:
        if cfg.b_symmetric:
            raise ValueError(
                "time_varying topology requires b_symmetric=False: the "
                "symmetric Watts-Strogatz(p=0) graph is deterministic, so "
                "'regenerating' it every iteration would silently produce "
                "an identical (static) topology")
        return np.stack([make_topology(cfg, n, seed=t)
                         for t in range(n_iter)])
    return make_topology(cfg, n)[None]


# --------------------------------------------------------------------------
# engine
# --------------------------------------------------------------------------

def _mix(A: jax.Array, stacked: Pytree) -> Pytree:
    """Neighbor exchange as one matmul per leaf: [N,N] @ [N,D] on the MXU."""
    n = A.shape[0]

    def go(v):
        return (A @ v.reshape(n, -1)).reshape(v.shape)
    return jax.tree.map(go, stacked)


def _per_node(omega: jax.Array, like: jax.Array) -> jax.Array:
    return omega.reshape((omega.shape[0],) + (1,) * (like.ndim - 1))


def make_online_run(predict_fn: Callable[[Pytree, jax.Array], jax.Array],
                    cfg: DecentralizedOnlineConfig):
    """Compile the full T*epoch-iteration run as one scanned jit.

    Returns ``run(x0_stacked, stream_x, stream_y, stream_mask, W_stack) ->
    (z_final_stacked, per_iteration_loss_sums)``.
    """
    mode = cfg.mode.upper()
    if mode not in MODES:
        raise ValueError(f"unknown mode {cfg.mode!r}; available: {MODES}")
    lr = cfg.learning_rate
    wd = cfg.weight_decay

    def loss_fn(params, x, y):
        return bce_with_logits(predict_fn(params, x), y)

    grad_fn = jax.vmap(jax.value_and_grad(loss_fn))

    @jax.jit
    def run(x0_stacked, stream_x, stream_y, stream_mask, W_bank, ts):
        n = stream_x.shape[0]
        K = W_bank.shape[0]

        def step(carry, idx):
            x_params, omega = carry
            t, wi = idx                  # data index (wraps mod T), W index
            Wt = W_bank[wi % K]
            # z_t: the consensus iterate the gradient is evaluated at
            if mode == "PUSHSUM":
                z = jax.tree.map(lambda a: a / _per_node(omega, a), x_params)
            else:
                z = x_params

            xt = stream_x[:, t]          # [N, D] one sample per node
            yt = stream_y[:, t].astype(jnp.float32)
            mt = stream_mask[:, t]       # 0 where the stream is padded

            losses, grads = grad_fn(z, xt, yt)
            if wd:
                grads = jax.tree.map(lambda g, zp: g + wd * zp, grads, z)
            # gradient applied to x at lr, masked on padded steps
            # (client_dsgd.py:68-70: x -= lr * grad_z)
            x_half = jax.tree.map(
                lambda xp, g: xp - lr * _per_node(mt, g) * g, x_params, grads)

            if mode == "LOCAL":
                x_next, omega_next = x_half, omega
            else:
                # receiver i accumulates sender j's x with weight W[j, i]
                # (client_dsgd.py:88-98 / client_pushsum.py:104-121) — i.e.
                # the transpose of the row-stochastic W: column-stochastic push
                A = Wt.T
                x_next = _mix(A, x_half)
                omega_next = A @ omega if mode == "PUSHSUM" else omega
            return (x_next, omega_next), (losses * mt).sum()

        omega0 = jnp.ones((n,), jnp.float32)
        (x_fin, omega_fin), loss_seq = jax.lax.scan(
            step, (x0_stacked, omega0), ts)  # ts = (data_idx, w_idx) arrays
        if mode == "PUSHSUM":
            z_fin = jax.tree.map(lambda a: a / _per_node(omega_fin, a), x_fin)
        else:
            z_fin = x_fin
        return z_fin, loss_seq

    return run


# --------------------------------------------------------------------------
# driver (decentralized_fl_api.py:20-99)
# --------------------------------------------------------------------------

class DecentralizedOnline:
    """N-node online learning over a (possibly directed, possibly
    time-varying) graph, executed as one scanned jit."""

    def __init__(self, streaming_data: Dict[int, List[dict]],
                 config: DecentralizedOnlineConfig,
                 predict_fn: Callable = lr_predict,
                 init_params: Optional[Pytree] = None):
        self.cfg = config
        self.x, self.y, self.mask = streaming_to_arrays(streaming_data)
        self.n = self.x.shape[0]
        T = min(config.iteration_number, self.x.shape[1])
        self.x = self.x[:, :T]
        self.y = self.y[:, :T]
        self.mask = self.mask[:, :T]
        self.T = T
        if init_params is None:
            init_params = init_lr_params(self.x.shape[-1])
        # every node starts from the same point, like the reference's shared
        # model object (decentralized_fl_api.py:52-66 passes one instance)
        self.x0 = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (self.n,) + a.shape), init_params)
        self._run = make_online_run(predict_fn, config)
        self.predict_fn = predict_fn
        self.history: List[Dict[str, float]] = []

    def run(self) -> Dict[str, Any]:
        cfg = self.cfg
        n_iter = self.T * max(cfg.epochs, 1)
        W_bank = _topology_bank(cfg, self.n, n_iter)
        it = np.arange(n_iter, dtype=np.int32)
        z_fin, loss_seq = self._run(
            self.x0, jnp.asarray(self.x), jnp.asarray(self.y),
            jnp.asarray(self.mask), jnp.asarray(W_bank),
            (jnp.asarray(it % self.T), jnp.asarray(it)))
        loss_seq = np.asarray(loss_seq)
        # average regret after t+1 iterations (cal_regret,
        # decentralized_fl_api.py:11-17)
        regret = np.cumsum(loss_seq) / (self.n * np.arange(1, n_iter + 1))
        self.history = [{"iteration": int(t), "average_loss": float(r)}
                        for t, r in enumerate(regret)]
        return {"params_z": z_fin, "regret": regret, "losses": loss_seq,
                "final_regret": float(regret[-1])}

    def accuracy(self, params_z: Pytree) -> float:
        """Fraction of stream samples node 0's final model classifies
        correctly (threshold 0.5 <=> logit 0)."""
        p0 = jax.tree.map(lambda a: a[0], params_z)
        logits = jax.vmap(lambda x: self.predict_fn(p0, x))(
            jnp.asarray(self.x.reshape(-1, self.x.shape[-1])))
        pred = (np.asarray(logits) > 0).astype(np.int32)
        y = self.y.reshape(-1)
        m = self.mask.reshape(-1) > 0
        return float((pred[m] == y[m]).mean())


def run_decentralized_online(streaming_data: Dict[int, List[dict]],
                             config: DecentralizedOnlineConfig,
                             **kw) -> Dict[str, Any]:
    """Functional parity entry (FedML_decentralized_fl,
    decentralized_fl_api.py:20)."""
    algo = DecentralizedOnline(streaming_data, config, **kw)
    out = algo.run()
    out["accuracy"] = algo.accuracy(out["params_z"])
    out["history"] = algo.history
    return out
