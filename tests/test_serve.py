"""Serving-layer contracts (ISSUE 3): bucket-padding invariance, torn-
read-free hot swaps under concurrent load, deadline shedding, drain-on-
shutdown, checkpoint watching across retention GC, and the HTTP surface.

The core invariants mirror the training side's: padding must be
bit-invisible (test_padding_invariance.py for cohorts, here for request
batches), and a reader must never observe half of a model swap (the
checkpointer's torn-save contract, now at serve time).
"""

import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from fedml_tpu.serve.batcher import MicroBatcher, ShedError
from fedml_tpu.serve.registry import CheckpointWatcher, ModelRegistry
from fedml_tpu.serve.server import ServeFrontend

DIM, CLASSES = 6, 4


def _linear_apply():
    return jax.jit(lambda p, x: x.reshape(x.shape[0], -1) @ p["w"] + p["b"])


def _params(version: int):
    """Version-fingerprinted params: row-0 kernel weight == version and
    bias == onehot(version % CLASSES), so a torn kernel/bias mix is
    detectable from any response (the serve_bench probe)."""
    w = np.zeros((DIM, CLASSES), np.float32)
    w[0, :] = float(version)
    b = np.zeros(CLASSES, np.float32)
    b[version % CLASSES] = 1.0
    return {"w": w, "b": b}


def _consistent(y: np.ndarray, version: int) -> bool:
    return (int(round(float(y.min()))) == version
            and int(np.argmax(y)) == version % CLASSES)


def _probe_x():
    x = np.zeros(DIM, np.float32)
    x[0] = 1.0
    return x


def _stack(buckets=(1, 2, 4, 8), version=0, **kw):
    registry = ModelRegistry(_linear_apply(), history=64)
    registry.publish(_params(version), version)
    batcher = MicroBatcher(registry, buckets=buckets, **kw)
    return registry, batcher


# -- bucket padding ----------------------------------------------------------

def test_bucket_padding_invariance():
    """3 live requests padded up to the 8-bucket must return EXACTLY the
    logits of an unpadded direct apply — padded rows are invisible."""
    registry, batcher = _stack(buckets=(8,), max_delay_s=0.05)
    batcher.start()
    rng = np.random.RandomState(0)
    xs = [rng.randn(DIM).astype(np.float32) for _ in range(3)]
    futs = [batcher.submit(x) for x in xs]
    outs = [f.result(10) for f in futs]
    m = registry.current()
    direct = np.asarray(m.apply_fn(m.params, np.stack(xs)))
    for i, out in enumerate(outs):
        np.testing.assert_allclose(np.asarray(out.y), direct[i], atol=1e-6)
        assert out.version == 0
    batcher.stop()


def test_requests_coalesce_into_one_bucket():
    """A burst lands in few, large batches (occupancy histogram moves),
    not one batch per request."""
    from fedml_tpu.obs import telemetry
    telemetry.enable()
    try:
        registry, batcher = _stack(buckets=(1, 2, 4, 8), max_delay_s=0.02)
        futs = [batcher.submit(_probe_x()) for _ in range(8)]  # queued:
        batcher.start()                              # worker not yet live
        for f in futs:
            f.result(10)
        stats = batcher._h_occupancy.stats()
        assert stats["max"] == 8.0, f"burst never coalesced: {stats}"
        batcher.stop()
    finally:
        telemetry.disable()


# -- hot swap under load -----------------------------------------------------

def test_hot_swap_no_torn_reads_and_monotone_versions():
    """4 reader threads hammer predict while versions 1..15 publish
    mid-load: every response must be internally consistent with the
    version that served it, and each reader's observed version sequence
    must be non-decreasing (the registry only moves forward)."""
    registry, batcher = _stack(max_delay_s=0.001, queue_depth=512)
    batcher.start()
    batcher.warmup(_probe_x())
    stop = threading.Event()
    errors, seqs = [], []

    def reader():
        seq = []
        while not stop.is_set():
            try:
                r = batcher.predict(_probe_x(), timeout=10)
            except ShedError:
                continue
            if not _consistent(np.asarray(r.y), r.version):
                errors.append((np.asarray(r.y), r.version))
            seq.append(r.version)
        seqs.append(seq)

    readers = [threading.Thread(target=reader) for _ in range(4)]
    for t in readers:
        t.start()
    for v in range(1, 16):
        time.sleep(0.01)
        registry.publish(_params(v), v)
    time.sleep(0.02)
    stop.set()
    for t in readers:
        t.join(timeout=10)
    batcher.stop()
    assert not errors, f"torn reads: {errors[:3]}"
    for seq in seqs:
        assert seq == sorted(seq), "reader observed a version regression"
    assert max(max(s) for s in seqs if s) == 15, "swaps never became live"


def test_registry_pin_rollback_and_stale_publish():
    registry = ModelRegistry(_linear_apply(), history=8)
    assert registry.current() is None
    registry.publish(_params(0), 0)
    registry.publish(_params(1), 1)
    assert registry.version == 1
    assert registry.rollback() == 0          # live back to 0, pinned
    assert registry.version == 0 and registry.pinned == 0
    assert registry.publish(_params(2), 2)   # lands in history only
    assert registry.version == 0
    registry.unpin()
    assert registry.version == 2 and registry.pinned is None
    registry.pin(1)
    assert registry.version == 1
    assert not registry.publish(_params(1), 1), "stale publish accepted"
    with pytest.raises(KeyError):
        registry.pin(99)


def test_history_eviction_never_drops_pinned_version():
    """Serve-while-train keeps publishing past a pin: eviction must skip
    the pinned/live version so it stays rollback-able/pin-able."""
    registry = ModelRegistry(_linear_apply(), history=3)
    for v in range(3):
        registry.publish(_params(v), v)
    registry.rollback()                       # live+pinned = 1
    for v in range(3, 10):                    # publishes keep landing
        registry.publish(_params(v), v)
    assert 1 in registry.versions(), "pinned version evicted"
    assert registry.version == 1
    with pytest.raises(RuntimeError):
        registry.rollback()  # nothing older than the pin survives: loud,
        #                      not a ValueError from a missing dict key
    registry.unpin()
    assert registry.version == 9


# -- shedding ----------------------------------------------------------------

def test_deadline_shedding():
    """A request whose deadline expires while queued is shed at dequeue,
    not served late; fresh requests still get answers."""
    registry = ModelRegistry(
        lambda p, x: (time.sleep(0.08), x @ p["w"] + p["b"])[1])
    registry.publish(_params(0), 0)
    batcher = MicroBatcher(registry, buckets=(1,), max_delay_s=0.0)
    batcher.start()
    blocker = batcher.submit(_probe_x())          # occupies the worker
    doomed = batcher.submit(_probe_x(), deadline_s=0.01)
    with pytest.raises(ShedError, match="deadline"):
        doomed.result(10)
    assert blocker.result(10).version == 0
    ok = batcher.submit(_probe_x(), deadline_s=5.0)
    assert ok.result(10).version == 0
    batcher.stop()


def test_queue_full_sheds_at_submit():
    registry, batcher = _stack(queue_depth=2)  # worker NOT started
    batcher.submit(_probe_x())
    batcher.submit(_probe_x())
    with pytest.raises(ShedError, match="queue_full"):
        batcher.submit(_probe_x())
    batcher.stop(drain=False)


def test_no_model_sheds():
    registry = ModelRegistry(_linear_apply())
    batcher = MicroBatcher(registry, buckets=(1,)).start()
    with pytest.raises(ShedError, match="no_model"):
        batcher.predict(_probe_x(), timeout=10)
    batcher.stop()


# -- shutdown ----------------------------------------------------------------

def test_drain_on_shutdown_answers_queued_requests():
    registry, batcher = _stack(buckets=(1, 2, 4), max_delay_s=0.001)
    futs = [batcher.submit(_probe_x()) for _ in range(10)]  # queued
    batcher.start()
    batcher.stop(drain=True)
    for f in futs:
        assert _consistent(np.asarray(f.result(0).y), 0)
    with pytest.raises(ShedError, match="shutdown"):
        batcher.submit(_probe_x())


def test_malformed_instance_fails_only_its_own_request():
    """One bad-shape x in a micro-batch must fail ITS request alone —
    batchmates still get answers."""
    registry, batcher = _stack(buckets=(4,), max_delay_s=0.01)
    good = [batcher.submit(_probe_x()) for _ in range(2)]
    bad = batcher.submit(np.zeros(3, np.float32))  # wrong sample shape
    batcher.start()
    for f in good:
        assert f.result(10).version == 0
    with pytest.raises(ValueError, match="does not match"):
        bad.result(10)
    # the malformed request arriving FIRST must not hijack the shape
    # anchor either (the model shape is learned from the good batch)
    bad_first = batcher.submit(np.zeros(3, np.float32))
    good_after = [batcher.submit(_probe_x()) for _ in range(2)]
    with pytest.raises(ValueError, match="does not match"):
        bad_first.result(10)
    for f in good_after:
        assert f.result(10).version == 0
    batcher.stop()


def test_cancelled_future_does_not_kill_worker():
    """A client cancelling its Future (client-side timeout) must not
    raise InvalidStateError out of the worker — everyone else's requests
    keep answering."""
    registry, batcher = _stack(buckets=(4,), max_delay_s=0.01)
    futs = [batcher.submit(_probe_x()) for _ in range(4)]
    assert futs[0].cancel()
    batcher.start()
    for f in futs[1:]:
        assert f.result(10).version == 0
    assert batcher.predict(_probe_x(), timeout=10).version == 0
    batcher.stop()


def test_abort_shutdown_sheds_queued_requests():
    registry, batcher = _stack()
    futs = [batcher.submit(_probe_x()) for _ in range(5)]
    batcher.stop(drain=False)   # never started: settles inline
    for f in futs:
        with pytest.raises(ShedError, match="shutdown"):
            f.result(0)


# -- checkpoint watcher ------------------------------------------------------

def _ck_params(seed):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(DIM, CLASSES).astype(np.float32),
            "b": rng.randn(CLASSES).astype(np.float32)}


def test_watcher_publishes_rounds_and_tolerates_gc(tmp_path):
    """Rounds appear → watcher publishes them in order; the retention GC
    (keep_last_n) deleting old steps — and a bogus/vanished step dir —
    must never kill the watcher or the live model."""
    from fedml_tpu.utils.checkpoint import RoundCheckpointer
    ck_dir = str(tmp_path / "ck")
    ck = RoundCheckpointer(ck_dir, save_every=1, keep_last_n=2)
    registry = ModelRegistry(_linear_apply(), history=16)
    watcher = CheckpointWatcher(registry, ck_dir, poll_s=0.05)

    def state(i):
        return {"params": _ck_params(i),
                "round_idx": np.asarray(i, np.int64)}

    assert watcher.poll_once() == 0            # empty dir: no-op
    ck.save(0, state(0))
    ck.save(1, state(1))
    assert watcher.poll_once() == 2
    assert registry.version == 1

    # retention GC: saves 2 and 3 evict 0 and 1 from disk
    ck.save(2, state(2))
    ck.save(3, state(3))
    import os
    steps = sorted(n for n in os.listdir(ck_dir) if n.isdigit())
    assert steps == ["2", "3"], f"keep_last_n GC kept {steps}"

    # a step dir that vanishes between list and load: simulate with a
    # bogus empty digit-dir — unreadable, must be skipped not fatal
    os.makedirs(str(tmp_path / "ck" / "7"))
    assert watcher.poll_once() == 2            # 2 and 3 load; 7 skipped
    assert registry.version == 3
    assert watcher._seen == 7                  # not retried forever
    np.testing.assert_allclose(
        np.asarray(registry.current().params["w"]), _ck_params(3)["w"])
    ck.close()


def test_serve_while_train_publish_hook(tmp_path):
    """The cross-silo server's publish hook feeds a registry each round:
    versions advance with training and the LAST round's global is what
    serves (the serve-while-train acceptance, pump-mode)."""
    from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                                 FedAvgServerActor)
    from fedml_tpu.comm.local import LocalHub

    init = {"dense": {"kernel": np.zeros((4, 3), np.float32)}}

    def train_fn(params, client_idx, round_idx):
        return jax.tree.map(lambda v: v + 1.0, params), 10

    registry = ModelRegistry(lambda p, x: x, history=8)
    hub = LocalHub()
    server = FedAvgServerActor(
        hub.transport(0), init, client_num_in_total=2,
        client_num_per_round=2, num_rounds=3, publish=registry.publish)
    clients = [FedAvgClientActor(i, hub.transport(i), train_fn)
               for i in (1, 2)]
    server.register_handlers()
    for c in clients:
        c.register_handlers()
    server.start()
    hub.pump()
    assert registry.versions() == [0, 1, 2]
    assert registry.version == 2
    np.testing.assert_allclose(
        np.asarray(registry.current().params["dense"]["kernel"]),
        np.full((4, 3), 3.0))


# -- HTTP frontend -----------------------------------------------------------

def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, json.loads(body) if body.startswith(b"{") else body


def _post(port, path, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    return resp.status, body


def test_http_frontend_lifecycle(tmp_path):
    registry = ModelRegistry(_linear_apply(), history=8)
    batcher = MicroBatcher(registry, buckets=(1, 2, 4), max_delay_s=0.001)
    frontend = ServeFrontend(registry, batcher, port=0).start()
    port = frontend.port
    try:
        # before any model: health 503 (LB keeps us out of rotation),
        # predict 503
        status, body = _get(port, "/healthz")
        assert status == 503 and body["status"] == "no_model"
        status, body = _post(port, "/predict", {"x": _probe_x().tolist()})
        assert status == 503 and body["reason"] == "no_model"

        registry.publish(_params(4), 4)
        status, body = _get(port, "/healthz")
        assert status == 200 and body["version"] == 4
        status, body = _get(port, "/healthz?probe=1")  # LB cache-buster
        assert status == 200
        status, body = _post(port, "/predict", {"x": _probe_x().tolist()})
        assert status == 200 and body["version"] == 4
        assert _consistent(np.asarray(body["y"]), 4)

        status, body = _get(port, "/version")
        assert status == 200 and body["version"] == 4
        assert body["history"] == [4]

        status, body = _post(port, "/predict", {"wrong_key": 1})
        assert status == 400
        status, body = _post(port, "/predict",
                             {"x": _probe_x().tolist(),
                              "deadline_ms": "fast"})
        assert status == 400, "non-numeric deadline must 400, not crash"
        status, _ = _get(port, "/nope")
        assert status == 404
        status, _ = _post(port, "/nope", {"x": [1]})
        assert status == 404
    finally:
        frontend.stop()
    # stopped batcher sheds: the frontend maps it to 429 — exercised via
    # the batcher directly (the listener is closed now)
    with pytest.raises(ShedError, match="shutdown"):
        batcher.submit(_probe_x())


def test_http_keepalive_two_requests_one_connection():
    """Satellite pin (ISSUE 15): the handler speaks HTTP/1.1 keep-alive
    with correct Content-Length framing — two requests ride ONE TCP
    connection, byte-accurate bodies, no per-request dial."""
    registry = ModelRegistry(_linear_apply(), history=8)
    registry.publish(_params(2), 2)
    batcher = MicroBatcher(registry, buckets=(1, 2), max_delay_s=0.001)
    frontend = ServeFrontend(registry, batcher, port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", frontend.port,
                                          timeout=10)
        conn.connect()
        sock_before = conn.sock
        for i in range(2):   # two POSTs, one connection
            conn.request("POST", "/predict",
                         json.dumps({"x": _probe_x().tolist()}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.version == 11, "handler fell back to HTTP/1.0"
            clen = resp.getheader("Content-Length")
            body = resp.read()
            assert clen is not None and int(clen) == len(body), (
                "Content-Length does not frame the body — keep-alive "
                "would desync on the next request")
            assert json.loads(body)["version"] == 2
        assert conn.sock is sock_before, "connection was re-dialed"
        # a GET on the SAME connection still frames correctly
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 200
        assert int(resp.getheader("Content-Length")) == len(resp.read())
        conn.close()
    finally:
        frontend.stop()


def test_registry_pin_survives_concurrent_publish_storm():
    """Satellite audit (ISSUE 15): a pinned version must never be
    evicted out from under a serving worker while publishes hammer the
    registry from another thread — current() stays the pinned snapshot
    and the pinned version stays in history throughout."""
    registry = ModelRegistry(_linear_apply(), history=3)
    for v in range(3):
        registry.publish(_params(v), v)
    registry.pin(1)
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            m = registry.current()
            if m is None or m.version != 1:
                errors.append(("lost pin", None if m is None
                               else m.version))
            if 1 not in registry.versions():
                errors.append(("pinned version evicted from history",))

    threads = [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for v in range(3, 40):
        registry.publish(_params(v), v)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors[:3]
    assert 1 in registry.versions()
    # history stayed bounded despite the protected entries
    assert len(registry.versions()) <= 4
    registry.unpin()
    assert registry.version == 39


def test_rollback_on_fully_evicted_history_fails_loudly():
    """Satellite audit: rollback() when eviction left nothing older than
    the live version raises — it must never serve None or a KeyError
    from a missing history slot."""
    registry = ModelRegistry(_linear_apply(), history=2)
    for v in range(6):   # eviction keeps only the newest + live
        registry.publish(_params(v), v)
    registry.rollback()          # one older version still exists
    assert registry.version == 4
    registry.unpin()
    for v in range(6, 12):
        registry.publish(_params(v), v)
    registry.rollback()
    with pytest.raises(RuntimeError, match="cannot rollback"):
        registry.rollback()      # nothing older survived eviction
    assert registry.current() is not None, "rollback left a None model"


def test_http_deadline_propagates_to_429():
    """A request whose deadline_ms cannot be met while the worker is
    busy answers 429 (shed), not a late 200."""
    registry = ModelRegistry(
        lambda p, x: (time.sleep(0.1), x @ p["w"] + p["b"])[1])
    registry.publish(_params(0), 0)
    batcher = MicroBatcher(registry, buckets=(1,), max_delay_s=0.0)
    frontend = ServeFrontend(registry, batcher, port=0).start()
    port = frontend.port
    try:
        blocker = threading.Thread(
            target=_post, args=(port, "/predict",
                                {"x": _probe_x().tolist()}))
        blocker.start()
        time.sleep(0.03)  # the blocker's batch is now on the worker
        status, body = _post(port, "/predict",
                             {"x": _probe_x().tolist(), "deadline_ms": 5})
        blocker.join(timeout=10)
        assert status == 429 and body["reason"] == "deadline"
    finally:
        frontend.stop()


@pytest.mark.slow
def test_sustained_load_acceptance(tmp_path):
    """The serve_bench v2 acceptance in miniature: the --smoke arm set
    (replay + http + decode, fresh subprocesses each) runs green, the
    artifact validates against the trend gate's schema, and the smoke
    replay arm still sheds nothing and tears nothing."""
    import subprocess
    import sys

    from fedml_tpu.obs.trend import validate_serve_bench
    out = str(tmp_path / "BENCH_serve_smoke.json")
    proc = subprocess.run(
        [sys.executable, "scripts/serve_bench.py", "--smoke",
         "--out", out],
        capture_output=True, text=True, timeout=900,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": "/tmp"},
        cwd=str(__import__("pathlib").Path(__file__).parent.parent))
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    bench = json.load(open(out))
    assert bench["version"] == 2 and bench["smoke"] is True
    assert validate_serve_bench(bench) == []
    replay = bench["arms"]["replay"]
    assert replay["torn_responses"] == 0
    assert replay["latency_ms"]["p99"] <= replay["deadline_ms"]
    decode = bench["arms"]["decode"]
    assert decode["occupancy_ratio"] >= 2.0
    assert decode["recompiles_after_warmup"] == 0
