"""Robust aggregation defenses — pure-JAX, fuseable into the aggregation step.

Re-implements ``fedml_core/robustness/robust_aggregation.py``:

* ``clip_update`` = ``RobustAggregator.norm_diff_clipping`` (:38-49): scale a
  client update so that ||w_client - w_global|| <= norm_bound.
* ``add_gaussian_noise`` = ``RobustAggregator.add_noise`` (:51-55): weak
  differential privacy via N(0, stddev) perturbation.

Unlike the reference (torch ops on CPU state_dicts, one client at a time),
these are jit-able and vmap over a stacked client axis, so the whole cohort's
defense + aggregation compiles to one XLA program.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fedml_tpu.core.pytree import tree_sub

Pytree = Any


def _masked_global_norm(tree: Pytree, is_weight) -> jax.Array:
    """L2 norm over leaves selected by ``is_weight(path)``."""
    total = 0.0
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        if is_weight(path):
            total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return jnp.sqrt(total)


def default_is_weight_param(path) -> bool:
    """Parity with ``is_weight_param`` (robust_aggregation.py:28-30): exclude
    normalization running statistics from the norm and from clipping.  In
    flax those live under a ``batch_stats`` collection (keys ``mean``/``var``);
    we also honor the reference's torch-style key names."""
    keys = "/".join(str(getattr(p, "key", getattr(p, "idx", ""))) for p in path)
    return not any(s in keys for s in
                   ("batch_stats", "running_mean", "running_var",
                    "num_batches_tracked"))


def clip_update(client_params: Pytree, global_params: Pytree,
                norm_bound: float, is_weight=default_is_weight_param) -> Pytree:
    """Norm-difference clipping (robust_aggregation.py:38-49).

    weight_diff_norm = ||client - global|| over *weight* leaves only;
    client' = global + (client-global) * min(1, bound/||diff||).  Non-weight
    leaves (running statistics) pass through unclipped, as in the reference's
    ``load_model_weight_diff`` (robust_aggregation.py:12-25).
    """
    diff = tree_sub(client_params, global_params)
    norm = _masked_global_norm(diff, is_weight)
    scale = jnp.minimum(1.0, norm_bound / jnp.maximum(norm, 1e-12))

    def _apply(path, g, d, c):
        if is_weight(path):
            return g + d * scale.astype(d.dtype)
        return c

    return jax.tree_util.tree_map_with_path(_apply, global_params, diff,
                                            client_params)


def add_gaussian_noise(params: Pytree, key: jax.Array, stddev: float) -> Pytree:
    """Weak-DP Gaussian noise (robust_aggregation.py:51-55)."""
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(key, len(leaves))
    # noise only float leaves; integer leaves (step counters, batch-norm
    # trackers) pass through — the reference perturbs weights only
    noised = [x + stddev * jax.random.normal(k, x.shape, x.dtype)
              if jnp.issubdtype(x.dtype, jnp.floating) else x
              for x, k in zip(leaves, keys)]
    return jax.tree.unflatten(treedef, noised)
