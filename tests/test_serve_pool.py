"""Multi-worker serving pool contracts (ISSUE 15): N accept loops × one
registry with unchanged hot-swap semantics, torn-read-free responses
under concurrent publish (the checksum/fingerprint trick from the wire
tests), worker-labeled telemetry, tiered shedding wired to the SAME
SloEvaluator verdicts as deep-healthz, shed-reason accounting under
saturation, the shared-socket fallback, and the BENCH_serve v2 schema
gate (`obs/trend.validate_serve_bench`).
"""

import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from fedml_tpu.obs import telemetry
from fedml_tpu.obs.perf import SloEvaluator
from fedml_tpu.obs.trend import validate_serve_bench
from fedml_tpu.serve.batcher import MicroBatcher, ShedError, TierGate
from fedml_tpu.serve.pool import ServeWorkerPool
from fedml_tpu.serve.registry import ModelRegistry

DIM, CLASSES = 6, 4


def _linear_apply():
    return jax.jit(lambda p, x: x.reshape(x.shape[0], -1) @ p["w"] + p["b"])


def _params(version: int):
    w = np.zeros((DIM, CLASSES), np.float32)
    w[0, :] = float(version)
    b = np.zeros(CLASSES, np.float32)
    b[version % CLASSES] = 1.0
    return {"w": w, "b": b}


def _consistent(y: np.ndarray, version: int) -> bool:
    return (int(round(float(y.min()))) == version
            and int(np.argmax(y)) == version % CLASSES)


def _probe_x():
    x = np.zeros(DIM, np.float32)
    x[0] = 1.0
    return x


def _pool(workers=2, version=0, history=64, **kw):
    registry = ModelRegistry(_linear_apply(), history=history)
    registry.publish(_params(version), version)
    kw.setdefault("max_delay_s", 0.001)
    pool = ServeWorkerPool(registry, workers=workers, **kw)
    return registry, pool


def _post(port, payload, conn=None):
    own = conn is None
    if own:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", "/predict", json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    if own:
        conn.close()
    return resp.status, body


def _get(port, path, conn=None):
    own = conn is None
    if own:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = json.loads(resp.read())
    if own:
        conn.close()
    return resp.status, body


# -- pool lifecycle ----------------------------------------------------------

@pytest.mark.parametrize("reuseport", [True, False])
def test_pool_serves_on_one_port_both_socket_modes(reuseport):
    registry, pool = _pool(workers=3, reuseport=reuseport)
    pool.start()
    try:
        workers_seen = set()
        for _ in range(12):
            status, body = _get(pool.port, "/healthz")
            assert status == 200
            assert body["workers"] == 3
            assert len(body["queue_depths"]) == 3
            workers_seen.add(body["worker"])
            status, body = _post(pool.port, {"x": _probe_x().tolist()})
            assert status == 200 and body["version"] == 0
            assert _consistent(np.asarray(body["y"]), 0)
        assert workers_seen <= {0, 1, 2}
    finally:
        pool.stop()


def test_pool_rejects_invalid_workers_and_factory_kwargs():
    registry = ModelRegistry(_linear_apply())
    with pytest.raises(ValueError, match="workers"):
        ServeWorkerPool(registry, workers=0)
    with pytest.raises(ValueError, match="factory"):
        ServeWorkerPool(registry, batcher_factory=lambda i: None,
                        queue_depth=8)
    # slo + custom factory: the pool cannot inject the gate, and
    # dropping it silently would divorce shedding from deep-healthz —
    # fail loudly instead
    with pytest.raises(ValueError, match="slo"):
        ServeWorkerPool(registry, batcher_factory=lambda i: None,
                        slo=object())


def test_pool_hot_swap_never_torn_and_versions_published_only():
    """Satellite: concurrent publish under multi-worker serving — every
    response's version is one that WAS published and its params are
    internally consistent (fingerprint kernel/bias pair), across all
    workers, while 15 swaps land mid-load."""
    registry, pool = _pool(workers=3, queue_depth=512)
    pool.start()
    published = {0}
    errors = []
    stop = threading.Event()

    def reader(tid):
        conn = http.client.HTTPConnection("127.0.0.1", pool.port,
                                          timeout=10)
        last = -1
        while not stop.is_set():
            try:
                status, body = _post(pool.port,
                                     {"x": _probe_x().tolist()}, conn)
            except Exception:
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", pool.port,
                                                  timeout=10)
                continue
            if status != 200:
                continue
            v = body["version"]
            y = np.asarray(body["y"])
            if v not in published:
                errors.append(("unpublished version", v))
            if not _consistent(y, v):
                errors.append(("torn", v, y.tolist()))
            if v < last:
                errors.append(("version regression", last, v))
            last = v
        conn.close()

    readers = [threading.Thread(target=reader, args=(i,))
               for i in range(4)]
    for t in readers:
        t.start()
    for v in range(1, 16):
        published.add(v)     # add BEFORE publish: readers may see it
        #                      the instant the registry swaps
        registry.publish(_params(v), v)
        time.sleep(0.01)
    time.sleep(0.05)
    stop.set()
    for t in readers:
        t.join(timeout=30)
    pool.stop()
    assert not errors, errors[:5]


def test_pool_worker_labeled_telemetry():
    telemetry.enable()
    try:
        registry, pool = _pool(workers=2)
        pool.start()
        for _ in range(6):
            _post(pool.port, {"x": _probe_x().tolist()})
        snap = telemetry.get_registry().snapshot()
        req_series = [k for k in snap["counters"]
                      if k.startswith("fedml_serve_requests_total")
                      and 'worker="' in k]
        assert req_series, "no worker-labeled request counters"
        gauges = [k for k in snap["gauges"]
                  if k.startswith("fedml_serve_queue_utilization_ratio")]
        assert gauges, "no queue-utilization gauges"
        assert snap["gauges"].get("fedml_serve_workers_value") == 2.0
        pool.stop()
    finally:
        telemetry.disable()


def test_pool_workers_land_on_one_metrics_scrape():
    """--metrics_port exposes EVERY pool worker on a single scrape:
    the workers are threads over one process registry, so one exposition
    carries each worker's labeled series side by side."""
    telemetry.enable()
    server = None
    try:
        registry, pool = _pool(workers=2)
        pool.start()
        for i in range(8):
            _post(pool.port, {"x": _probe_x().tolist()})
        server = telemetry.start_http_server(0, host="127.0.0.1")
        assert server is not None
        port = server.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        workers_seen = {w for w in ("0", "1")
                        if f'fedml_serve_requests_total{{worker="{w}"}}'
                        in text}
        assert workers_seen == {"0", "1"}, \
            f"one scrape must carry every worker, saw {workers_seen}"
        pool.stop()
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        telemetry.disable()


def test_metrics_endpoint_fails_loud_when_telemetry_disabled():
    """start_http_server over the Null registry would serve an empty
    exposition forever — it must raise, not lie."""
    assert telemetry.get_registry().__class__.__name__ == "NullRegistry"
    with pytest.raises(ValueError, match="telemetry is disabled"):
        telemetry.start_http_server(0, host="127.0.0.1")


# -- tiered admission + SLO coupling ----------------------------------------

def test_best_effort_sheds_at_soft_watermark_interactive_keeps_reserve():
    registry = ModelRegistry(_linear_apply())
    registry.publish(_params(0), 0)
    batcher = MicroBatcher(registry, queue_depth=4,
                           best_effort_headroom=0.5)  # BE cap = 2
    batcher.submit(_probe_x())
    batcher.submit(_probe_x())
    with pytest.raises(ShedError, match="queue_full"):
        batcher.submit(_probe_x(), tier="best_effort")
    batcher.submit(_probe_x())          # interactive still admitted
    batcher.submit(_probe_x())
    with pytest.raises(ShedError, match="queue_full"):
        batcher.submit(_probe_x())      # hard cap for everyone
    with pytest.raises(ValueError, match="unknown tier"):
        batcher.submit(_probe_x(), tier="bulk")
    batcher.stop(drain=False)


def test_tier_gate_and_deep_healthz_read_the_same_verdict():
    """The contract satellite (c) pins: when tiered admission sheds
    best_effort for slo_degraded, /healthz?deep=1 answers 503 naming
    the SAME breached objective — one evaluator, never two stories."""
    telemetry.enable()
    try:
        reg = telemetry.get_registry()
        slo = SloEvaluator(registry=reg)
        registry, pool = _pool(workers=2, queue_depth=4, slo=slo)
        pool.start()
        gate = pool.batchers[0].tier_gate
        assert isinstance(gate, TierGate)
        assert gate.degraded() is False
        # worker 0 reports a nearly-full queue (the gauge every batcher
        # maintains on submit/dequeue): utilization 1.0 breaches the
        # serve_queue_utilization_ratio objective (threshold 0.9)
        reg.gauge("fedml_serve_queue_utilization_ratio",
                  worker="0").set(1.0)
        gate._checked_at = -1e30    # expire the TTL cache
        assert gate.degraded() is True
        with pytest.raises(ShedError, match="slo_degraded"):
            pool.batchers[1].submit(_probe_x(), tier="best_effort")
        status, body = _get(pool.port, "/healthz?deep=1")
        assert status == 503, body
        assert body["status"] == "slo_breach"
        assert not body["slo"]["serve_queue_utilization_ratio"]["ok"]
        # interactive traffic still flows on the healthy worker
        assert pool.batchers[1].submit(_probe_x()) is not None
        pool.stop()
    finally:
        telemetry.disable()


def test_slo_reads_worst_worker_not_the_average():
    telemetry.enable()
    try:
        reg = telemetry.get_registry()
        reg.gauge("fedml_serve_queue_utilization_ratio",
                  worker="0").set(0.05)
        reg.gauge("fedml_serve_queue_utilization_ratio",
                  worker="1").set(0.97)
        slo = SloEvaluator(registry=reg)
        out = slo.evaluate(count_breaches=False)
        v = out["serve_queue_utilization_ratio"]
        assert v["value"] == 0.97 and not v["ok"]
    finally:
        telemetry.disable()


def test_shed_reason_accounting_under_saturation():
    """Satellite: every 429 under saturation is accounted, by reason and
    tier, in fedml_serve_shed_total — counters and observed sheds agree
    exactly."""
    telemetry.enable()
    try:
        registry = ModelRegistry(_linear_apply())
        registry.publish(_params(0), 0)
        batcher = MicroBatcher(registry, queue_depth=3,
                               best_effort_headroom=1 / 3, worker="7")
        sheds = {"queue_full": 0}
        admitted = 0
        for i in range(10):
            tier = "best_effort" if i % 2 else "interactive"
            try:
                batcher.submit(_probe_x(), tier=tier)
                admitted += 1
            except ShedError as e:
                sheds[e.reason] += 1
        assert admitted == 3 and sheds["queue_full"] == 7
        snap = telemetry.get_registry().snapshot()
        total = sum(v for k, v in snap["counters"].items()
                    if k.startswith("fedml_serve_shed_total")
                    and 'reason="queue_full"' in k and 'worker="7"' in k)
        assert total == 7
        be = sum(v for k, v in snap["counters"].items()
                 if k.startswith("fedml_serve_shed_total")
                 and 'tier="best_effort"' in k and 'worker="7"' in k)
        assert be >= 4    # best_effort shed first (soft watermark)
        batcher.stop(drain=False)
    finally:
        telemetry.disable()


def test_slo_degraded_sheds_do_not_feed_the_shed_rate_objective():
    """Tier-gate sheds must not inflate serve_shed_rate: counting them
    would close a feedback loop (sheds raise the rate, the rate keeps
    the gate degraded, the gate sheds more) that latches a transient
    breach into a permanent one."""
    telemetry.enable()
    try:
        reg = telemetry.get_registry()
        reg.counter("fedml_serve_requests_total").inc(100)
        reg.counter("fedml_serve_shed_total", reason="queue_full",
                    tier="interactive").inc(2)
        reg.counter("fedml_serve_shed_total", reason="slo_degraded",
                    tier="best_effort").inc(500)
        slo = SloEvaluator(registry=reg)
        v = slo.evaluate(count_breaches=False)["serve_shed_rate"]
        assert v["value"] == 0.02, (
            f"slo_degraded sheds leaked into shed_rate: {v}")
        assert v["ok"]
    finally:
        telemetry.disable()


def test_unbounded_queue_has_no_best_effort_watermark():
    """queue_depth=0 (unbounded) must not collapse the best-effort cap
    to 1 — there is no fill fraction, so there is no watermark (the
    tier gate still applies)."""
    from fedml_tpu.serve.batcher import best_effort_cap
    assert best_effort_cap(0, 0.5) is None
    assert best_effort_cap(8, 0.5) == 4
    with pytest.raises(ValueError, match="headroom"):
        best_effort_cap(8, 1.5)
    registry = ModelRegistry(_linear_apply())
    registry.publish(_params(0), 0)
    batcher = MicroBatcher(registry, queue_depth=0)
    batcher.submit(_probe_x())
    batcher.submit(_probe_x(), tier="best_effort")   # not blackholed
    batcher.stop(drain=False)


def test_tier_gate_ttl_caches_the_evaluator():
    calls = []

    class _Slo:
        def evaluate(self, count_breaches=True):
            calls.append(count_breaches)
            return {"x": {"ok": True}}

    gate = TierGate(_Slo(), ttl_s=60.0)
    for _ in range(50):
        assert gate.degraded() is False
    assert len(calls) == 1, "gate must not evaluate per request"
    assert calls[0] is False, "admission probes must not count breaches"


# -- CLI config gates --------------------------------------------------------

class TestServeConfigGates:
    def test_serve_workers_requires_serve_port(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="serve_port"):
            main(["--algo", "cross_silo", "--serve_workers", "2"])

    def test_serve_workers_must_be_positive(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="serve_workers"):
            main(["--algo", "cross_silo", "--serve_port", "8351",
                  "--serve_workers", "0"])

    def test_best_effort_headroom_bounds(self):
        from fedml_tpu.experiments.main import main
        with pytest.raises(ValueError, match="best_effort_headroom"):
            main(["--algo", "cross_silo", "--serve_port", "8351",
                  "--serve_best_effort_headroom", "1.5"])


# -- BENCH_serve v2 schema gate ---------------------------------------------

def _bench_v2(**over):
    arm = {"backend": "cpu", "torn_responses": 0,
           "gates": {"g": {"ok": True}}}
    obj = {"bench": "serve", "version": 2, "smoke": False,
           "arms": {"replay": dict(arm), "http": dict(arm),
                    "decode": dict(arm)}}
    obj.update(over)
    return obj


def test_validate_serve_bench_accepts_committed_shape():
    assert validate_serve_bench(_bench_v2()) == []


def test_validate_serve_bench_rejects_failed_gate_and_missing_arm():
    bad = _bench_v2()
    bad["arms"]["replay"]["gates"]["g"] = {"ok": False, "value": 1}
    assert any("FAILED" in p for p in validate_serve_bench(bad))
    noarm = _bench_v2()
    del noarm["arms"]["decode"]
    assert any("decode" in p for p in validate_serve_bench(noarm))
    v1 = {"bench": "serve", "throughput_rps": 1500.0}
    assert validate_serve_bench(v1), "v1 artifact must not validate"
    torn = _bench_v2()
    torn["arms"]["http"]["torn_responses"] = 2
    assert any("torn" in p for p in validate_serve_bench(torn))
    nolabel = _bench_v2()
    del nolabel["arms"]["http"]["backend"]
    assert any("backend" in p for p in validate_serve_bench(nolabel))


def test_validate_serve_bench_failed_gate_not_excused_by_smoke_label():
    """A smoke label must not waive failed gate verdicts, and the
    committed-trend-line mode (allow_smoke=False, what perf_trend uses)
    rejects smoke artifacts outright — a /tmp smoke run can never be
    re-committed as the trend anchor."""
    smoked = _bench_v2(smoke=True)
    smoked["arms"]["replay"]["gates"]["g"] = {"ok": False}
    assert any("FAILED" in p for p in validate_serve_bench(smoked))
    clean_smoke = _bench_v2(smoke=True)
    assert validate_serve_bench(clean_smoke) == []
    assert any("smoke" in p for p in
               validate_serve_bench(clean_smoke, allow_smoke=False))


def test_committed_bench_serve_passes_the_gate():
    import pathlib
    path = pathlib.Path(__file__).parent.parent / "BENCH_serve.json"
    obj = json.loads(path.read_text())
    assert validate_serve_bench(obj, allow_smoke=False) == [], (
        "committed BENCH_serve.json fails its own trend gate")
    assert obj["arms"]["replay"]["throughput_rps"] >= 10000
    assert obj["arms"]["decode"]["occupancy_ratio"] >= 2.0
    assert obj["arms"]["decode"]["recompiles_after_warmup"] == 0
    assert any("decode_step" in n
               for n in obj["arms"]["decode"]["compile_ledger"])
