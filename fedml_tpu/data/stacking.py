"""Host-side cohort staging: ragged per-client data -> padded device arrays.

The reference feeds each client a torch DataLoader over its own tensor list
(MNIST/data_loader.py:51-75) and the simulator re-points one trainer at a
different client's loader each round (FedAVGTrainer.update_dataset,
FedAVGTrainer.py:25-29).  The TPU equivalent (SURVEY.md §2.4): keep ALL
clients' data in stacked host arrays ``[num_clients, S, B, ...]`` padded to
a common S, and per round *gather* the sampled cohort's rows and ship one
contiguous block to device.  Masks keep padded rows out of loss/metrics, so
sample-weighted aggregation stays exact despite padding.

This is the "process k plays client i" trick turned into an indexed gather —
no per-round re-staging, no re-jit (cohort shapes are static).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

Array = np.ndarray


@dataclasses.dataclass
class FederatedData:
    """The uniform dataset contract (TPU-native version of the reference's
    9-tuple, e.g. main_fedavg.py:118-120).

    train: dict of stacked arrays {x: [N, S, B, ...], y: [N, S, B, ...],
           mask: [N, S, B], num_samples: [N]} over all N clients.
    test/global test: same layout (or None).
    """
    client_num: int
    class_num: int
    train: Dict[str, Array]
    test: Optional[Dict[str, Array]] = None
    train_global: Optional[Dict[str, Array]] = None
    test_global: Optional[Dict[str, Array]] = None

    @property
    def train_data_num(self) -> int:
        return int(self.train["num_samples"].sum())


def stack_client_data(xs: Sequence[Array], ys: Sequence[Array],
                      batch_size: int, steps: Optional[int] = None,
                      shuffle_seed: Optional[int] = None) -> Dict[str, Array]:
    """Stack ragged per-client (x, y) into [C, S, B, ...] + mask + counts.

    S = ceil(max_i n_i / B) unless given.  Clients with fewer samples get
    zero-padded batches with mask 0.  With ``shuffle_seed`` each client's
    samples are shuffled once (the reference shuffles MNIST with fixed seed
    100, MNIST/data_loader.py:51-56)."""
    C = len(xs)
    assert C == len(ys)
    rng = np.random.RandomState(shuffle_seed) if shuffle_seed is not None else None
    counts = np.asarray([len(x) for x in xs], dtype=np.int64)
    if steps is None:
        steps = int(np.ceil(max(int(counts.max()), 1) / batch_size))
    cap = steps * batch_size

    # derive shapes/dtypes from the first NON-empty client, so absent users
    # (LEAF splits missing a user yield shape-(0,) arrays) don't poison the
    # stacked layout
    x0 = next((np.asarray(x) for x in xs if len(x)), np.asarray(xs[0]))
    sample_shape = x0.shape[1:]
    x_out = np.zeros((C, steps, batch_size) + sample_shape, dtype=x0.dtype)
    y0 = next((np.asarray(y) for y in ys if len(y)), np.asarray(ys[0]))
    y_shape = y0.shape[1:]
    y_dtype = y0.dtype
    y_out = np.zeros((C, steps, batch_size) + y_shape, dtype=y_dtype)
    mask = np.zeros((C, steps, batch_size), dtype=np.float32)

    clipped = np.minimum(counts, cap)
    for c in range(C):
        n = int(clipped[c])
        if n == 0:  # empty client: all-zero padding, mask 0, weight 0
            continue
        x = np.asarray(xs[c])[:n]
        y = np.asarray(ys[c])[:n]
        if rng is not None and n > 1:
            perm = rng.permutation(n)
            x, y = x[perm], y[perm]
        flat_x = x_out[c].reshape((cap,) + sample_shape)
        flat_y = y_out[c].reshape((cap,) + y_shape)
        flat_m = mask[c].reshape(cap)
        flat_x[:n] = x
        flat_y[:n] = y
        flat_m[:n] = 1.0
    return {"x": x_out, "y": y_out, "mask": mask,
            "num_samples": clipped.astype(np.float32)}


def batch_global(x: Array, y: Array, batch_size: int) -> Dict[str, Array]:
    """Batch one (global) dataset into [S, B, ...] + mask (for centralized
    training / server-side eval)."""
    d = stack_client_data([x], [y], batch_size)
    return {"x": d["x"][0], "y": d["y"][0], "mask": d["mask"][0]}


def save_stacked(stacked: Dict[str, Array], out_dir: str) -> None:
    """Persist a stacked client tree as one ``.npy`` per key (the staging
    format for corpora that exceed RAM — see load_stacked_memmap)."""
    import os
    os.makedirs(out_dir, exist_ok=True)
    for k, v in stacked.items():
        np.save(os.path.join(out_dir, f"{k}.npy"), np.asarray(v))


def load_stacked_memmap(in_dir: str) -> Dict[str, Array]:
    """Load a saved stacked tree memory-mapped (SURVEY.md §7 hard part (f):
    342k-client StackOverflow without re-staging).

    The [N, S, B, ...] arrays stay on disk; ``gather_cohort``'s fancy-index
    ``v[ids]`` copies ONLY the sampled cohort's rows per round, so host RAM
    holds one cohort, not the corpus.  FedAvg's HBM budget check reads
    ``nbytes`` without materialising, so an over-budget memmap dataset
    automatically stays on the per-round host-gather path."""
    import os
    out = {}
    for f in sorted(os.listdir(in_dir)):
        if f.endswith(".npy"):
            out[f[:-4]] = np.load(os.path.join(in_dir, f), mmap_mode="r")
    return out


def gather_cohort(stacked: Dict[str, Array], client_ids: Sequence[int],
                  pad_to: Optional[int] = None) -> Dict[str, Any]:
    """Select the sampled cohort's rows; optionally pad with weight-0 dummy
    clients to a static cohort size (kills per-round re-jit, SURVEY.md §7
    "hard parts" (a)).

    The padded-slot contract, which the static-wave cross-device path
    makes the COMMON case rather than the edge case (pinned in
    tests/test_cross_device.py): a padded slot aliases client 0's rows
    but carries ``mask 0`` and ``num_samples 0``, so the local trainer
    freezes its params at the round global (every batch fully padded)
    and any weighted reduction sees an exact ``+0.0`` — a wave of ALL
    pad slots therefore folds as weight 0, never a 0/0 normalizer.  A
    cohort LARGER than ``pad_to`` is a caller bug (the jit downstream
    would silently retrace on the odd-sized stack) and fails loudly."""
    ids = np.asarray(client_ids, dtype=np.int64)
    if pad_to is not None and len(ids) > pad_to:
        raise ValueError(
            f"gather_cohort: {len(ids)} sampled clients exceed "
            f"pad_to={pad_to}; the static cohort shape cannot hold them "
            f"(chunk the cohort — device_cohort.plan_waves — or raise "
            f"pad_to)")
    if pad_to is not None and len(ids) < pad_to:
        ids = np.concatenate([ids, np.zeros(pad_to - len(ids), np.int64)])
        live = np.concatenate([np.ones(len(client_ids)), np.zeros(pad_to - len(client_ids))])
    else:
        live = np.ones(len(ids))
    out = {k: jnp.asarray(v[ids]) for k, v in stacked.items()}
    out["mask"] = out["mask"] * jnp.asarray(live, jnp.float32)[:, None, None]
    out["num_samples"] = out["num_samples"] * jnp.asarray(live, jnp.float32)
    return out
