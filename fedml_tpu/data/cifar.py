"""CIFAR10 / CIFAR100 / CINIC10 centralized-then-partitioned datasets.

The reference wraps torchvision datasets in ``*_truncated`` views and
partitions with ``partition_data``'s homo/hetero/hetero-fix switch
(``fedml_api/data_preprocessing/cifar10/data_loader.py:102-205``).  Here the
raw archives are parsed directly (CIFAR pickle batches; CINIC10 ImageFolder
pngs) — no torchvision dependency — and partitioning reuses
`fedml_tpu.core.partition`.  Images ship to device as float32 [0,1] HWC;
crop/flip/normalize/Cutout run *inside* the jit'd train step
(`fedml_tpu.data.augment.cifar_train_augment`), which is the TPU-native
replacement for the host-side transform pipeline at
cifar10/data_loader.py:57-99.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from ..core.partition import (partition_dirichlet_hetero, partition_homo,
                              record_data_stats)
from .stacking import FederatedData, stack_client_data, batch_global


def _load_cifar10_arrays(data_dir: str) -> Tuple[np.ndarray, np.ndarray,
                                                 np.ndarray, np.ndarray]:
    """cifar-10-batches-py pickle layout: 5 train batches + test_batch, each
    {data: [n, 3072] uint8 CHW-flat, labels: [n]}."""
    root = os.path.join(data_dir, "cifar-10-batches-py")
    xs, ys = [], []
    for b in range(1, 6):
        with open(os.path.join(root, f"data_batch_{b}"), "rb") as f:
            d = pickle.load(f, encoding="latin1")
        xs.append(d["data"])
        ys.extend(d["labels"])
    x_train = np.concatenate(xs)
    y_train = np.asarray(ys)
    with open(os.path.join(root, "test_batch"), "rb") as f:
        d = pickle.load(f, encoding="latin1")
    return x_train, y_train, np.asarray(d["data"]), np.asarray(d["labels"])


def _load_cifar100_arrays(data_dir: str):
    """cifar-100-python layout: train/test pickles with fine_labels."""
    root = os.path.join(data_dir, "cifar-100-python")
    out = []
    for split in ("train", "test"):
        with open(os.path.join(root, split), "rb") as f:
            d = pickle.load(f, encoding="latin1")
        out.extend([np.asarray(d["data"]), np.asarray(d["fine_labels"])])
    return tuple(out)


def _to_hwc01(flat: np.ndarray) -> np.ndarray:
    return (flat.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            .astype(np.float32) / 255.0)


def _load_cinic10_arrays(data_dir: str):
    """CINIC10 ImageFolder: {train,test}/<class>/*.png.  Loaded via PIL."""
    from PIL import Image
    classes = None
    out = []
    for split in ("train", "test"):
        root = os.path.join(data_dir, split)
        if classes is None:
            classes = sorted(d for d in os.listdir(root)
                             if os.path.isdir(os.path.join(root, d)))
        xs, ys = [], []
        for yi, cls in enumerate(classes):
            cdir = os.path.join(root, cls)
            for fn in sorted(os.listdir(cdir)):
                with Image.open(os.path.join(cdir, fn)) as im:
                    xs.append(np.asarray(im.convert("RGB"), dtype=np.uint8))
                ys.append(yi)
        out.extend([np.stack(xs).astype(np.float32) / 255.0,
                    np.asarray(ys)])
    return tuple(out)


_LOADERS = {"cifar10": (_load_cifar10_arrays, 10, True),
            "cifar100": (_load_cifar100_arrays, 100, True),
            "cinic10": (_load_cinic10_arrays, 10, False)}


def load_cifar_partitioned(dataset: str, data_dir: str, client_num: int,
                           partition_method: str = "hetero",
                           partition_alpha: float = 0.5,
                           batch_size: int = 64,
                           seed: Optional[int] = None,
                           arrays: Optional[Tuple] = None) -> FederatedData:
    """The partition_data switch (cifar10/data_loader.py:113-161):
    ``homo`` = shuffled even split, ``hetero`` = per-class Dirichlet with the
    min-size-10 retry loop.  Test data stays global (the reference's
    get_dataloader_test serves each client the full test set unless given
    explicit test indices — local test dicts here are even homo shards so
    per-client eval exists without duplicating the test set C times).

    ``arrays`` lets callers inject (x_tr, y_tr, x_te, y_te) directly — the
    hermetic-test path and the hook for pre-staged data.
    """
    if arrays is None:
        loader, class_num, flat = _LOADERS[dataset]
        x_tr, y_tr, x_te, y_te = loader(data_dir)
        if flat:
            x_tr, x_te = _to_hwc01(x_tr), _to_hwc01(x_te)
    else:
        x_tr, y_tr, x_te, y_te = arrays
        class_num = int(np.max(y_tr)) + 1

    if partition_method == "homo":
        idx_map = partition_homo(len(y_tr), client_num, seed=seed)
    elif partition_method == "hetero":
        idx_map = partition_dirichlet_hetero(
            y_tr, client_num, class_num, partition_alpha, seed=seed)
    else:
        raise ValueError(f"unknown partition method {partition_method!r}")
    record_data_stats(y_tr, idx_map)

    xs = [x_tr[idx_map[c]] for c in range(client_num)]
    ys = [y_tr[idx_map[c]] for c in range(client_num)]
    te_map = partition_homo(len(y_te), client_num, seed=seed)
    train = stack_client_data(xs, ys, batch_size)
    test = stack_client_data([x_te[te_map[c]] for c in range(client_num)],
                             [y_te[te_map[c]] for c in range(client_num)],
                             batch_size)
    return FederatedData(
        client_num=client_num, class_num=class_num, train=train, test=test,
        train_global=batch_global(x_tr, y_tr, batch_size),
        test_global=batch_global(x_te, y_te, batch_size))
