"""Real-data end-to-end: the reference's SHIPPED LEAF json.

Every other learning proof in this suite runs on hermetic twins or
regenerated synthetic data; these tests read the one real federated
dataset present in the sandbox — the FedProx synthetic_0.5_0.5 LEAF file
the reference ships at data/synthetic_0.5_0.5/test/mytest.json (generator:
data/synthetic_0.5_0.5/generate_synthetic.py; only the test split is
checked in) — and (a) assert our loader reproduces the reference reader's
statistics on it, (b) train FedAvg-LR at the published hyperparameters to
the published >60% accuracy target (benchmark/README.md:14, Tabular
Synthetic(α,β) row: 30 clients, 10/round, B=10, SGD lr=0.01, E=1,
rounds>200, accuracy>60).
"""

import json
import os

import numpy as np
import pytest

def _src(variant: str) -> str:
    return f"/root/reference/data/synthetic_{variant}/test/mytest.json"


SRC = _src("0.5_0.5")

# the loader-statistics test is pinned to the 0.5_0.5 file's invariants;
# the training test carries its own per-variant skip
_needs_half = pytest.mark.skipif(
    not os.path.exists(SRC),
    reason="reference synthetic_0.5_0.5 LEAF file not present")


@pytest.fixture(scope="module")
def raw():
    if not os.path.exists(SRC):
        pytest.skip("reference synthetic_0.5_0.5 LEAF file not present")
    with open(SRC) as f:
        return json.load(f)


def _split_80_20(raw, root):
    """Deterministic per-user 80/20 split of a shipped file into the
    LEAF train/test directory layout load_synthetic_leaf expects (the
    reference ships only the test split of these datasets)."""
    (root / "train").mkdir()
    (root / "test").mkdir()
    tr = {"users": raw["users"], "num_samples": [], "user_data": {}}
    te = {"users": raw["users"], "num_samples": [], "user_data": {}}
    rng = np.random.RandomState(42)
    for u in raw["users"]:
        x = np.asarray(raw["user_data"][u]["x"], np.float32)
        y = np.asarray(raw["user_data"][u]["y"], np.int32)
        idx = rng.permutation(len(x))
        cut = max(1, int(0.8 * len(x)))
        tr_i, te_i = idx[:cut], (idx[cut:] if len(idx) > cut else idx[:1])
        tr["user_data"][u] = {"x": x[tr_i].tolist(), "y": y[tr_i].tolist()}
        tr["num_samples"].append(len(tr_i))
        te["user_data"][u] = {"x": x[te_i].tolist(), "y": y[te_i].tolist()}
        te["num_samples"].append(len(te_i))
    (root / "train" / "mytrain.json").write_text(json.dumps(tr))
    (root / "test" / "mytest.json").write_text(json.dumps(te))
    return str(root)


@pytest.fixture(scope="module")
def leaf_dir(raw, tmp_path_factory):
    return _split_80_20(raw, tmp_path_factory.mktemp("synthetic_leaf"))


@_needs_half
def test_loader_statistics_match_reference_reader(raw, leaf_dir):
    """Our reader must agree with the reference reader's view of the real
    file (MNIST/data_loader.py:8-47 semantics): user census, per-user
    sample counts (via the padded stack's masks), feature dim, label set."""
    from fedml_tpu.data.leaf import load_synthetic_leaf, read_leaf_dirs

    # raw-file invariants the reference loader relies on
    assert len(raw["users"]) == 30
    assert sum(raw["num_samples"]) == 2248
    for u, n in zip(raw["users"], raw["num_samples"]):
        ud = raw["user_data"][u]
        assert len(ud["x"]) == n and len(ud["y"]) == n
        assert all(len(row) == 60 for row in ud["x"])
        assert set(int(v) for v in ud["y"]) <= set(range(10))

    users, _, train_data, test_data = read_leaf_dirs(
        os.path.join(leaf_dir, "train"), os.path.join(leaf_dir, "test"))
    assert users == sorted(raw["users"])

    data = load_synthetic_leaf(leaf_dir, batch_size=10)
    assert data.client_num == 30 and data.class_num == 10
    # mask sums recover the true per-user counts despite padding, and the
    # train/test split partitions exactly the shipped 2248 samples
    per_user = (np.asarray(data.train["mask"]).sum(axis=(1, 2))
                + np.asarray(data.test["mask"]).sum(axis=(1, 2)))
    np.testing.assert_array_equal(
        per_user.astype(int),
        [len(train_data[u]["x"]) + len(test_data[u]["x"]) for u in users])
    assert int(per_user.sum()) == 2248
    assert data.train["x"].shape[-1] == 60


@pytest.mark.slow
@pytest.mark.parametrize("variant", ["0_0", "0.5_0.5", "1_1"])
def test_fedavg_lr_hits_published_target_on_real_data(variant,
                                                      tmp_path_factory):
    """benchmark/README.md:14,17: Synthetic(α,β) + LR + FedAvg ⇒ >60%
    accuracy at 30 clients, 10/round, B=10, SGD lr=0.01, E=1, for ALL
    THREE published variants (α,β) ∈ {(0,0), (0.5,0.5), (1,1)} — the
    reference ships all three LEAF files.  Trained on the REAL shipped
    samples (80% split), evaluated on the held-out 20%."""
    import jax
    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
    from fedml_tpu.data.leaf import load_synthetic_leaf
    from fedml_tpu.models import LogisticRegression
    from fedml_tpu.trainer.workload import ClassificationWorkload

    src = _src(variant)
    if not os.path.exists(src):
        pytest.skip(f"reference synthetic_{variant} LEAF file not present")
    with open(src) as f:
        raw_v = json.load(f)
    leaf = _split_80_20(raw_v, tmp_path_factory.mktemp(
        f"syn_{variant.replace('.', '_')}"))

    data = load_synthetic_leaf(leaf, batch_size=10)
    assert data.client_num == 30
    wl = ClassificationWorkload(LogisticRegression(60, 10), num_classes=10)
    cfg = FedAvgConfig(comm_round=200, client_num_per_round=10, epochs=1,
                       batch_size=10, lr=0.01, frequency_of_the_test=200)
    algo = FedAvg(wl, data, cfg)
    params = algo.run(rng=jax.random.key(0))
    stats = algo.evaluate_global(params)
    assert stats["test_acc"] > 0.60, (variant, stats)
