#!/usr/bin/env python
"""Round critical-path observatory bench (ISSUE 17): the cost contract
behind `BENCH_ingest.json`.

Each traffic arm is a fresh subprocess running the REAL federation with
the full observatory on — ``--trace_dir`` (per-upload ingest spans),
``--perf --perf_strict`` (ledger + recompile sentry), ``--telemetry``
(fedml_ingest_* gauges) — and the committed claims are re-derived from
the run's own artifacts, not summarized by the script:

  * every perf.jsonl round line carries a well-formed ``critical_path``
    record (obs/critical_path.validate_record), on all four arms;
  * the record's attribution covers >= 95%% of the round's wall clock
    (the sweep PARTITIONS the round, so this is ~1.0 by construction —
    the gate catches a future regression, not noise);
  * zero recompiles after warmup with tracing enabled, under the strict
    sentry (tracing must not poison jit caches);
  * the receive path actually emitted ingest spans into the trace dir
    (the observatory is on, not silently disabled);
  * disabled mode retains ZERO bytes and reuses the one module-level
    null context — the one-branch-per-event contract, pinned in-process
    with tracemalloc (deterministic, unlike wall-clock thresholds on a
    shared CPU container).

Any gate failure exits 1 and writes nothing.  CPU-container honest:
``backend`` is labeled per arm; wall times in the records are advisory
context — the pinned claims are structural (record shape, coverage,
recompiles, allocation).

    python scripts/ingest_bench.py             # full arms -> BENCH_ingest.json
    python scripts/ingest_bench.py --smoke     # relaxed scale, /tmp output
"""

import argparse
import json
import os
import sys
import tempfile
import tracemalloc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _base_cmd(rounds, run_dir, trace_dir):
    return [sys.executable, "-m", "fedml_tpu",
            "--model", "lr", "--dataset", "mnist",
            "--comm_round", str(rounds),
            "--frequency_of_the_test", str(rounds),
            "--batch_size", "4", "--log_stdout", "false",
            "--perf", "true", "--perf_strict", "true",
            "--telemetry", "true",
            "--run_dir", run_dir, "--trace_dir", trace_dir,
            "--perf_ledger", os.path.join(run_dir, "perf.jsonl")]


def arm_cmds(smoke):
    n = 4 if smoke else 8
    rounds = 2 if smoke else 4
    silo = ["--algo", "cross_silo",
            "--client_num_in_total", str(n),
            "--client_num_per_round", str(n)]
    return {
        # int8 wire codec: the production cross-silo shape, and it puts
        # the per-upload ingest:decode micro-span on the receive path
        "cross_silo": (rounds, silo + ["--wire_compression", "int8"]),
        "cross_device": (rounds, [
            "--algo", "cross_device",
            "--client_num_in_total", str(8 * n),
            "--client_num_per_round", str(4 * n),
            "--wave_size", str(n)]),
        "sharded": (rounds, silo + ["--agg_mode", "stream",
                                    "--model_shards", "2"]),
        "secagg": (rounds, silo + ["--agg_mode", "stream",
                                   "--secagg", "pairwise"]),
    }


def run_arm(name, rounds, extra, workdir):
    import subprocess
    run_dir = os.path.join(workdir, name)
    trace_dir = os.path.join(run_dir, "trace")
    cmd = _base_cmd(rounds, run_dir, trace_dir) + extra
    print(f"== arm {name}: rounds={rounds}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=1800)
    if proc.returncode != 0:
        raise SystemExit(f"arm {name} failed rc={proc.returncode}:\n"
                         f"{proc.stderr[-3000:]}")

    from fedml_tpu.obs import critical_path as cpath
    from fedml_tpu.obs import report
    ledger = os.path.join(run_dir, "perf.jsonl")
    rows = [json.loads(l) for l in open(ledger) if l.strip()]

    gates, failures = {}, []
    records = [r.get("critical_path") for r in rows]
    present = all(isinstance(r, dict) for r in records)
    gates["critical_path_on_every_round"] = {
        "ok": present, "rounds": len(rows)}
    if not present:
        failures.append(f"{name}: ledger line(s) without a critical_path "
                        f"record")
        return None, failures

    problems = []
    for i, rec in enumerate(records):
        problems += cpath.validate_record(rec, path=f"round {i}")
    gates["record_shape"] = {"ok": not problems, "problems": problems[:5]}
    if problems:
        failures.append(f"{name}: malformed critical_path record(s): "
                        f"{problems[:3]}")

    min_cov = min(r["coverage"] for r in records)
    gates["coverage"] = {"ok": min_cov >= 0.95, "min": round(min_cov, 4),
                         "threshold": 0.95}
    if min_cov < 0.95:
        failures.append(f"{name}: attribution covers only {min_cov:.0%} "
                        f"of the round wall clock")

    warm = sum(r.get("recompiles", 0) for r in rows[1:])
    gates["recompiles_after_warmup"] = {"ok": warm == 0, "count": warm}
    if warm:
        failures.append(f"{name}: {warm} recompiles after warmup with "
                        f"tracing enabled (under --perf_strict)")

    spans = report.load_trace_events(trace_dir)
    n_ingest = sum(1 for e in spans
                   if str(e.get("name", "")).startswith("ingest:"))
    n_recv = sum(1 for e in spans
                 if str(e.get("name", "")).startswith("recv:"))
    # cross_device waves fold device-side at wave completion — arrivals
    # ride the perf recorder, not per-upload receive spans
    want_spans = name != "cross_device"
    gates["ingest_spans_emitted"] = {
        "ok": (n_ingest > 0 and n_recv > 0) or not want_spans,
        "ingest": n_ingest, "recv": n_recv, "required": want_spans}
    if want_spans and (n_ingest == 0 or n_recv == 0):
        failures.append(f"{name}: trace dir carries no per-upload "
                        f"receive-path spans (ingest={n_ingest}, "
                        f"recv={n_recv}) — the ingest path ran untraced")

    import jax
    bindings = sorted({r["binding"] for r in records})
    print(f"   rounds={len(rows)} min_coverage={min_cov:.3f} "
          f"recompiles_after_warmup={warm} ingest_spans={n_ingest} "
          f"bindings={bindings}")
    arm = {"backend": jax.default_backend(), "rounds": records,
           "recompiles_after_warmup": warm, "gates": gates,
           "bindings": bindings, "ingest_spans": n_ingest}
    return arm, failures


def _run_pipeline_member(name, extra, rounds, workdir, pipelined):
    """One member of an inline/pipelined twin: a fresh subprocess on the
    REAL federation (same seed — the config default — on both members),
    slimmed to the per-round facts the committed gates re-derive from."""
    import subprocess
    run_dir = os.path.join(workdir, name)
    cmd = [sys.executable, "-m", "fedml_tpu",
           "--model", "lr", "--dataset", "mnist",
           "--comm_round", str(rounds),
           "--frequency_of_the_test", str(rounds),
           "--batch_size", "8", "--epochs", "1", "--log_stdout", "false",
           "--perf", "true", "--perf_strict", "true", "--telemetry", "true",
           "--run_dir", run_dir,
           "--perf_ledger", os.path.join(run_dir, "perf.jsonl")] + extra
    if pipelined:
        cmd += ["--ingest_pipeline", "true"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=1800)
    if proc.returncode != 0:
        raise SystemExit(f"pipeline member {name} failed "
                         f"rc={proc.returncode}:\n{proc.stderr[-3000:]}")
    ledger = os.path.join(run_dir, "perf.jsonl")
    rows = [json.loads(l) for l in open(ledger) if l.strip()]
    slim = []
    for r in rows:
        cp = r.get("critical_path") or {}
        slim.append({
            "round": r.get("round"),
            "global_crc": r.get("global_crc"),
            "fold_overlap_ratio": cp.get("fold_overlap_ratio"),
            "last_arrival_s": cp.get("last_arrival_s"),
            "round_s": cp.get("round_s"),
            "bytes_in": (r.get("wire") or {}).get("bytes_in", 0),
            "recompiles": r.get("recompiles", 0),
        })
    return {"rows": slim,
            "jit_cache_sizes": rows[-1].get("jit_cache_sizes", {})}


def pipeline_twins(smoke, workdir):
    """ISSUE 20's proof: inline vs `--ingest_pipeline` twins, same seed,
    fresh subprocess each.  The committed claims:

      * ``waves`` (cross-device, >=2048 uploads per round): the
        pipelined member hides aggregation entirely behind upload
        production — ``fold_overlap_ratio >= 0.99`` and round wall
        clock <= 1.15x pure network time (t0 -> last arrival);
      * ``replicated`` (cross-silo stream): the transport thread only
        validates + enqueues, so the wire drains at least as fast as
        inline (bytes_in / last_arrival_s), and the arena + fused
        screen key ONE compile-ledger entry each with zero recompiles
        after warmup under --perf_strict;
      * ``sharded`` (--model_shards 4): per-shard arenas, same
        single-entry ledger pin;
      * every twin: the final models are BIT-EQUAL — the pipelined
        fold order per shard is deterministic arrival order, so the
        global is bit-identical to inline (the crc32 sequence in the
        perf ledger, one per round, must match exactly).

    Smoke mode shrinks scale and relaxes the noise-sensitive numeric
    thresholds (overlap/wall/wire-speed) — the structural gates
    (bit-parity, single-entry ledger, zero recompiles) stay strict.
    The committed-trend validator re-derives the strict thresholds
    from the rows itself and refuses smoke artifacts outright."""
    cohort = 128 if smoke else 2048
    cd_rounds = 2 if smoke else 3
    silo_rounds = 2 if smoke else 4
    n_silo = 4 if smoke else 8
    th_overlap = 0.5 if smoke else 0.99
    th_wall = 1.5 if smoke else 1.15
    th_wire = 0.5 if smoke else 1.0
    silo = ["--algo", "cross_silo", "--agg_mode", "stream",
            "--client_num_in_total", str(n_silo),
            "--client_num_per_round", str(n_silo),
            "--admission", "on"]
    twins_cfg = {
        "waves": (cd_rounds, [
            "--algo", "cross_device",
            "--client_num_in_total", str(cohort),
            "--client_num_per_round", str(cohort),
            "--wave_size", "4", "--admission", "on"]),
        "replicated": (silo_rounds, silo),
        "sharded": (silo_rounds, silo + ["--model_shards", "4"]),
    }
    failures, twins = [], {}
    for tname, (rounds, extra) in twins_cfg.items():
        print(f"== pipeline twin {tname}: rounds={rounds}")
        inline = _run_pipeline_member(
            f"pipe_{tname}_inline", extra, rounds, workdir, False)
        piped = _run_pipeline_member(
            f"pipe_{tname}_pipelined", extra, rounds, workdir, True)
        gates = {}

        crc_in = [r["global_crc"] for r in inline["rows"]]
        crc_pi = [r["global_crc"] for r in piped["rows"]]
        bit_equal = bool(crc_in) and crc_in == crc_pi
        gates["bit_equal_finals"] = {"ok": bit_equal, "rounds": len(crc_in)}
        if not bit_equal:
            failures.append(f"pipeline/{tname}: pipelined global is NOT "
                            f"bit-equal to inline (crc {crc_in} vs "
                            f"{crc_pi})")

        warm = [r for r in piped["rows"][1:]]
        recompiles = sum(r["recompiles"] for r in warm)
        gates["zero_recompiles_after_warmup"] = {
            "ok": recompiles == 0, "count": recompiles}
        if recompiles:
            failures.append(f"pipeline/{tname}: {recompiles} recompiles "
                            f"after warmup under --perf_strict")

        if tname == "waves":
            min_ov = min(r["fold_overlap_ratio"] for r in warm)
            gates["fold_overlap"] = {"ok": min_ov >= th_overlap,
                                     "min": round(min_ov, 6),
                                     "threshold": th_overlap}
            if min_ov < th_overlap:
                failures.append(f"pipeline/waves: fold_overlap_ratio "
                                f"{min_ov:.4f} < {th_overlap}")
            max_wall = max(r["round_s"] / r["last_arrival_s"]
                           for r in warm)
            gates["network_bound_wall_clock"] = {
                "ok": max_wall <= th_wall, "max_ratio": round(max_wall, 6),
                "threshold": th_wall}
            if max_wall > th_wall:
                failures.append(f"pipeline/waves: round wall clock is "
                                f"{max_wall:.3f}x pure network time "
                                f"(> {th_wall}x)")
        if tname == "replicated":
            def _bps(member):
                rows = member["rows"][1:]
                net = sum(r["last_arrival_s"] for r in rows)
                return (sum(r["bytes_in"] for r in rows) / net
                        if net > 0 else 0.0)
            bps_in, bps_pi = _bps(inline), _bps(piped)
            ok = bps_in > 0 and bps_pi >= th_wire * bps_in
            gates["wire_speed"] = {
                "ok": ok, "inline_bps": round(bps_in, 1),
                "pipelined_bps": round(bps_pi, 1),
                "min_ratio": th_wire}
            if not ok:
                failures.append(f"pipeline/replicated: pipelined wire "
                                f"drain {bps_pi:.0f} B/s < {th_wire}x "
                                f"inline ({bps_in:.0f} B/s)")
        if tname in ("replicated", "sharded"):
            sizes = piped["jit_cache_sizes"]
            arena_keys = sorted(k for k in sizes
                                if k.startswith("ingest")
                                and (k.endswith("_arena")
                                     or k.endswith("_screen")))
            want = 8 if tname == "sharded" else 2
            ok = (len(arena_keys) == want
                  and all(sizes[k] == 1 for k in arena_keys))
            gates["arena_single_jit_entry"] = {
                "ok": ok, "entries": {k: sizes.get(k) for k in arena_keys},
                "expected_keys": want}
            if not ok:
                failures.append(f"pipeline/{tname}: arena/screen jits do "
                                f"not key exactly one ledger entry each "
                                f"({ {k: sizes.get(k) for k in arena_keys} })")

        ov = [r["fold_overlap_ratio"] for r in warm]
        print(f"   bit_equal={bit_equal} recompiles={recompiles} "
              f"overlap={[round(o, 4) for o in ov]}")
        twins[tname] = {
            "config": {"rounds": rounds, "args": extra},
            "inline": inline, "pipelined": piped, "gates": gates}
    import jax
    return {"backend": jax.default_backend(), "twins": twins}, failures


def disabled_pin_arm():
    """The cost contract's other half, measured in THIS process with
    observability off: the span helpers return the shared null context
    (identity) and the hot path retains zero bytes."""
    from fedml_tpu.comm.actors import ServerManager
    from fedml_tpu.comm.local import LocalHub
    from fedml_tpu.obs import trace

    failures = []
    if trace.get_tracer() is not None:
        return None, ["disabled_pin: a tracer is live in the bench "
                      "process — the pin needs observability OFF"]

    class Probe(ServerManager):
        def register_handlers(self):
            pass

    mgr = Probe(0, LocalHub().transport(0))
    null_ok = (mgr._span("ingest:fold", deterministic=True)
               is trace.NULL_CONTEXT
               and mgr._perf_phase("fold") is trace.NULL_CONTEXT)
    if not null_ok:
        failures.append("disabled_pin: span helpers allocate a fresh "
                        "context with tracing off")

    def hot_path():
        for _ in range(1000):
            with mgr._span("ingest:decode", deterministic=True):
                pass
            with mgr._perf_phase("decode"):
                pass
            mgr._note_arrival()

    import gc
    # two warm-up passes: the second crosses the interpreter's adaptive
    # specialization threshold, so the measured pass is steady-state
    hot_path()
    hot_path()
    tracemalloc.start()
    gc.collect()
    before = tracemalloc.take_snapshot()
    hot_path()
    gc.collect()   # collectible cycles are transients, not retention
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    # attribute retained bytes to the observatory's own code: the pin is
    # about what the disabled span/perf helpers keep, not interpreter
    # noise elsewhere in a process that just ran four subprocess arms
    flt = [tracemalloc.Filter(True, "*fedml_tpu*")]
    stats = after.filter_traces(flt).compare_to(
        before.filter_traces(flt), "lineno")
    retained = sum(s.size_diff for s in stats)
    if retained > 0:
        failures.append(f"disabled_pin: hot path retained {retained} "
                        f"bytes with observability off")
    import jax
    print(f"== arm disabled_pin: null_context={null_ok} "
          f"retained_bytes={retained}")
    arm = {"backend": jax.default_backend(),
           "gates": {
               "shared_null_context": {"ok": null_ok},
               "zero_retained_bytes": {"ok": retained <= 0,
                                       "bytes": max(retained, 0),
                                       "events": 3000}}}
    return arm, failures


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="relaxed scale; output under /tmp (never the "
                        "committed artifact)")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    out_path = args.out or (
        os.path.join(tempfile.gettempdir(), "BENCH_ingest.json")
        if args.smoke else os.path.join(REPO, "BENCH_ingest.json"))
    workdir = tempfile.mkdtemp(prefix="ingest_bench.")

    arms, failures = {}, []
    for name, (rounds, extra) in arm_cmds(args.smoke).items():
        arm, fails = run_arm(name, rounds, extra, workdir)
        failures += fails
        if arm is not None:
            arms[name] = arm
    arm, fails = disabled_pin_arm()
    failures += fails
    if arm is not None:
        arms["disabled_pin"] = arm
    pipeline, fails = pipeline_twins(args.smoke, workdir)
    failures += fails

    artifact = {
        "bench": "ingest", "version": 1, "smoke": bool(args.smoke),
        "note": ("1-core-CPU-container run: wall attributions in the "
                 "records are advisory context; the pinned claims are "
                 "structural (record on every round, >=95%% coverage, 0 "
                 "recompiles after warmup with tracing, zero-allocation "
                 "disabled mode) plus the pipeline twins' re-derivable "
                 "rows (bit-equal finals, fold overlap, wire speed)"),
        "arms": arms,
        "pipeline": pipeline,
    }
    from fedml_tpu.obs import trend
    failures += [f"schema: {x}"
                 for x in trend.validate_ingest_bench(artifact)]
    if failures:
        for f in failures:
            print(f"FAIL {f}")
        return 1
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"== ingest bench OK -> {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
