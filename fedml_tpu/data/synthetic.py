"""Synthetic federated datasets (in-memory, no downloads).

Two generators:

* ``generate_synthetic_alpha_beta`` — the LEAF synthetic_(α,β) logistic task
  (``data/synthetic_0.5_0.5/generate_synthetic.py:16-70``): per-user weight
  matrices W_i ~ N(u_i, 1) with u_i ~ N(0, α), per-user feature means
  v_i ~ N(B_i, 1) with B_i ~ N(0, β), features x ~ N(v_i, Σ) with
  Σ_jj = j^-1.2, labels y = argmax softmax(xW + b).  α controls model
  heterogeneity, β feature heterogeneity; iid=True shares one global (W, b).
* ``synthetic_federated_dataset`` — a generic stand-in that mimics any real
  loader's shapes (image / sequence / tabular) so every pipeline in the
  framework is testable hermetically (the reference's CI downloads real data,
  CI-install.sh:40-86 — we do not have that luxury on an air-gapped TPU host).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from .stacking import FederatedData, stack_client_data, batch_global


def generate_synthetic_alpha_beta(
        alpha: float = 0.5, beta: float = 0.5, iid: bool = False,
        num_users: int = 30, dimension: int = 60, num_classes: int = 10,
        seed: int = 0, min_samples: int = 50
        ) -> Tuple[list, list]:
    """Per-user (X, y) lists; sample counts ~ lognormal(4, 2) + min_samples
    (generate_synthetic.py:19-21)."""
    rng = np.random.RandomState(seed)
    samples_per_user = rng.lognormal(4, 2, num_users).astype(int) + min_samples

    mean_W = rng.normal(0, alpha, num_users)
    B = rng.normal(0, beta, num_users)
    cov_x = np.diag(np.power(np.arange(1, dimension + 1), -1.2))

    mean_x = np.zeros((num_users, dimension))
    for i in range(num_users):
        mean_x[i] = B[i] if iid else rng.normal(B[i], 1, dimension)

    if iid:
        W_g = rng.normal(0, 1, (dimension, num_classes))
        b_g = rng.normal(0, 1, num_classes)

    X_split, y_split = [], []
    for i in range(num_users):
        W = W_g if iid else rng.normal(mean_W[i], 1, (dimension, num_classes))
        b = b_g if iid else rng.normal(mean_W[i], 1, num_classes)
        xx = rng.multivariate_normal(mean_x[i], cov_x, samples_per_user[i])
        yy = np.argmax(xx @ W + b, axis=1)
        X_split.append(xx.astype(np.float32))
        y_split.append(yy.astype(np.int32))
    return X_split, y_split


def load_synthetic(alpha: float = 0.5, beta: float = 0.5, iid: bool = False,
                   num_users: int = 30, batch_size: int = 10,
                   train_frac: float = 0.9, seed: int = 0) -> FederatedData:
    """synthetic_(α,β) as FederatedData with a 90/10 train/test split per user
    (generate_synthetic.py main: num_samples * 0.9)."""
    X, y = generate_synthetic_alpha_beta(alpha, beta, iid, num_users, seed=seed)
    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for xi, yi in zip(X, y):
        n_tr = int(len(yi) * train_frac)
        xs_tr.append(xi[:n_tr])
        ys_tr.append(yi[:n_tr])
        xs_te.append(xi[n_tr:])
        ys_te.append(yi[n_tr:])
    train = stack_client_data(xs_tr, ys_tr, batch_size)
    test = stack_client_data(xs_te, ys_te, batch_size)
    return FederatedData(
        client_num=num_users, class_num=10, train=train, test=test,
        train_global=batch_global(np.concatenate(xs_tr),
                                  np.concatenate(ys_tr), batch_size),
        test_global=batch_global(np.concatenate(xs_te),
                                 np.concatenate(ys_te), batch_size))


def mnist_learnable_twin(num_clients: int = 1000, class_num: int = 10,
                         dim: int = 784, batch_size: int = 10,
                         noise: float = 7.0, max_samples: int = 64,
                         seed: int = 0) -> FederatedData:
    """A LEARNABLE MNIST stand-in for convergence validation: each class is
    a random prototype vector, samples are prototype + N(0, noise), client
    sizes follow the LEAF power law (lognormal), class mix per client is
    non-uniform (two dominant classes per client, like LEAF MNIST's
    power-law label skew).

    The default noise is calibrated so the published MNIST-LR config
    (benchmark/README.md:12 — 1000 clients, 10/round, B=10, lr=0.03,
    E=1) NEEDS its >100-round budget and lands where real MNIST-LR
    lands: measured train acc 0.11 → 0.54 → 0.73 → 0.81 → 0.86 at
    rounds 0/30/60/90/119 (seed 0; 0.88 at seed 1), comfortably past
    the >75 target but far from saturation.  The earlier noise=0.9
    setting separated classes by ~40σ along the discriminant — LR hit
    1.0 within 30 rounds and the published budget proved nothing (the
    same saturating-proxy trap the CIFAR twin had; see
    FLAGSHIP_TWIN_KWARGS)."""
    rng = np.random.RandomState(seed)
    protos = rng.randn(class_num, dim).astype(np.float32)
    sizes = np.minimum(rng.lognormal(3.0, 1.0, num_clients).astype(int) + 8,
                       max_samples)
    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for c in range(num_clients):
        # two dominant classes per client (non-IID label skew)
        dom = rng.choice(class_num, 2, replace=False)
        p = np.full(class_num, 0.1 / (class_num - 2))
        p[dom] = 0.45
        n = int(sizes[c])
        n_te = max(1, n // 5)
        for xs, ys, m in ((xs_tr, ys_tr, n), (xs_te, ys_te, n_te)):
            y = rng.choice(class_num, m, p=p).astype(np.int32)
            x = (protos[y] + noise * rng.randn(m, dim)).astype(np.float32)
            xs.append(x)
            ys.append(y)
    train = stack_client_data(xs_tr, ys_tr, batch_size)
    test = stack_client_data(xs_te, ys_te, batch_size)
    return FederatedData(
        client_num=num_clients, class_num=class_num, train=train, test=test,
        train_global=batch_global(np.concatenate(xs_tr),
                                  np.concatenate(ys_tr), batch_size),
        test_global=batch_global(np.concatenate(xs_te),
                                 np.concatenate(ys_te), batch_size))


# THE flagship-proxy twin difficulty (one definition: the CI retention
# proxy in tests/test_convergence.py and the full-size TPU run in
# scripts/flagship_accuracy.py must measure the SAME task, or the
# FLAGSHIP_CURVE artifact silently desyncs from the CI evidence)
FLAGSHIP_TWIN_KWARGS = {"noise": 1.4, "modes": 4}


def cifar_learnable_twin(num_clients: int = 10, class_num: int = 10,
                         samples_per_client: int = 500,
                         partition_alpha: float = 0.5,
                         batch_size: int = 64, noise: float = 0.35,
                         seed: int = 0, modes: int = 1) -> FederatedData:
    """A LEARNABLE CIFAR-shaped twin for flagship-config accuracy proofs
    (benchmark/README.md:105 — real CIFAR is not downloadable here):
    each class is a smooth random 32x32x3 prototype (low-res pattern,
    bilinearly upsampled) plus pixel noise, partitioned across clients
    with the REAL LDA(alpha) partitioner (core/partition.py) so the
    non-IID label skew matches the published config's.  A conv net
    separates the classes well (centralized accuracy lands in the 90s at
    the default noise), leaving federated runs the same "non-IID gap" to
    close that the reference's 93.19 -> 87.12 row documents.

    ``modes > 1`` gives each class ``modes`` distinct prototypes with a
    per-sample random mode draw — intra-class variation that a single
    fixed prototype lacks.  At modes=1 the task is linearly-clustered
    and saturates (fed == cent == 1.0, a retention ratio that probes
    nothing); with several modes + noise the centralized model lands
    below 1.0 and the federated run has a REAL non-IID gap to close, so
    the retention proxy measures what the published 93.19→87.12 row
    measures (tests/test_convergence.py)."""
    from fedml_tpu.core.partition import partition_dirichlet_hetero

    rng = np.random.RandomState(seed)
    n_total = num_clients * samples_per_client
    low = rng.randn(class_num, modes, 8, 8, 3).astype(np.float32)
    protos = np.stack([np.stack([_upsample_bilinear(m, 32) for m in p])
                       for p in low])  # [class, mode, 32, 32, 3]

    def make_split(n, rng):
        y = rng.randint(0, class_num, n).astype(np.int32)
        mode = rng.randint(0, modes, n)
        x = protos[y, mode] + noise * rng.randn(
            n, 32, 32, 3).astype(np.float32)
        return x.astype(np.float32), y

    x_tr, y_tr = make_split(n_total, rng)
    x_te, y_te = make_split(max(class_num * 20, n_total // 5), rng)
    idx_map = partition_dirichlet_hetero(y_tr, num_clients, class_num,
                                         partition_alpha, seed=seed)
    # per-client 80/20 train/test split of the client's OWN shard, so the
    # federated test metric sees each client's non-IID label mix (the
    # reference's local_test_on_all_clients semantics)
    xs, ys, xs_te, ys_te = [], [], [], []
    for c in range(num_clients):
        idx = idx_map[c]
        n_te = max(1, len(idx) // 5)
        xs.append(x_tr[idx[:-n_te]])
        ys.append(y_tr[idx[:-n_te]])
        xs_te.append(x_tr[idx[-n_te:]])
        ys_te.append(y_tr[idx[-n_te:]])
    return FederatedData(
        client_num=num_clients, class_num=class_num,
        train=stack_client_data(xs, ys, batch_size),
        test=stack_client_data(xs_te, ys_te, batch_size),
        train_global=batch_global(np.concatenate(xs), np.concatenate(ys),
                                  batch_size),
        test_global=batch_global(x_te, y_te, batch_size))


def _upsample_bilinear(img: np.ndarray, size: int) -> np.ndarray:
    """[h, w, c] -> [size, size, c] bilinear (numpy-only, no jax import at
    data-gen time)."""
    h, w, c = img.shape
    ys = np.linspace(0, h - 1, size)
    xs = np.linspace(0, w - 1, size)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    top = img[y0][:, x0] * (1 - fx) + img[y0][:, x1] * fx
    bot = img[y1][:, x0] * (1 - fx) + img[y1][:, x1] * fx
    return (top * (1 - fy) + bot * fy).astype(np.float32)


def synthetic_federated_dataset(
        num_clients: int = 8, samples_per_client: int = 32,
        sample_shape: Sequence[int] = (28, 28, 1), class_num: int = 10,
        batch_size: int = 8, seed: int = 0,
        x_dtype=np.float32, sequence_vocab: Optional[int] = None,
        multilabel: bool = False, heterogeneous_sizes: bool = True
        ) -> FederatedData:
    """Shape-compatible stand-in for any real loader.

    * image/tabular: x ~ N(0,1) in ``sample_shape``, y uniform in class_num
    * ``sequence_vocab`` set: x int32 ids in [0, vocab), y = shifted ids
      (language-model layout, like fed_shakespeare)
    * ``multilabel``: y is a float multi-hot of width class_num (like
      stackoverflow_lr)
    """
    rng = np.random.RandomState(seed)
    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for c in range(num_clients):
        n = samples_per_client
        if heterogeneous_sizes:
            n = max(2, int(samples_per_client * rng.uniform(0.4, 1.6)))
        n_te = max(1, n // 5)
        for xs, ys, m in ((xs_tr, ys_tr, n), (xs_te, ys_te, n_te)):
            if sequence_vocab is not None:
                seq = rng.randint(0, sequence_vocab,
                                  (m,) + tuple(sample_shape)).astype(np.int32)
                xs.append(seq)
                ys.append(np.concatenate(
                    [seq[:, 1:], seq[:, :1]], axis=1).astype(np.int32))
            else:
                xs.append(rng.randn(*((m,) + tuple(sample_shape)))
                          .astype(x_dtype))
                if multilabel:
                    ys.append((rng.rand(m, class_num) < 0.05)
                              .astype(np.float32))
                else:
                    ys.append(rng.randint(0, class_num, m).astype(np.int32))
    train = stack_client_data(xs_tr, ys_tr, batch_size)
    test = stack_client_data(xs_te, ys_te, batch_size)
    return FederatedData(
        client_num=num_clients, class_num=class_num, train=train, test=test,
        train_global=batch_global(np.concatenate(xs_tr),
                                  np.concatenate(ys_tr), batch_size),
        test_global=batch_global(np.concatenate(xs_te),
                                 np.concatenate(ys_te), batch_size))
