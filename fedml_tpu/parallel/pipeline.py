"""Pipeline parallelism (pp): transformer blocks sharded over a
``stages`` mesh axis, GPipe-style microbatching via shard_map + ppermute.

The reference's only pipeline notion is SplitNN's two-party activation
exchange (fedml_api/standalone/split_nn); this module is the general
S-stage form for models too deep for one chip: each device holds L/S
consecutive blocks, microbatches stream through the stages, and the
activation hand-off between stages is a `lax.ppermute` hop riding ICI.
The whole schedule — fill, steady state, drain — is ONE `lax.scan` inside
ONE shard_map program, so XLA sees static shapes and the backward pass
falls out of jax autodiff (the transpose of ppermute is the reverse
permute, so gradients stream backward through the stages automatically —
no hand-written 1F1B needed for correctness).

Layout contract: block parameters carry an explicit leading layer axis
``[L, ...]`` (built by vmapped init), reshaped to ``[S, L/S, ...]`` and
placed with `P("stages")` — placement-as-parallelism, like tp
(mesh.tp_shard_params) and ep (expert.ep_shard_params).

Bubble accounting: a (M + S - 1)-step schedule does M steps of useful
work per stage — efficiency M/(M+S-1); pick n_micro >= n_stages for
>=50% (classic GPipe guidance).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.models.transformer import CausalSelfAttention
from fedml_tpu.trainer.workload import Workload, make_nwp_loss_metrics


def make_stage_mesh(n_stages: int,
                    devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_stages:
        raise ValueError(f"need {n_stages} devices for the stages axis, "
                         f"have {len(devices)}")
    return Mesh(np.asarray(devices[:n_stages]), ("stages",))


class TransformerBlock(nn.Module):
    """One pre-LN block (LN→MHA→residual, LN→GELU MLP→residual) — the
    repeating unit the pipeline distributes.  Matches the DENSE inline
    blocks of models.transformer.TransformerLM (attention is the shared
    CausalSelfAttention module; only the LN/residual wiring is repeated
    here — mirror any change to that wiring in both places).  The MoE FFN
    variant is deliberately NOT pipelined: its balance loss rides a sown
    collection that this module's scan-over-layers apply would silently
    drop — combining ep with pp is future work, not a silent degradation."""
    n_heads: int
    d_model: int
    d_ff: int
    dtype: object = None

    @nn.compact
    def __call__(self, x, positions):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = CausalSelfAttention(self.n_heads, self.d_model,
                                dtype=self.dtype, name="attn")(h, positions)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.dtype)(h)
        return x + h


class PipelineLM:
    """Decoder-only LM with an EXPLICIT stacked-blocks pytree, built for
    pipelining: ``params = {"embed", "blocks" ([L, ...] leaves), "final"}``.

    ``apply_seq`` is the single-device reference (scan over layers);
    ``make_pp_apply`` returns the same function distributed over a
    [stages] mesh.  Embedding and head stay replicated — tiny next to the
    block stack that motivates pp — so only block activations travel."""

    def __init__(self, vocab_size: int, d_model: int = 128, n_heads: int = 4,
                 n_layers: int = 4, d_ff: int = 512, max_len: int = 2048,
                 dtype=None):
        self.n_layers = n_layers
        self.dtype = dtype
        self.block = TransformerBlock(n_heads, d_model, d_ff, dtype=dtype)
        self.d_model = d_model
        self.vocab_size = vocab_size
        self.max_len = max_len

        class _Embed(nn.Module):
            dtype = None

            @nn.compact
            def __call__(s, toks, positions):
                x = nn.Embed(vocab_size, d_model, dtype=dtype,
                             name="tok_embed")(toks)
                return x + nn.Embed(max_len, d_model, dtype=dtype,
                                    name="pos_embed")(positions)[None]

        class _Final(nn.Module):
            @nn.compact
            def __call__(s, x):
                return nn.Dense(vocab_size, dtype=dtype, name="lm_head")(
                    nn.LayerNorm(dtype=dtype)(x))

        self._embed = _Embed()
        self._final = _Final()

    def init(self, rng: jax.Array, toks: jax.Array) -> Any:
        t = toks.shape[1]
        positions = jnp.arange(t)
        r_embed, r_blocks, r_final = jax.random.split(rng, 3)
        embed = self._embed.init(r_embed, toks, positions)["params"]
        x = self._embed.apply({"params": embed}, toks, positions)
        block_keys = jax.random.split(r_blocks, self.n_layers)
        blocks = jax.vmap(
            lambda k: self.block.init(k, x, positions)["params"])(block_keys)
        final = self._final.init(r_final, x)["params"]
        return {"embed": embed, "blocks": blocks, "final": final}

    def _run_blocks(self, blocks, x, positions):
        def one(h, layer_params):
            return self.block.apply({"params": layer_params}, h,
                                    positions), None
        out, _ = jax.lax.scan(one, x, blocks)
        return out

    def apply_seq(self, params: Any, toks: jax.Array) -> jax.Array:
        """Single-device reference forward: [B, T] -> [B, T, V]."""
        positions = jnp.arange(toks.shape[1])
        x = self._embed.apply({"params": params["embed"]}, toks, positions)
        x = self._run_blocks(params["blocks"], x, positions)
        return self._final.apply({"params": params["final"]}, x)

    # ---- pipeline execution ---------------------------------------------
    def pp_shard_params(self, params: Any, mesh: Mesh, n_stages: int) -> Any:
        """[L, ...] block leaves -> [S, L/S, ...] placed on the stages
        axis; embed/final replicated."""
        if self.n_layers % n_stages:
            raise ValueError(f"n_layers={self.n_layers} not divisible by "
                             f"n_stages={n_stages}")
        lps = self.n_layers // n_stages
        blocks = jax.tree.map(
            lambda v: jax.device_put(
                v.reshape((n_stages, lps) + v.shape[1:]),
                NamedSharding(mesh, P("stages"))), params["blocks"])
        rep = lambda t: jax.tree.map(
            lambda v: jax.device_put(v, NamedSharding(mesh, P())), t)
        return {"embed": rep(params["embed"]), "blocks": blocks,
                "final": rep(params["final"])}

    def make_pp_apply(self, mesh: Mesh, n_micro: int):
        """Returns ``fn(pp_params, toks) -> logits`` running the block
        stack as a GPipe pipeline over ``mesh``'s stages axis.  ``toks``
        batch must divide into ``n_micro`` microbatches."""
        n_stages = mesh.shape["stages"]

        def fn(params, toks):
            b, t = toks.shape
            if b % n_micro:
                raise ValueError(f"batch {b} not divisible into "
                                 f"{n_micro} microbatches")
            positions = jnp.arange(t)
            x = self._embed.apply({"params": params["embed"]}, toks,
                                  positions)
            x_mb = x.reshape((n_micro, b // n_micro) + x.shape[1:])

            @partial(jax.shard_map, mesh=mesh,
                     in_specs=(P("stages"), P()), out_specs=P())
            def pipeline(blocks_sharded, xm):
                sp = jax.tree.map(lambda v: v[0], blocks_sharded)
                s = jax.lax.axis_index("stages")

                def step(carry, ti):
                    act, out = carry
                    inp = jnp.where(s == 0,
                                    xm[jnp.clip(ti, 0, n_micro - 1)], act)
                    y = self._run_blocks(sp, inp, positions)
                    nxt = jax.lax.ppermute(
                        y, "stages",
                        [(i, i + 1) for i in range(n_stages - 1)]) \
                        if n_stages > 1 else y
                    oidx = ti - (n_stages - 1)
                    write = (s == n_stages - 1) & (oidx >= 0)
                    upd = jax.lax.dynamic_update_index_in_dim(
                        out, y, jnp.clip(oidx, 0, n_micro - 1), 0)
                    out = jnp.where(write, upd, out)
                    return (nxt, out), None

                # the carry becomes device-varying inside the loop (each
                # stage holds different activations); mark the zero init
                # accordingly or the scan typecheck rejects it (same
                # pattern as cohort.py's sharded path)
                init = jax.lax.pcast(
                    (jnp.zeros_like(xm[0]), jnp.zeros_like(xm)),
                    ("stages",), to="varying")
                (_, out), _ = jax.lax.scan(
                    step, init, jnp.arange(n_micro + n_stages - 1))
                # only the last stage holds real outputs; psum replicates
                out = jnp.where(s == n_stages - 1, out,
                                jnp.zeros_like(out))
                return jax.lax.psum(out, "stages")

            y = pipeline(params["blocks"], x_mb)
            y = y.reshape((b, t, self.d_model))
            return self._final.apply({"params": params["final"]}, y)

        return fn


@dataclasses.dataclass(frozen=True)
class _PPWorkload(Workload):
    """Workload whose params are PipelineLM's explicit pytree (no flax
    'params' collection to unwrap) and whose forward is an explicit
    callable (PipelineLM has no flax ``.apply``)."""
    forward: Any = None  # forward(params, toks) -> logits

    def init(self, rng, sample_batch):
        return self.model.init(rng, sample_batch["x"])

    def apply(self, params, x, train=False, rng=None):
        return self.forward(params, x)


def _nwp_workload_over(plm: PipelineLM, forward, pad_id: int) -> Workload:
    """NWP loss/metrics (the shared make_nwp_loss_metrics semantics) over
    an arbitrary ``forward(params, toks)`` — the pipelined workload and
    its sequential parity twin."""
    loss_fn, metric_fn = make_nwp_loss_metrics(
        lambda params, x, rng, train: (forward(params, x), 0.0), pad_id)
    return _PPWorkload(model=plm, loss_fn=loss_fn, metric_fn=metric_fn,
                       grad_clip_norm=None, forward=forward)


def make_pp_nwp_workload(plm: PipelineLM, mesh: Mesh, n_micro: int,
                         pad_id: int = 0) -> Workload:
    """Next-word-prediction Workload whose forward runs the GPipe
    pipeline — plugs pipeline parallelism into every Workload consumer
    (the local trainer, evaluators, the cross-silo silo train_fn), so a
    silo can train a model too deep for one chip over its local [stages]
    mesh.

    Scope: SILO-LOCAL training (make_local_trainer directly).  The
    vmapped cohort engine cannot consume it — a shard_map pipeline under
    vmap-over-clients is not a meaningful composition (each client would
    need its own stage mesh); federated use is cross-silo, where
    aggregation rides the wire and each silo runs this workload on its
    own chips.  Params come from ``plm.init`` and should be placed with
    ``plm.pp_shard_params`` before training."""
    return _nwp_workload_over(plm, plm.make_pp_apply(mesh, n_micro), pad_id)


def make_seq_nwp_workload(plm: PipelineLM, pad_id: int = 0) -> Workload:
    """The single-device reference twin of make_pp_nwp_workload (same
    params pytree, apply_seq forward) — the parity oracle."""
    return _nwp_workload_over(plm, plm.apply_seq, pad_id)
