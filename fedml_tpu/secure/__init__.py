"""Secure aggregation: finite-field MPC primitives + TPU-native masking.

Reference equivalent: the TurboAggregate algorithm family
(``fedml_api/distributed/turboaggregate/`` and ``standalone/turboaggregate``)
whose kernel is ``mpc_function.py`` — Lagrange-coded computing (LCC), BGW
(Shamir) secret sharing, and additive secret shares over a prime field.

Two layers here:

- `fedml_tpu.secure.field` — the exact finite-field toolbox (host-side
  numpy, vectorized): Shamir/BGW sharing, Lagrange coefficient generation,
  LCC encode/decode, additive shares, DH-style key agreement.  This is what
  rides the cross-silo transport between mutually-distrusting silos.
- `fedml_tpu.secure.secagg` — the TPU-native hot path: pairwise additive
  masking in the ring Z_2^32 (uint32 wraparound — mod arithmetic for free,
  the construction of practical SecAgg), so the masked cohort sum is a plain
  `lax.psum` inside the jit round program; masks cancel exactly.
- `fedml_tpu.secure.protocol` — the LIVE round protocol: the same ring
  masking spoken over `Message`/`Transport` between real actors (mask
  agreement with Shamir-shared seeds, masked uploads, ring fold at
  arrival, unmask with dropout recovery) — `--secagg {pairwise,grouped}`
  on the cross-silo path.
"""

from fedml_tpu.secure.field import (
    mod_inv, mod_div, prod_mod, lagrange_coeffs, bgw_encode, bgw_decode,
    lcc_encode, lcc_decode, lcc_encode_with_points, lcc_decode_with_points,
    additive_shares, pk_gen, key_agreement,
)
from fedml_tpu.secure.pallas_mask import fused_quantize_mask
from fedml_tpu.secure.protocol import (SecAggClient, SecAggError,
                                       SecAggServer, masked_template)
from fedml_tpu.secure.secagg import (
    quantize, dequantize, pairwise_masks, ring_budget_scale,
    validate_ring_budget, SecureCohortAggregator,
)

__all__ = [
    "mod_inv", "mod_div", "prod_mod", "lagrange_coeffs", "bgw_encode",
    "bgw_decode", "lcc_encode", "lcc_decode", "lcc_encode_with_points",
    "lcc_decode_with_points", "additive_shares", "pk_gen", "key_agreement",
    "quantize", "dequantize", "pairwise_masks", "ring_budget_scale",
    "validate_ring_budget", "SecureCohortAggregator",
    "fused_quantize_mask",
    "SecAggClient", "SecAggServer", "SecAggError", "masked_template",
]
