"""Comm layer: codec round-trip, local hub choreography, gRPC loopback.

The reference has no tests for its communication stack at all (SURVEY.md §4);
the closest artifact is the missing MOCK backend.  These tests exercise the
exact message protocol of the distributed FedAvg choreography
(FedAvgServerManager.py / FedAvgClientManager.py) in-process.
"""

import threading
import types

import numpy as np
import pytest

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.algorithms.cross_silo import (
    FedAvgClientActor, FedAvgServerActor, MsgType)
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.sampling import sample_clients


def _params_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {"dense": {"kernel": rng.randn(4, 3).astype(np.float32),
                      "bias": rng.randn(3).astype(np.float32)},
            "steps": np.int32(7)}


class TestMessageCodec:
    def test_roundtrip_pytree(self):
        msg = Message(5, sender_id=2, receiver_id=0)
        tree = _params_tree()
        msg.add(Message.ARG_MODEL_PARAMS, tree)
        msg.add(Message.ARG_NUM_SAMPLES, 123)
        msg.add("note", "hello")
        msg.add("stats", {"acc": 0.5, "loss": 1.25})
        out = Message.from_bytes(msg.to_bytes())
        assert out.type == 5 and out.sender_id == 2 and out.receiver_id == 0
        assert out.get(Message.ARG_NUM_SAMPLES) == 123
        assert out.get("note") == "hello"
        assert out.get("stats") == {"acc": 0.5, "loss": 1.25}
        got = out.get(Message.ARG_MODEL_PARAMS)
        np.testing.assert_array_equal(got["dense"]["kernel"],
                                      tree["dense"]["kernel"])
        np.testing.assert_array_equal(got["steps"], tree["steps"])
        assert got["dense"]["bias"].dtype == np.float32

    def test_roundtrip_mixed_containers(self):
        msg = Message("typed", 1, 2)
        msg.add("batch", [np.arange(4), ("tag", np.ones((2, 2)))])
        out = Message.from_bytes(msg.to_bytes())
        batch = out.get("batch")
        np.testing.assert_array_equal(batch[0], np.arange(4))
        assert batch[1][0] == "tag"
        np.testing.assert_array_equal(batch[1][1], np.ones((2, 2)))

    def test_roundtrip_fuzz_random_pytrees(self):
        """Property fuzz: 50 random nested pytrees (mixed dtypes, shapes,
        empties, scalars, strings, bools, deep nesting) survive the wire
        codec exactly."""
        rng = np.random.RandomState(42)
        dtypes = [np.float32, np.float64, np.int32, np.int64, np.uint32,
                  np.bool_, np.float16]

        def rand_leaf(depth):
            kind = rng.randint(0, 6)
            if kind == 0:
                shape = tuple(rng.randint(0, 5, rng.randint(0, 4)))
                return np.asarray(rng.standard_normal(shape)).astype(
                    dtypes[rng.randint(len(dtypes))])
            if kind == 1:
                return float(rng.randn())
            if kind == 2:
                return int(rng.randint(-1000, 1000))
            if kind == 3:
                return "s" * rng.randint(0, 8)
            if kind == 4:
                return bool(rng.randint(2))
            return None

        def rand_tree(depth=0):
            if depth >= 3 or rng.rand() < 0.4:
                return rand_leaf(depth)
            if rng.rand() < 0.5:
                return {f"k{i}": rand_tree(depth + 1)
                        for i in range(rng.randint(0, 4))}
            return [rand_tree(depth + 1) for _ in range(rng.randint(0, 4))]

        for i in range(50):
            tree = rand_tree()
            msg = Message(i, sender_id=1, receiver_id=2).add("payload", tree)
            got = Message.from_bytes(msg.to_bytes()).get("payload")

            def check(a, b):
                if isinstance(a, np.ndarray):
                    assert a.dtype == b.dtype and a.shape == b.shape, (a, b)
                    np.testing.assert_array_equal(a, b)
                elif isinstance(a, dict):
                    assert set(a) == set(b)
                    for k in a:
                        check(a[k], b[k])
                elif isinstance(a, (list, tuple)):
                    assert len(a) == len(b)
                    for x, y in zip(a, b):
                        check(x, y)
                else:
                    assert a == b or (a is None and b is None), (a, b)

            check(tree, got)

    def test_binary_beats_json_size(self):
        # the codec exists to kill the reference's float->json-list overhead
        # (fedavg/utils.py:7-16); check the frame is close to raw array bytes
        import json
        arr = np.random.RandomState(0).randn(1000).astype(np.float32)
        msg = Message(1, 0, 1).add("w", arr)
        frame = msg.to_bytes()
        json_size = len(json.dumps(arr.tolist()))
        assert len(frame) < arr.nbytes + 500
        assert len(frame) < json_size / 2


def _run_fedavg_over_hub(codec_roundtrip):
    """Full FedAvg message choreography on the synchronous hub: 3 rounds,
    4 silos, deterministic 'training' (add client_idx+1 to every weight)."""
    hub = LocalHub(codec_roundtrip=codec_roundtrip)
    n_total, n_per_round, rounds = 10, 4, 3
    init = _params_tree()

    history = []
    server = FedAvgServerActor(
        hub.transport(0), init, n_total, n_per_round, rounds,
        on_round_done=lambda r, p: history.append((r, p)))

    def train_fn(params, client_idx, round_idx):
        new = {"dense": {k: v + (client_idx + 1)
                         for k, v in params["dense"].items()},
               "steps": params["steps"]}
        return new, 10 * (client_idx + 1)

    clients = [FedAvgClientActor(i, hub.transport(i), train_fn)
               for i in range(1, n_per_round + 1)]
    server.register_handlers()
    for c in clients:
        c.register_handlers()
    server.start()
    hub.pump()
    return history, init


@pytest.mark.parametrize("codec_roundtrip", [False, True])
def test_cross_silo_fedavg_choreography(codec_roundtrip):
    history, init = _run_fedavg_over_hub(codec_roundtrip)
    assert [r for r, _ in history] == [0, 1, 2]

    # round 0 aggregation must equal the weighted mean over the seeded sample
    ids = sample_clients(0, 10, 4)
    weights = np.array([10.0 * (i + 1) for i in ids], np.float32)
    expect = tree_weighted_mean(
        [{"dense": {k: v + (i + 1) for k, v in init["dense"].items()},
          "steps": init["steps"]} for i in ids], weights)
    got = history[0][1]
    np.testing.assert_allclose(np.asarray(got["dense"]["kernel"]),
                               np.asarray(expect["dense"]["kernel"]), rtol=1e-6)


def test_threaded_local_transport():
    """Threaded drive mode: client loop runs in a worker thread."""
    hub = LocalHub()
    t_server, t_client = hub.transport(0), hub.transport(1)
    got = []

    class Echo:
        def receive_message(self, msg_type, msg):
            if msg_type == "ping":
                t_client.send_message(
                    Message("pong", 1, 0).add("v", msg.get("v") + 1))

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append(msg.get("v"))
            t_client.stop()
            t_server.stop()

    t_client.add_observer(Echo())
    t_server.add_observer(Collect())
    worker = threading.Thread(target=t_client.run)
    worker.start()
    t_server.send_message(Message("ping", 0, 1).add("v", 41))
    t_server.run()  # blocks until Collect stops both
    worker.join(timeout=5)
    assert got == [42]


def test_grpc_loopback():
    """gRPC transport over 127.0.0.1 (the reference tests gRPC the same way:
    an all-loopback grpc_ipconfig.csv, SURVEY.md §4.3)."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from fedml_tpu.comm.grpc_transport import GrpcTransport

    table = {0: "127.0.0.1", 1: "127.0.0.1"}
    a = GrpcTransport(0, table, base_port=56210)
    b = GrpcTransport(1, table, base_port=56210)
    try:
        got = []

        class Collect:
            def receive_message(self, msg_type, msg):
                got.append(msg)
                b.stop()

        b.add_observer(Collect())
        tree = _params_tree(3)
        a.send_message(Message(9, 0, 1).add(Message.ARG_MODEL_PARAMS, tree)
                       .add(Message.ARG_NUM_SAMPLES, 55))
        b.run()  # blocks until Collect stops it
        assert got[0].type == 9
        assert got[0].get(Message.ARG_NUM_SAMPLES) == 55
        np.testing.assert_array_equal(
            got[0].get(Message.ARG_MODEL_PARAMS)["dense"]["kernel"],
            tree["dense"]["kernel"])
    finally:
        a.stop()


def test_ip_table_parser(tmp_path):
    from fedml_tpu.comm.grpc_transport import load_ip_table
    p = tmp_path / "ipconfig.csv"
    p.write_text("receiver_id,ip\n0,10.0.0.1\n1,10.0.0.2\n")
    assert load_ip_table(str(p)) == {0: "10.0.0.1", 1: "10.0.0.2"}


def test_pump_delivers_after_stop():
    """Regression: a message queued behind a _STOP must still deliver."""
    hub = LocalHub()
    t0 = hub.transport(0)
    got = []

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append(msg_type)

    t0.add_observer(Collect())
    t0.stop()
    hub.route(Message("late", 1, 0))
    assert hub.pump() == 1
    assert got == ["late"]


def test_server_barrier_caps_at_total_clients():
    """Regression: client_num_per_round > client_num_in_total must not
    deadlock the receive barrier (sample_clients caps the cohort)."""
    hub = LocalHub()
    init = _params_tree()
    history = []
    server = FedAvgServerActor(hub.transport(0), init,
                               client_num_in_total=2, client_num_per_round=5,
                               num_rounds=1,
                               on_round_done=lambda r, p: history.append(r))
    clients = [FedAvgClientActor(i, hub.transport(i),
                                 lambda p, ci, ri: (p, 10))
               for i in range(1, 3)]
    server.register_handlers()
    for c in clients:
        c.register_handlers()
    server.start()
    hub.pump()
    assert history == [0]


def test_ring_weights_two_nodes():
    """Regression: 2-node rings alias left/right neighbors; the extracted
    weights must still mix stochastically (sum to 1)."""
    from fedml_tpu.algorithms.decentralized import _ring_weights
    w_self, w_left, w_right = _ring_weights(
        np.array([[0.5, 0.5], [0.5, 0.5]], np.float64))
    assert abs(w_self + w_left + w_right - 1.0) < 1e-9
    with pytest.raises(ValueError):
        _ring_weights(np.array([[0.9, 0.5], [0.5, 0.5]], np.float64))


class _FakeMqttBroker:
    """In-process pub/sub broker standing in for a real MQTT daemon — routes
    published payloads to subscribed fake clients by exact topic match."""

    def __init__(self):
        self.subs = {}  # topic -> list of clients

    def subscribe(self, topic, client):
        self.subs.setdefault(topic, []).append(client)

    def publish(self, topic, payload):
        for client in self.subs.get(topic, []):
            client._deliver(topic, payload)


class _FakeMqttClient:
    """paho-mqtt Client API surface used by MqttTransport (connect,
    subscribe, publish, loop_start/stop, disconnect, on_message)."""

    _broker: "_FakeMqttBroker" = None  # class-level: shared per test

    def __init__(self, client_id=""):
        self.client_id = client_id
        self.on_message = None

    def connect(self, host, port):
        assert host == "fake-broker"

    def subscribe(self, topic, qos=0):
        self._broker.subscribe(topic, self)

    def publish(self, topic, payload, qos=0):
        self._broker.publish(topic, payload)

    def _deliver(self, topic, payload):
        msg = types.SimpleNamespace(topic=topic, payload=payload)
        if self.on_message is not None:
            self.on_message(self, None, msg)

    def loop_start(self):
        pass

    def loop_stop(self):
        pass

    def disconnect(self):
        pass


def test_mqtt_transport_loopback(monkeypatch):
    """MqttTransport over a broker fake: topic scheme, binary pytree codec,
    observer dispatch, clean stop (the reference never tests its
    MqttCommManager at all — mqtt_comm_manager.py has no test)."""
    from fedml_tpu.comm import mqtt_transport as mt

    class _FakeModule:
        Client = _FakeMqttClient

    _FakeMqttClient._broker = _FakeMqttBroker()
    monkeypatch.setattr(mt, "_mqtt", _FakeModule)
    monkeypatch.setattr(mt, "HAVE_MQTT", True)

    a = mt.MqttTransport(0, "fake-broker")
    b = mt.MqttTransport(1, "fake-broker")
    got = []

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append((msg_type, msg))
            b.stop()

    b.add_observer(Collect())
    tree = _params_tree(5)
    a.send_message(Message(3, 0, 1).add(Message.ARG_MODEL_PARAMS, tree))
    b.run()  # drains inbox until stop
    assert len(got) == 1
    mtype, msg = got[0]
    assert mtype == 3 and msg.sender_id == 0 and msg.receiver_id == 1
    np.testing.assert_array_equal(
        msg.get(Message.ARG_MODEL_PARAMS)["dense"]["kernel"],
        tree["dense"]["kernel"])


def test_mqtt_without_paho_uses_inrepo_client(monkeypatch):
    """Without paho the transport no longer raises: it falls back to the
    in-repo MQTT 3.1.1 client (comm/mqtt_client.py) — end-to-end over
    real sockets in tests/test_mqtt_broker.py."""
    from fedml_tpu.comm import mqtt_transport as mt
    from fedml_tpu.comm.mqtt_broker import MqttBroker
    from fedml_tpu.comm.mqtt_client import MiniMqttClient
    monkeypatch.setattr(mt, "HAVE_MQTT", False)
    with MqttBroker() as broker:
        t = mt.MqttTransport(0, "127.0.0.1", broker.port)
        assert isinstance(t._client, MiniMqttClient)
        t.stop()


class _DeafClientActor(FedAvgClientActor):
    """A silo that never responds to sync messages (crashed/partitioned) but
    still honors FINISH so the test can shut it down."""

    def register_handlers(self):
        self.register_handler(MsgType.S2C_FINISH, lambda m: self.finish())


def _silo_train_fn(delta):
    def fn(params, client_idx, round_idx):
        import jax
        return jax.tree.map(lambda v: v + delta, params), 10 * delta
    return fn


def test_straggler_drop_policy_completes_rounds():
    """With straggler_policy='drop', a dead silo stalls each round only for
    the timeout, then the quorum aggregates without it (the reference's
    barrier would hang forever, FedAvgServerManager.py:51)."""
    hub = LocalHub()
    t_server = hub.transport(0)
    t_c1, t_c2 = hub.transport(1), hub.transport(2)
    init = _params_tree(0)
    history = []
    server = FedAvgServerActor(
        t_server, init, client_num_in_total=2, client_num_per_round=2,
        num_rounds=2,
        on_round_done=lambda r, p: history.append((r, p)),
        straggler_policy="drop", round_timeout_s=0.25, min_silo_frac=0.5)
    c1 = FedAvgClientActor(1, t_c1, _silo_train_fn(1))
    c2 = _DeafClientActor(2, t_c2, _silo_train_fn(2))

    threads = [threading.Thread(target=a.run) for a in (c1, c2)]
    for th in threads:
        th.start()
    server.register_handlers()
    server.start()
    server.transport.run()  # until FINISH after num_rounds
    for th in threads:
        th.join(timeout=5)

    assert server.round_idx == 2 and not server.aborted
    assert server.dropped_silos == {0: [2], 1: [2]}
    # both rounds aggregated silo 1 alone: params = init + round_count
    np.testing.assert_allclose(
        np.asarray(server.params["dense"]["kernel"]),
        np.asarray(init["dense"]["kernel"]) + 2, rtol=1e-6)
    assert [r for r, _ in history] == [0, 1]


def test_straggler_abort_policy():
    hub = LocalHub()
    t_server = hub.transport(0)
    t_c1, t_c2 = hub.transport(1), hub.transport(2)
    server = FedAvgServerActor(
        t_server, _params_tree(0), client_num_in_total=2,
        client_num_per_round=2, num_rounds=3,
        straggler_policy="abort", round_timeout_s=0.2)
    c1 = FedAvgClientActor(1, t_c1, _silo_train_fn(1))
    c2 = _DeafClientActor(2, t_c2, _silo_train_fn(2))
    threads = [threading.Thread(target=a.run) for a in (c1, c2)]
    for th in threads:
        th.start()
    server.register_handlers()
    server.start()
    server.transport.run()
    for th in threads:
        th.join(timeout=5)
    assert server.aborted and server.round_idx == 0


def test_stale_round_upload_discarded():
    """A straggler's upload tagged with a closed round must not count toward
    the current barrier."""
    hub = LocalHub()
    server = FedAvgServerActor(
        hub.transport(0), _params_tree(0), client_num_in_total=2,
        client_num_per_round=2, num_rounds=5)
    server.register_handlers()
    server.round_idx = 3
    server._num_silos = 2
    stale = Message(MsgType.C2S_MODEL, 2, 0)
    stale.add(Message.ARG_MODEL_PARAMS, _params_tree(1))
    stale.add(Message.ARG_NUM_SAMPLES, 5)
    stale.add(Message.ARG_ROUND, 2)  # old round
    server._on_model(stale)
    assert server._received == {}


def test_base_framework_template_demo():
    """The copy-me scaffold (base_framework/algorithm_api.py:16-38) runs its
    sum-of-client-indexes demo: with 3 clients each round aggregates
    0+1+2 = 3."""
    from fedml_tpu.algorithms.base_framework import run_base_framework_demo
    assert run_base_framework_demo(client_num=3, num_rounds=2) == [3, 3]
