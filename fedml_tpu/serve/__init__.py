"""TPU-native serving: the train → aggregate → checkpoint → **serve** leg.

The reference FedML stack (and PRs 0-2 here) ends at the aggregated
checkpoint — there is no path from a federation round to an inference
request.  This package closes the loop, stdlib-only (plus jax), in three
layers plus a bench harness:

    fedml_tpu.serve.registry  versioned model registry: atomic hot-swap of
                              the live (params, apply_fn, version) triple,
                              pin/rollback, background checkpoint watcher
                              (serve-while-train against RoundCheckpointer)
    fedml_tpu.serve.batcher   dynamic micro-batching queue: size/deadline
                              flush triggers, power-of-two shape buckets
                              (one jit compile per bucket — the FedJAX
                              static-shapes lesson, arXiv:2108.02117),
                              deadline-based load shedding, drain-on-stop
    fedml_tpu.serve.server    ThreadingHTTPServer frontend (/predict,
                              /healthz, /version, /metrics) with admission
                              control and per-request deadline propagation
    fedml_tpu.serve.pool      multi-worker frontend (ISSUE 15): N
                              SO_REUSEPORT accept loops × N micro-batchers
                              over ONE shared registry, worker-labeled
                              telemetry, pool-wide health payloads
    fedml_tpu.serve.decode    continuous-batching decode scheduler for
                              autoregressive models: one compiled step
                              over fixed [slots], per-step slot admission,
                              swap-barrier version consistency
    fedml_tpu.serve.release   train-to-serve release gate (ISSUE 16):
                              every finalized global enters as a CANARY;
                              promotion gated on shadow-traffic
                              divergence, health-observatory alarms, and
                              held-out eval regression — fail rolls back
                              (the live slot never moved) with cooldown/
                              backoff, all crash-consistent
    scripts/serve_bench.py    open-loop load generator → BENCH_serve.json
    scripts/release_bench.py  gated release pipeline under live load →
                              BENCH_release.json

Everything is instrumented through the PR 2 telemetry registry under
``fedml_serve_*`` (see the README metric table) and designed to survive
chaos: a mid-load hot swap must never produce a torn read (the whole
triple swaps as one immutable snapshot), and a checkpoint directory GC'd
between list and load is tolerated, not fatal.
"""

from fedml_tpu.serve.batcher import (MicroBatcher, ShedError, TierGate,
                                     TIERS)
from fedml_tpu.serve.decode import DecodeResult, DecodeScheduler
from fedml_tpu.serve.pool import ServeWorkerPool
from fedml_tpu.serve.registry import ModelRegistry, ServedModel
from fedml_tpu.serve.release import ReleaseController, ShadowSampler
from fedml_tpu.serve.server import ServeFrontend

__all__ = ["MicroBatcher", "ShedError", "TierGate", "TIERS",
           "DecodeResult", "DecodeScheduler", "ServeWorkerPool",
           "ModelRegistry", "ServedModel", "ServeFrontend",
           "ReleaseController", "ShadowSampler"]
