"""ImageNet (ILSVRC2012) and Google Landmarks (gld23k / gld160k) loaders.

The reference treats ImageNet as 1000 pre-assigned "clients" (one per class
folder, ``ImageNet/data_loader.py``) and Landmarks as a CSV-mapped federated
split ``user_id,image_id,class`` (``Landmarks/data_loader.py:120-160``,
mapping files data_user_dict/gld23k_user_dict_*.csv).  Both are too large to
stack eagerly; these loaders materialize *per-client index tables* plus a
lazy decode function, and `materialize_clients` stages any subset into the
standard stacked layout.  Landmarks train transform = RandomResizedCrop(224)
+ flip (+Cutout 16 in the hdf5 variant); we decode resized 224×224 RGB here
and leave flip/cutout on device.
"""

from __future__ import annotations

import csv
import os
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .stacking import FederatedData, stack_client_data, batch_global


def _decode_image(path: str, size: int = 224) -> np.ndarray:
    from PIL import Image
    with Image.open(path) as im:
        im = im.convert("RGB").resize((size, size))
        return np.asarray(im, dtype=np.float32) / 255.0


def index_imagenet_folders(data_dir: str, split: str = "train"
                           ) -> Tuple[Dict[int, List[str]], int]:
    """class folder -> file list; client i = class i (the reference's
    federated ImageNet assigns whole classes to clients)."""
    root = os.path.join(data_dir, split)
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    table = {i: [os.path.join(root, c, f)
                 for f in sorted(os.listdir(os.path.join(root, c)))]
             for i, c in enumerate(classes)}
    return table, len(classes)


def read_landmarks_mapping(csv_path: str
                           ) -> Dict[str, List[Tuple[str, int]]]:
    """user_id -> [(image_id, class), ...] (Landmarks/data_loader.py:120-153;
    columns user_id,image_id,class are required there too)."""
    out: Dict[str, List[Tuple[str, int]]] = defaultdict(list)
    with open(csv_path) as f:
        for row in csv.DictReader(f):
            out[row["user_id"]].append((row["image_id"], int(row["class"])))
    return dict(out)


def landmarks_image_path(data_dir: str, image_id: str) -> str:
    """GLD images live at <data_dir>/images/<first 3 chars as dirs>/<id>.jpg
    (the standard GLDv2 layout)."""
    return os.path.join(data_dir, "images", image_id[0], image_id[1],
                        image_id[2], image_id + ".jpg")


def materialize_clients(index: Dict, decode: Callable[[object], Tuple],
                        client_ids: Sequence, batch_size: int,
                        class_num: int,
                        test_index: Optional[Dict] = None,
                        image_size: int = 224) -> FederatedData:
    """Stage a subset of clients into stacked arrays.  ``decode`` maps one
    index entry to (x, y)."""
    empty_shape = (0, image_size, image_size, 3)

    def stage(table, cids):
        xs, ys = [], []
        for cid in cids:
            pairs = [decode(e) for e in table.get(cid, [])]
            xs.append(np.stack([p[0] for p in pairs]) if pairs
                      else np.zeros(empty_shape, np.float32))
            ys.append(np.asarray([p[1] for p in pairs], np.int32))
        return xs, ys

    xs_tr, ys_tr = stage(index, client_ids)
    train = stack_client_data(xs_tr, ys_tr, batch_size)
    test = None
    test_global = None
    if test_index is not None:
        te_ids = list(test_index)
        xs_te, ys_te = stage(test_index, te_ids)
        test = stack_client_data(xs_te, ys_te, batch_size)
        test_global = batch_global(np.concatenate(xs_te),
                                   np.concatenate(ys_te), batch_size)
    return FederatedData(
        client_num=len(client_ids), class_num=class_num, train=train,
        test=test,
        train_global=batch_global(np.concatenate(xs_tr),
                                  np.concatenate(ys_tr), batch_size),
        test_global=test_global)


def load_landmarks(data_dir: str, mapping_csv: str, batch_size: int = 20,
                   max_clients: Optional[int] = None,
                   image_size: int = 224) -> FederatedData:
    """gld23k (233 clients / 203 classes) or gld160k (1262 / 2028), chosen by
    which mapping csv is passed (Landmarks/data_loader.py docstring).
    A relative ``mapping_csv`` resolves against ``data_dir``."""
    if not os.path.isabs(mapping_csv):
        mapping_csv = os.path.join(data_dir, mapping_csv)
    mapping = read_landmarks_mapping(mapping_csv)
    cids = sorted(mapping)[:max_clients]
    class_num = 1 + max(c for entries in mapping.values()
                        for _, c in entries)
    decode = lambda e: (_decode_image(landmarks_image_path(data_dir, e[0]),
                                      image_size), e[1])
    return materialize_clients(mapping, decode, cids, batch_size, class_num,
                               image_size=image_size)


def load_imagenet(data_dir: str, batch_size: int = 32,
                  max_clients: Optional[int] = None,
                  image_size: int = 224) -> FederatedData:
    train_idx, class_num = index_imagenet_folders(data_dir, "train")
    cids = list(train_idx)[:max_clients]
    # entry = (path, class); rebuild table with labels attached
    table = {c: [(p, c) for p in train_idx[c]] for c in cids}
    decode = lambda e: (_decode_image(e[0], image_size), e[1])
    return materialize_clients(table, decode, cids, batch_size, class_num,
                               image_size=image_size)
