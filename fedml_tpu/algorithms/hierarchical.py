"""Hierarchical FL — two-tier client -> group (edge) -> global averaging.

Parity with fedml_api/standalone/hierarchical_fl/:
* random client->group assignment (trainer.py:12-18, ``group_method ==
  'random'``);
* per global round: the plain seeded sampler picks clients, which are routed
  to their groups (trainer.py:32-41);
* each group runs ``group_comm_round`` FedAvg rounds among its sampled
  clients (group.py:24-46), then groups average weighted by their sampled
  clients' sample counts (trainer.py:56-62).

TPU mapping (SURVEY.md §2.5): group tier = ICI within a pod slice, global
tier = DCN across slices.  In this single-program form each group round is a
cohort-engine jit; group cohorts are padded to one static bucket so all
groups share one compiled program.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.data.stacking import gather_cohort

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class HierarchicalConfig(FedAvgConfig):
    group_num: int = 2
    group_comm_round: int = 2
    group_method: str = "random"


class HierarchicalFedAvg(FedAvg):
    def __init__(self, workload, data, config: HierarchicalConfig, mesh=None, sink=None):
        super().__init__(workload, data, config, mesh=mesh, sink=sink)
        cfg = config
        if cfg.group_method != "random":
            raise ValueError(f"unknown group_method {cfg.group_method!r}")
        rng = np.random.RandomState(cfg.seed)
        self.group_indexes = rng.randint(0, cfg.group_num, data.client_num)

    def _group_clients(self, ids: np.ndarray) -> Dict[int, List[int]]:
        groups: Dict[int, List[int]] = {}
        for cid in ids:
            groups.setdefault(int(self.group_indexes[cid]), []).append(int(cid))
        return groups

    def run(self, params=None, rng=None, checkpointer=None):
        cfg = self.cfg
        rng = rng if rng is not None else jax.random.key(cfg.seed)
        if params is None:
            rng, init_rng = jax.random.split(rng)
            params = self.workload.init(init_rng, jax.tree.map(
                lambda v: v[0, 0], {k: self.data.train[k]
                                    for k in ("x", "y", "mask")}))
        params, rng, start_round = self._maybe_resume(checkpointer, params, rng)

        from jax.sharding import PartitionSpec as P
        from fedml_tpu.parallel.mesh import stage_global
        params = stage_global(params, self.mesh)
        for global_round in range(start_round, cfg.comm_round):
            ids = sample_clients(global_round, self.data.client_num,
                                 cfg.client_num_per_round)
            groups = self._group_clients(np.asarray(ids))
            group_params, group_weights = [], []
            for gidx in sorted(groups):
                gids = groups[gidx]
                w_group = params
                cohort = gather_cohort(self.data.train, gids,
                                       pad_to=cfg.client_num_per_round)
                cohort = stage_global(cohort, self.mesh, P("clients"))
                for group_round in range(cfg.group_comm_round):
                    rng, rr = jax.random.split(rng)
                    rr = stage_global(rr, self.mesh)
                    w_group, _ = self.cohort_step(w_group, cohort, rr)
                group_params.append(w_group)
                group_weights.append(
                    float(self.data.train["num_samples"][gids].sum()))
            params = tree_weighted_mean(group_params,
                                        jax.numpy.asarray(group_weights))

            if (global_round % cfg.frequency_of_the_test == 0
                    or global_round == cfg.comm_round - 1):
                stats = self.evaluate_global(params)
                stats["round"] = global_round
                self.history.append(stats)
                logger.info("global round %d: %s", global_round, stats)
                if self.sink is not None:
                    self.sink.log(stats, step=global_round)
            if checkpointer is not None:
                checkpointer.maybe_save(
                    global_round,
                    self._ckpt_state(params, rng, global_round),
                    last_round=global_round == cfg.comm_round - 1)
        return params
