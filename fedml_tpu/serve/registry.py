"""Versioned model registry with atomic hot-swap — the serving side of
the checkpoint contract.

The federation produces a new global model every round; requests must
never see half of one.  The whole live state is one immutable
`ServedModel` snapshot (params, apply_fn, version) swapped by a single
attribute assignment, so a reader that grabbed the snapshot keeps a
consistent triple no matter how many swaps land mid-request — zero
request downtime, zero torn reads (tests/test_serve.py hammers this
under concurrent load).

Feeds:

* ``publish(params, version)`` — direct, used by the cross-silo server's
  serve-while-train hook (`FedAvgServerActor(publish=registry.publish)`):
  the federation serves its own global model *while training*.
* `CheckpointWatcher` — a background thread polling a `RoundCheckpointer`
  directory (utils/checkpoint.py) for new round steps and publishing
  them; tolerant of a step directory GC'd (``keep_last_n``) between list
  and load.

Operational controls: ``pin(version)`` freezes serving on a known-good
version while publishes keep accumulating history; ``rollback()`` steps
the live model back one version (and pins there, so the next publish
doesn't immediately re-roll); ``unpin()`` resumes following the newest.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from fedml_tpu.obs import telemetry

log = logging.getLogger(__name__)

Pytree = Any


class ServedModel:
    """One immutable serving snapshot.  Readers hold the OBJECT, never the
    registry's mutable slot — consistency by construction."""
    __slots__ = ("params", "apply_fn", "version")

    def __init__(self, params: Pytree, apply_fn: Callable, version: int):
        self.params = params
        self.apply_fn = apply_fn
        self.version = int(version)

    def __repr__(self):
        return f"ServedModel(version={self.version})"


class ModelRegistry:
    """Monotonic version store + the single live-model slot.

    Writers (publish/pin/rollback) serialize on a lock; readers call
    ``current()`` lock-free — the live slot is swapped by one reference
    assignment (atomic under the GIL), and every snapshot is immutable.
    """

    def __init__(self, apply_fn: Callable, history: int = 4):
        if history < 2:
            raise ValueError(f"history must keep >= 2 versions for "
                             f"rollback; got {history}")
        self._apply_fn = apply_fn
        self._max_history = history
        self._lock = threading.Lock()
        self._history: "OrderedDict[int, ServedModel]" = OrderedDict()
        self._pinned: Optional[int] = None
        self._live: Optional[ServedModel] = None
        reg = telemetry.get_registry()
        self._g_version = reg.gauge("fedml_serve_model_version_total")
        self._c_swap = reg.counter("fedml_serve_hot_swap_total")
        self._c_rollback = reg.counter("fedml_serve_rollback_total")

    # -- read path (request hot path) ---------------------------------------
    def current(self) -> Optional[ServedModel]:
        """The live snapshot, or None before the first publish."""
        return self._live

    @property
    def version(self) -> Optional[int]:
        m = self._live
        return None if m is None else m.version

    @property
    def pinned(self) -> Optional[int]:
        return self._pinned

    def versions(self) -> list:
        with self._lock:
            return list(self._history)

    # -- write path ---------------------------------------------------------
    def publish(self, params: Pytree, version: int) -> bool:
        """Register a new model version; hot-swap it live unless a pin is
        holding an older version.  Returns True when the version was NEW
        (stale/duplicate publishes — e.g. a watcher and a train hook both
        feeding the registry — are ignored, preserving monotonicity)."""
        version = int(version)
        snapshot = ServedModel(params, self._apply_fn, version)
        with self._lock:
            if self._history and version <= next(reversed(self._history)):
                return False
            self._history[version] = snapshot
            while len(self._history) > self._max_history:
                # evict oldest-first but NEVER the pinned or live version:
                # a long serve-while-train run publishing past a pin must
                # not make the pinned model un-rollback-able
                protected = {self._pinned}
                if self._live is not None:
                    protected.add(self._live.version)
                evict = next((k for k in self._history
                              if k not in protected), None)
                if evict is None:
                    break
                del self._history[evict]
            if self._pinned is None:
                self._live = snapshot
                self._c_swap.inc()
            if self._live is not None:  # gauge tracks the SERVING version
                self._g_version.set(self._live.version)
        log.info("registry: published version %d%s", version,
                 " (pinned, not live)" if self._pinned is not None else "")
        return True

    def pin(self, version: int) -> None:
        """Freeze serving on ``version`` (must still be in history).
        Publishes keep landing in history but stop swapping live."""
        with self._lock:
            if version not in self._history:
                raise KeyError(
                    f"version {version} not in registry history "
                    f"{list(self._history)}; cannot pin")
            self._pinned = version
            self._live = self._history[version]
            self._g_version.set(version)

    def unpin(self) -> None:
        """Resume following the newest published version."""
        with self._lock:
            self._pinned = None
            if self._history:
                self._live = self._history[next(reversed(self._history))]
                self._g_version.set(self._live.version)

    def rollback(self) -> int:
        """Step the live model back one version and pin there (so the
        next publish doesn't instantly re-roll).  Returns the version now
        live; raises if there is no earlier version to fall back to."""
        with self._lock:
            if self._live is None:
                raise RuntimeError("rollback before any publish")
            versions = list(self._history)
            idx = versions.index(self._live.version)
            if idx == 0:
                raise RuntimeError(
                    f"no version older than {self._live.version} in "
                    f"history {versions}; cannot rollback")
            target = versions[idx - 1]
            self._pinned = target
            self._live = self._history[target]
            self._g_version.set(target)
            self._c_rollback.inc()
        log.warning("registry: rolled back to version %d (pinned)", target)
        return target


def _list_steps(ckpt_dir: str) -> list:
    """Integer-named child dirs = completed orbax steps (orbax writes to a
    tmp-named dir and renames, so a digit-named dir is a durable step)."""
    try:
        names = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return []
    return sorted(int(n) for n in names if n.isdigit())


class CheckpointWatcher:
    """Background thread: poll a `RoundCheckpointer` directory, publish
    new rounds into a `ModelRegistry`.

    Each load opens a FRESH read-side `RoundCheckpointer` so the live
    writer's orbax manager (possibly mid-async-save in another process)
    is never shared.  A step that vanishes between list and load — the
    checkpointer's ``keep_last_n`` GC racing us — is counted and skipped,
    never fatal; it is marked seen so the watcher doesn't spin on it.
    """

    def __init__(self, registry: ModelRegistry, ckpt_dir: str,
                 poll_s: float = 0.5, param_key: str = "params"):
        self.registry = registry
        self.ckpt_dir = ckpt_dir
        self.poll_s = poll_s
        self.param_key = param_key
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seen = -1  # highest step already published or skipped
        reg = telemetry.get_registry()
        self._c_loads = reg.counter("fedml_serve_checkpoint_load_total",
                                    outcome="ok")
        self._c_vanished = reg.counter("fedml_serve_checkpoint_load_total",
                                       outcome="vanished")

    def poll_once(self) -> int:
        """One list-and-load sweep (the thread's loop body; also the
        deterministic test surface).  Returns how many new versions were
        published."""
        published = 0
        for step in _list_steps(self.ckpt_dir):
            if step <= self._seen:
                continue
            params = self._load(step)
            self._seen = max(self._seen, step)
            if params is not None:
                self.registry.publish(params, step)
                self._c_loads.inc()
                published += 1
        return published

    def _load(self, step: int):
        from fedml_tpu.utils.checkpoint import RoundCheckpointer
        try:
            ck = RoundCheckpointer(self.ckpt_dir)
            try:
                state = ck.restore(step)
            finally:
                ck.close()
            return state[self.param_key]
        except (FileNotFoundError, KeyError, ValueError, OSError) as e:
            # the step was GC'd between list and load, or is from a
            # different state schema — skip it, keep serving
            self._c_vanished.inc()
            log.warning("watcher: step %d unreadable (%s: %s); skipping",
                        step, type(e).__name__, e)
            return None

    def start(self) -> "CheckpointWatcher":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-ckpt-watcher")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watcher must outlive
                log.exception("watcher: poll failed; retrying")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
