"""Observability subsystem (fedml_tpu/obs): trace-context propagation
across transports and faults, telemetry registry semantics + thread
safety, the crash-readable MetricsSink summary, and the report merger.

Contract under test (ISSUE 2 acceptance): one federated round stitches
into a single cross-node trace (broadcast → train → upload → aggregate);
retry/fault/health counters mirror the comm layer exactly; disabled
observability costs a branch, not threads or allocations."""

import json
import os
import threading

import numpy as np
import pytest

from fedml_tpu.comm.actors import NodeManager
from fedml_tpu.comm.chaos import ChaosPlan, ChaosTransport, LinkChaos
from fedml_tpu.comm.local import LocalHub
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilient import ResilientTransport, RetryPolicy
from fedml_tpu.comm.transport import Transport
from fedml_tpu.obs import report, telemetry, trace
from fedml_tpu.utils.metrics import MetricsSink


@pytest.fixture
def obs():
    """Enabled registry + tracer, torn down after the test (the process
    globals must not leak into other tests' Null-mode expectations)."""
    reg = telemetry.enable()
    tr = trace.enable(node="test")
    yield reg, tr
    telemetry.disable()
    trace.disable()


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(3, 2).astype(np.float32)}


def _run_local_federation(n_silos=2, n_rounds=2):
    from fedml_tpu.algorithms.cross_silo import (FedAvgClientActor,
                                                 FedAvgServerActor)
    hub = LocalHub(codec_roundtrip=True)
    server = FedAvgServerActor(hub.transport(0), _params(),
                               client_num_in_total=n_silos,
                               client_num_per_round=n_silos,
                               num_rounds=n_rounds)
    server.register_handlers()

    def train_fn(params, client_idx, round_idx):
        import jax
        return jax.tree.map(lambda v: v + 1.0, params), 10

    silos = [FedAvgClientActor(i, hub.transport(i), train_fn)
             for i in range(1, n_silos + 1)]
    for s in silos:
        s.register_handlers()
    server.start()
    hub.pump()
    return server


# --------------------------------------------------------------------------
# trace propagation
# --------------------------------------------------------------------------

def test_round_trace_stitches_across_local_transport(obs):
    """The acceptance trace: every phase span of a round shares the
    round's trace id, parent-linked server broadcast → silo train →
    upload → server aggregate — and survives the binary codec
    (codec_roundtrip hub)."""
    _, tr = obs
    _run_local_federation(n_silos=2, n_rounds=2)
    spans = tr.spans
    rounds = [s for s in spans if s["name"] == "round"]
    assert len(rounds) == 2
    for root in rounds:
        tid = root["trace_id"]
        members = [s for s in spans if s["trace_id"] == tid]
        names = {s["name"] for s in members}
        assert {"round", "broadcast", "train", "upload",
                "aggregate"} <= names
        by_id = {s["span_id"]: s for s in members}
        # silo-side spans hang off the broadcast via the recv span; the
        # server-side aggregate hangs off the round root — one connected
        # tree per round
        for s in members:
            if s["parent_id"] is not None:
                assert s["parent_id"] in by_id, \
                    f"orphan span {s['name']} in trace {tid}"
        trains = [s for s in members if s["name"] == "train"]
        assert {s["node"] for s in trains} == {1, 2}
        for t in trains:
            recv = by_id[t["parent_id"]]
            assert recv["name"].startswith("recv:")
            bcast = by_id[recv["parent_id"]]
            assert bcast["name"] == "broadcast" and bcast["node"] == 0


def test_trace_context_rides_message_codec(obs):
    _, tr = obs
    msg = Message(1, 0, 1).add(Message.ARG_MODEL_PARAMS, _params())
    with tr.span("root") as sp:
        trace.inject(msg, sp.context)
    decoded = Message.from_bytes(msg.to_bytes())
    ctx = trace.extract(decoded)
    assert ctx is not None
    assert ctx.trace_id == sp.trace_id and ctx.span_id == sp.span_id
    # arrays still round-trip next to the context header
    np.testing.assert_array_equal(
        decoded.get(Message.ARG_MODEL_PARAMS)["w"], _params()["w"])


def test_trace_disabled_is_nullpath():
    """No tracer => actors neither stamp contexts nor record spans."""
    assert trace.get_tracer() is None
    received = []

    class Probe(NodeManager):
        def register_handlers(self):
            self.register_handler("x", received.append)

    hub = LocalHub()
    a, b = Probe(0, hub.transport(0)), Probe(1, hub.transport(1))
    a.register_handlers(), b.register_handlers()
    a.send("x", 1)
    hub.pump()
    assert len(received) == 1
    assert received[0].get(trace.CTX_KEY) is None


# --------------------------------------------------------------------------
# telemetry x fault layer
# --------------------------------------------------------------------------

class _Flaky(Transport):
    """Raises on the first ``fail_first`` attempts per message."""

    def __init__(self, fail_first):
        super().__init__()
        self.fail_first = fail_first
        self.attempts = {}
        self.delivered = []

    def send_message(self, msg):
        n = self.attempts.get(msg.get("v"), 0)
        self.attempts[msg.get("v")] = n + 1
        if n < self.fail_first:
            raise ConnectionError("flaky")
        self.delivered.append(msg.get("v"))

    def run(self):
        pass

    def stop(self):
        pass


def _drain(rt, done, timeout=5.0):
    """Wait for the sender thread to finish the message's retry loop
    BEFORE stopping (stop() aborts in-flight retries by design)."""
    import time
    deadline = time.monotonic() + timeout
    while not done() and time.monotonic() < deadline:
        time.sleep(0.005)
    rt.stop()


def test_retry_counter_increments_exactly_per_attempt(obs):
    reg, _ = obs
    inner = _Flaky(fail_first=2)
    rt = ResilientTransport(inner, RetryPolicy(
        max_attempts=4, base_backoff_s=0.001, max_backoff_s=0.002,
        jitter_frac=0.0, send_deadline_s=5.0))
    rt.send_message(Message("t", 0, 1).add("v", 1))
    _drain(rt, lambda: inner.delivered or rt.dead_letters)
    snap = reg.snapshot()["counters"]
    # 3 attempts total: 2 failures -> exactly 2 retries, 1 success, 0 dead
    assert snap["fedml_comm_send_retries_total"] == 2
    assert snap["fedml_comm_send_ok_total"] == 1
    assert snap.get("fedml_comm_dead_letter_total", 0) == 0
    assert rt.retries == 2  # attribute counter stays in lockstep


def test_dead_letter_counter_on_exhaustion(obs):
    reg, _ = obs
    rt = ResilientTransport(_Flaky(fail_first=99), RetryPolicy(
        max_attempts=3, base_backoff_s=0.001, max_backoff_s=0.002,
        jitter_frac=0.0, send_deadline_s=5.0),
        on_dead_letter=lambda m, e: None)
    rt.send_message(Message("t", 0, 1).add("v", 1))
    _drain(rt, lambda: rt.dead_letters)
    snap = reg.snapshot()["counters"]
    # ISSUE 19: dead letters are labeled by reason (lazy registration —
    # the series exists only because this dead letter happened)
    assert snap['fedml_comm_dead_letter_total{reason="send_failed"}'] == 1
    assert snap["fedml_comm_send_retries_total"] == 2  # attempts 1..2 retried


def test_trace_context_survives_resilient_retries(obs):
    """A message that needs 3 wire attempts still lands with its span
    context intact, records ONE recv span, and the retry counter shows
    the attempts."""
    reg, tr = obs
    handled = []

    class Probe(NodeManager):
        def register_handlers(self):
            self.register_handler("x", handled.append)

    hub = LocalHub(codec_roundtrip=True)

    class FlakyWire(Transport):
        """First two sends of each frame raise; then route into the hub."""

        def __init__(self):
            super().__init__()
            self.calls = 0

        def send_message(self, msg):
            self.calls += 1
            if self.calls <= 2:
                raise ConnectionError("flaky")
            hub.route(msg)

        def run(self):
            pass

        def stop(self):
            pass

    wire = FlakyWire()
    rt = ResilientTransport(wire, RetryPolicy(
        max_attempts=5, base_backoff_s=0.001, max_backoff_s=0.002,
        jitter_frac=0.0, send_deadline_s=5.0))
    sender = Probe(0, rt)
    receiver = Probe(1, hub.transport(1))
    sender.register_handlers(), receiver.register_handlers()
    with tr.span("root") as root:
        sender.send("x", 1)
    _drain(rt, lambda: wire.calls >= 3)
    hub.pump()
    assert len(handled) == 1
    ctx = trace.extract(handled[0])
    assert ctx is not None and ctx.trace_id == root.trace_id
    recv_spans = [s for s in tr.spans if s["name"] == "recv:x"]
    assert len(recv_spans) == 1
    assert recv_spans[0]["parent_id"] == root.span_id
    assert reg.snapshot()["counters"]["fedml_comm_send_retries_total"] == 2


def test_chaos_dup_spans_dedupe_by_span_id(obs):
    """A duplicated frame re-runs the handler but records ONE recv span
    (deterministic ids), while the chaos dup counter says what the wire
    actually did."""
    reg, tr = obs
    handled = []

    class Probe(NodeManager):
        def register_handlers(self):
            self.register_handler("x", handled.append)

    hub = LocalHub()
    plan = ChaosPlan(seed=0, default=LinkChaos(dup_prob=1.0))
    sender = Probe(0, ChaosTransport(hub.transport(0), plan))
    receiver = Probe(1, hub.transport(1))
    sender.register_handlers(), receiver.register_handlers()
    with tr.span("root"):
        sender.send("x", 1)
    hub.pump()
    assert len(handled) == 2  # the wire really delivered twice
    recv_spans = [s for s in tr.spans if s["name"] == "recv:x"]
    assert len(recv_spans) == 1
    assert reg.snapshot()["counters"][
        'fedml_chaos_faults_total{kind="dup"}'] == 1


def test_chaos_reorder_keeps_distinct_spans(obs):
    """Reordered (held/released) messages are DISTINCT deliveries: two
    sends yield two recv spans even when their order flips."""
    reg, tr = obs
    order = []

    class Probe(NodeManager):
        def register_handlers(self):
            self.register_handler("x", lambda m: order.append(m.get("v")))

    hub = LocalHub()
    plan = ChaosPlan(seed=0, default=LinkChaos(reorder_prob=1.0,
                                               max_delay_s=0.05))
    sender = Probe(0, ChaosTransport(hub.transport(0), plan))
    receiver = Probe(1, hub.transport(1))
    sender.register_handlers(), receiver.register_handlers()
    with tr.span("root"):
        sender.send("x", 1, v=1)   # held
        sender.send("x", 1, v=2)   # held; releases v=1
    sender.transport.stop()        # flushes the still-held message
    hub.pump()
    assert sorted(order) == [1, 2]  # both frames land exactly once
    recv_spans = [s for s in tr.spans if s["name"] == "recv:x"]
    assert len(recv_spans) == 2
    assert len({s["span_id"] for s in recv_spans}) == 2
    assert reg.snapshot()["counters"][
        'fedml_chaos_faults_total{kind="reorder"}'] >= 1


def test_grpc_dup_spans_dedupe_by_span_id(obs):
    """The chaos-dup dedupe contract holds on the gRPC backend path: a
    duplicated RPC delivery re-runs the handler but records ONE recv
    span — the deterministic id rides the frame's trace header across
    the real wire, not the object identity the local hub shares."""
    pytest.importorskip("grpc")
    from fedml_tpu.comm.grpc_transport import GrpcTransport
    reg, tr = obs
    handled = []
    table = {0: "127.0.0.1", 1: "127.0.0.1"}
    ta = GrpcTransport(0, table, base_port=56240)
    tb = GrpcTransport(1, table, base_port=56240)

    class Probe(NodeManager):
        def register_handlers(self):
            self.register_handler("x", self._on)

        def _on(self, m):
            handled.append(m)
            if len(handled) >= 2:
                tb.stop()

    sender = Probe(0, ChaosTransport(ta, ChaosPlan(
        seed=0, default=LinkChaos(dup_prob=1.0))))
    receiver = Probe(1, tb)
    sender.register_handlers()
    # watchdog: if the dup never lands, unblock run() so the assert
    # below reports the real failure instead of hanging the suite
    killer = threading.Timer(20, tb.stop)
    killer.daemon = True
    killer.start()
    try:
        with tr.span("root") as root:
            sender.send("x", 1, v=1)
        receiver.run()     # blocks until the second delivery stops it
    finally:
        killer.cancel()
        ta.stop()
    assert len(handled) == 2   # the wire really delivered twice
    recv_spans = [s for s in tr.spans if s["name"] == "recv:x"]
    assert len(recv_spans) == 1
    assert recv_spans[0]["parent_id"] == root.span_id


def test_per_process_trace_export_merges_without_collisions(tmp_path):
    """Each process/worker exports its OWN trace file (the runner names
    them ``trace-node<id>-<pid>.json``); a Faultline respawn builds a
    FRESH tracer in the same process.  The merged report must keep every
    span — the per-tracer nonce guarantees generated ids never collide
    across tracer instances, and the loader's span-id dedupe only
    collapses true duplicates."""
    files = []
    n_spans = 0
    for node in ("node0", "node1"):
        for incarnation in range(2):   # original + respawned actor
            tr = trace.SpanTracer(node=node)
            with tr.span("round"):
                with tr.span("ingest:fold"):
                    pass
            n_spans += 2
            p = tmp_path / f"trace-{node}-{incarnation}.json"
            tr.export(str(p))
            files.append(p)
    events = report.load_trace_events(str(tmp_path))
    assert len(events) == n_spans
    ids = [e["args"]["span_id"] for e in events]
    assert len(set(ids)) == n_spans, "span-id collision across exports"
    # idempotence: a merged file written INTO the dir must not double
    report.merge_traces(str(tmp_path), str(tmp_path / "merged.json"))
    assert len(report.load_trace_events(str(tmp_path))) == n_spans


# --------------------------------------------------------------------------
# telemetry registry semantics
# --------------------------------------------------------------------------

def test_registry_thread_safety(obs):
    """Counters/gauges/histograms under concurrent actor-style threads:
    no lost updates."""
    reg, _ = obs
    c = reg.counter("fedml_test_threads_total")
    h = reg.histogram("fedml_test_threads_seconds")
    n_threads, n_iter = 8, 2000

    def work():
        for i in range(n_iter):
            c.inc()
            h.observe(i * 1e-4)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_iter
    assert h.count == n_threads * n_iter


def test_registry_rejects_bad_names(obs):
    reg, _ = obs
    with pytest.raises(ValueError):
        reg.counter("requests_total")           # missing fedml_ prefix
    with pytest.raises(ValueError):
        reg.counter("fedml_send_count")         # missing unit suffix
    with pytest.raises(ValueError):
        reg.gauge("fedml_Bad_total")            # uppercase


def test_registry_kind_conflict(obs):
    reg, _ = obs
    reg.counter("fedml_conflict_total")
    with pytest.raises(ValueError):
        reg.gauge("fedml_conflict_total")


def test_null_registry_is_free_and_silent():
    reg = telemetry.get_registry()
    assert not reg.enabled
    c = reg.counter("fedml_whatever_total", link="0->1")
    c.inc(5)
    assert reg.snapshot() == {} and reg.render_prometheus() == ""


def test_prometheus_rendering_and_http(obs):
    reg, _ = obs
    reg.counter("fedml_http_hits_total", link="0->1").inc(3)
    reg.histogram("fedml_http_wait_seconds", buckets=(0.1, 1.0)).observe(0.5)
    text = reg.render_prometheus()
    assert '# TYPE fedml_http_hits_total counter' in text
    assert 'fedml_http_hits_total{link="0->1"} 3' in text
    assert 'fedml_http_wait_seconds_bucket{le="1.0"} 1' in text
    assert 'fedml_http_wait_seconds_bucket{le="+Inf"} 1' in text
    assert 'fedml_http_wait_seconds_count 1' in text
    # the stdlib /metrics endpoint serves the same text
    import urllib.request
    server = telemetry.start_http_server(0, reg)  # port 0: OS-assigned
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert body == text
    finally:
        server.shutdown()
        server.server_close()


# --------------------------------------------------------------------------
# MetricsSink satellites
# --------------------------------------------------------------------------

def test_summary_json_flushes_before_close(tmp_path):
    """A crashed run (sink never closed) still leaves a readable,
    non-torn summary.json after flush_summary_every logs."""
    sink = MetricsSink(str(tmp_path), flush_summary_every=3)
    for i in range(7):
        sink.log({"round": i, "acc": i / 10}, step=i)
    # NOT closed — simulates the crash the recovery path resumes from
    path = tmp_path / "summary.json"
    assert path.exists()
    data = json.loads(path.read_text())
    assert data["round"] == 5  # last flushed multiple of 3 (logs 1..6)
    assert not (tmp_path / "summary.json.tmp").exists()  # atomic replace
    sink.close()
    assert json.loads(path.read_text())["round"] == 6


def test_summary_written_atomically(tmp_path, monkeypatch):
    """os.replace (not in-place write) publishes the summary."""
    sink = MetricsSink(str(tmp_path))
    calls = []
    real_replace = os.replace

    def spy(src, dst):
        calls.append((src, dst))
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", spy)
    sink.log({"x": 1})
    sink.close()
    assert any(dst.endswith("summary.json") and src.endswith(".tmp")
               for src, dst in calls)


# --------------------------------------------------------------------------
# report merger
# --------------------------------------------------------------------------

def test_report_renders_round_timeline(obs, tmp_path):
    reg, tr = obs
    _run_local_federation(n_silos=2, n_rounds=2)
    trace_dir = tmp_path / "trace"
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    tr.export(str(trace_dir / "trace-node0.json"))
    reg.save(str(run_dir / "telemetry.json"))
    with MetricsSink(str(run_dir)) as sink:
        sink.log({"round": 0, "train_acc": 0.5}, step=0)
    text = report.render_report(str(run_dir), str(trace_dir))
    assert "round timelines" in text
    assert "broadcast" in text and "train" in text and "aggregate" in text
    assert "fedml_comm_send_total" in text
    assert "train_acc" in text
    # merged Perfetto file is loadable trace_event JSON: spans plus the
    # process_name metadata that labels each node's track
    out = tmp_path / "merged.json"
    n = report.merge_traces(str(trace_dir), str(out))
    assert n > 0
    merged = json.loads(out.read_text())
    assert {e["ph"] for e in merged["traceEvents"]} == {"X", "M"}
    meta = [e for e in merged["traceEvents"] if e["ph"] == "M"]
    assert {e["args"]["name"] for e in meta} >= {"node 0", "node 1"}


def test_report_tolerates_missing_artifacts(tmp_path):
    text = report.render_report(str(tmp_path), None)
    assert "report" in text  # renders, no crash, no sections
