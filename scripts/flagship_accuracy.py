"""Flagship accuracy run (VERDICT r3 item 3): the benchmark/README.md:105
CIFAR10 ResNet-56 config — 10 clients, LDA(0.5) non-IID, B=64, SGD
lr=0.001 wd=0.001, E=20 local epochs, 100 rounds — executed end-to-end,
with the centralized twin trained at the same budget for the published
centralized-vs-federated comparison (93.19 vs 87.12).

Real CIFAR10 is not downloadable on this host, so by default the run uses
the LDA-partitioned learnable CIFAR twin (data/synthetic.py
cifar_learnable_twin); pass --data_dir to run on a real CIFAR-10 pickle
tree instead.  Writes FLAGSHIP_CURVE.json:

* the full federated accuracy curve (eval every ``--eval_every`` rounds),
* the centralized curve at the same number of gradient steps,
* the retention ratio fed/centralized — the hermetic proxy for the
  published 87.12/93.19 = 0.935,
* the reference's published trajectory (normalized round fraction) when
  the pretrained curve files parse, for shape comparison.

TPU: `python scripts/flagship_accuracy.py` (full config, ~100 rounds).
CPU sanity: `--preset cpu_small` shrinks rounds/epochs/samples to
minutes while keeping model, partition, and optimizer real.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_CURVES = "/root/reference/fedml_api/model/cv/pretrained/CIFAR10/resnet56"


class PartialSink:
    """MetricsSink that appends every eval to <json_out>.partial as it
    lands: a tunnel wedge (or timeout kill) mid-run must still leave the
    curve measured so far on disk (round-4 hardening — the tunnel was
    seen wedging mid-session after a clean probe)."""

    def __init__(self, path, meta):
        self.path, self.meta, self.curve = path, meta, []

    def log(self, metrics, step=None):
        self.curve.append({"round": step,
                           "train_acc": metrics.get("train_acc"),
                           "test_acc": metrics.get("test_acc")})
        with open(self.path, "w") as f:
            json.dump({"partial": True, "config": self.meta,
                       "federated_curve_so_far": self.curve}, f,
                      indent=1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="tpu", choices=["cpu", "tpu"])
    ap.add_argument("--preset", default="full",
                    choices=["full", "cpu_small"],
                    help="full = published config; cpu_small = scaled "
                         "minutes-long sanity run (same model/partition)")
    ap.add_argument("--data_dir", default=None,
                    help="real CIFAR-10 pickle tree; default = learnable twin")
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--samples_per_client", type=int, default=None)
    ap.add_argument("--eval_every", type=int, default=5)
    ap.add_argument("--json_out", default="FLAGSHIP_CURVE.json")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax
    if args.platform != "tpu":
        # pin before any backend query (a wedged tunnel blocks forever)
        jax.config.update("jax_platforms", args.platform)

    full = args.preset == "full"
    rounds = args.rounds or (100 if full else 8)
    epochs = args.epochs or (20 if full else 2)
    samples = args.samples_per_client or (5000 if full else 192)

    from fedml_tpu.algorithms.centralized import CentralizedTrainer
    from fedml_tpu.algorithms.fedavg import FedAvg, FedAvgConfig
    from fedml_tpu.models import resnet56
    from fedml_tpu.trainer.workload import ClassificationWorkload

    if args.data_dir:
        from fedml_tpu.data import load_data
        data = load_data("cifar10", data_dir=args.data_dir, batch_size=64,
                         client_num=10, partition_method="hetero",
                         partition_alpha=0.5, seed=args.seed)
        source = f"real:{args.data_dir}"
    else:
        from fedml_tpu.data.synthetic import (FLAGSHIP_TWIN_KWARGS,
                                              cifar_learnable_twin)
        # the multi-mode twin whose non-IID gap is REAL (the single-
        # prototype default saturates at fed == cent == 1.0 — a retention
        # ratio that probes nothing); difficulty shared with the CI
        # retention proxy via FLAGSHIP_TWIN_KWARGS so both measure the
        # same task
        data = cifar_learnable_twin(num_clients=10,
                                    samples_per_client=samples,
                                    partition_alpha=0.5, batch_size=64,
                                    seed=args.seed,
                                    **FLAGSHIP_TWIN_KWARGS)
        source = (f"learnable_twin(spc={samples}, lda=0.5, "
                  f"{FLAGSHIP_TWIN_KWARGS})")

    wl = ClassificationWorkload(resnet56(10), num_classes=10)
    # scan engine on CPU: compiling the 10-client vmapped resnet56 cohort
    # takes tens of minutes there; scan compiles ONE client's program
    # (identical results — parity-tested).  TPU keeps the default.
    cfg = FedAvgConfig(comm_round=rounds, client_num_per_round=10,
                       epochs=epochs, batch_size=64, lr=0.001, wd=0.001,
                       frequency_of_the_test=args.eval_every,
                       seed=args.seed,
                       client_axis="scan" if args.platform == "cpu"
                       else "vmap")
    sink = PartialSink(args.json_out + ".partial",
                       {"rounds": rounds, "epochs": epochs,
                        "samples_per_client": samples, "source": source,
                        "preset": args.preset})
    algo = FedAvg(wl, data, cfg, sink=sink)
    t0 = time.time()
    algo.run()
    fed_wall = time.time() - t0
    fed_curve = [{"round": h["round"],
                  "train_acc": h.get("train_acc"),
                  "test_acc": h.get("test_acc")} for h in algo.history]
    fed_final = fed_curve[-1]["test_acc"]
    fed_final_split = "test"
    if fed_final is None:  # dataset without a per-client test split
        fed_final = fed_curve[-1]["train_acc"]
        fed_final_split = "train"

    # centralized twin at the same gradient-step budget (the reference's
    # 93.19 column): all clients' data pooled; each FedAvg round did
    # ``epochs`` local epochs per client in parallel, so the pooled twin
    # trains rounds * epochs epochs over the pooled set
    import jax as _jax
    import jax.numpy as jnp
    cent_epochs = rounds * epochs
    trainer = CentralizedTrainer(wl, lr=0.001, wd=0.001, epochs_per_call=1)
    pooled = {k: jnp.asarray(v) for k, v in data.train_global.items()}
    cent_eval_split = "test" if data.test_global is not None else "train"
    test_g = {k: jnp.asarray(v) for k, v in data.test_global.items()} \
        if data.test_global is not None else pooled
    params_c = wl.init(_jax.random.key(args.seed),
                       _jax.tree.map(lambda v: v[0], pooled))
    cent_curve = []
    t0 = time.time()
    rng_c = _jax.random.key(args.seed + 1)
    eval_stride = max(1, cent_epochs // 20)
    for e in range(cent_epochs):
        rng_c, r = _jax.random.split(rng_c)
        params_c, _ = trainer.local_train(params_c, pooled, r)
        if (e + 1) % eval_stride == 0 or e == cent_epochs - 1:
            st = trainer.metrics(params_c, test_g)
            cent_curve.append({"epoch": e + 1, "acc": st.get("acc"),
                               "split": cent_eval_split})
            with open(args.json_out + ".partial", "w") as f:
                json.dump({"partial": True, "config": sink.meta,
                           "federated_curve": sink.curve,
                           "centralized_curve_so_far": cent_curve}, f,
                          indent=1)
    cent_wall = time.time() - t0
    cent_final = cent_curve[-1]["acc"]

    report = {
        "config": {"model": "resnet56", "clients": 10, "lda_alpha": 0.5,
                   "batch_size": 64, "lr": 0.001, "wd": 0.001,
                   "epochs": epochs, "rounds": rounds, "source": source,
                   "platform": jax.default_backend(), "preset": args.preset},
        "published_reference": {"centralized": 93.19, "federated": 87.12,
                                "retention": 87.12 / 93.19,
                                "anchor": "benchmark/README.md:105"},
        "federated": {"curve": fed_curve, "final_acc": fed_final,
                      "final_acc_split": fed_final_split,
                      "wall_s": round(fed_wall, 1)},
        "centralized": {"final_acc": cent_final,
                        "eval_split": cent_eval_split,
                        "wall_s": round(cent_wall, 1),
                        "curve": cent_curve},
        "retention": (fed_final / cent_final
                      if fed_final is not None and cent_final else None),
    }
    try:
        from fedml_tpu.utils.reference_curves import load_reference_curve
        ref = load_reference_curve(os.path.join(REF_CURVES, "train_metrics"))
        report["published_trajectory_top1"] = [
            e["train_accTop1"] for e in ref]
    except Exception as e:  # torch unpickle may be unavailable
        report["published_trajectory_top1"] = f"unavailable: {e}"
    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=1)
    try:  # clean completion supersedes the incremental checkpoint
        os.remove(args.json_out + ".partial")
    except OSError:
        pass
    print(json.dumps({k: report[k] for k in
                      ("config", "retention")}, default=str))
    print("federated final:", fed_final, "centralized final:", cent_final)


if __name__ == "__main__":
    main()
