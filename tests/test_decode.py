"""Continuous-batching decode contracts (ISSUE 15): incremental-decode
parity vs the full forward, slot isolation and clean slot reuse, the
scheduler's greedy correctness under mid-flight joins, drain-vs-
continuous occupancy, the swap-barrier version contract (a KV cache
computed under version v must never meet params v+1), jit-once per
(slots, cache-bucket), and tiered shedding on the decode queue.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.models.transformer import TransformerLM, init_decode_cache
from fedml_tpu.serve.batcher import ShedError
from fedml_tpu.serve.decode import DecodeScheduler
from fedml_tpu.serve.registry import ModelRegistry

VOCAB = 61


def _model(**kw):
    cfg = dict(vocab_size=VOCAB, d_model=32, n_heads=2, n_layers=2,
               d_ff=64, max_len=64)
    cfg.update(kw)
    return TransformerLM(**cfg)


def _params(model, seed=0):
    return model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, 8), jnp.int32))


def _registry(params, version=0):
    reg = ModelRegistry(lambda p, x: x, history=8)
    reg.publish(params, version)
    return reg


def _ref_greedy(model, params, prompt, max_new):
    """Reference greedy decode via the FULL forward pass each step —
    the oracle the incremental path must match."""
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        logits = model.apply(params, jnp.asarray([toks]))
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


# -- incremental decode vs full forward --------------------------------------

def test_decode_logits_match_full_forward():
    """Token-by-token cached decode reproduces the full forward's
    per-position logits (same params, same math, explicit KV state)."""
    model = _model()
    params = _params(model)
    B, T = 3, 12
    seq = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, VOCAB)
    full = model.apply(params, seq)
    cache = init_decode_cache(model, slots=B, cache_len=16)
    steps = []
    for t in range(T):
        logits, cache = model.apply(params, seq[:, t],
                                    positions=jnp.full((B,), t),
                                    cache=cache)
        steps.append(logits)
    dec = jnp.stack(steps, axis=1)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(full),
                               atol=1e-4, rtol=1e-4)


def test_decode_slots_are_isolated_and_positions_independent():
    """Two sequences decoding in one batch at DIFFERENT positions match
    each decoded alone — a slot never reads a neighbor's cache rows."""
    model = _model()
    params = _params(model)
    rng = np.random.RandomState(0)
    seq_a = rng.randint(0, VOCAB, size=8)
    seq_b = rng.randint(0, VOCAB, size=8)

    def alone(seq, upto):
        cache = init_decode_cache(model, slots=1, cache_len=16)
        for t in range(upto + 1):
            logits, cache = model.apply(
                params, jnp.asarray([seq[t]]),
                positions=jnp.asarray([t]), cache=cache)
        return np.asarray(logits[0])

    # batch: slot 0 walks seq_a from t=0; slot 1 starts seq_b LATER so
    # the two slots sit at different positions every joint step
    cache = init_decode_cache(model, slots=2, cache_len=16)
    for t in range(3):   # slot 1 idle: feed its own prefix only in slot 0
        logits, cache = model.apply(
            params, jnp.asarray([seq_a[t], 0]),
            positions=jnp.asarray([t, 0]), cache=cache)
    # now slot 1 begins at position 0 while slot 0 continues at t
    for i in range(4):
        logits, cache = model.apply(
            params, jnp.asarray([seq_a[3 + i], seq_b[i]]),
            positions=jnp.asarray([3 + i, i]), cache=cache)
    np.testing.assert_allclose(logits[0], alone(seq_a, 6),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(logits[1], alone(seq_b, 3),
                               atol=1e-4, rtol=1e-4)


def test_slot_reuse_masks_previous_occupant():
    """A slot restarting at position 0 over a DIRTY cache (previous
    occupant's rows still there) decodes exactly like a fresh cache —
    the kv_idx <= position mask hides stale state by construction."""
    model = _model()
    params = _params(model)
    rng = np.random.RandomState(1)
    first = rng.randint(0, VOCAB, size=10)
    second = rng.randint(0, VOCAB, size=5)
    cache = init_decode_cache(model, slots=1, cache_len=16)
    for t, tok in enumerate(first):     # dirty the cache deep
        _, cache = model.apply(params, jnp.asarray([tok]),
                               positions=jnp.asarray([t]), cache=cache)
    dirty = cache
    fresh = init_decode_cache(model, slots=1, cache_len=16)
    for t, tok in enumerate(second):    # same tokens over both caches
        out_d, dirty = model.apply(params, jnp.asarray([tok]),
                                   positions=jnp.asarray([t]),
                                   cache=dirty)
        out_f, fresh = model.apply(params, jnp.asarray([tok]),
                                   positions=jnp.asarray([t]),
                                   cache=fresh)
        np.testing.assert_array_equal(np.asarray(out_d),
                                      np.asarray(out_f))


def test_decode_requires_positions_and_rejects_ring_axis():
    model = _model()
    params = _params(model)
    cache = init_decode_cache(model, slots=1, cache_len=8)
    with pytest.raises(ValueError, match="positions"):
        model.apply(params, jnp.asarray([1]), cache=cache)
    with pytest.raises(ValueError, match="ring_axis"):
        model.apply(params, jnp.asarray([1]),
                    positions=jnp.asarray([0]), cache=cache,
                    ring_axis="seq")


def test_cache_len_must_fit_positional_table():
    model = _model(max_len=32)
    with pytest.raises(ValueError, match="max_len"):
        init_decode_cache(model, slots=2, cache_len=64)


def test_moe_decode_runs():
    """The MoE variant decodes through the same cache path (SwitchFFN is
    shape-generic over T=1)."""
    model = _model(moe_experts=2)
    params = _params(model)
    cache = init_decode_cache(model, slots=2, cache_len=8)
    logits, cache = model.apply(params, jnp.asarray([3, 4]),
                                positions=jnp.asarray([0, 0]),
                                cache=cache)
    assert logits.shape == (2, VOCAB)
    assert np.isfinite(np.asarray(logits)).all()


# -- scheduler ---------------------------------------------------------------

def test_scheduler_matches_reference_greedy_with_mid_flight_joins():
    """More requests than slots: later requests join mid-flight as
    earlier ones finish, and every result still matches the full-forward
    greedy oracle — scheduling is numerically invisible."""
    model = _model()
    params = _params(model)
    reg = _registry(params)
    sched = DecodeScheduler(reg, model, slots=2, cache_len=32,
                            max_new=5).start()
    assert sched.warmup()
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(1, VOCAB, size=rng.randint(1, 6)))
               for _ in range(7)]
    futs = [sched.submit(p, max_new=5) for p in prompts]
    for p, f in zip(prompts, futs):
        r = f.result(60)
        assert r.tokens == _ref_greedy(model, params, p, 5)
        assert r.version == 0 and not r.truncated
    assert sched._cache_size() == 1, "mid-flight joins retraced the step"
    sched.stop()


def test_drain_mode_admits_only_when_all_slots_free():
    """The drain baseline holds occupancy strictly to batch boundaries:
    mean occupancy under mixed lengths sits well below continuous."""
    model = _model()
    params = _params(model)
    reg = _registry(params)
    results = {}
    for continuous in (False, True):
        sched = DecodeScheduler(reg, model, slots=4, cache_len=32,
                                continuous=continuous).start()
        assert sched.warmup()
        prompts = [[1 + i] for i in range(16)]
        max_news = [20 if i % 4 == 0 else 3 for i in range(16)]
        futs = [sched.submit(p, max_new=m)
                for p, m in zip(prompts, max_news)]
        toks = [f.result(60).tokens for f in futs]
        results[continuous] = (sched.occupancy(), toks)
        sched.stop()
    occ_drain, toks_drain = results[False]
    occ_cont, toks_cont = results[True]
    assert toks_drain == toks_cont, "schedule changed the greedy tokens"
    assert occ_cont > occ_drain * 1.5, (
        f"continuous occupancy {occ_cont:.2f} not clearly above "
        f"drain {occ_drain:.2f}")


def test_swap_barrier_pins_version_for_in_flight_sequences():
    """A publish mid-generation must NOT touch live sequences (their KV
    cache is state of the OLD params): they finish on the pinned
    version, admission pauses, and post-drain requests get the new one
    — with tokens matching each version's own oracle."""
    model = _model()
    params0 = _params(model, seed=0)
    params1 = jax.tree.map(lambda v: v - 0.02, params0)
    reg = _registry(params0, version=0)
    sched = DecodeScheduler(reg, model, slots=2, cache_len=32,
                            max_new=24).start()
    assert sched.warmup()
    futs = [sched.submit([5, 6], max_new=24) for _ in range(2)]
    # wait until both sequences are demonstrably in flight
    deadline = time.monotonic() + 10
    while sched.steps < 3 and time.monotonic() < deadline:
        time.sleep(0.001)
    assert sched.steps >= 3, "sequences never started"
    reg.publish(params1, 1)
    late = sched.submit([7, 8], max_new=4)
    for f in futs:
        r = f.result(60)
        assert r.version == 0, "swap landed mid-sequence"
        assert r.tokens == _ref_greedy(model, params0, [5, 6], 24)
    r = late.result(60)
    assert r.version == 1, "post-drain admission kept the stale snapshot"
    assert r.tokens == _ref_greedy(model, params1, [7, 8], 4)
    assert sched._cache_size() == 1
    sched.stop()


def test_scheduler_jit_once_registered_with_sentry_and_ledger():
    from fedml_tpu.obs.device import DeviceRecorder
    from fedml_tpu.obs.perf import RecompileSentry
    model = _model()
    reg = _registry(_params(model))
    sched = DecodeScheduler(reg, model, slots=2, cache_len=16)
    recorder = DeviceRecorder(cost_analysis=False)
    sentry = RecompileSentry(strict=True)
    name = sched.register_obs(recorder, sentry)
    assert name == "decode_step[s2,c16]"
    recorder.round_start()
    assert sched.warmup()
    compiles = recorder.round_snapshot(None)["compiles"]
    assert any(c["fn"] == name for c in compiles), compiles
    sentry.check(0)
    sched.start()
    recorder.round_start()
    futs = [sched.submit([1, 2], max_new=4) for _ in range(5)]
    for f in futs:
        f.result(60)
    assert sentry.check(1) == {}, "decode step retraced under load"
    assert recorder.round_snapshot(None)["compiles"] == []
    assert sched._cache_size() == 1
    sched.stop()


def test_truncation_at_cache_bucket_is_flagged():
    model = _model()
    reg = _registry(_params(model))
    sched = DecodeScheduler(reg, model, slots=1, cache_len=8,
                            max_new=32).start()
    assert sched.warmup()
    # prompt 3 + requested 32 > bucket 8: admission caps max_new at 5
    # and the result says so — the generation WAS cut by the bucket
    r = sched.generate([1, 2, 3], max_new=32)
    assert len(r.tokens) == 5 and r.truncated
    # a request that FITS is never flagged
    r2 = sched.generate([1, 2, 3], max_new=5)
    assert len(r2.tokens) == 5 and not r2.truncated
    with pytest.raises(ValueError, match="does not fit"):
        sched.submit(list(range(1, 9)))   # prompt alone fills the bucket
    sched.stop()


def test_decode_shedding_queue_full_deadline_shutdown_no_model():
    model = _model()
    reg = ModelRegistry(lambda p, x: x, history=4)   # EMPTY registry
    sched = DecodeScheduler(reg, model, slots=1, cache_len=16,
                            queue_depth=2).start()
    f = sched.submit([1], max_new=2)
    with pytest.raises(ShedError, match="no_model"):
        f.result(30)
    sched.stop()

    reg2 = _registry(_params(model))
    sched2 = DecodeScheduler(reg2, model, slots=1, cache_len=16,
                             queue_depth=2)   # worker NOT started
    sched2.submit([1])
    sched2.submit([1])
    with pytest.raises(ShedError, match="queue_full"):
        sched2.submit([1])
    sched2.stop(drain=False)
    with pytest.raises(ShedError, match="shutdown"):
        sched2.submit([1])

    sched3 = DecodeScheduler(reg2, model, slots=1, cache_len=16)
    doomed = sched3.submit([1], deadline_s=0.0)
    time.sleep(0.01)
    sched3.start()
    with pytest.raises(ShedError, match="deadline"):
        doomed.result(30)
    sched3.stop()


def test_decode_tier_gate_sheds_best_effort_on_breach():
    """Best-effort decode submits read the SAME objective verdicts as
    deep-healthz: a breaching gate sheds them (slo_degraded) while
    interactive requests keep flowing."""
    class _Gate:
        def __init__(self):
            self.bad = False

        def degraded(self):
            return self.bad

    model = _model()
    reg = _registry(_params(model))
    gate = _Gate()
    sched = DecodeScheduler(reg, model, slots=1, cache_len=16,
                            slo=gate).start()
    assert sched.warmup()
    assert sched.generate([1], max_new=2, tier="best_effort").tokens
    gate.bad = True
    with pytest.raises(ShedError, match="slo_degraded"):
        sched.submit([1], tier="best_effort")
    assert sched.generate([1], max_new=2).tokens   # interactive unharmed
    with pytest.raises(ValueError, match="unknown tier"):
        sched.submit([1], tier="bulk")
    sched.stop()


def test_drain_on_stop_answers_queued_sequences():
    model = _model()
    reg = _registry(_params(model))
    sched = DecodeScheduler(reg, model, slots=2, cache_len=16,
                            max_new=3)
    futs = [sched.submit([1 + i], max_new=3) for i in range(5)]
    sched.start()
    sched.stop(drain=True)   # may race the worker's FIRST iteration:
    # the drain contract must hold even when no snapshot was pinned yet
    for f in futs:
        assert len(f.result(0).tokens) == 3
    # never-started scheduler: same contract, settled inline
    sched2 = DecodeScheduler(reg, model, slots=2, cache_len=16,
                             max_new=3)
    futs2 = [sched2.submit([2 + i], max_new=3) for i in range(3)]
    sched2.stop(drain=True)
    for f in futs2:
        assert len(f.result(0).tokens) == 3


def test_queue_utilization_gauge_recovers_after_burst():
    """The queue-fill gauge must fall back as the worker drains — a
    submit-only gauge would latch a burst's high-water mark and
    self-sustain an SLO breach (and best-effort shedding) on an idle
    instance."""
    from fedml_tpu.obs import telemetry
    telemetry.enable()
    try:
        model = _model()
        reg = _registry(_params(model))
        sched = DecodeScheduler(reg, model, slots=2, cache_len=16,
                                max_new=2, queue_depth=8)
        futs = [sched.submit([1 + i], max_new=2) for i in range(8)]
        snap = telemetry.get_registry().snapshot()
        g = [v for k, v in snap["gauges"].items()
             if k.startswith("fedml_serve_queue_utilization_ratio")]
        assert g and max(g) == 1.0, "burst never registered"
        sched.start()
        for f in futs:
            f.result(60)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            snap = telemetry.get_registry().snapshot()
            g = [v for k, v in snap["gauges"].items()
                 if k.startswith("fedml_serve_queue_utilization_ratio")]
            if max(g) == 0.0:
                break
            time.sleep(0.01)
        assert max(g) == 0.0, f"gauge latched at {max(g)} after drain"
        sched.stop()
    finally:
        telemetry.disable()
