"""ResilientTransport: retry/backoff/dead-letter semantics, reconnection
hooks, and transport teardown idempotency (the contract the reference's
one-shot-send transports never had — grpc_comm_manager.py:70-76 has no
retry, mqtt_comm_manager.py never reconnects)."""

import threading
import time

import pytest

from fedml_tpu.comm.local import LocalHub, LocalTransport
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilient import (ResilientTransport, RetryPolicy,
                                      SendDeadlineExceeded, SendQueueFull)
from fedml_tpu.comm.transport import Transport


class _FlakyTransport(Transport):
    """Fails the first ``fail_first`` sends of each message value, then
    delivers into ``delivered``.  Records reconnect() calls."""

    def __init__(self, fail_first=0):
        super().__init__()
        self.fail_first = fail_first
        self.attempts = {}
        self.delivered = []
        self.reconnects = 0

    def send_message(self, msg):
        n = self.attempts.get(msg.get("v"), 0)
        self.attempts[msg.get("v")] = n + 1
        if n < self.fail_first:
            raise ConnectionError(f"flaky wire (attempt {n + 1})")
        self.delivered.append(msg.get("v"))

    def reconnect(self):
        self.reconnects += 1

    def run(self):
        pass

    def stop(self):
        pass


def _fast_policy(**kw):
    base = dict(max_attempts=4, base_backoff_s=0.005, max_backoff_s=0.02,
                jitter_frac=0.2, send_deadline_s=5.0)
    base.update(kw)
    return RetryPolicy(**base)


def _drain(rt, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not rt._queue.empty() and time.monotonic() < deadline:
        time.sleep(0.005)


def test_retry_recovers_from_transient_failures():
    inner = _FlakyTransport(fail_first=2)
    rt = ResilientTransport(inner, _fast_policy())
    for v in range(3):
        rt.send_message(Message("m", 1, 0).add("v", v))
    _drain(rt)
    time.sleep(0.2)
    assert sorted(inner.delivered) == [0, 1, 2]
    assert inner.delivered == [0, 1, 2]  # FIFO order survives retries
    assert rt.retries >= 6 and rt.sent_ok == 3 and rt.dead_letters == 0
    assert inner.reconnects >= 6  # reconnect between every failed attempt
    rt.stop()


def test_dead_letter_after_attempts_exhausted():
    inner = _FlakyTransport(fail_first=99)
    letters = []
    rt = ResilientTransport(inner, _fast_policy(max_attempts=3),
                            on_dead_letter=lambda m, e: letters.append((m, e)))
    rt.send_message(Message("m", 1, 0).add("v", 0))
    _drain(rt)
    time.sleep(0.3)
    assert rt.dead_letters == 1 and rt.sent_ok == 0
    assert len(letters) == 1
    assert isinstance(letters[0][1], ConnectionError)
    rt.stop()


def test_send_deadline_bounds_total_retry_time():
    inner = _FlakyTransport(fail_first=99)
    letters = []
    rt = ResilientTransport(
        inner,
        _fast_policy(max_attempts=1000, base_backoff_s=0.05,
                     max_backoff_s=0.05, send_deadline_s=0.2),
        on_dead_letter=lambda m, e: letters.append(e))
    t0 = time.monotonic()
    rt.send_message(Message("m", 1, 0).add("v", 0))
    _drain(rt)
    time.sleep(0.5)
    assert len(letters) == 1
    # the dead-letter must be typed as a deadline exhaustion, not the raw
    # wire error, so handlers can tell budget-gone from peer-broken
    assert isinstance(letters[0], SendDeadlineExceeded)
    assert time.monotonic() - t0 < 3.0  # nowhere near 1000 attempts
    rt.stop()


def test_bounded_queue_dead_letters_overflow():
    class _Blocked(Transport):
        def __init__(self):
            super().__init__()
            self.gate = threading.Event()

        def send_message(self, msg):
            self.gate.wait(5)

        def run(self):
            pass

        def stop(self):
            self.gate.set()

    inner = _Blocked()
    letters = []
    rt = ResilientTransport(inner, _fast_policy(), max_in_flight=2,
                            on_dead_letter=lambda m, e: letters.append(e))
    for v in range(8):  # 1 in flight + 2 queued; the rest overflow
        rt.send_message(Message("m", 1, 0).add("v", v))
    assert len(letters) >= 5
    assert all(isinstance(e, SendQueueFull) for e in letters)
    inner.gate.set()  # unblock the in-flight send so stop() joins fast
    rt.stop()


def test_resilient_passes_observers_and_run_through():
    hub = LocalHub()
    t0, t1 = hub.transport(0), hub.transport(1)
    rt = ResilientTransport(t1, _fast_policy())
    got = []

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append(msg.get("v"))

    rt.add_observer(Collect())
    t0.send_message(Message("m", 0, 1).add("v", 41))
    hub.pump()
    assert got == [41]
    rt.remove_observer(Collect())  # unknown observer: idempotent no-op
    rt.stop()
    rt.stop()  # idempotent


def test_stop_drains_queued_messages_one_attempt_each():
    """Regression: a FINISH broadcast enqueued right before stop() must
    still go out (one attempt each, no retry loop) — the server stops its
    transport immediately after queueing the shutdown messages, and
    discarding them left gRPC silos hanging until their idle timeout."""
    hub = LocalHub()
    sink = hub.transport(0)
    got = []

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append(msg.get("v"))

    sink.add_observer(Collect())
    rt = ResilientTransport(LocalTransport(hub, 1), _fast_policy())
    for v in range(5):
        rt.send_message(Message("finish", 1, 0).add("v", v))
    rt.stop()  # joins the sender: everything queued before _STOP drains
    hub.pump()
    assert got == list(range(5))


def test_grpc_send_survives_receiver_restart():
    """The federation-grade scenario: the receiving server dies mid-run
    and comes back on the same address; a resilient sender retries with
    channel re-dial until the new process answers."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from fedml_tpu.comm.grpc_transport import GrpcTransport

    table = {0: "127.0.0.1", 1: "127.0.0.1"}
    a = GrpcTransport(0, table, base_port=56310, send_timeout_s=0.3)
    rt = ResilientTransport(
        a, RetryPolicy(max_attempts=30, base_backoff_s=0.05,
                       max_backoff_s=0.2, send_deadline_s=20.0))
    b = GrpcTransport(1, table, base_port=56310)
    got = []

    class Collect:
        def receive_message(self, msg_type, msg):
            got.append(msg.get("v"))

    try:
        b.add_observer(Collect())
        bt = threading.Thread(target=b.run, daemon=True)
        bt.start()
        rt.send_message(Message("m", 0, 1).add("v", 1))
        deadline = time.monotonic() + 5
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        assert got == [1]

        b.stop()  # receiver crashes...
        bt.join(timeout=5)
        rt.send_message(Message("m", 0, 1).add("v", 2))
        time.sleep(0.4)  # the send is now failing/retrying
        b = GrpcTransport(1, table, base_port=56310)  # ...and restarts
        b.add_observer(Collect())
        bt = threading.Thread(target=b.run, daemon=True)
        bt.start()
        deadline = time.monotonic() + 15
        while len(got) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert got == [1, 2], "resilient sender never reached the " \
                              "restarted receiver"
        assert rt.retries > 0
    finally:
        rt.stop()
        b.stop()


def test_mqtt_reconnect_reestablishes_subscription():
    """MqttTransport.reconnect() redoes CONNECT/SUBSCRIBE against the
    in-repo broker; traffic flows again after a socket loss."""
    from fedml_tpu.comm import mqtt_transport as mt
    from fedml_tpu.comm.mqtt_broker import MqttBroker

    have = mt.HAVE_MQTT
    mt.HAVE_MQTT = False  # force the in-repo MiniMqttClient
    try:
        with MqttBroker() as broker:
            a = mt.MqttTransport(0, "127.0.0.1", broker.port)
            b = mt.MqttTransport(1, "127.0.0.1", broker.port)
            got = []

            class Collect:
                def receive_message(self, msg_type, msg):
                    got.append(msg.get("v"))

            b.add_observer(Collect())
            a.send_message(Message("m", 0, 1).add("v", 1))

            # sever a's socket behind its back, then reconnect
            a._client._sock.close()
            a.reconnect()
            a.send_message(Message("m", 0, 1).add("v", 2))

            deadline = time.monotonic() + 5
            while len(b._inbox.queue) < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            th = threading.Thread(target=b.run, daemon=True)
            th.start()
            time.sleep(0.2)
            b.stop()
            th.join(timeout=5)
            assert got == [1, 2]
            a.stop()
            a.stop()  # idempotent
            b.stop()  # idempotent
    finally:
        mt.HAVE_MQTT = have


def test_transport_stop_idempotency_matrix():
    """Every transport flavor tolerates double-stop and double
    remove_observer (the teardown paths overlap in practice)."""
    from fedml_tpu.comm.chaos import ChaosPlan, ChaosTransport

    hub = LocalHub()
    local = hub.transport(0)

    class Obs:
        def receive_message(self, msg_type, msg):
            pass

    obs = Obs()
    local.add_observer(obs)
    local.remove_observer(obs)
    local.remove_observer(obs)  # second removal: no ValueError
    local.stop()
    local.stop()

    chaos = ChaosTransport(hub.transport(1), ChaosPlan())
    chaos.stop()
    chaos.stop()

    rt = ResilientTransport(LocalTransport(hub, 2), _fast_policy())
    rt.stop()
    rt.stop()
