"""FedNAS — federated neural architecture search over the DARTS space.

Reference choreography (``fedml_api/distributed/fednas/``):

* **search phase**: each client alternates a weight step on its train split
  with an architecture step on its validation split
  (FedNASTrainer.local_search:82-120).  The α gradient is the reference's
  ``Architect.step_v2`` (darts/architect.py:58-99): ∇α L_val + λ·∇α L_train
  — both first-order, no unrolled second-order term.
* **aggregation**: the server sample-weight-averages BOTH the network
  weights (FedNASAggregator.py:71-93) and the α tensors (:95-113), then
  decodes and logs the global genotype each round
  (record_model_global_architecture :173).
* **train phase**: after search, the decoded genotype builds the discrete
  net and plain FedAvg trains it (FedNASTrainer.train).

TPU-native design: one jit'd ``search_round`` per client runs the
alternating w/α scan; the cohort is vmapped so all clients search in
parallel; aggregation is the same weighted pytree mean used everywhere
(α is just another pytree leaf pair).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.models.darts import (DARTSSearchNetwork, Genotype,
                                    init_alphas, parse_genotype)

Pytree = Any


@dataclasses.dataclass
class FedNASConfig:
    rounds: int = 5
    epochs: int = 1               # local search epochs per round
    w_lr: float = 0.025           # --learning_rate (main_fednas.py)
    w_momentum: float = 0.9
    w_weight_decay: float = 3e-4
    arch_lr: float = 3e-4         # --arch_learning_rate
    arch_weight_decay: float = 1e-3
    lambda_train_regularizer: float = 1.0   # step_v2 λ (main_fednas.py:91)
    grad_clip: float = 5.0        # --grad_clip
    seed: int = 0
    # Reference parity: FedNASAggregator averages only weights and α; each
    # client keeps its own optimizer state (momentum / Adam moments) across
    # rounds.  True = TPU-native deviation that sample-weight-averages the
    # optimizer states too (shares momentum across the cohort).
    aggregate_opt_state: bool = False

    def __post_init__(self):
        if self.epochs < 1:
            raise ValueError("FedNAS requires epochs >= 1")


class FedNAS:
    def __init__(self, model: DARTSSearchNetwork, cfg: FedNASConfig):
        self.model = model
        self.cfg = cfg
        self.w_opt = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.add_decayed_weights(cfg.w_weight_decay),
            optax.sgd(cfg.w_lr, momentum=cfg.w_momentum))
        # Architect optimizer: Adam(arch_lr, betas=(0.5, 0.999), wd)
        # (darts/architect.py:15-30)
        self.a_opt = optax.chain(
            optax.add_decayed_weights(cfg.arch_weight_decay),
            optax.adam(cfg.arch_lr, b1=0.5, b2=0.999))
        self._build()

    def _build(self):
        cfg = self.cfg

        def loss_fn(params, alphas, batch):
            logits = self.model.apply({"params": params}, batch["x"], alphas,
                                      train=True)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"])
            m = batch["mask"]
            return jnp.sum(ce * m) / jnp.maximum(jnp.sum(m), 1.0)

        def search_step(carry, xs):
            """One (α step on valid, w step on train) pair — the loop body
            of local_search (FedNASTrainer.py:87-120)."""
            params, alphas, w_state, a_state = carry
            train_batch, valid_batch = xs

            # architect step_v2: ∇α L_val + λ ∇α L_train (first-order)
            g_val = jax.grad(loss_fn, argnums=1)(params, alphas, valid_batch)
            g_train = jax.grad(loss_fn, argnums=1)(params, alphas, train_batch)
            g_alpha = jax.tree.map(
                lambda gv, gt: gv + cfg.lambda_train_regularizer * gt,
                g_val, g_train)
            a_updates, a_state = self.a_opt.update(g_alpha, a_state, alphas)
            alphas = optax.apply_updates(alphas, a_updates)

            # weight step on the train batch (grad-clip 5 in w_opt chain)
            loss, g_w = jax.value_and_grad(loss_fn)(params, alphas, train_batch)
            w_updates, w_state = self.w_opt.update(g_w, w_state, params)
            params = optax.apply_updates(params, w_updates)
            return (params, alphas, w_state, a_state), loss

        def search_round(params, alphas, w_state, a_state, train, valid):
            """E epochs of alternating steps over one client's batches."""
            carry = (params, alphas, w_state, a_state)
            for _ in range(cfg.epochs):
                carry, losses = jax.lax.scan(search_step, carry,
                                             (train, valid))
            return carry + (jnp.mean(losses),)

        # all sampled clients search in parallel (vs N MPI processes);
        # optimizer states are per-client (stacked on axis 0) — clients keep
        # their own momentum/Adam moments, as in the reference
        self._cohort_search = jax.jit(jax.vmap(
            search_round, in_axes=(None, None, 0, 0, 0, 0)))

        def metrics(params, alphas, batch):
            logits = self.model.apply({"params": params}, batch["x"], alphas)
            pred = jnp.argmax(logits, -1)
            m = batch["mask"]
            return {"correct": jnp.sum((pred == batch["y"]) * m),
                    "total": jnp.sum(m)}

        self._metrics = jax.jit(metrics)

    def init(self, rng: jax.Array, sample_x: jnp.ndarray):
        ra, rp = jax.random.split(rng)
        alphas = init_alphas(ra, self.model.steps)
        params = self.model.init(rp, sample_x, alphas)["params"]
        return params, alphas

    def run(self, train_cohort: Dict[str, jnp.ndarray],
            valid_cohort: Dict[str, jnp.ndarray],
            rng: Optional[jax.Array] = None) -> Dict[str, Any]:
        """cohorts: stacked {"x": [C, S, B, ...], "y", "mask"}; valid is each
        client's local search/validation split (local_search draws val
        batches alongside train batches, FedNASTrainer.py:98-101)."""
        cfg = self.cfg
        rng = rng if rng is not None else jax.random.key(cfg.seed)
        params, alphas = self.init(rng, train_cohort["x"][0, 0])
        C = train_cohort["x"].shape[0]

        def stack_per_client(t):
            return jax.tree.map(lambda x: jnp.stack([jnp.asarray(x)] * C), t)

        # one optimizer state PER CLIENT, carried across rounds (the
        # reference's clients own their optimizers; the server never sees
        # momentum — FedNASAggregator aggregates only weights and α)
        w_state = stack_per_client(self.w_opt.init(params))
        a_state = stack_per_client(self.a_opt.init(alphas))
        history: List[Dict[str, Any]] = []
        weights = train_cohort["num_samples"] if "num_samples" in train_cohort \
            else jnp.sum(train_cohort["mask"], axis=(1, 2))

        for rnd in range(cfg.rounds):
            c_params, c_alphas, w_state_c, a_state_c, losses = \
                self._cohort_search(params, alphas, w_state, a_state,
                                    {k: train_cohort[k]
                                     for k in ("x", "y", "mask")},
                                    {k: valid_cohort[k]
                                     for k in ("x", "y", "mask")})
            # server aggregates BOTH weights and α, sample-weighted.
            # (tuple roots — α pairs, optax namedtuple states — are wrapped
            # in a dict so tree_weighted_mean sees ONE stacked pytree, not a
            # sequence of separate trees)
            wrap = lambda t: tree_weighted_mean({"t": t}, weights)["t"]
            params = tree_weighted_mean(c_params, weights)
            alphas = wrap(c_alphas)
            if cfg.aggregate_opt_state:
                # opt-in deviation: share momentum across the cohort
                w_state = jax.tree.map(
                    lambda a, s: jnp.stack([a.astype(s.dtype)] * C),
                    wrap(w_state_c), w_state_c)
                a_state = jax.tree.map(
                    lambda a, s: jnp.stack([a.astype(s.dtype)] * C),
                    wrap(a_state_c), a_state_c)
            else:  # reference behavior: clients keep their own states
                w_state, a_state = w_state_c, a_state_c
            genotype = self.genotype(alphas)
            history.append({"round": rnd,
                            "search_loss": float(jnp.mean(losses)),
                            "genotype": genotype})
        return {"params": params, "alphas": alphas, "history": history}

    def genotype(self, alphas) -> Genotype:
        """Decode the global architecture
        (FedNASAggregator.record_model_global_architecture:173)."""
        an, ar = alphas
        return parse_genotype(np.asarray(an), np.asarray(ar),
                              self.model.steps, self.model.multiplier)

    def evaluate(self, params, alphas, data: Dict[str, jnp.ndarray]
                 ) -> Dict[str, float]:
        correct = total = 0.0
        for s in range(data["x"].shape[0]):
            m = self._metrics(params, alphas,
                              {k: data[k][s] for k in ("x", "y", "mask")})
            correct += float(m["correct"])
            total += float(m["total"])
        return {"acc": correct / max(total, 1.0)}
