from fedml_tpu.models.linear import LogisticRegression
from fedml_tpu.models.cnn import CNNOriginalFedAvg, CNNDropOut
from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow
